// HNSW graph index over SQ8-encoded vectors — the host-side native engine
// behind the `hnswsq` builder (reference analog: faiss IndexHNSWSQ,
// distributed_faiss/index.py:51-60). Graph traversal is pointer-chasing and
// TPU-hostile, so this one index family runs on the host CPU; everything
// else in the framework is XLA/Pallas.
//
// Clean-room implementation of the HNSW algorithm (Malkov & Yashunin):
// geometric level assignment, greedy descent through upper layers, best-first
// ef-bounded search on layer 0, bidirectional linking with closest-first
// pruning. Distances are asymmetric: fp32 query vs uint8 codes dequantized
// on the fly (d = sum_i (q_i - (vmin_i + c_i * step_i))^2, L2 only — the
// reference asserts L2 for hnswsq too).
//
// Concurrency model (mirrors the discipline of FAISS's OpenMP HNSW, which
// the reference gets for free):
//   - Adjacency lists are FIXED-CAPACITY arrays of std::atomic<int> with an
//     atomic count. Readers take no locks: acquire-load the count, read the
//     prefix. Writers mutate only under a striped per-node mutex and publish
//     with a release-store of the count, so a reader never sees a torn or
//     out-of-bounds neighbor. (This is why capacities are fixed: a growable
//     vector would invalidate concurrent readers on realloc.)
//   - add_batch() appends codes/levels/link-frames sequentially (cheap),
//     then builds the graph links for the batch on per-call worker threads
//     (parallel_for below spawns+joins std::threads each call — NOT a
//     persistent pool; fine for big build batches, and search's per-call
//     spawn cost only matters for tiny high-QPS batches on many-core
//     hosts). Only one stripe lock is ever held at a time -> no deadlock.
//   - search() is lock-free w.r.t. the graph and uses a pooled per-call
//     visited table, so concurrent searches on ONE graph are safe; batched
//     queries also fan out over per-call worker threads.
//   - The one remaining exclusion the CALLER must provide: add_batch() must
//     not overlap search()/save() (codes_/levels_ vectors grow). The engine's
//     index_lock already provides this in the serving path.
//
// C API at the bottom (ctypes-consumed by models/hnsw.py).

#include <algorithm>
#include <atomic>
#include <cmath>
#if defined(__AVX512F__)
#include <immintrin.h>
#endif
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Neighbor {
    float dist;
    int id;
};
struct NearCmp {  // min-heap by distance
    bool operator()(const Neighbor& a, const Neighbor& b) const { return a.dist > b.dist; }
};
struct FarCmp {  // max-heap by distance
    bool operator()(const Neighbor& a, const Neighbor& b) const { return a.dist < b.dist; }
};

// Fixed-capacity adjacency list readable without locks (see module comment).
struct Links {
    std::unique_ptr<std::atomic<int>[]> ids;
    std::atomic<int> count{0};
    int cap = 0;

    void init(int c) {
        cap = c;
        ids.reset(new std::atomic<int>[c]);
    }
    // snapshot the stable prefix into out
    void read(std::vector<int>* out) const {
        int c = count.load(std::memory_order_acquire);
        out->resize(c);
        for (int i = 0; i < c; ++i) (*out)[i] = ids[i].load(std::memory_order_relaxed);
    }
    // writer-side (caller holds the node's stripe lock)
    void rewrite(const std::vector<Neighbor>& v) {
        count.store(0, std::memory_order_release);
        int c = std::min<int>(cap, v.size());
        for (int i = 0; i < c; ++i) ids[i].store(v[i].id, std::memory_order_relaxed);
        count.store(c, std::memory_order_release);
    }
    bool append(int id) {  // false when full
        int c = count.load(std::memory_order_relaxed);
        if (c >= cap) return false;
        ids[c].store(id, std::memory_order_relaxed);
        count.store(c + 1, std::memory_order_release);
        return true;
    }
};

// reusable visited-epoch scratch; pooled so concurrent searches never share
struct Visited {
    std::vector<uint32_t> v;
    uint32_t epoch = 0;

    void begin(size_t n) {
        if (v.size() < n) v.resize(n, 0u);
        if (++epoch == 0) {
            std::fill(v.begin(), v.end(), 0u);
            epoch = 1;
        }
    }
    bool test_set(int i) {
        if (v[i] == epoch) return true;
        v[i] = epoch;
        return false;
    }
};

class VisitedPool {
  public:
    std::unique_ptr<Visited> get() {
        std::lock_guard<std::mutex> g(mu_);
        if (free_.empty()) return std::unique_ptr<Visited>(new Visited());
        auto out = std::move(free_.back());
        free_.pop_back();
        return out;
    }
    void put(std::unique_ptr<Visited> v) {
        std::lock_guard<std::mutex> g(mu_);
        free_.push_back(std::move(v));
    }

  private:
    std::mutex mu_;
    std::vector<std::unique_ptr<Visited>> free_;
};

int default_threads() {
    if (const char* e = std::getenv("DFT_HNSW_THREADS")) {
        int v = std::atoi(e);
        if (v > 0) return v;
    }
    unsigned hc = std::thread::hardware_concurrency();
    return hc ? static_cast<int>(hc) : 1;
}

// run fn(i) for i in [0, n) on up to nthreads workers
template <typename F>
void parallel_for(int n, int nthreads, F fn) {
    nthreads = std::min(nthreads, n);
    if (nthreads <= 1) {
        for (int i = 0; i < n; ++i) fn(i);
        return;
    }
    std::atomic<int> next{0};
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
        ts.emplace_back([&] {
            for (;;) {
                int i = next.fetch_add(1, std::memory_order_relaxed);
                if (i >= n) return;
                fn(i);
            }
        });
    }
    for (auto& t : ts) t.join();
}

class HNSW {
  public:
    HNSW(int dim, int M, int ef_construction, unsigned seed)
        : dim_(dim), M_(M), M0_(2 * M), efc_(ef_construction), rng_(seed),
          ml_(1.0f / std::log(static_cast<float>(M))), entry_(-1), max_level_(-1),
          nthreads_(default_threads()) {
        vmin_.assign(dim, 0.f);
        step_.assign(dim, 1.f / 255.f);
    }

    void set_codec(const float* vmin, const float* step) {
        std::copy(vmin, vmin + dim_, vmin_.begin());
        std::copy(step, step + dim_, step_.begin());
    }

    void set_threads(int n) { nthreads_ = n > 0 ? n : default_threads(); }

    int size() const { return static_cast<int>(levels_.size()); }

    void add_batch(int n, const uint8_t* codes) {
        if (n <= 0) return;
        int base = size();
        // sequential prep: codes, deterministic levels, link frames. After
        // this the per-node Links objects are stable for the parallel phase.
        codes_.insert(codes_.end(), codes, codes + static_cast<size_t>(n) * dim_);
        std::uniform_real_distribution<float> uni(1e-9f, 1.0f);
        for (int i = 0; i < n; ++i) {
            int level = static_cast<int>(-std::log(uni(rng_)) * ml_);
            levels_.push_back(level);
            auto l0 = std::unique_ptr<Links>(new Links());
            l0->init(M0_);
            links0_.push_back(std::move(l0));
            auto up = std::unique_ptr<std::vector<Links>>(new std::vector<Links>(
                level > 0 ? level : 0));
            for (auto& l : *up) l.init(M_);
            upper_.push_back(std::move(up));
        }
        int start = base;
        if (entry_.load(std::memory_order_acquire) < 0) {
            // bootstrap the graph with one synchronous insert
            link_node(base);
            start = base + 1;
        }
        int todo = base + n - start;
        if (todo > 0) {
            parallel_for(todo, nthreads_, [&](int i) { link_node(start + i); });
        }
    }

    void search(int nq, const float* q, int k, int ef,
                float* out_d, int64_t* out_i) const {
        parallel_for(nq, nthreads_, [&](int i) {
            search_one(q + static_cast<size_t>(i) * dim_, k, ef,
                       out_d + static_cast<size_t>(i) * k,
                       out_i + static_cast<size_t>(i) * k);
        });
    }

    bool save(const char* path) const;
    static HNSW* load(const char* path);

  private:
    static constexpr int kStripes = 1024;

    int dim_, M_, M0_, efc_;
    std::mt19937 rng_;
    float ml_;
    std::atomic<int> entry_, max_level_;
    int nthreads_;
    std::vector<float> vmin_, step_;

    // a plain vector is fine for code storage because add/search never
    // overlap (caller contract) — within one add_batch the vector is fully
    // grown before the parallel phase reads it
    std::vector<uint8_t> codes_;  // n * dim

    std::vector<int> levels_;                                   // per node
    std::vector<std::unique_ptr<Links>> links0_;                // layer 0
    std::vector<std::unique_ptr<std::vector<Links>>> upper_;    // layers >= 1
    mutable std::mutex stripes_[kStripes];
    std::mutex entry_mu_;
    mutable VisitedPool visited_pool_;

    std::mutex& stripe(int v) const { return stripes_[v & (kStripes - 1)]; }

    // Asymmetric fp32-query vs SQ8-code distance: the single hottest loop
    // (search and construction are both dist-dominated). gcc's auto-
    // vectorizer handles the uint8->float convert poorly (measured 7.3
    // Mdist/s at dim=96 under -O3 -march=native vs 66 for the folded
    // AVX-512 form, 104 for this pre-centered form — identical results).
    //
    // CONTRACT: qa is the PRE-CENTERED query qa[i] = q[i] - vmin_[i]
    // (precenter() / decode_centered() produce it once per query scope),
    // so d = sum_i (qa_i - c_i * step_i)^2. Hoisting the vmin subtract out
    // of the per-candidate loop removes 2 of ~8 ops per SIMD step.
    float dist(const float* qa, int b) const {
        const uint8_t* c = codes_.data() + static_cast<size_t>(b) * dim_;
#if defined(__AVX512F__) && defined(__AVX512BW__) && defined(__AVX512VL__)
        const float* step = step_.data();
        __m512 acc = _mm512_setzero_ps();
        int i = 0;
        for (; i + 16 <= dim_; i += 16) {
            __m128i cb = _mm_loadu_si128(reinterpret_cast<const __m128i*>(c + i));
            __m512 cf = _mm512_cvtepi32_ps(_mm512_cvtepu8_epi32(cb));
            __m512 t = _mm512_fnmadd_ps(cf, _mm512_loadu_ps(step + i),
                                        _mm512_loadu_ps(qa + i));
            acc = _mm512_fmadd_ps(t, t, acc);
        }
        if (i < dim_) {
            __mmask16 m = static_cast<__mmask16>((1u << (dim_ - i)) - 1);
            __m128i cb = _mm_maskz_loadu_epi8(m, reinterpret_cast<const __m128i*>(c + i));
            __m512 cf = _mm512_cvtepi32_ps(_mm512_cvtepu8_epi32(cb));
            __m512 t = _mm512_fnmadd_ps(cf, _mm512_maskz_loadu_ps(m, step + i),
                                        _mm512_maskz_loadu_ps(m, qa + i));
            acc = _mm512_mask3_fmadd_ps(t, t, acc, m);
        }
        return _mm512_reduce_add_ps(acc);
#else
        float acc = 0.f;
        for (int i = 0; i < dim_; ++i) {
            float t = qa[i] - c[i] * step_[i];
            acc += t * t;
        }
        return acc;
#endif
    }

    // qa[i] = q[i] - vmin_[i]: the once-per-query companion of dist()
    void precenter(const float* q, float* qa) const {
        for (int i = 0; i < dim_; ++i) qa[i] = q[i] - vmin_[i];
    }

    // pre-centered reconstruction of a stored code: decode(b) - vmin = c*step
    void decode_centered(int b, float* out) const {
        const uint8_t* c = codes_.data() + static_cast<size_t>(b) * dim_;
        for (int i = 0; i < dim_; ++i) out[i] = c[i] * step_[i];
    }

    void decode(int b, float* out) const {
        const uint8_t* c = codes_.data() + static_cast<size_t>(b) * dim_;
        for (int i = 0; i < dim_; ++i) out[i] = vmin_[i] + c[i] * step_[i];
    }

    const Links& links(int v, int level) const {
        return level == 0 ? *links0_[v] : (*upper_[v])[level - 1];
    }
    Links& links(int v, int level) {
        return level == 0 ? *links0_[v] : (*upper_[v])[level - 1];
    }

    // best-first search at one layer; returns up to ef closest as a sorted
    // (ascending) vector. Lock-free graph reads (see Links).
    std::vector<Neighbor> search_layer(const float* q, int entry, float entry_d,
                                       int ef, int level, Visited& vis,
                                       std::vector<int>& nbuf) const {
        vis.begin(levels_.size());

        std::priority_queue<Neighbor, std::vector<Neighbor>, NearCmp> cand;
        std::priority_queue<Neighbor, std::vector<Neighbor>, FarCmp> result;
        cand.push({entry_d, entry});
        result.push({entry_d, entry});
        vis.test_set(entry);

        while (!cand.empty()) {
            Neighbor cur = cand.top();
            if (cur.dist > result.top().dist && static_cast<int>(result.size()) >= ef)
                break;
            cand.pop();
            links(cur.id, level).read(&nbuf);
            for (int nb : nbuf) {
                if (vis.test_set(nb)) continue;
                float d = dist(q, nb);
                if (static_cast<int>(result.size()) < ef || d < result.top().dist) {
                    cand.push({d, nb});
                    result.push({d, nb});
                    if (static_cast<int>(result.size()) > ef) result.pop();
                }
            }
        }
        std::vector<Neighbor> out(result.size());
        for (size_t i = result.size(); i-- > 0;) {
            out[i] = result.top();
            result.pop();
        }
        return out;
    }

    int greedy_descend(const float* q, int from_level, int to_level,
                       int entry, float* d_io, std::vector<int>& nbuf) const {
        int cur = entry;
        float cur_d = *d_io;
        for (int l = from_level; l > to_level; --l) {
            bool improved = true;
            while (improved) {
                improved = false;
                links(cur, l).read(&nbuf);
                for (int nb : nbuf) {
                    float d = dist(q, nb);
                    if (d < cur_d) {
                        cur_d = d;
                        cur = nb;
                        improved = true;
                    }
                }
            }
        }
        *d_io = cur_d;
        return cur;
    }

    // closest-first pruning to cap (simple variant of the paper's heuristic)
    static void prune(std::vector<Neighbor>* cands, int cap) {
        std::sort(cands->begin(), cands->end(),
                  [](const Neighbor& a, const Neighbor& b) { return a.dist < b.dist; });
        if (static_cast<int>(cands->size()) > cap) cands->resize(cap);
    }

    // build the graph links of one already-appended node (thread-safe)
    void link_node(int id) {
        int level = levels_[id];
        std::vector<float> qf(dim_);
        decode_centered(id, qf.data());  // dist() takes pre-centered queries
        const float* q = qf.data();

        int entry = entry_.load(std::memory_order_acquire);
        if (entry < 0) {
            std::lock_guard<std::mutex> g(entry_mu_);
            if (entry_.load(std::memory_order_relaxed) < 0) {
                entry_.store(id, std::memory_order_release);
                max_level_.store(level, std::memory_order_release);
                return;
            }
            entry = entry_.load(std::memory_order_relaxed);
        }
        // entry_ and max_level_ are separate atomics: a concurrent max-level
        // bump can hand us (old entry, new top). Clamp the descent start to
        // the entry node's own level so links(entry, l) never goes OOB.
        int top = std::min(max_level_.load(std::memory_order_acquire), levels_[entry]);

        auto vis = visited_pool_.get();
        std::vector<int> nbuf;
        nbuf.reserve(M0_);

        float d = dist(q, entry);
        int cur = greedy_descend(q, top, std::min(level, top), entry, &d, nbuf);

        std::vector<float> nbf(dim_);
        std::vector<Neighbor> rel;
        for (int l = std::min(level, top); l >= 0; --l) {
            auto found = search_layer(q, cur, d, efc_, l, *vis, nbuf);
            int cap = (l == 0) ? M0_ : M_;
            std::vector<Neighbor> sel(found);
            prune(&sel, M_);
            {
                // own links: append under our stripe (backlinking threads
                // may already be touching this node)
                std::lock_guard<std::mutex> g(stripe(id));
                Links& my = links(id, l);
                for (const auto& nb : sel) {
                    if (!my.append(nb.id)) break;  // full: keep closest-first set
                }
            }
            for (const auto& nb : sel) {
                std::lock_guard<std::mutex> g(stripe(nb.id));
                Links& theirs = links(nb.id, l);
                if (!theirs.append(id)) {
                    // full: re-rank their links from their own viewpoint
                    decode_centered(nb.id, nbf.data());
                    rel.clear();
                    int c = theirs.count.load(std::memory_order_relaxed);
                    rel.reserve(c + 1);
                    for (int i = 0; i < c; ++i) {
                        int t = theirs.ids[i].load(std::memory_order_relaxed);
                        rel.push_back({dist(nbf.data(), t), t});
                    }
                    rel.push_back({dist(nbf.data(), id), id});
                    prune(&rel, cap);
                    theirs.rewrite(rel);
                }
            }
            if (!found.empty()) {
                cur = found[0].id;
                d = found[0].dist;
            }
        }
        if (level > max_level_.load(std::memory_order_acquire)) {
            std::lock_guard<std::mutex> g(entry_mu_);
            if (level > max_level_.load(std::memory_order_relaxed)) {
                max_level_.store(level, std::memory_order_release);
                entry_.store(id, std::memory_order_release);
            }
        }
        visited_pool_.put(std::move(vis));
    }

    void search_one(const float* raw_q, int k, int ef, float* out_d, int64_t* out_i) const {
        int entry = entry_.load(std::memory_order_acquire);
        if (entry < 0) {
            for (int i = 0; i < k; ++i) {
                out_d[i] = HUGE_VALF;
                out_i[i] = -1;
            }
            return;
        }
        auto vis = visited_pool_.get();
        std::vector<int> nbuf;
        nbuf.reserve(M0_);
        std::vector<float> qa(dim_);
        precenter(raw_q, qa.data());
        const float* q = qa.data();
        float d = dist(q, entry);
        // clamp as in link_node: (entry, max_level) is not one atomic pair
        int top = std::min(max_level_.load(std::memory_order_acquire), levels_[entry]);
        int cur = greedy_descend(q, top, 0, entry, &d, nbuf);
        auto found = search_layer(q, cur, d, std::max(ef, k), 0, *vis, nbuf);
        int n = std::min<int>(k, found.size());
        for (int i = 0; i < n; ++i) {
            out_d[i] = found[i].dist;
            out_i[i] = found[i].id;
        }
        for (int i = n; i < k; ++i) {
            out_d[i] = HUGE_VALF;
            out_i[i] = -1;
        }
        visited_pool_.put(std::move(vis));
    }
};

// ---------------------------------------------------------------- serialization
// On-disk format is unchanged from the pre-parallel engine (vectors of int),
// so graphs saved by older builds load fine.

template <typename T>
void wr(FILE* f, const T& v) { std::fwrite(&v, sizeof(T), 1, f); }
template <typename T>
bool rd(FILE* f, T* v) { return std::fread(v, sizeof(T), 1, f) == 1; }

void wr_links(FILE* f, const Links& l) {
    std::vector<int> v;
    l.read(&v);
    int64_t n = v.size();
    wr(f, n);
    if (n) std::fwrite(v.data(), sizeof(int), n, f);
}
bool rd_links(FILE* f, Links* l, int cap) {
    int64_t n;
    if (!rd(f, &n)) return false;
    if (n > cap) cap = static_cast<int>(n);  // defensive: never truncate
    l->init(cap);
    std::vector<int> v(n);
    if (n && std::fread(v.data(), sizeof(int), n, f) != static_cast<size_t>(n))
        return false;
    for (int64_t i = 0; i < n; ++i) l->ids[i].store(v[i], std::memory_order_relaxed);
    l->count.store(static_cast<int>(n), std::memory_order_release);
    return true;
}

bool HNSW::save(const char* path) const {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    const uint32_t magic = 0x44465448;  // "DFTH"
    wr(f, magic);
    wr(f, dim_); wr(f, M_); wr(f, M0_); wr(f, efc_);
    int entry = entry_.load(std::memory_order_acquire);
    int max_level = max_level_.load(std::memory_order_acquire);
    wr(f, entry); wr(f, max_level); wr(f, ml_);
    int64_t n = size();
    wr(f, n);
    std::fwrite(vmin_.data(), sizeof(float), dim_, f);
    std::fwrite(step_.data(), sizeof(float), dim_, f);
    if (n) {
        std::fwrite(codes_.data(), 1, codes_.size(), f);
        std::fwrite(levels_.data(), sizeof(int), n, f);
    }
    for (int64_t i = 0; i < n; ++i) {
        wr_links(f, *links0_[i]);
        int32_t nl = upper_[i]->size();
        wr(f, nl);
        for (const auto& lv : *upper_[i]) wr_links(f, lv);
    }
    std::fclose(f);
    return true;
}

HNSW* HNSW::load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    uint32_t magic;
    int dim, M, M0, efc, entry, max_level;
    float ml;
    int64_t n;
    if (!rd(f, &magic) || magic != 0x44465448 || !rd(f, &dim) || !rd(f, &M) ||
        !rd(f, &M0) || !rd(f, &efc) || !rd(f, &entry) || !rd(f, &max_level) ||
        !rd(f, &ml) || !rd(f, &n)) {
        std::fclose(f);
        return nullptr;
    }
    HNSW* h = new HNSW(dim, M, efc, 0);
    h->M0_ = M0;
    h->entry_.store(entry, std::memory_order_release);
    h->max_level_.store(max_level, std::memory_order_release);
    h->ml_ = ml;
    bool ok = std::fread(h->vmin_.data(), sizeof(float), dim, f) == static_cast<size_t>(dim)
           && std::fread(h->step_.data(), sizeof(float), dim, f) == static_cast<size_t>(dim);
    h->codes_.resize(static_cast<size_t>(n) * dim);
    h->levels_.resize(n);
    if (ok && n) {
        ok = std::fread(h->codes_.data(), 1, h->codes_.size(), f) == h->codes_.size()
          && std::fread(h->levels_.data(), sizeof(int), n, f) == static_cast<size_t>(n);
    }
    h->links0_.resize(n);
    h->upper_.resize(n);
    for (int64_t i = 0; ok && i < n; ++i) {
        h->links0_[i].reset(new Links());
        ok = rd_links(f, h->links0_[i].get(), M0);
        int32_t nl = 0;
        ok = ok && rd(f, &nl);
        if (ok) {
            h->upper_[i].reset(new std::vector<Links>(nl));
            for (int32_t l = 0; ok && l < nl; ++l)
                ok = rd_links(f, &(*h->upper_[i])[l], M);
        }
    }
    std::fclose(f);
    if (!ok) {
        delete h;
        return nullptr;
    }
    return h;
}

}  // namespace

// ---------------------------------------------------------------------- C API

extern "C" {

void* dft_hnsw_create(int dim, int M, int ef_construction, unsigned seed) {
    return new HNSW(dim, M, ef_construction, seed);
}
void dft_hnsw_free(void* h) { delete static_cast<HNSW*>(h); }
void dft_hnsw_set_codec(void* h, const float* vmin, const float* step) {
    static_cast<HNSW*>(h)->set_codec(vmin, step);
}
void dft_hnsw_set_threads(void* h, int n) { static_cast<HNSW*>(h)->set_threads(n); }
void dft_hnsw_add(void* h, int n, const uint8_t* codes) {
    static_cast<HNSW*>(h)->add_batch(n, codes);
}
int dft_hnsw_size(void* h) { return static_cast<HNSW*>(h)->size(); }
void dft_hnsw_search(void* h, int nq, const float* q, int k, int ef,
                     float* out_d, int64_t* out_i) {
    static_cast<HNSW*>(h)->search(nq, q, k, ef, out_d, out_i);
}
int dft_hnsw_save(void* h, const char* path) {
    return static_cast<HNSW*>(h)->save(path) ? 1 : 0;
}
void* dft_hnsw_load(const char* path) { return HNSW::load(path); }

}  // extern "C"
