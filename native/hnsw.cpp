// HNSW graph index over SQ8-encoded vectors — the host-side native engine
// behind the `hnswsq` builder (reference analog: faiss IndexHNSWSQ,
// distributed_faiss/index.py:51-60). Graph traversal is pointer-chasing and
// TPU-hostile, so this one index family runs on the host CPU; everything
// else in the framework is XLA/Pallas.
//
// Clean-room implementation of the HNSW algorithm (Malkov & Yashunin):
// geometric level assignment, greedy descent through upper layers, best-first
// ef-bounded search on layer 0, bidirectional linking with closest-first
// pruning. Distances are asymmetric: fp32 query vs uint8 codes dequantized
// on the fly (d = sum_i (q_i - (vmin_i + c_i * step_i))^2, L2 only — the
// reference asserts L2 for hnswsq too).
//
// C API at the bottom (ctypes-consumed by models/hnsw.py).
//
// Thread-safety: search() reuses a shared visited-epoch scratch, so
// concurrent searches on ONE graph are NOT safe; the serving engine already
// serializes per-index device/search calls via its index_lock (the same
// discipline the reference applies to FAISS, index.py:246-252). Distinct
// HNSW instances are independent.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <queue>
#include <random>
#include <vector>

namespace {

struct Neighbor {
    float dist;
    int id;
};
struct NearCmp {  // min-heap by distance
    bool operator()(const Neighbor& a, const Neighbor& b) const { return a.dist > b.dist; }
};
struct FarCmp {  // max-heap by distance
    bool operator()(const Neighbor& a, const Neighbor& b) const { return a.dist < b.dist; }
};

class HNSW {
  public:
    HNSW(int dim, int M, int ef_construction, unsigned seed)
        : dim_(dim), M_(M), M0_(2 * M), efc_(ef_construction), rng_(seed),
          ml_(1.0f / std::log(static_cast<float>(M))), entry_(-1), max_level_(-1) {
        vmin_.assign(dim, 0.f);
        step_.assign(dim, 1.f / 255.f);
    }

    void set_codec(const float* vmin, const float* step) {
        std::copy(vmin, vmin + dim_, vmin_.begin());
        std::copy(step, step + dim_, step_.begin());
    }

    int size() const { return static_cast<int>(levels_.size()); }

    void add_batch(int n, const uint8_t* codes) {
        for (int i = 0; i < n; ++i) insert(codes + static_cast<size_t>(i) * dim_);
    }

    void search(int nq, const float* q, int k, int ef,
                float* out_d, int64_t* out_i) const {
        for (int i = 0; i < nq; ++i) {
            search_one(q + static_cast<size_t>(i) * dim_, k, ef,
                       out_d + static_cast<size_t>(i) * k,
                       out_i + static_cast<size_t>(i) * k);
        }
    }

    bool save(const char* path) const;
    static HNSW* load(const char* path);

  private:
    int dim_, M_, M0_, efc_;
    std::mt19937 rng_;
    float ml_;
    int entry_, max_level_;
    std::vector<float> vmin_, step_;
    std::vector<uint8_t> codes_;           // n * dim
    std::vector<int> levels_;              // per node
    std::vector<std::vector<int>> links0_; // layer-0 adjacency per node
    // upper layers: upper_[node] has (level) adjacency lists, 1-indexed by
    // layer (upper_[v][l-1] = neighbors of v at layer l); only nodes with
    // level >= 1 have entries
    std::vector<std::vector<std::vector<int>>> upper_;
    mutable std::vector<uint32_t> visited_;
    mutable uint32_t epoch_ = 0;

    float dist(const float* q, int b) const {
        const uint8_t* c = codes_.data() + static_cast<size_t>(b) * dim_;
        float acc = 0.f;
        for (int i = 0; i < dim_; ++i) {
            float v = vmin_[i] + c[i] * step_[i];
            float t = q[i] - v;
            acc += t * t;
        }
        return acc;
    }

    void decode(int b, float* out) const {
        const uint8_t* c = codes_.data() + static_cast<size_t>(b) * dim_;
        for (int i = 0; i < dim_; ++i) out[i] = vmin_[i] + c[i] * step_[i];
    }

    const std::vector<int>& neighbors(int v, int level) const {
        return level == 0 ? links0_[v] : upper_[v][level - 1];
    }
    std::vector<int>& neighbors(int v, int level) {
        return level == 0 ? links0_[v] : upper_[v][level - 1];
    }

    // best-first search at one layer; returns up to ef closest as a sorted
    // (ascending) vector
    std::vector<Neighbor> search_layer(const float* q, int entry, float entry_d,
                                       int ef, int level) const {
        if (++epoch_ == 0) {  // wrapped: clear and restart
            std::fill(visited_.begin(), visited_.end(), 0u);
            epoch_ = 1;
        }
        if (visited_.size() < levels_.size()) visited_.resize(levels_.size(), 0u);

        std::priority_queue<Neighbor, std::vector<Neighbor>, NearCmp> cand;
        std::priority_queue<Neighbor, std::vector<Neighbor>, FarCmp> result;
        cand.push({entry_d, entry});
        result.push({entry_d, entry});
        visited_[entry] = epoch_;

        while (!cand.empty()) {
            Neighbor cur = cand.top();
            if (cur.dist > result.top().dist && static_cast<int>(result.size()) >= ef)
                break;
            cand.pop();
            for (int nb : neighbors(cur.id, level)) {
                if (visited_[nb] == epoch_) continue;
                visited_[nb] = epoch_;
                float d = dist(q, nb);
                if (static_cast<int>(result.size()) < ef || d < result.top().dist) {
                    cand.push({d, nb});
                    result.push({d, nb});
                    if (static_cast<int>(result.size()) > ef) result.pop();
                }
            }
        }
        std::vector<Neighbor> out(result.size());
        for (size_t i = result.size(); i-- > 0;) {
            out[i] = result.top();
            result.pop();
        }
        return out;
    }

    int greedy_descend(const float* q, int from_level, int to_level,
                       int entry, float* d_io) const {
        int cur = entry;
        float cur_d = *d_io;
        for (int l = from_level; l > to_level; --l) {
            bool improved = true;
            while (improved) {
                improved = false;
                for (int nb : neighbors(cur, l)) {
                    float d = dist(q, nb);
                    if (d < cur_d) {
                        cur_d = d;
                        cur = nb;
                        improved = true;
                    }
                }
            }
        }
        *d_io = cur_d;
        return cur;
    }

    // closest-first pruning to cap (simple variant of the paper's heuristic)
    void prune(std::vector<Neighbor>& cands, int cap) const {
        std::sort(cands.begin(), cands.end(),
                  [](const Neighbor& a, const Neighbor& b) { return a.dist < b.dist; });
        if (static_cast<int>(cands.size()) > cap) cands.resize(cap);
    }

    void insert(const uint8_t* code) {
        int id = size();
        codes_.insert(codes_.end(), code, code + dim_);
        std::uniform_real_distribution<float> uni(1e-9f, 1.0f);
        int level = static_cast<int>(-std::log(uni(rng_)) * ml_);
        levels_.push_back(level);
        links0_.emplace_back();
        upper_.emplace_back();
        upper_.back().resize(level > 0 ? level : 0);

        std::vector<float> qf(dim_);
        decode(id, qf.data());
        const float* q = qf.data();

        if (entry_ < 0) {
            entry_ = id;
            max_level_ = level;
            return;
        }

        float d = dist(q, entry_);
        int cur = greedy_descend(q, max_level_, std::min(level, max_level_), entry_, &d);

        for (int l = std::min(level, max_level_); l >= 0; --l) {
            auto found = search_layer(q, cur, d, efc_, l);
            int cap = (l == 0) ? M0_ : M_;
            std::vector<Neighbor> sel(found);
            prune(sel, M_);
            auto& my = neighbors(id, l);
            for (const auto& nb : sel) {
                my.push_back(nb.id);
                auto& theirs = neighbors(nb.id, l);
                theirs.push_back(id);
                if (static_cast<int>(theirs.size()) > cap) {
                    // re-rank their links from their own viewpoint
                    std::vector<float> nbf(dim_);
                    decode(nb.id, nbf.data());
                    std::vector<Neighbor> rel;
                    rel.reserve(theirs.size());
                    for (int t : theirs) rel.push_back({dist(nbf.data(), t), t});
                    prune(rel, cap);
                    theirs.clear();
                    for (const auto& r : rel) theirs.push_back(r.id);
                }
            }
            if (!found.empty()) {
                cur = found[0].id;
                d = found[0].dist;
            }
        }
        if (level > max_level_) {
            max_level_ = level;
            entry_ = id;
        }
    }

    void search_one(const float* q, int k, int ef, float* out_d, int64_t* out_i) const {
        if (entry_ < 0) {
            for (int i = 0; i < k; ++i) {
                out_d[i] = HUGE_VALF;
                out_i[i] = -1;
            }
            return;
        }
        float d = dist(q, entry_);
        int cur = greedy_descend(q, max_level_, 0, entry_, &d);
        auto found = search_layer(q, cur, d, std::max(ef, k), 0);
        int n = std::min<int>(k, found.size());
        for (int i = 0; i < n; ++i) {
            out_d[i] = found[i].dist;
            out_i[i] = found[i].id;
        }
        for (int i = n; i < k; ++i) {
            out_d[i] = HUGE_VALF;
            out_i[i] = -1;
        }
    }
};

// ---------------------------------------------------------------- serialization

template <typename T>
void wr(FILE* f, const T& v) { std::fwrite(&v, sizeof(T), 1, f); }
template <typename T>
bool rd(FILE* f, T* v) { return std::fread(v, sizeof(T), 1, f) == 1; }

void wr_vec_i(FILE* f, const std::vector<int>& v) {
    int64_t n = v.size();
    wr(f, n);
    if (n) std::fwrite(v.data(), sizeof(int), n, f);
}
bool rd_vec_i(FILE* f, std::vector<int>* v) {
    int64_t n;
    if (!rd(f, &n)) return false;
    v->resize(n);
    return n == 0 || std::fread(v->data(), sizeof(int), n, f) == static_cast<size_t>(n);
}

bool HNSW::save(const char* path) const {
    FILE* f = std::fopen(path, "wb");
    if (!f) return false;
    const uint32_t magic = 0x44465448;  // "DFTH"
    wr(f, magic);
    wr(f, dim_); wr(f, M_); wr(f, M0_); wr(f, efc_);
    wr(f, entry_); wr(f, max_level_); wr(f, ml_);
    int64_t n = size();
    wr(f, n);
    std::fwrite(vmin_.data(), sizeof(float), dim_, f);
    std::fwrite(step_.data(), sizeof(float), dim_, f);
    if (n) {
        std::fwrite(codes_.data(), 1, codes_.size(), f);
        std::fwrite(levels_.data(), sizeof(int), n, f);
    }
    for (int64_t i = 0; i < n; ++i) {
        wr_vec_i(f, links0_[i]);
        int32_t nl = upper_[i].size();
        wr(f, nl);
        for (const auto& lv : upper_[i]) wr_vec_i(f, lv);
    }
    std::fclose(f);
    return true;
}

HNSW* HNSW::load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    uint32_t magic;
    int dim, M, M0, efc, entry, max_level;
    float ml;
    int64_t n;
    if (!rd(f, &magic) || magic != 0x44465448 || !rd(f, &dim) || !rd(f, &M) ||
        !rd(f, &M0) || !rd(f, &efc) || !rd(f, &entry) || !rd(f, &max_level) ||
        !rd(f, &ml) || !rd(f, &n)) {
        std::fclose(f);
        return nullptr;
    }
    HNSW* h = new HNSW(dim, M, efc, 0);
    h->M0_ = M0;
    h->entry_ = entry;
    h->max_level_ = max_level;
    h->ml_ = ml;
    bool ok = std::fread(h->vmin_.data(), sizeof(float), dim, f) == static_cast<size_t>(dim)
           && std::fread(h->step_.data(), sizeof(float), dim, f) == static_cast<size_t>(dim);
    h->codes_.resize(static_cast<size_t>(n) * dim);
    h->levels_.resize(n);
    if (ok && n) {
        ok = std::fread(h->codes_.data(), 1, h->codes_.size(), f) == h->codes_.size()
          && std::fread(h->levels_.data(), sizeof(int), n, f) == static_cast<size_t>(n);
    }
    h->links0_.resize(n);
    h->upper_.resize(n);
    for (int64_t i = 0; ok && i < n; ++i) {
        ok = rd_vec_i(f, &h->links0_[i]);
        int32_t nl = 0;
        ok = ok && rd(f, &nl);
        if (ok) {
            h->upper_[i].resize(nl);
            for (int32_t l = 0; ok && l < nl; ++l) ok = rd_vec_i(f, &h->upper_[i][l]);
        }
    }
    std::fclose(f);
    if (!ok) {
        delete h;
        return nullptr;
    }
    return h;
}

}  // namespace

// ---------------------------------------------------------------------- C API

extern "C" {

void* dft_hnsw_create(int dim, int M, int ef_construction, unsigned seed) {
    return new HNSW(dim, M, ef_construction, seed);
}
void dft_hnsw_free(void* h) { delete static_cast<HNSW*>(h); }
void dft_hnsw_set_codec(void* h, const float* vmin, const float* step) {
    static_cast<HNSW*>(h)->set_codec(vmin, step);
}
void dft_hnsw_add(void* h, int n, const uint8_t* codes) {
    static_cast<HNSW*>(h)->add_batch(n, codes);
}
int dft_hnsw_size(void* h) { return static_cast<HNSW*>(h)->size(); }
void dft_hnsw_search(void* h, int nq, const float* q, int k, int ef,
                     float* out_d, int64_t* out_i) {
    static_cast<HNSW*>(h)->search(nq, q, k, ef, out_d, out_i);
}
int dft_hnsw_save(void* h, const char* path) {
    return static_cast<HNSW*>(h)->save(path) ? 1 : 0;
}
void* dft_hnsw_load(const char* path) { return HNSW::load(path); }

}  // extern "C"
