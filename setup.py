#!/usr/bin/env python3
"""Packaging (parity: reference setup.py; deps swapped for the TPU stack)."""

from setuptools import find_packages, setup

with open("README.md") as f:
    readme = f.read()

setup(
    name="distributed_faiss_tpu",
    version="0.1.0",
    description="TPU-native distributed approximate nearest-neighbor search",
    long_description=readme,
    long_description_content_type="text/markdown",
    license="MIT",
    python_requires=">=3.10",
    packages=find_packages(exclude=("tests", "scripts")),
    install_requires=[
        "jax",
        "numpy",
    ],
    extras_require={
        "slurm": ["submitit>=1.1.5"],
        "dev": ["pytest"],
    },
)
