"""Replication acceptance gate (ISSUE 8): SIGKILL any rank of an R=2
cluster under a live mux query storm — search results must stay
byte-identical to the healthy cluster's golden answer (no missing shard
rows), every acknowledged write must survive, and the killed rank must
rejoin via MANIFEST shard transfer and serve again WITHOUT a client
restart."""

import os
import socket
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel.client import IndexClient
from distributed_faiss_tpu.testing.chaos import QueryStorm, ServerHarness
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg
from distributed_faiss_tpu.utils.state import IndexState

pytestmark = [pytest.mark.replication, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", 16)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 50)
    return IndexCfg(**kw)


def wait_drained(client, index_id, n, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (client.get_state(index_id) == IndexState.TRAINED
                and client.get_buffer_depth(index_id) == 0
                and client.get_ntotal(index_id) >= n):
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster never drained to {n} indexed rows")


# R=2 with write_quorum=1: the sane R=2 deployment — a single rank death
# neither stalls writes (the surviving replica acks, the dead one is
# recorded for repair) nor costs reads (failover to the survivor).
# Majority quorum of 2 would be 2, i.e. any death blocks the dead
# rank's group; docs/OPERATIONS.md spells out the trade.
def repl_cfg():
    return ReplicationCfg(replication=2, write_quorum=1)


@pytest.mark.parametrize("victim_pos", [0, 1])
def test_sigkill_any_rank_under_storm_stays_golden(tmp_path, victim_pos):
    """The gate, parametrized over a victim in each replica group:

    1. healthy R=2 cluster (4 ranks, 2 groups), ingest + golden search;
    2. mux query storm from 4 threads; SIGKILL the victim mid-storm;
    3. keep ingesting through the outage (acks at quorum 1, the missed
       replica recorded as under-replicated);
    4. every storm result — before, during, and after the kill — must be
       byte-identical to golden, with zero search errors;
    5. restart the victim EMPTY, stream the shard back from its group
       peer (MANIFEST transfer), pin reads onto it, and get golden again;
    6. zero acked-write loss across the whole episode.
    """
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(4, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(disc, replication_cfg=repl_cfg())
        client.create_index("gidx", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((300, 16)).astype(np.float32)

        acked = set()
        for s in range(0, 300, 50):
            ids = [(i,) for i in range(s, s + 50)]
            client.add_index_data("gidx", x[s:s + 50], ids)
            acked.update(i for (i,) in ids)
        wait_drained(client, "gidx", 300)
        client.save_index("gidx")

        q = np.ascontiguousarray(x[:16])
        g_scores, g_meta = client.search(q, 5, "gidx")

        group = client.membership.group_of(victim_pos)
        victim_rank = client.sub_indexes[victim_pos].port - h.base_port
        survivor_pos = next(p for p in client.membership.replicas(group)
                            if p != victim_pos)
        # rows ingested DURING the storm sit far from every query, so the
        # golden top-5 is invariant under the live ingest
        far = (rng.standard_normal((200, 16)) + 50.0).astype(np.float32)

        with QueryStorm(client, "gidx", q, 5, threads=4) as storm:
            time.sleep(0.7)  # storm hits the healthy cluster first
            h.kill(victim_rank)
            time.sleep(1.5)  # storm keeps running against the outage
        results, errors = storm.stop()
        # ingest through the outage (after the storm window: a rank
        # draining its buffer is legitimately in ADD and rejects
        # searches — an engine contract, not a replication gap): every
        # batch still acks at quorum 1 on the surviving replicas
        for s in range(0, 200, 50):
            ids = [(300 + s + i,) for i in range(50)]
            client.add_index_data("gidx", far[s:s + 50], ids)
            acked.update(i for (i,) in ids)

        assert errors == [], f"storm saw search errors: {errors[:3]}"
        assert len(results) >= 10, "storm produced too few samples"
        for scores, meta in results:
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta
        # read failover really happened and was pinned
        assert client.counters["failovers"] >= 1
        # the dead replica's missed writes were recorded for repair
        assert client.counters["under_replicated"] >= 1
        assert client.get_replication_stats()["repair"]["pending"] >= 1
        # a repair pass against the still-dead rank keeps records queued
        out = client.repair_under_replicated()
        assert out["repaired"] == 0 and out["still_pending"] >= 1

        # ---- rejoin: restart EMPTY (no --load-index), stream the shard
        h.restart(victim_rank, load_index=False,
                  extra_env={"DFT_SHARD_GROUP": str(group)})
        h.wait_port(victim_rank)
        deadline = time.time() + 60
        while True:
            try:
                out = client.resync_rank("gidx", victim_pos,
                                         source_pos=survivor_pos)
                break
            except Exception:
                assert time.time() < deadline, "victim never resynced"
                time.sleep(0.3)
        assert out["shard_group"] == group
        assert out["ntotal"] + out["buffered"] > 0
        # the env registration survived into the restarted process
        assert client.sub_indexes[victim_pos].generic_fun(
            "get_shard_group") == group
        # the transfer committed a MANIFEST generation on the victim's disk
        victim_dir = os.path.join(storage, "gidx", str(victim_rank))
        assert serialization.list_generations(victim_dir)
        # the transferred snapshot already covers the under-replicated
        # batches (the source replica acked them), so the records are
        # obsolete: drain instead of re-sending duplicates (runbook step)
        client.repair_queue.drain()

        deadline = time.time() + 120
        while client.get_buffer_depth("gidx") > 0:
            assert time.time() < deadline, "rejoined rank never drained"
            time.sleep(0.2)

        # pin reads onto the REJOINED replica: it must serve golden too,
        # on the same client, without any restart
        with client._stats_lock:
            client._preferred[group] = victim_pos
        scores2, meta2 = client.search(q, 5, "gidx")
        np.testing.assert_array_equal(scores2, g_scores)
        assert meta2 == g_meta
        served = client.sub_indexes[victim_pos].generic_fun(
            "get_perf_stats")
        assert served.get("search", {}).get("count", 0) >= 1, (
            "pinned search was not served by the rejoined rank")

        # zero acked-write loss across kill + outage + rejoin
        present = set(client.get_ids("gidx"))
        lost = acked - present
        assert not lost, f"{len(lost)} acked ids lost: {sorted(lost)[:10]}"
        client.close()


def test_quorum_majority_blocks_writes_to_dead_group(tmp_path):
    """The OTHER side of the quorum trade, live: with the default
    majority quorum (2 of 2), a dead replica makes its group unwritable
    — the partial placement raises QuorumError instead of silently
    acking or duplicating rows across groups — while the OTHER group
    keeps acking normally."""
    from distributed_faiss_tpu.parallel.client import QuorumError

    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(2, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(
            disc, replication_cfg=ReplicationCfg(replication=2))
        assert client.quorum == 2
        client.create_index("qidx", flat_cfg())
        rng = np.random.default_rng(1)
        x = rng.standard_normal((100, 16)).astype(np.float32)
        client.add_index_data("qidx", x[:50], [(i,) for i in range(50)])

        victim_rank = client.sub_indexes[0].port - h.base_port
        h.kill(victim_rank)
        with pytest.raises(QuorumError):
            client.add_index_data("qidx", x[50:], [(i,) for i in range(50, 100)])
        assert client.counters["quorum_failures"] == 1
        assert len(client.repair_queue) >= 1
        client.close()
