"""Extended factory grammar: OPQ/PCA pre-transforms, HNSW specs, RFlat.

The reference forwards factory strings verbatim to faiss.index_factory
(distributed_faiss/index.py:396), so the whole FAISS grammar is reachable
from its cfg files; round 1 covered only the specs its configs actually
use. These pin the wider grammar.
"""

import numpy as np
import pytest

from distributed_faiss_tpu.models import factory
from distributed_faiss_tpu.models.flat import FlatIndex
from distributed_faiss_tpu.models.ivf import IVFFlatIndex, IVFPQIndex
from distributed_faiss_tpu.models.pretransform import PreTransformIndex
from distributed_faiss_tpu.utils.config import IndexCfg


def build(spec, dim=64, metric="l2", **extra):
    return factory.build_index(IndexCfg(faiss_factory=spec, dim=dim, metric=metric, **extra))


def corpus(rng, n=2000, d=64):
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((8, d)).astype(np.float32)
    return x, q


def exact_ids(x, q, k, metric="l2"):
    idx = FlatIndex(x.shape[1], metric)
    idx.add(x)
    return idx.search(q, k)[1]


def recall(ids, gt):
    k = gt.shape[1]
    return np.mean([len(set(ids[i]) & set(gt[i])) / k for i in range(len(gt))])


# ---- parsing shapes ------------------------------------------------------


def test_opq_prefix_builds_pretransform():
    idx = build("OPQ8,IVF16,PQ8")
    assert isinstance(idx, PreTransformIndex)
    assert isinstance(idx.inner, IVFPQIndex)
    assert idx.opq_m == 8 and idx.inner.m == 8 and idx.inner.nlist == 16


def test_opq_dim_reduction_spec():
    idx = build("OPQ8_32,IVF16,PQ8")
    assert idx.dim == 64 and idx.inner.dim == 32


def test_pca_prefix():
    idx = build("PCA32,IVF16,Flat")
    assert isinstance(idx, PreTransformIndex) and idx.pca
    assert isinstance(idx.inner, IVFFlatIndex) and idx.inner.dim == 32


def test_pcar_alias():
    assert isinstance(build("PCAR32,Flat"), PreTransformIndex)


def test_rflat_suffix_sets_refine():
    idx = build("IVF16,PQ8,RFlat")
    assert isinstance(idx, IVFPQIndex) and idx.refine_k_factor == 8
    idx = build("IVF16,PQ8,Refine(Flat)", refine_k_factor=4)
    assert idx.refine_k_factor == 4


def test_rflat_on_exact_inner_warns_not_raises():
    idx = build("IVF16,Flat,RFlat")
    assert isinstance(idx, IVFFlatIndex) and idx.refine_k_factor == 0


def test_rflat_on_sq8_wires_refine_and_lifts_recall(rng):
    """FAISS 'IVF<n>,SQ8,RFlat' exactly reranks the sq8 shortlist; ours
    must too (the round-2 review caught this silently dropping refine).

    Outlier rows inflate the per-dim sq8 ranges so quantization (not
    probing — nprobe = nlist) limits the plain config's recall, which the
    exact rerank must then recover."""
    x, q = corpus(rng, n=4000)
    x[:64] *= 50.0  # blow up the trained vmin/span -> coarse sq8 steps
    gt = exact_ids(x, q, 10)

    def run(spec):
        idx = build(spec, refine_k_factor=8)
        idx.train(x[:2000])
        idx.add(x)
        idx.set_nprobe(16)
        return recall(idx.search(q, 10)[1], gt), idx

    rec_plain, plain = run("IVF16,SQ8")
    rec_refined, refined = run("IVF16,SQ8,RFlat")
    assert plain.refine_k_factor == 0 and refined.refine_k_factor == 8
    assert rec_refined >= rec_plain - 1e-9
    assert rec_plain < 0.9, rec_plain  # the setup genuinely stresses sq8
    assert rec_refined >= 0.95, (rec_plain, rec_refined)


def test_ivf_sq8_refine_save_load_roundtrip(rng, tmp_path):
    from distributed_faiss_tpu.utils import serialization

    x, q = corpus(rng)
    idx = build("IVF16,SQ8,RFlat")
    idx.train(x[:1000])
    idx.add(x)
    idx.set_nprobe(8)
    _, ids = idx.search(q, 5)
    path = str(tmp_path / "r.npz")
    serialization.save_state(path, idx.state_dict())
    idx2 = factory.index_from_state_dict(serialization.load_state(path))
    assert idx2.refine_k_factor == idx.refine_k_factor == 8
    idx2.set_nprobe(8)
    np.testing.assert_array_equal(ids, idx2.search(q, 5)[1])


def test_knnlm_builder_opq_extra(rng):
    """builder-path OPQ: IndexCfg(index_builder_type='knnlm', opq=True)."""
    from distributed_faiss_tpu.models.ivf import IVFPQIndex

    cfg = IndexCfg(index_builder_type="knnlm", dim=32, metric="l2",
                   centroids=8, code_size=8, opq=True, kmeans_iters=4)
    idx = factory.build_index(cfg)
    assert isinstance(idx, PreTransformIndex)
    assert isinstance(idx.inner, IVFPQIndex) and idx.opq_m == 8
    assert cfg.extra.get("opq") is True  # caller's cfg not mutated

    x = rng.standard_normal((1500, 32)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    _, ids = idx.search(x[:4] + 0.001, 5)
    assert all(ids[i][0] == i for i in range(4))


def test_pca_dout_exceeding_dim_rejected_at_parse():
    with pytest.raises(RuntimeError, match="> input dim"):
        build("PCA128,Flat")
    with pytest.raises(RuntimeError, match="> input dim"):
        build("OPQ8_128,IVF16,PQ8")


def test_hnsw_specs_build():
    for spec in ("HNSW32", "HNSW32,SQ8", "HNSW32,Flat"):
        idx = build(spec)
        assert idx is not None


def test_hnsw_requires_l2():
    with pytest.raises(RuntimeError, match="l2"):
        build("HNSW32", metric="dot")


def test_unknown_specs_still_raise():
    for spec in ("IVF16,XX", "Junk", "OPQ8,Junk", "HNSW32,PQ8"):
        with pytest.raises(RuntimeError):
            build(spec)


# ---- end-to-end behavior -------------------------------------------------


@pytest.mark.slow  # ~10s; OPQ math covered by the rotation-reconstruction test
def test_opq_end_to_end_recall(rng):
    x, q = corpus(rng)
    idx = build("OPQ8,IVF4,PQ8,RFlat")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    _, ids = idx.search(q, 10)
    assert recall(ids, exact_ids(x, q, 10)) >= 0.8


def test_opq_rotation_beats_or_matches_plain_pq_reconstruction(rng):
    """The point of OPQ: lower PQ reconstruction error than unrotated PQ on
    correlated data."""
    from distributed_faiss_tpu.ops import opq, pq
    import jax.numpy as jnp

    # correlated dims (random covariance) — where rotation pays off
    d, n = 32, 4000
    a = rng.standard_normal((d, d)).astype(np.float32)
    x = (rng.standard_normal((n, d)).astype(np.float32) @ a)

    cb = pq.pq_train(x, 4, iters=8)
    rec_plain = np.asarray(pq.pq_decode(pq.pq_encode(x, cb), cb))
    err_plain = np.mean((x - rec_plain) ** 2)

    r, cb_r = opq.opq_train(x, 4, opq_iters=6, pq_iters=8)
    xr = x @ np.asarray(r)
    rec_rot = np.asarray(pq.pq_decode(pq.pq_encode(jnp.asarray(xr), cb_r), cb_r))
    err_opq = np.mean((xr - rec_rot) ** 2)  # orthogonal: same-norm space
    assert err_opq <= err_plain * 1.02, (err_opq, err_plain)


def test_pca_end_to_end(rng):
    # correlated data: top-32 principal axes carry most of the variance
    # (isotropic gaussians have no low-dim structure for PCA to keep)
    a = rng.standard_normal((16, 64)).astype(np.float32)
    x = rng.standard_normal((2000, 16)).astype(np.float32) @ a
    x += 0.05 * rng.standard_normal(x.shape).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32) @ a
    idx = build("PCA32,Flat")
    idx.train(x)
    idx.add(x)
    _, ids = idx.search(q, 10)
    assert recall(ids, exact_ids(x, q, 10)) >= 0.8
    rec = idx.reconstruct_batch(np.arange(4))
    assert rec.shape == (4, 64)


def test_pretransform_save_load_roundtrip(rng, tmp_path):
    from distributed_faiss_tpu.utils import serialization

    x, q = corpus(rng)
    idx = build("OPQ8,IVF4,PQ8")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    _, ids = idx.search(q, 5)

    path = str(tmp_path / "pt.npz")
    serialization.save_state(path, idx.state_dict())
    idx2 = factory.index_from_state_dict(serialization.load_state(path))
    assert isinstance(idx2, PreTransformIndex)
    idx2.set_nprobe(4)
    _, ids2 = idx2.search(q, 5)
    np.testing.assert_array_equal(ids, ids2)
    assert idx2.ntotal == idx.ntotal
