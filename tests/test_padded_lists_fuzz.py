"""Model-based fuzz of the padded inverted-list structures.

PaddedLists (and its mesh-sharded sibling) are the central data-structure
design of the framework (SURVEY §7 "variable-length inverted lists on
TPU"); these tests drive random append schedules against a plain
dict-of-lists model and assert exact equivalence of contents, order, and
bookkeeping through growth reallocation.
"""

import numpy as np
import pytest

from distributed_faiss_tpu.models.base import PaddedLists


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_padded_lists_random_schedule_matches_model(seed):
    rng = np.random.default_rng(seed)
    nlist, d = int(rng.integers(2, 9)), 3
    lists = PaddedLists(nlist, (d,), np.float32, min_cap=4)
    model = {l: [] for l in range(nlist)}
    next_gid = 0

    for _ in range(12):
        n = int(rng.integers(1, 200))
        li = rng.integers(0, nlist, n)
        rows = rng.standard_normal((n, d)).astype(np.float32)
        gids = np.arange(next_gid, next_gid + n, dtype=np.int64)
        next_gid += n
        lists.append(li, rows, gids)
        for j in range(n):
            model[int(li[j])].append((int(gids[j]), rows[j]))

        # full-state equivalence after every batch
        assert lists.ntotal == next_gid
        data = np.asarray(lists.data)
        ids = np.asarray(lists.ids)
        sizes = np.asarray(lists.sizes)
        for l in range(nlist):
            want = model[l]
            assert lists.sizes_host[l] == len(want) == sizes[l]
            got_ids = ids[l, : len(want)]
            got_rows = data[l, : len(want)]
            np.testing.assert_array_equal(got_ids, [g for g, _ in want])
            np.testing.assert_allclose(
                got_rows, np.stack([r for _, r in want]) if want else
                np.zeros((0, d), np.float32), rtol=0, atol=0)
            # padding slots beyond the fill stay at the -1 sentinel
            assert (ids[l, len(want):] == -1).all()


def test_padded_lists_growth_preserves_prefix():
    rng = np.random.default_rng(3)
    lists = PaddedLists(4, (2,), np.float32, min_cap=4)
    first = rng.standard_normal((8, 2)).astype(np.float32)
    lists.append(np.zeros(8, np.int64), first, np.arange(8, dtype=np.int64))
    cap0 = lists.cap
    # force growth of list 0 well past the current capacity
    more = rng.standard_normal((100, 2)).astype(np.float32)
    lists.append(np.zeros(100, np.int64), more, np.arange(8, 108, dtype=np.int64))
    assert lists.cap > cap0
    np.testing.assert_allclose(np.asarray(lists.data)[0, :8], first)
    np.testing.assert_array_equal(np.asarray(lists.ids)[0, :8], np.arange(8))
