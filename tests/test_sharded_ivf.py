"""Sharded-inverted-list IVF tests on the virtual 8-device mesh."""

import numpy as np
import pytest

from distributed_faiss_tpu.models.ivf import IVFFlatIndex
from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex, ShardedPaddedLists, make_mesh


def brute_ids(q, x, k, metric):
    if metric == "dot":
        s = q @ x.T
    else:
        s = -((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return np.argsort(-s, axis=1)[:, :k]


def test_sharded_lists_bookkeeping(rng):
    m = make_mesh()
    lists = ShardedPaddedLists(10, (4,), np.float32, m, min_cap=8)
    li = rng.integers(0, 10, 50).astype(np.int64)
    rows = rng.standard_normal((50, 4)).astype(np.float32)
    lists.append(li, rows, np.arange(50, dtype=np.int64))
    assert lists.ntotal == 50
    np.testing.assert_array_equal(lists.sizes_host, np.bincount(li, minlength=10))
    # every appended row is present exactly once under its list's slot
    data = np.asarray(lists.data)
    ids = np.asarray(lists.ids)
    seen = ids[ids >= 0]
    assert sorted(seen.tolist()) == list(range(50))
    for g in range(50):
        slot = int(lists.slot_of(li[g]))
        row_pos = np.where(ids[slot] == g)[0]
        assert row_pos.size == 1
        np.testing.assert_allclose(data[slot, row_pos[0]], rows[g], rtol=1e-6)


def test_sharded_lists_growth(rng):
    m = make_mesh()
    lists = ShardedPaddedLists(4, (2,), np.float32, m, min_cap=8)
    for batch in range(4):
        li = np.zeros(16, np.int64)  # hammer one list to force growth
        rows = rng.standard_normal((16, 2)).astype(np.float32)
        lists.append(li, rows, np.arange(batch * 16, batch * 16 + 16, dtype=np.int64))
    assert lists.cap >= 64
    ids = np.asarray(lists.ids)
    assert sorted(ids[ids >= 0].tolist()) == list(range(64))


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_sharded_ivf_full_probe_exact(rng, metric):
    """nprobe == nlist: sharded IVF must equal brute force exactly."""
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = rng.standard_normal((6, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 8, metric)
    idx.train(x[:800])
    idx.add(x[:700])
    idx.add(x[700:])
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, metric)
    np.testing.assert_array_equal(I, wi)


def test_sharded_ivf_matches_single_device(rng):
    """Same data, same centroids count: sharded and single-device IVF agree
    at full probe."""
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    sharded = ShardedIVFFlatIndex(16, 8, "l2")
    sharded.train(x)
    sharded.add(x)
    sharded.set_nprobe(8)
    single = IVFFlatIndex(16, 8, "l2")
    single.train(x)
    single.add(x)
    single.set_nprobe(8)
    Ds, Is = sharded.search(q, 10)
    Du, Iu = single.search(q, 10)
    np.testing.assert_array_equal(Is, Iu)
    np.testing.assert_allclose(Ds, Du, rtol=1e-3, atol=1e-3)


def test_sharded_ivf_partial_probe_recall(rng):
    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((10, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 16, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, "l2")
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 10 for i in range(10)])
    assert recall > 0.6


def test_sharded_ivf_state_round_trip(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import index_from_state_dict
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    x = rng.standard_normal((900, 8)).astype(np.float32)
    q = rng.standard_normal((4, 8)).astype(np.float32)
    idx = ShardedIVFFlatIndex(8, 4, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    D0, I0 = idx.search(q, 6)
    p = str(tmp_path / "sivf.npz")
    save_state(p, idx.state_dict())
    # through the registry — the engine/server restore path
    idx2 = index_from_state_dict(load_state(p))
    assert isinstance(idx2, ShardedIVFFlatIndex)
    D1, I1 = idx2.search(q, 6)
    np.testing.assert_array_equal(I0, I1)
    # reconstruct path inherited from IVFFlat host mirrors
    rec = idx2.reconstruct_batch(I1[0][:3])
    np.testing.assert_allclose(rec, x[I1[0][:3]], rtol=1e-5)


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_routed_full_probe_exact(rng, metric):
    """Probe routing at nprobe == nlist: exactly brute force (uniform
    ownership — the bucket never drops)."""
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = rng.standard_normal((6, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 8, metric, probe_routing=True)
    idx.train(x[:800])
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, metric)
    np.testing.assert_array_equal(I, wi)


def test_routed_matches_masked(rng):
    """Routed and masked sharded search agree given identical trained state
    (same probes -> same candidate set)."""
    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((12, 16)).astype(np.float32)
    masked = ShardedIVFFlatIndex(16, 16, "l2")
    masked.train(x)
    masked.add(x)
    masked.set_nprobe(6)
    routed = ShardedIVFFlatIndex(16, 16, "l2", probe_routing=True)
    routed.centroids = masked.centroids
    routed.lists = masked.lists
    routed._host_rows, routed._host_assign = masked._host_rows, masked._host_assign
    routed._n = masked._n
    routed.set_nprobe(6)
    Dm, Im = masked.search(q, 10)
    Dr, Ir = routed.search(q, 10)
    np.testing.assert_array_equal(Im, Ir)
    np.testing.assert_allclose(Dm, Dr, rtol=1e-3, atol=1e-3)


def test_routed_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                   centroids=8, nprobe=4, shard_lists=True, probe_routing=True)
    idx = build_index(cfg)
    assert idx.probe_routing
    x = rng.standard_normal((900, 8)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(x[:4], 5)
    assert (I[:, 0] == np.arange(4)).all()


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_sharded_ivf_pq_matches_single_device(rng, metric):
    """Sharded IVF-PQ == single-device IVF-PQ when sharing trained state."""
    from distributed_faiss_tpu.models.ivf import IVFPQIndex
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((2000, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    single = IVFPQIndex(d, 8, m=m, metric=metric)
    single.train(x)
    single.add(x)
    single.set_nprobe(8)
    sharded = ShardedIVFPQIndex(d, 8, m=m, metric=metric)
    # share the trained coarse+codebooks so rankings must be identical
    sharded.centroids, sharded.codebooks = single.centroids, single.codebooks
    from distributed_faiss_tpu.parallel.mesh import ShardedPaddedLists
    sharded.lists = ShardedPaddedLists(8, (m,), np.uint8, sharded.mesh)
    sharded.add(x)
    sharded.set_nprobe(8)
    Du, Iu = single.search(q, 10)
    Ds, Is = sharded.search(q, 10)
    np.testing.assert_array_equal(Is, Iu)
    np.testing.assert_allclose(Ds, Du, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_routed_pq_matches_masked(rng, metric):
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((2000, d)).astype(np.float32)
    q = rng.standard_normal((9, d)).astype(np.float32)
    masked = ShardedIVFPQIndex(d, 8, m=m, metric=metric)
    masked.train(x)
    masked.add(x)
    masked.set_nprobe(5)
    routed = ShardedIVFPQIndex(d, 8, m=m, metric=metric, probe_routing=True)
    routed.centroids, routed.codebooks = masked.centroids, masked.codebooks
    routed.lists = masked.lists
    routed._host_rows, routed._host_assign = masked._host_rows, masked._host_assign
    routed._n = masked._n
    routed.set_nprobe(5)
    Dm, Im = masked.search(q, 10)
    Dr, Ir = routed.search(q, 10)
    np.testing.assert_array_equal(Im, Ir)
    np.testing.assert_allclose(Dm, Dr, rtol=1e-3, atol=1e-3)


def test_sharded_ivf_pq_lifecycle(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import build_index, index_from_state_dict
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    cfg = IndexCfg(index_builder_type="knnlm", dim=16, metric="l2",
                   centroids=4, nprobe=4, code_size=4, shard_lists=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFPQIndex)
    x = rng.standard_normal((800, 16)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D0, I0 = idx.search(x[:4], 5)
    assert (I0[:, 0] == np.arange(4)).all()
    p = str(tmp_path / "spq.npz")
    save_state(p, idx.state_dict())
    idx2 = index_from_state_dict(load_state(p))
    D1, I1 = idx2.search(x[:4], 5)
    np.testing.assert_array_equal(I0, I1)


def test_ivf_tpu_shard_lists_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                   centroids=4, nprobe=4, shard_lists=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFFlatIndex)
    x = rng.standard_normal((600, 8)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(x[:3], 4)
    assert (I[:, 0] == np.arange(3)).all()
