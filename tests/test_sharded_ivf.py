"""Sharded-inverted-list IVF tests on the virtual 8-device mesh."""

import numpy as np
import pytest

from distributed_faiss_tpu.models.ivf import IVFFlatIndex
from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex, ShardedPaddedLists, make_mesh


def brute_ids(q, x, k, metric):
    if metric == "dot":
        s = q @ x.T
    else:
        s = -((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return np.argsort(-s, axis=1)[:, :k]


def test_sharded_lists_bookkeeping(rng):
    m = make_mesh()
    lists = ShardedPaddedLists(10, (4,), np.float32, m, min_cap=8)
    li = rng.integers(0, 10, 50).astype(np.int64)
    rows = rng.standard_normal((50, 4)).astype(np.float32)
    lists.append(li, rows, np.arange(50, dtype=np.int64))
    assert lists.ntotal == 50
    np.testing.assert_array_equal(lists.sizes_host, np.bincount(li, minlength=10))
    # every appended row is present exactly once under its list's slot
    data = np.asarray(lists.data)
    ids = np.asarray(lists.ids)
    seen = ids[ids >= 0]
    assert sorted(seen.tolist()) == list(range(50))
    for g in range(50):
        slot = int(lists.slot_of(li[g]))
        row_pos = np.where(ids[slot] == g)[0]
        assert row_pos.size == 1
        np.testing.assert_allclose(data[slot, row_pos[0]], rows[g], rtol=1e-6)


def test_sharded_lists_growth(rng):
    m = make_mesh()
    lists = ShardedPaddedLists(4, (2,), np.float32, m, min_cap=8)
    for batch in range(4):
        li = np.zeros(16, np.int64)  # hammer one list to force growth
        rows = rng.standard_normal((16, 2)).astype(np.float32)
        lists.append(li, rows, np.arange(batch * 16, batch * 16 + 16, dtype=np.int64))
    assert lists.cap >= 64
    ids = np.asarray(lists.ids)
    assert sorted(ids[ids >= 0].tolist()) == list(range(64))


def test_sharded_lists_int32_cell_space_guard(rng):
    """nlist_pad * cap past int32 must refuse loudly, not wrap (scatter
    positions and the drop sentinel are int32 flat cell addresses)."""
    m = make_mesh()
    # construction-time guard fires before any device allocation
    with pytest.raises(ValueError, match="int32"):
        ShardedPaddedLists(2**26, (4,), np.float32, m, min_cap=64)
    # growth-time guard: small list count, growth request that would
    # overflow the flat space; raises before the pad allocates
    lists = ShardedPaddedLists(8, (2,), np.float32, m, min_cap=8)
    with pytest.raises(ValueError, match="int32"):
        lists._grow(2**28 + 1)
    assert lists.cap == 8  # untouched by the refused growth
    # a legal append still works after the refusal
    lists.append(np.zeros(4, np.int64), np.ones((4, 2), np.float32),
                 np.arange(4, dtype=np.int64))
    assert lists.ntotal == 4


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_sharded_ivf_full_probe_exact(rng, metric):
    """nprobe == nlist: sharded IVF must equal brute force exactly."""
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = rng.standard_normal((6, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 8, metric)
    idx.train(x[:800])
    idx.add(x[:700])
    idx.add(x[700:])
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, metric)
    np.testing.assert_array_equal(I, wi)


def test_sharded_ivf_matches_single_device(rng):
    """Same data, same centroids count: sharded and single-device IVF agree
    at full probe."""
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    sharded = ShardedIVFFlatIndex(16, 8, "l2")
    sharded.train(x)
    sharded.add(x)
    sharded.set_nprobe(8)
    single = IVFFlatIndex(16, 8, "l2")
    single.train(x)
    single.add(x)
    single.set_nprobe(8)
    Ds, Is = sharded.search(q, 10)
    Du, Iu = single.search(q, 10)
    np.testing.assert_array_equal(Is, Iu)
    np.testing.assert_allclose(Ds, Du, rtol=1e-3, atol=1e-3)


def test_sharded_ivf_partial_probe_recall(rng):
    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((10, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 16, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, "l2")
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 10 for i in range(10)])
    assert recall > 0.6


def test_sharded_ivf_state_round_trip(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import index_from_state_dict
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    x = rng.standard_normal((900, 8)).astype(np.float32)
    q = rng.standard_normal((4, 8)).astype(np.float32)
    idx = ShardedIVFFlatIndex(8, 4, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    D0, I0 = idx.search(q, 6)
    p = str(tmp_path / "sivf.npz")
    save_state(p, idx.state_dict())
    # through the registry — the engine/server restore path
    idx2 = index_from_state_dict(load_state(p))
    assert isinstance(idx2, ShardedIVFFlatIndex)
    D1, I1 = idx2.search(q, 6)
    np.testing.assert_array_equal(I0, I1)
    # reconstruct path inherited from IVFFlat host mirrors
    rec = idx2.reconstruct_batch(I1[0][:3])
    np.testing.assert_allclose(rec, x[I1[0][:3]], rtol=1e-5)


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_routed_full_probe_exact(rng, metric):
    """Probe routing at nprobe == nlist: exactly brute force (uniform
    ownership — the bucket never drops)."""
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = rng.standard_normal((6, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 8, metric, probe_routing=True)
    idx.train(x[:800])
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wi = brute_ids(q, x, 10, metric)
    np.testing.assert_array_equal(I, wi)


def test_routed_matches_masked(rng):
    """Routed and masked sharded search agree given identical trained state
    (same probes -> same candidate set)."""
    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((12, 16)).astype(np.float32)
    masked = ShardedIVFFlatIndex(16, 16, "l2")
    masked.train(x)
    masked.add(x)
    masked.set_nprobe(6)
    routed = ShardedIVFFlatIndex(16, 16, "l2", probe_routing=True)
    routed.centroids = masked.centroids
    routed.lists = masked.lists
    routed._host_pos, routed._host_assign = masked._host_pos, masked._host_assign
    routed._n = masked._n
    routed.set_nprobe(6)
    Dm, Im = masked.search(q, 10)
    Dr, Ir = routed.search(q, 10)
    np.testing.assert_array_equal(Im, Ir)
    np.testing.assert_allclose(Dm, Dr, rtol=1e-3, atol=1e-3)


def test_routed_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                   centroids=8, nprobe=4, shard_lists=True, probe_routing=True)
    idx = build_index(cfg)
    assert idx.probe_routing
    x = rng.standard_normal((900, 8)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(x[:4], 5)
    assert (I[:, 0] == np.arange(4)).all()


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_sharded_ivf_pq_matches_single_device(rng, metric):
    """Sharded IVF-PQ == single-device IVF-PQ when sharing trained state."""
    from distributed_faiss_tpu.models.ivf import IVFPQIndex
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((2000, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    single = IVFPQIndex(d, 8, m=m, metric=metric)
    single.train(x)
    single.add(x)
    single.set_nprobe(8)
    sharded = ShardedIVFPQIndex(d, 8, m=m, metric=metric)
    # share the trained coarse+codebooks so rankings must be identical
    sharded.centroids, sharded.codebooks = single.centroids, single.codebooks
    from distributed_faiss_tpu.parallel.mesh import ShardedPaddedLists
    sharded.lists = ShardedPaddedLists(8, (m,), np.uint8, sharded.mesh)
    sharded.add(x)
    sharded.set_nprobe(8)
    Du, Iu = single.search(q, 10)
    Ds, Is = sharded.search(q, 10)
    np.testing.assert_array_equal(Is, Iu)
    np.testing.assert_allclose(Ds, Du, rtol=1e-3, atol=1e-3)


@pytest.mark.slow  # 65-80s each on the 1-core box (suite time budget, r4)
@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_routed_pq_matches_masked(rng, metric):
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((2000, d)).astype(np.float32)
    q = rng.standard_normal((9, d)).astype(np.float32)
    masked = ShardedIVFPQIndex(d, 8, m=m, metric=metric)
    masked.train(x)
    masked.add(x)
    masked.set_nprobe(5)
    routed = ShardedIVFPQIndex(d, 8, m=m, metric=metric, probe_routing=True)
    routed.centroids, routed.codebooks = masked.centroids, masked.codebooks
    routed.lists = masked.lists
    routed._host_pos, routed._host_assign = masked._host_pos, masked._host_assign
    routed._n = masked._n
    routed.set_nprobe(5)
    Dm, Im = masked.search(q, 10)
    Dr, Ir = routed.search(q, 10)
    np.testing.assert_array_equal(Im, Ir)
    np.testing.assert_allclose(Dm, Dr, rtol=1e-3, atol=1e-3)


def test_sharded_ivf_pq_lifecycle(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import build_index, index_from_state_dict
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    cfg = IndexCfg(index_builder_type="knnlm", dim=16, metric="l2",
                   centroids=4, nprobe=4, code_size=4, shard_lists=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFPQIndex)
    x = rng.standard_normal((800, 16)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D0, I0 = idx.search(x[:4], 5)
    assert (I0[:, 0] == np.arange(4)).all()
    p = str(tmp_path / "spq.npz")
    save_state(p, idx.state_dict())
    idx2 = index_from_state_dict(load_state(p))
    D1, I1 = idx2.search(x[:4], 5)
    np.testing.assert_array_equal(I0, I1)


def test_ivf_tpu_shard_lists_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                   centroids=4, nprobe=4, shard_lists=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFFlatIndex)
    x = rng.standard_normal((600, 8)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(x[:3], 4)
    assert (I[:, 0] == np.arange(3)).all()


# ---------------------------------------------- sharded refine + pallas ADC


@pytest.mark.parametrize("routing", [False, True])
def test_sharded_pq_refine_scores_are_exact(rng, routing):
    """refine_k_factor on the sharded path: returned scores must equal the
    exact metric computed against the (fp16-rounded) raw rows of the
    returned ids — pins that the pre-merge rerank really rescores exactly."""
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((1500, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    idx = ShardedIVFPQIndex(d, 8, m=m, metric="l2", probe_routing=routing,
                            refine_k_factor=8)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 5)
    assert (I >= 0).all()
    x16 = x.astype(np.float16).astype(np.float32)
    for qi in range(q.shape[0]):
        exact = ((q[qi][None, :] - x16[I[qi]]) ** 2).sum(-1)
        np.testing.assert_allclose(D[qi], exact, rtol=1e-3, atol=1e-2)


@pytest.mark.slow  # ~18s pair; exactness covered by refine_scores_are_exact
@pytest.mark.parametrize("routing", [False, True])
def test_sharded_pq_refine_lifts_recall(rng, routing):
    """Same trained state, same nprobe: the refined sharded search must
    reach at least the recall of the unrefined one, and its top-1 on
    self-queries must be the query row itself (exact rescoring pins it)."""
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex, ShardedPaddedLists

    d, m = 32, 4
    x = rng.standard_normal((2000, d)).astype(np.float32)
    q = x[:16] + 1e-5
    base_idx = ShardedIVFPQIndex(d, 16, m=m, metric="l2", probe_routing=routing)
    base_idx.train(x)
    base_idx.add(x)
    base_idx.set_nprobe(8)
    ref = ShardedIVFPQIndex(d, 16, m=m, metric="l2", probe_routing=routing,
                            refine_k_factor=16)
    ref.centroids, ref.codebooks = base_idx.centroids, base_idx.codebooks
    ref.lists = base_idx.lists
    ref.raw_lists = ShardedPaddedLists(16, (d,), np.float16, ref.mesh)
    from distributed_faiss_tpu.models.ivf import clip_f16
    assign = base_idx._host_assign_array()
    ref.raw_lists.append(assign, clip_f16(x), np.arange(x.shape[0], dtype=np.int64))
    ref._host_pos, ref._host_assign = base_idx._host_pos, base_idx._host_assign
    ref._n = base_idx._n
    ref.set_nprobe(8)

    gt = brute_ids(q, x, 10, "l2")
    _, Ib = base_idx.search(q, 10)
    _, Ir = ref.search(q, 10)
    rec_b = np.mean([len(set(Ib[i]) & set(gt[i])) / 10 for i in range(q.shape[0])])
    rec_r = np.mean([len(set(Ir[i]) & set(gt[i])) / 10 for i in range(q.shape[0])])
    assert rec_r >= rec_b - 1e-9, (rec_r, rec_b)
    assert (Ir[:, 0] == np.arange(16)).all()


@pytest.mark.parametrize("routing", [False, True])
@pytest.mark.parametrize("refine", [0, 8])
def test_sharded_pq_pallas_matches_xla(rng, routing, refine):
    """pallas_adc on the sharded path (interpreted off-TPU) must reproduce
    the XLA one-hot path bit-for-bit on ids."""
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex

    d, m = 32, 8
    x = rng.standard_normal((1200, d)).astype(np.float32)
    q = rng.standard_normal((5, d)).astype(np.float32)
    a = ShardedIVFPQIndex(d, 8, m=m, metric="l2", probe_routing=routing,
                          refine_k_factor=refine)
    a.train(x)
    a.add(x)
    a.set_nprobe(4)
    b = ShardedIVFPQIndex(d, 8, m=m, metric="l2", probe_routing=routing,
                          refine_k_factor=refine, use_pallas=True)
    b.centroids, b.codebooks = a.centroids, a.codebooks
    b.lists, b.raw_lists = a.lists, a.raw_lists
    b._host_pos, b._host_assign, b._n = a._host_pos, a._host_assign, a._n
    b.set_nprobe(4)
    Da, Ia = a.search(q, 8)
    Db, Ib = b.search(q, 8)
    assert b._pallas_runtime_ok, "pallas path silently fell back"
    np.testing.assert_array_equal(Ia, Ib)
    np.testing.assert_allclose(Da, Db, rtol=1e-4, atol=1e-4)


def test_sharded_pq_refine_state_round_trip(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import build_index, index_from_state_dict
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFPQIndex
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    cfg = IndexCfg(index_builder_type="knnlm", dim=16, metric="l2",
                   centroids=4, nprobe=4, code_size=4, shard_lists=True,
                   refine_k_factor=4, pallas_adc=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFPQIndex)
    assert idx.refine_k_factor == 4 and idx.use_pallas
    x = rng.standard_normal((900, 16)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    D0, I0 = idx.search(x[:4], 5)
    assert (I0[:, 0] == np.arange(4)).all()
    p = str(tmp_path / "spq_refine.npz")
    save_state(p, idx.state_dict())
    idx2 = index_from_state_dict(load_state(p))
    assert idx2.refine_k_factor == 4 and idx2.raw_lists is not None
    D1, I1 = idx2.search(x[:4], 5)
    np.testing.assert_array_equal(I0, I1)
    np.testing.assert_allclose(D0, D1, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # ~10s; the routed-path equality above covers correctness
def test_routed_bucket_auto_resize_under_skew(rng, caplog):
    """Adversarial skew: every added row lands in ONE list, so one chip owns
    all (query, probe) pairs and the default 2x-slack bucket must drop.
    The driver has to resize and re-run until zero pairs are dropped —
    results must equal brute force over the hot cluster, with no recall-loss
    warning left standing."""
    import logging

    from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex

    # sized so the skew actually exceeds the default bucket: cap 4096 at
    # d=64 gives pair group 64; 256 real queries x nprobe=1 all owned by one
    # chip = 256 owned pairs vs a 2x-slack bucket of 64
    d = 64
    centers = rng.standard_normal((8, d)).astype(np.float32) * 20.0
    train = np.concatenate(
        [centers[i] + 0.01 * rng.standard_normal((40, d)).astype(np.float32)
         for i in range(8)]
    )
    idx = ShardedIVFFlatIndex(d, 8, "l2", probe_routing=True)
    idx.train(train)
    # all corpus rows in the single cluster 0 -> one list owns everything
    # (unit spread keeps distances well-separated so the brute-force golden
    # comparison has no fp32 near-ties, while 20-sigma center spacing keeps
    # every row assigned to list 0)
    x = centers[0] + rng.standard_normal((4096, d)).astype(np.float32)
    idx.add(x)
    idx.set_nprobe(1)
    q = centers[0] + rng.standard_normal((256, d)).astype(np.float32)
    with caplog.at_level(logging.INFO, logger="distributed_faiss_tpu.parallel.mesh"):
        D, I = idx.search(q, 10)
    assert any("retrying block" in r.getMessage() for r in caplog.records), (
        "skew did not trigger a resize — test premise broken"
    )
    assert not any("still dropped" in r.getMessage() for r in caplog.records)
    # fp32 near-ties can swap adjacent ranks; assert via distances + recall
    gt = brute_ids(q, x, 10, "l2")
    gt_d = np.sort(((q[:, None, :] - x[gt]) ** 2).sum(-1), axis=1)
    # the kernel's qn - 2ip + bn formulation differs from the direct
    # difference-of-squares by ~1e-4 relative on these magnitudes
    np.testing.assert_allclose(np.sort(D, axis=1), gt_d, rtol=1e-3, atol=1e-2)
    recall = np.mean([len(set(I[i]) & set(gt[i])) / 10 for i in range(len(q))])
    assert recall > 0.995, recall
    assert idx._routed_slack > 2.0


def test_large_query_batch_sharded_modes(rng):
    """A few-hundred-query batch (the launch-bound serving regime the
    block sizing targets) through both sharded modes: full probe ==
    brute force, and routed == masked at partial probe."""
    x = rng.standard_normal((1200, 8)).astype(np.float32)
    q = rng.standard_normal((300, 8)).astype(np.float32)
    masked = ShardedIVFFlatIndex(8, 8, "l2")
    masked.train(x[:600])
    masked.add(x)
    masked.set_nprobe(8)
    D, I = masked.search(q, 5)
    np.testing.assert_array_equal(I, brute_ids(q, x, 5, "l2"))

    routed = ShardedIVFFlatIndex(8, 8, "l2", probe_routing=True)
    routed.centroids = masked.centroids
    routed.lists = masked.lists
    routed._host_pos, routed._host_assign = masked._host_pos, masked._host_assign
    routed._n = masked._n
    routed.set_nprobe(3)
    masked.set_nprobe(3)
    Dm, Im = masked.search(q, 5)
    Dr, Ir = routed.search(q, 5)
    np.testing.assert_array_equal(Im, Ir)
    np.testing.assert_allclose(Dm, Dr, rtol=1e-3, atol=1e-3)
