"""SearchBatcher: concurrent searches coalesce into shared launches with
identical results (utils/batching.py; no reference analog — FAISS
searches there serialize one-launch-per-RPC under index_lock)."""

import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.utils.batching import SearchBatcher


def brute(q, x, k):
    d2 = ((q[:, None, :] - x[None]) ** 2).sum(2)
    ids = np.argsort(d2, axis=1)[:, :k]
    return np.take_along_axis(d2, ids, axis=1), ids


def make_runner(x, counter=None, delay=0.0):
    def run(q, k):
        if counter is not None:
            counter.append(q.shape[0])
        if delay:
            time.sleep(delay)
        return brute(q, x, k)
    return run


def test_batched_results_equal_individual(rng):
    x = rng.standard_normal((500, 8)).astype(np.float32)
    b = SearchBatcher(make_runner(x))
    qs = [rng.standard_normal((3, 8)).astype(np.float32) for _ in range(16)]
    want = [brute(q, x, 4) for q in qs]
    got = [None] * 16
    errs = []

    def worker(i):
        try:
            got[i] = b.search(qs[i], 4)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    for (ws, wi), (gs, gi) in zip(want, got):
        np.testing.assert_array_equal(wi, gi)
        np.testing.assert_allclose(ws, gs, rtol=1e-5)


def test_window_coalesces_concurrent_callers(rng):
    """With a wait window, followers that arrive during the leader's wait
    ride the leader's launch: far fewer underlying calls than callers."""
    x = rng.standard_normal((200, 4)).astype(np.float32)
    calls = []
    b = SearchBatcher(make_runner(x, counter=calls), window_ms=150)
    start = threading.Barrier(8)

    def worker():
        start.wait()
        b.search(np.zeros((2, 4), np.float32), 3)

    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # all 8 callers were served by at most a few launches (typically 1-2);
    # without coalescing there would be exactly 8
    assert len(calls) < 8
    assert sum(calls) == 16  # every row searched exactly once


def test_mixed_k_grouping(rng):
    x = rng.standard_normal((100, 4)).astype(np.float32)
    b = SearchBatcher(make_runner(x), window_ms=50)
    out = {}

    def worker(i, k):
        out[(i, k)] = b.search(np.full((1, 4), i, np.float32), k)

    ts = [threading.Thread(target=worker, args=(i, k))
          for i, k in enumerate([2, 5, 2, 5, 2])]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for (i, k), (s, ids) in out.items():
        assert ids.shape == (1, k)
        ws, wi = brute(np.full((1, 4), i, np.float32), x, k)
        np.testing.assert_array_equal(wi, ids)


def test_error_propagates_to_all_group_members():
    def run(q, k):
        raise ValueError("device on fire")

    b = SearchBatcher(run, window_ms=50)
    errs = []

    def worker():
        try:
            b.search(np.zeros((1, 4), np.float32), 3)
        except ValueError as e:
            errs.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(errs) == 4
    # batcher is not wedged afterwards
    with pytest.raises(ValueError):
        b.search(np.zeros((1, 4), np.float32), 3)


def test_base_exception_wakes_every_group(rng):
    """A BaseException from the launch mid multi-group batch must wake
    callers in ALL groups of the popped batch, not just the failing one
    (ADVICE r2: groups the _serve loop never reached would hang forever)."""
    calls = []

    def run(q, k):
        calls.append(k)
        if len(calls) == 1:
            raise KeyboardInterrupt  # first group's launch dies hard
        return np.zeros((q.shape[0], k), np.float32), np.zeros((q.shape[0], k), np.int64)

    b = SearchBatcher(run, window_ms=80)
    results = {}

    def worker(i, k):
        try:
            results[i] = ("ok", b.search(np.full((1, 4), i, np.float32), k))
        except BaseException as e:  # noqa: BLE001 - the test wants the class
            results[i] = ("err", type(e).__name__)

    # two k-groups coalesced into one batch window; one group's launch
    # raises KeyboardInterrupt — every caller must still return/raise
    ts = [threading.Thread(target=worker, args=(i, k))
          for i, k in enumerate([2, 2, 5, 5])]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in ts), "stranded callers"
    assert len(results) == 4
    # the batcher is usable afterwards
    s, ids = b.search(np.zeros((1, 4), np.float32), 3)
    assert ids.shape == (1, 3)


def test_engine_concurrent_search_equality(rng):
    """Engine-level: concurrent searches through the batcher return the
    same (scores, metadata) as sequential ones."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg

    x = rng.standard_normal((400, 16)).astype(np.float32)
    cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2", train_num=1,
                   batch_window_ms=30)
    idx = Index(cfg)
    idx.add_batch(x, list(range(400)), train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 60
    from distributed_faiss_tpu.utils.state import IndexState
    while idx.get_state() != IndexState.TRAINED:
        assert time.time() < deadline
        time.sleep(0.05)

    want = [idx.search(x[i:i + 2], 3) for i in range(0, 20, 2)]
    got = [None] * 10

    def worker(j):
        got[j] = idx.search(x[2 * j:2 * j + 2], 3)

    ts = [threading.Thread(target=worker, args=(j,)) for j in range(10)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for (ws, wm, _), (gs, gm, _) in zip(want, got):
        np.testing.assert_allclose(ws, gs, rtol=1e-5)
        assert wm == gm


def test_bad_dim_caller_fails_alone(rng):
    """Fault isolation: a wrong-dim query shares no group with valid ones."""
    x = rng.standard_normal((100, 8)).astype(np.float32)
    b = SearchBatcher(make_runner(x), window_ms=60)
    results, errs = {}, {}

    def good(i):
        results[i] = b.search(rng.standard_normal((2, 8)).astype(np.float32), 3)

    def bad():
        try:
            b.search(np.zeros((2, 5), np.float32), 3)  # wrong dim
        except Exception as e:
            errs["bad"] = e

    ts = [threading.Thread(target=good, args=(i,)) for i in range(3)]
    ts.append(threading.Thread(target=bad))
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(results) == 3 and all(v[1].shape == (2, 3) for v in results.values())
    assert "bad" in errs  # its own group failed (shape mismatch in brute)

    with pytest.raises(ValueError):
        b.search(np.zeros((4,), np.float32), 3)  # 1-D rejected at entry


def test_leadership_handoff_under_load(rng):
    """With max_rounds=1, a follower arriving during the leader's launch is
    promoted to leader and still gets served."""
    x = rng.standard_normal((100, 4)).astype(np.float32)
    calls = []
    b = SearchBatcher(make_runner(x, counter=calls, delay=0.15),
                      window_ms=0, max_rounds=1)
    got = {}

    def first():
        got["first"] = b.search(np.zeros((1, 4), np.float32), 3)

    def second():
        time.sleep(0.05)  # arrive while the leader's launch is in flight
        got["second"] = b.search(np.ones((1, 4), np.float32), 3)

    t1 = threading.Thread(target=first)
    t2 = threading.Thread(target=second)
    t1.start(); t2.start()
    t1.join(timeout=10); t2.join(timeout=10)
    assert not t1.is_alive() and not t2.is_alive()
    assert got["first"][1].shape == (1, 3) and got["second"][1].shape == (1, 3)
