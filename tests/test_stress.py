"""Threaded stress tests: the race-safety coverage the reference lacks
(SURVEY §5.2 — its safety is three mutexes and GIL luck, no stress tests).

Hammers one engine shard with concurrent add/search/save/state traffic and
asserts invariants (no exceptions besides the documented not-trained error,
conserved vector counts, consistent final state).
"""

import threading
import time

import numpy as np

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.state import IndexState


def test_concurrent_add_search_save(rng, tmp_path):
    cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                   train_num=50, buffer_bsz=64,
                   index_storage_dir=str(tmp_path / "shard"))
    idx = Index(cfg)
    errors = []
    n_writers, batches, bs = 4, 12, 25
    stop = threading.Event()

    def writer(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(batches):
                idx.add_batch(r.standard_normal((bs, 16)).astype(np.float32), None)
                time.sleep(0.001)
        except Exception as e:
            errors.append(("writer", e))

    def searcher():
        r = np.random.default_rng(99)
        try:
            while not stop.is_set():
                try:
                    idx.search(r.standard_normal((3, 16)).astype(np.float32), 5)
                except RuntimeError as e:
                    # only the documented not-trained refusal is acceptable
                    if "not trained" not in str(e):
                        raise
                time.sleep(0.001)
        except Exception as e:
            errors.append(("searcher", e))

    def saver():
        try:
            while not stop.is_set():
                idx.save()
                time.sleep(0.005)
        except Exception as e:
            errors.append(("saver", e))

    def poller():
        try:
            while not stop.is_set():
                idx.get_state()
                idx.get_idx_data_num()
                time.sleep(0.001)
        except Exception as e:
            errors.append(("poller", e))

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(n_writers)]
    aux = [threading.Thread(target=searcher), threading.Thread(target=saver),
           threading.Thread(target=poller)]
    for t in aux:
        t.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    total = n_writers * batches * bs
    deadline = time.time() + 60
    while time.time() < deadline:
        buf, indexed = idx.get_idx_data_num()
        if idx.get_state() == IndexState.TRAINED and buf == 0 and indexed == total:
            break
        time.sleep(0.05)
    stop.set()
    for t in aux:
        t.join()

    assert not errors, errors
    buf, indexed = idx.get_idx_data_num()
    assert (buf, indexed) == (0, total)
    assert len(idx.id_to_metadata) == total
    # post-stress search works and metadata joins hold
    D, M, _ = idx.search(np.zeros((2, 16), np.float32), 5)
    assert D.shape == (2, 5)


def test_no_stranded_rows_after_add_race(rng):
    """Rows appended in the drain-exit window must still reach the index
    without further add_batch calls (the reference strands them until the
    next add; our drain re-trigger fixes it)."""
    for trial in range(3):
        idx = Index(IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                             train_num=1, buffer_bsz=256))

        def writer(seed):
            r = np.random.default_rng(seed)
            for _ in range(20):
                idx.add_batch(r.standard_normal((37, 8)).astype(np.float32), None)

        threads = [threading.Thread(target=writer, args=(trial * 10 + i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = 4 * 20 * 37
        deadline = time.time() + 60
        while time.time() < deadline:
            buf, n = idx.get_idx_data_num()
            if buf == 0 and n == total and idx.get_state() == IndexState.TRAINED:
                break
            time.sleep(0.05)
        assert idx.get_idx_data_num() == (0, total)


def test_concurrent_drop_during_add(rng):
    """drop_index racing the async add worker must not wedge the state."""
    for trial in range(3):
        cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                       train_num=10, buffer_bsz=32)
        idx = Index(cfg)
        idx.add_batch(rng.standard_normal((64, 8)).astype(np.float32), None,
                      train_async_if_triggered=False)
        idx.add_batch(rng.standard_normal((256, 8)).astype(np.float32), None)
        time.sleep(0.002 * trial)
        idx.drop_index()
        time.sleep(0.2)
        st = idx.get_state()
        assert st == IndexState.NOT_TRAINED, st
        assert idx.get_idx_data_num() == (0, 0)
        # shard is reusable after the drop
        idx.add_batch(rng.standard_normal((20, 8)).astype(np.float32), None,
                      train_async_if_triggered=False)
        deadline = time.time() + 30
        while idx.get_state() != IndexState.TRAINED and time.time() < deadline:
            time.sleep(0.02)
        assert idx.get_state() == IndexState.TRAINED


def test_get_ids_does_not_stall_adds_on_large_store():
    """get_ids builds its set OUTSIDE buffer_lock: a 1e6-row metadata store
    must not stall a concurrent add_batch for the duration of the O(ntotal)
    Python iteration (VERDICT r4 weak #5)."""
    idx = Index(IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                         train_num=10**9, buffer_bsz=10**9))
    n = 1_000_000
    idx.id_to_metadata.extend((i, f"m{i}") for i in range(n))

    t0 = time.time()
    ids = idx.get_ids()
    get_ids_s = time.time() - t0
    assert len(ids) == n

    done = threading.Event()
    waits = []

    def prober():
        while not done.is_set():
            t = time.time()
            with idx.buffer_lock:
                pass
            waits.append(time.time() - t)
            time.sleep(0.001)

    p = threading.Thread(target=prober)
    p.start()
    for _ in range(3):
        idx.get_ids()
    done.set()
    p.join()

    # the lock is held only for the (array ref, length) snapshot — even with
    # the whole-store iteration in flight, a waiter must get through orders
    # of magnitude faster than one full get_ids pass. Assert on the MEDIAN
    # wait with a 0.25 s floor, not the max with 0.05 s: on a loaded
    # single-core CI host, scheduler jitter alone can park the prober past
    # 50 ms once, and a single descheduling must not fail the test — a
    # genuinely held lock would drag the median, not just the tail.
    assert waits, "prober never ran"
    median_wait = sorted(waits)[len(waits) // 2]
    assert median_wait < max(0.25, get_ids_s / 4), (median_wait, max(waits), get_ids_s)
