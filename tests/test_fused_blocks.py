"""Single-launch multi-block search: fused lax.map path vs per-block loop.

The fused path only engages when a batch spans multiple query blocks
(block = base.pick_query_block(...)); these tests shrink MAX_QUERY_BLOCK so
small corpora exercise it, then pin bit-parity against the per-block loop
and against brute force.
"""

import numpy as np
import pytest

from distributed_faiss_tpu.models import base
from distributed_faiss_tpu.models.flat import FlatIndex  # noqa: F401  (import check)
from distributed_faiss_tpu.models.ivf import IVFFlatIndex, IVFPQIndex


@pytest.fixture
def small_blocks(monkeypatch):
    # pick_query_block reads the module global at call time; 8-query blocks
    # force multi-block execution at test sizes (minimum floor is bypassed
    # because block starts at MAX_QUERY_BLOCK)
    monkeypatch.setattr(base, "MAX_QUERY_BLOCK", 8)


def brute(q, x, k, metric):
    if metric == "dot":
        s = q @ x.T
    else:
        s = -((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return np.argsort(-s, axis=1)[:, :k]


@pytest.mark.parametrize("metric,codec", [("dot", "f32"), ("l2", "f32"), ("l2", "sq8")])
def test_fused_flat_index_exact(rng, metric, codec, small_blocks):
    d, n, nq, k = 16, 300, 27, 5
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = x[:nq] + rng.standard_normal((nq, d)).astype(np.float32) * 1e-3
    idx = FlatIndex(d, metric=metric, codec=codec)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(q, k)
    if codec == "f32":
        np.testing.assert_array_equal(I, brute(q, x, k, metric))
    else:
        # sq8 + l2 near-duplicate queries: the self row must still win
        np.testing.assert_array_equal(I[:, 0], np.arange(nq))


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_fused_flat_full_probe_exact(rng, metric, small_blocks):
    d, n, nq, k = 16, 400, 37, 5  # 37 queries -> 5 blocks of 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((nq, d)).astype(np.float32)
    idx = IVFFlatIndex(d, 8, metric=metric)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)  # full probe -> exact
    D, I = idx.search(q, k)
    np.testing.assert_array_equal(I, brute(q, x, k, metric))


def test_fused_matches_per_block_loop(rng, small_blocks):
    d, n, nq, k = 16, 500, 29, 4
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((nq, d)).astype(np.float32)
    idx = IVFFlatIndex(d, 16, metric="l2", codec="sq8", refine_k_factor=4)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)

    D_fused, I_fused = idx.search(q, k)

    # same search with the fused path disabled: route through the loop
    nprobe = 4
    import distributed_faiss_tpu.models.ivf as ivfmod
    nb = base.pick_query_block(idx.lists.cap * d * 4)
    g = ivfmod.probe_group_size(nprobe, nb * idx.lists.cap * d * 4)
    scan_k = k * idx.refine_k_factor

    def run(b):
        vals, ids = ivfmod._ivf_flat_search(
            idx.centroids, idx.lists.data, idx.lists.ids, idx.lists.sizes,
            b, scan_k, nprobe, g, "l2", "sq8",
            vmin=idx.sq_params["vmin"], span=idx.sq_params["span"],
            list_norms=idx._scan_norms(),
        )
        return ivfmod._rerank_exact(idx.refine_store.data, b, ids, k, "l2")

    D_loop, I_loop = idx._search_blocks(q, k, run, block=nb, fused_fn=None)
    np.testing.assert_array_equal(I_fused, I_loop)
    np.testing.assert_allclose(D_fused, D_loop, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_fused_pq_refine_recall(rng, small_blocks, use_pallas):
    d, m, n, nq, k = 32, 8, 1500, 41, 10
    # low-intrinsic-dim mixture (isotropic corpora sink PQ recall — see
    # knnlm corpus-model note in benchmarks/)
    centers = rng.standard_normal((12, d)).astype(np.float32) * 3
    x = (centers[rng.integers(0, 12, n)]
         + rng.standard_normal((n, d)).astype(np.float32) * 0.3)
    q = (centers[rng.integers(0, 12, nq)]
         + rng.standard_normal((nq, d)).astype(np.float32) * 0.3)
    idx = IVFPQIndex(d, 16, m=m, metric="l2", refine_k_factor=8,
                     use_pallas=use_pallas)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(16)  # full probe isolates PQ+refine quality
    D, I = idx.search(q, k)
    assert idx._pallas_runtime_ok
    truth = brute(q, x, k, "l2")
    recall = np.mean([len(set(I[i]) & set(truth[i])) / k for i in range(nq)])
    assert recall >= 0.9, recall
    assert I.shape == (nq, k) and (I >= 0).all()


@pytest.mark.parametrize("use_pallas", [False, True])
def test_fused_sharded_masked_paths(rng, small_blocks, use_pallas):
    """shard_map under lax.map: the masked sharded flat and PQ+refine paths
    must survive the single-launch fusion on the 8-device mesh."""
    from distributed_faiss_tpu.parallel.mesh import (
        ShardedIVFFlatIndex, ShardedIVFPQIndex)

    d, n, nq, k = 32, 800, 21, 5
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = x[:nq] + rng.standard_normal((nq, d)).astype(np.float32) * 0.01
    flat = ShardedIVFFlatIndex(d, 8, "l2")
    flat.train(x)
    flat.add(x)
    flat.set_nprobe(8)  # full probe -> exact
    D, I = flat.search(q, k)
    np.testing.assert_array_equal(I[:, 0], np.arange(nq))

    pq_idx = ShardedIVFPQIndex(d, 8, m=4, metric="l2", refine_k_factor=8,
                               use_pallas=use_pallas)
    pq_idx.train(x)
    pq_idx.add(x)
    pq_idx.set_nprobe(8)
    Dp, Ip = pq_idx.search(q, k)
    assert pq_idx._pallas_runtime_ok
    assert (Ip[:, 0] == np.arange(nq)).mean() >= 0.9


def test_fused_engages_only_past_one_block(rng, small_blocks):
    """nq <= block must stay on the single-launch-per-block path (no padded
    map overhead for the common serving case)."""
    d = 8
    x = rng.standard_normal((200, d)).astype(np.float32)
    idx = IVFFlatIndex(d, 4, metric="l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    called = {"fused": 0}

    orig = idx._search_blocks

    def spy(q, k, fn, block=256, fused_fn=None):
        if fused_fn is not None and np.asarray(q).shape[0] > block:
            called["fused"] += 1
        return orig(q, k, fn, block=block, fused_fn=fused_fn)

    idx._search_blocks = spy
    idx.search(rng.standard_normal((8, d)).astype(np.float32), 3)
    assert called["fused"] == 0
    idx.search(rng.standard_normal((9, d)).astype(np.float32), 3)
    assert called["fused"] == 1
