"""Chaos matrix: every fault class from the proxy + rank kill/restart from
the harness, driven against REAL server processes (acceptance criteria of
the robustness layer):

(a) search(allow_partial=True) keeps serving from survivors under rank
    death and hung-rank faults;
(b) every batch acknowledged by add_index_data is present in get_ids()
    after recovery (reroute + restart);
(c) a shard killed at a random point during save() loads the latest
    complete generation — never a torn set — on restart;
(d) garbled/cut frames drop one connection, in BOTH serving loops, and
    broadcast ops degrade to structured MultiRankError under an outage.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import rpc
from distributed_faiss_tpu.parallel.client import IndexClient, MultiRankError
from distributed_faiss_tpu.parallel.server import IndexServer
from distributed_faiss_tpu.testing.chaos import ChaosProxy, Fault, ServerHarness
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.state import IndexState

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", 16)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 50)
    return IndexCfg(**kw)


def wait_drained(client, index_id, n, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (client.get_state(index_id) == IndexState.TRAINED
                and client.get_buffer_depth(index_id) == 0
                and client.get_ntotal(index_id) >= n):
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster never drained to {n} indexed rows")


# --------------------------------------------------- (a) search under kill


def test_search_survives_kill_and_restart(tmp_path):
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(3, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(disc)
        client.create_index("cidx", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((300, 16)).astype(np.float32)
        for s in range(0, 300, 50):
            client.add_index_data("cidx", x[s:s + 50],
                                  [(i,) for i in range(s, s + 50)])
        wait_drained(client, "cidx", 300)
        client.save_index("cidx")

        victim = 1
        h.kill(victim)
        scores, metas, missing = client.search(
            x[:20], 5, "cidx", allow_partial=True, partial_timeout=15.0)
        assert len(missing) == 1 and missing[0]["port"] == h.port(victim)
        assert scores.shape == (20, 5)

        h.restart(victim, load_index=True)
        h.wait_port(victim)
        deadline = time.time() + 60
        while True:
            try:
                assert client.load_index("cidx", force_reload=False)
                break
            except (OSError, MultiRankError):
                assert time.time() < deadline, "restarted rank never rejoined"
                time.sleep(0.3)
        scores, metas, missing = client.search(
            x[:20], 5, "cidx", allow_partial=True, partial_timeout=15.0)
        assert missing == []
        for i in range(20):
            assert metas[i][0] == (i,)  # full corpus self-hits again
        client.close()


# ------------------------------------ (b) ingest under mid-stream rank death


def test_ingest_rank_death_zero_acked_batch_loss(tmp_path):
    """Kill a rank mid-ingest: batches placed on it REROUTE to survivors;
    after restarting the victim from its last save, every id whose batch
    was ACKNOWLEDGED is present in get_ids()."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(3, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(disc)
        client.create_index("zidx", flat_cfg())
        rng = np.random.default_rng(1)
        x = rng.standard_normal((900, 16)).astype(np.float32)

        acked = set()
        # phase 1: healthy ingest, then make it durable everywhere
        for s in range(0, 300, 50):
            ids = [(i,) for i in range(s, s + 50)]
            client.add_index_data("zidx", x[s:s + 50], ids)
            acked.update(i for (i,) in ids)
        wait_drained(client, "zidx", 300)
        client.save_index("zidx")

        # phase 2: kill one rank mid-stream; every add must still ack
        victim = 2
        h.kill(victim)
        for s in range(300, 900, 50):
            ids = [(i,) for i in range(s, s + 50)]
            client.add_index_data("zidx", x[s:s + 50], ids)  # never raises
            acked.update(i for (i,) in ids)
        assert client.reroutes, "dead rank was never skipped?"
        # discovery-file order (= stub id order) is registration order, not
        # rank order: identify the victim's stub by its port
        victim_stub = next(s.id for s in client.sub_indexes
                           if s.port == h.port(victim))
        assert {r["skipped_server"] for r in client.reroutes} == {victim_stub}
        assert all(r["port"] == h.port(victim) for r in client.reroutes)

        # recovery: restart the victim from its snapshot
        h.restart(victim, load_index=True)
        h.wait_port(victim)
        deadline = time.time() + 60
        while True:
            try:
                client.load_index("zidx", force_reload=False)
                break
            except (OSError, MultiRankError):
                assert time.time() < deadline
                time.sleep(0.3)
        deadline = time.time() + 120
        while client.get_buffer_depth("zidx") > 0:
            assert time.time() < deadline
            time.sleep(0.2)

        present = set(client.get_ids("zidx"))  # ids extracted from meta[0]
        lost = acked - present
        assert not lost, f"{len(lost)} acknowledged ids lost: {sorted(lost)[:10]}"
        client.close()


# -------------------------------------------- (c) kill -9 during save sweep


def test_snapshot_kill9_sweep(tmp_path):
    """SIGKILL a saving shard at randomized points in the save; the restart
    must always load the latest COMPLETE generation (possibly the one being
    written, if its manifest landed) and serve consistent metadata."""
    saver = str(tmp_path / "saver.py")
    with open(saver, "w") as f:
        f.write(
            "import os, sys, time\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            f"sys.path.insert(0, {REPO_ROOT!r})\n"
            "import numpy as np\n"
            "from distributed_faiss_tpu.engine import Index\n"
            "from distributed_faiss_tpu.utils.config import IndexCfg\n"
            "from distributed_faiss_tpu.utils.state import IndexState\n"
            "storage = sys.argv[1]\n"
            "cfg = IndexCfg(index_builder_type='flat', dim=16, metric='l2',\n"
            "               train_num=20, index_storage_dir=storage)\n"
            "idx = Index(cfg)\n"
            "rng = np.random.default_rng(0)\n"
            "rows = 0\n"
            "x = rng.standard_normal((40, 16)).astype(np.float32)\n"
            "idx.add_batch(x, [('m', rows + i) for i in range(40)],\n"
            "              train_async_if_triggered=False)\n"
            "rows += 40\n"
            "while idx.get_state() != IndexState.TRAINED:\n"
            "    time.sleep(0.01)\n"
            "print('READY', flush=True)\n"
            "while True:\n"
            "    x = rng.standard_normal((40, 16)).astype(np.float32)\n"
            "    idx.add_batch(x, [('m', rows + i) for i in range(40)],\n"
            "                  train_async_if_triggered=False)\n"
            "    rows += 40\n"
            "    while idx.get_idx_data_num()[0] > 0:\n"
            "        time.sleep(0.005)\n"
            "    idx.save()\n"
        )
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils import serialization

    kill_rng = np.random.default_rng(42)
    for trial in range(5):
        storage = str(tmp_path / f"shard-{trial}")
        proc = subprocess.Popen([sys.executable, saver, storage],
                                stdout=subprocess.PIPE, text=True,
                                env={**os.environ, **ENV})
        assert proc.stdout.readline().strip() == "READY"
        # wait until at least one generation has COMMITTED, then SIGKILL at
        # a random later moment — each trial lands at a different byte
        # offset of some in-flight save
        deadline = time.time() + 60
        while not serialization.list_generations(storage):
            assert time.time() < deadline, "first save never committed"
            time.sleep(0.01)
        time.sleep(float(kill_rng.uniform(0.0, 0.8)))
        proc.kill()
        proc.wait()

        loaded = Index.from_storage_dir(storage)
        assert loaded is not None, f"trial {trial}: nothing loadable"
        # the loaded generation is internally consistent: saves only ever
        # committed drained multiples of 40 rows, and ids join cleanly
        n = loaded.tpu_index.ntotal
        assert n >= 40 and n % 40 == 0, (trial, n)
        assert len(loaded.id_to_metadata) == n
        scores, meta, _ = loaded.search(np.zeros((2, 16), np.float32), 3)
        assert all(m is None or m[0] == "m" for row in meta for m in row)
        # a committed generation survived the kill (the torn one, if any,
        # is quarantined — never silently consumed)
        assert serialization.list_generations(storage)


@pytest.mark.mesh
def test_snapshot_kill9_mesh_sweep(tmp_path):
    """Mesh-backed variant of the kill -9 sweep (ISSUE 6 acceptance): a
    rank whose corpus is sharded over the virtual 8-device mesh is
    SIGKILLed at random points during save; the restart must load the
    latest COMPLETE generation through the checksum-verified fallback and
    serve it from a (re-built) sharded index."""
    saver = str(tmp_path / "mesh_saver.py")
    with open(saver, "w") as f:
        f.write(
            "import os, sys, time\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "flags = os.environ.get('XLA_FLAGS', '')\n"
            "if 'xla_force_host_platform_device_count' not in flags:\n"
            "    os.environ['XLA_FLAGS'] = (\n"
            "        flags + ' --xla_force_host_platform_device_count=8').strip()\n"
            f"sys.path.insert(0, {REPO_ROOT!r})\n"
            "import numpy as np\n"
            "from distributed_faiss_tpu.engine import Index\n"
            "from distributed_faiss_tpu.utils.config import IndexCfg\n"
            "from distributed_faiss_tpu.utils.state import IndexState\n"
            "storage = sys.argv[1]\n"
            "cfg = IndexCfg(index_builder_type='flat', dim=16, metric='l2',\n"
            "               train_num=20, index_storage_dir=storage,\n"
            "               mesh_shards=True)\n"
            "idx = Index(cfg)\n"
            "rng = np.random.default_rng(0)\n"
            "rows = 0\n"
            "x = rng.standard_normal((40, 16)).astype(np.float32)\n"
            "idx.add_batch(x, [('m', rows + i) for i in range(40)],\n"
            "              train_async_if_triggered=False)\n"
            "rows += 40\n"
            "while idx.get_state() != IndexState.TRAINED:\n"
            "    time.sleep(0.01)\n"
            "print('READY', flush=True)\n"
            "while True:\n"
            "    x = rng.standard_normal((40, 16)).astype(np.float32)\n"
            "    idx.add_batch(x, [('m', rows + i) for i in range(40)],\n"
            "                  train_async_if_triggered=False)\n"
            "    rows += 40\n"
            "    while idx.get_idx_data_num()[0] > 0:\n"
            "        time.sleep(0.005)\n"
            "    idx.save()\n"
        )
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.parallel.mesh import ShardedFlatIndex
    from distributed_faiss_tpu.utils import serialization

    kill_rng = np.random.default_rng(7)
    for trial in range(3):
        storage = str(tmp_path / f"mesh-shard-{trial}")
        proc = subprocess.Popen([sys.executable, saver, storage],
                                stdout=subprocess.PIPE, text=True,
                                env={**os.environ, **ENV})
        assert proc.stdout.readline().strip() == "READY"
        deadline = time.time() + 120
        while not serialization.list_generations(storage):
            assert time.time() < deadline, "first mesh save never committed"
            time.sleep(0.01)
        time.sleep(float(kill_rng.uniform(0.0, 0.8)))
        proc.kill()
        proc.wait()

        loaded = Index.from_storage_dir(storage)
        assert loaded is not None, f"trial {trial}: nothing loadable"
        assert isinstance(loaded.tpu_index, ShardedFlatIndex)
        n = loaded.tpu_index.ntotal
        assert n >= 40 and n % 40 == 0, (trial, n)
        assert len(loaded.id_to_metadata) == n
        scores, meta, _ = loaded.search(np.zeros((2, 16), np.float32), 3)
        assert all(m is None or m[0] == "m" for row in meta for m in row)
        # the restored rank serves the merged-window contract: one launch
        s = loaded.perf.summary()
        assert s["device_launches"]["max_s"] == 1.0
        assert serialization.list_generations(storage)


# --------------------------- (d) frame faults + broadcast degradation matrix


@pytest.mark.parametrize("mode", ["blocking", "selector"])
@pytest.mark.parametrize("kind", ["garble", "cut", "garble-down"])
def test_frame_faults_drop_connection_not_server(tmp_path, mode, kind):
    """Garbled and cut-mid-frame traffic through the proxy must cost only
    that connection, in both serving loops; the same client recovers on a
    fresh connection and other clients never notice. garble-down corrupts
    the RESPONSE: the client itself detects the bad frame (FrameError) and
    the failure must classify as TRANSPORT so the write path retries it."""
    port = free_port()
    srv = IndexServer(0, str(tmp_path))
    target = srv.start_blocking if mode == "blocking" else srv.start
    threading.Thread(target=target, args=(port,), daemon=True).start()
    time.sleep(0.3)

    fault = {
        "garble": Fault("garble", after_bytes=2, nbytes=6, direction="up"),
        "cut": Fault("cut", after_bytes=7, direction="up"),
        "garble-down": Fault("garble", after_bytes=0, nbytes=4,
                             direction="down"),
    }[kind]
    with ChaosProxy("localhost", port, plan=[fault]) as proxy:
        bystander = rpc.Client(1, "localhost", port)  # direct, no faults
        c = rpc.Client(0, "localhost", proxy.port)
        with pytest.raises(rpc.TRANSPORT_ERRORS) as ei:
            c.generic_fun("get_rank", (), {}, timeout=10.0)
        assert rpc.RetryPolicy().is_retryable(ei.value)
        # connection 1 is pass-through: the SAME stub redials and succeeds
        assert c.generic_fun("get_rank", (), {}, timeout=10.0) == 0
        assert bystander.get_rank() == 0  # server never stopped serving
        c.close()
        bystander.close()
    srv.stop()


def test_latency_and_blackhole_bounded_by_deadline(tmp_path):
    port = free_port()
    srv = IndexServer(0, str(tmp_path))
    threading.Thread(target=srv.start_blocking, args=(port,), daemon=True).start()
    time.sleep(0.3)

    with ChaosProxy("localhost", port,
                    plan=[Fault("latency", delay=0.2, direction="up")]) as proxy:
        c = rpc.Client(0, "localhost", proxy.port)
        t0 = time.time()
        assert c.generic_fun("get_rank", (), {}, timeout=10.0) == 0
        assert time.time() - t0 >= 0.2  # the latency really was injected
        c.close()

    with ChaosProxy("localhost", port, plan=[Fault("blackhole")]) as proxy:
        c = rpc.Client(0, "localhost", proxy.port)
        t0 = time.time()
        with pytest.raises(OSError):
            c.generic_fun("get_rank", (), {}, timeout=1.0)
        assert time.time() - t0 < 5.0, "deadline did not bound the hang"
        c.close()
    srv.stop()


def test_write_path_retry_heals_reset_and_broadcast_reports_outage(tmp_path):
    """Connection-reset on the first attempt: the retry policy redials and
    the add acks (self-healing); with a rank hard-down, save_index degrades
    to MultiRankError naming exactly the dead rank while live ranks DID
    save."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(2, disc, storage, base_port=free_port(), env=ENV) as h:
        # interpose a proxy in front of rank 0 for this client only; the
        # plan scripts per-connection: conn 0 = the stub's initial dial
        # (clean), conn 1 = the first REDIAL gets RST, conn 2+ = clean
        with ChaosProxy("localhost", h.port(0),
                        plan=[None, Fault("reset")]) as proxy:
            disc2 = str(tmp_path / "disc2.txt")
            # wait for both ranks to register before rewriting the list
            entries = IndexClient.read_server_list(disc)
            rank1 = next(hp for hp in entries if hp[1] != h.port(0))
            with open(disc2, "w") as f:
                f.write(f"2\nlocalhost,{proxy.port}\n{rank1[0]},{rank1[1]}\n")
            client = IndexClient(
                disc2,
                retry_policy=rpc.RetryPolicy(max_attempts=4, base_delay=0.02,
                                             jitter=0.0))
            client.create_index("ridx", flat_cfg())
            rng = np.random.default_rng(3)
            x = rng.standard_normal((200, 16)).astype(np.float32)
            for s in range(0, 100, 50):
                client.add_index_data("ridx", x[s:s + 50],
                                      [(i,) for i in range(s, s + 50)])
            wait_drained(client, "ridx", 100)

            # sever the proxied stub's live socket: its next call fails,
            # redials into the scripted RST (attempt 2), then heals on a
            # clean redial (attempt 3) — same rank, no reroute
            stub0 = next(s for s in client.sub_indexes if s.port == proxy.port)
            stub0.sock.close()
            client.cur_server_ids["ridx"] = client.sub_indexes.index(stub0)
            before = list(client.reroutes)
            client.add_index_data("ridx", x[100:150],
                                  [(i,) for i in range(100, 150)])
            assert list(client.reroutes) == before, "retry healed, so no reroute"
            assert proxy.connections_seen() >= 3  # dial + RST'd + healed
            wait_drained(client, "ridx", 150)

            # hard outage: rank 1 dies; broadcast degrades structurally
            h.kill(1)
            stub1 = next(s for s in client.sub_indexes if s.port != proxy.port)
            with pytest.raises(MultiRankError) as ei:
                client.save_index("ridx")
            err = ei.value
            assert [o["server"] for o in err.failures] == [stub1.id]
            assert len(err.results) == 1  # the live rank saved
            client.close()
