"""Per-id version acceptance gate (ISSUE 12): upserts RACING deletes
across an R=2 group under a live mux query storm, with the victim
SIGKILLed (twice — the second kill lands mid-sweep/heal) and restarted
from pre-mutation storage. Every replica must converge to the LAST
WRITER's value — the upserted rows survive the replica that only ever
saw the delete (the exact interleaving PRs 9/10 documented as a
delete-wins loss), nothing resurrects, and a client repair replay of the
outage's records double-applies NOTHING (versioned no-op fast path).
Convergence is verified by byte-identical wire digests (including the
versioned live_vhash plane) and golden result comparison; the same
cluster then serves ``search_at_generation`` against the PRE-mutation
retained generations."""

import os
import socket
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import rpc
from distributed_faiss_tpu.parallel.client import IndexClient
from distributed_faiss_tpu.testing.chaos import QueryStorm, ServerHarness
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg
from distributed_faiss_tpu.utils.state import IndexState

pytestmark = [pytest.mark.versions, pytest.mark.chaos, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# fast sweeps so convergence lands inside the test budget; compaction off
# to keep the gate focused on LWW reconciliation
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT,
       "DFT_ANTIENTROPY_INTERVAL": "0.5", "DFT_COMPACT": "0"}

DIM = 16


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg():
    return IndexCfg(index_builder_type="flat", dim=DIM, metric="l2",
                    train_num=50)


def wait_drained(client, index_id, n, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (client.get_state(index_id) == IndexState.TRAINED
                and client.get_buffer_depth(index_id) == 0
                and client.get_ntotal(index_id) >= n):
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster never drained to {n} indexed rows")


def rank_digest(port, index_id, timeout=5.0):
    resp = rpc.digest_exchange(
        "localhost", port, {"rank": None, "group": None, "want": None},
        timeout=timeout)
    return resp["digests"].get(index_id)


def wait_converged(ports, index_id, timeout=90.0):
    """Poll both ranks' wire digests until byte-identical (dict equality
    covers the versioned live_vhash plane too)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            digs = [rank_digest(p, index_id) for p in ports]
        except Exception as e:  # a rank mid-restart: keep polling
            last = e
            time.sleep(0.3)
            continue
        if all(d is not None for d in digs) and all(d == digs[0]
                                                    for d in digs):
            return digs[0]
        last = digs
        time.sleep(0.3)
    raise AssertionError(f"replicas never converged: {last}")


def test_upsert_vs_delete_storm_converges_to_last_writer_gate(tmp_path):
    """The gate, end to end:

    1. healthy R=2 group, 240 rows ingested + saved on both replicas;
       pre-mutation generations PINNED (the point-in-time handle);
    2. delete a victim-id set on BOTH replicas (versioned), then SIGKILL
       replica 1 and UPSERT the same ids with fresh vectors — replica 1
       now holds only the delete, the survivor the newer re-add: the
       delete-wins interleaving that used to destroy the upsert;
    3. golden = post-mutation search on the survivor (upserted vectors
       must surface); mux query storm starts against the degraded group;
    4. restart replica 1 from its stale storage — the sweepers alone
       must converge it to the LAST WRITER (upserts live, nothing
       resurrected), through a second SIGKILL landing mid-sweep;
    5. byte-identical wire digests (id AND version planes), zero storm
       errors, every storm result byte-identical to golden, reads pinned
       onto the healed replica serve golden;
    6. the client's repair replay of the outage's records double-applies
       NOTHING on the healed replica (versioned no-ops, counted);
    7. ``search_at_generation`` with the pre-mutation pins returns the
       PRE-mutation results on the same cluster.
    """
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(2, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(
            disc, replication_cfg=ReplicationCfg(
                replication=2, write_quorum=1))
        group = client.membership.group_of(0)
        assert client.membership.replicas(group) == [0, 1]
        client.create_index("vidx", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((240, DIM)).astype(np.float32)
        for s in range(0, 240, 60):
            client.add_index_data("vidx", x[s:s + 60],
                                  [(i,) for i in range(s, s + 60)])
        wait_drained(client, "vidx", 240)
        client.save_index("vidx")

        # the point-in-time handle, taken BEFORE any mutation
        pins = client.pin_generations("vidx")
        assert set(pins) == {0, 1}, pins
        q = np.ascontiguousarray(x[50:58])
        pre_scores, pre_meta = client.search(q, 5, "vidx")

        victim_pos = 1
        victim_rank = client.sub_indexes[victim_pos].port - h.base_port
        victim_port = client.sub_indexes[victim_pos].port
        survivor_port = client.sub_indexes[0].port

        # ---- the race: delete lands EVERYWHERE, upsert only on the
        # survivor (the victim is down) — the replica that only saw the
        # delete used to win reconciliation and destroy the upsert
        doomed = list(range(50, 58))
        removed = client.remove_ids("vidx", doomed)
        assert removed == len(doomed)
        h.kill(victim_rank)
        new_vecs = (x[doomed] + 0.25).astype(np.float32)
        client.upsert("vidx", doomed, new_vecs,
                      [(i,) for i in doomed])
        # plus plain ingest through the outage (repair records pile up)
        far = (rng.standard_normal((60, DIM)) + 50.0).astype(np.float32)
        client.add_index_data("vidx", far,
                              [(240 + i,) for i in range(60)])
        repl = client.get_replication_stats()
        assert repl["repair"]["pending"] >= 1, repl["repair"]
        survivor = client.sub_indexes[0]
        deadline = time.time() + 120
        while survivor.generic_fun("get_aggregated_ntotal", ("vidx",)) > 0:
            assert time.time() < deadline, "survivor never drained"
            time.sleep(0.2)

        # golden AFTER the mutations: the UPSERTED vectors must be the
        # top hits for their own queries (the last writer's value)
        g_scores, g_meta = client.search(q, 5, "vidx")
        upq = np.ascontiguousarray(new_vecs)
        gu_scores, gu_meta = client.search(upq, 1, "vidx")
        assert [m[0] for m in gu_meta] == [(i,) for i in doomed]

        def reload_vidx():
            deadline = time.time() + 60
            while True:
                try:
                    client.sub_indexes[victim_pos].generic_fun(
                        "load_index", ("vidx", None), timeout=30.0)
                    return
                except Exception:
                    assert time.time() < deadline, "victim never reloaded"
                    time.sleep(0.3)

        with QueryStorm(client, "vidx", q, 5, threads=4) as storm:
            time.sleep(0.5)  # storm baseline against the degraded group

            # ---- restart from stale (delete-only) storage: the sweep
            # must converge to the last writer, not delete-wins
            h.restart(victim_rank,
                      extra_env={"DFT_SHARD_GROUP": str(group)})
            h.wait_port(victim_rank)
            reload_vidx()
            wait_converged([survivor_port, victim_port], "vidx")

            # ---- SIGKILL again mid-sweep window, restart, re-converge
            h.kill(victim_rank)
            time.sleep(0.3)
            h.restart(victim_rank,
                      extra_env={"DFT_SHARD_GROUP": str(group)})
            h.wait_port(victim_rank)
            reload_vidx()
            final_digest = wait_converged([survivor_port, victim_port],
                                          "vidx")
            time.sleep(1.0)  # storm keeps sampling the converged group
        results, errors = storm.stop()

        assert errors == [], f"storm saw search errors: {errors[:3]}"
        assert len(results) >= 10, "storm produced too few samples"
        for scores, meta in results:
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta

        # digests carry both planes and the deletes' ledger entries
        assert "live_vhash" in final_digest
        assert final_digest["dead_n"] >= 0

        # the healed replica serves the LAST WRITER's values: pin reads
        # onto it — the upserted vectors hit, byte-identical to golden
        deadline = time.time() + 120
        while client.get_buffer_depth("vidx") > 0:
            assert time.time() < deadline, "healed rank never drained"
            time.sleep(0.2)
        with client._stats_lock:
            client._preferred[group] = victim_pos
        v_scores, v_meta = client.search(upq, 1, "vidx")
        np.testing.assert_array_equal(v_scores, gu_scores)
        assert v_meta == gu_meta
        v_scores5, v_meta5 = client.search(q, 5, "vidx")
        np.testing.assert_array_equal(v_scores5, g_scores)
        assert v_meta5 == g_meta

        # ---- zero double-applies: replay the outage's repair records
        # against the ALREADY-HEALED replica — versioned no-ops only
        stub = client.sub_indexes[victim_pos]
        nt_before = stub.generic_fun("get_ntotal", ("vidx",))
        dg_before = rank_digest(victim_port, "vidx")
        out = client.repair_under_replicated()
        assert out["still_pending"] == 0, out
        deadline = time.time() + 60
        while stub.generic_fun("get_aggregated_ntotal", ("vidx",)) > 0:
            assert time.time() < deadline
            time.sleep(0.2)
        assert stub.generic_fun("get_ntotal", ("vidx",)) == nt_before
        assert rank_digest(victim_port, "vidx") == dg_before
        mut = stub.generic_fun("get_perf_stats")["mutation"]["vidx"]
        assert (mut["version_noop_adds"] > 0
                or mut["version_noop_deletes"] > 0), mut

        # no acked id lost, upserted ids live everywhere, cluster-wide
        present = set(client.get_ids("vidx"))
        assert set(doomed) <= present, "upserted ids lost (delete won)"
        assert {240 + i for i in range(60)} <= present

        # ---- point-in-time: the PRE-mutation pins still serve the
        # pre-mutation truth on the same (now fully mutated) cluster
        pin_scores, pin_meta = client.search_at_generation(
            q, 5, "vidx", pins=pins)
        np.testing.assert_array_equal(pin_scores, pre_scores)
        assert pin_meta == pre_meta
        client.close()
