"""Tracing/observability unit tests (SURVEY §5.1 — the reference has none)."""

import threading
import time

from distributed_faiss_tpu.utils.tracing import LatencyStats, traced


def test_latency_stats_concurrent():
    stats = LatencyStats()

    def worker():
        for _ in range(50):
            stats.record("op", 0.01)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = stats.summary()["op"]
    assert s["count"] == 400
    assert abs(s["mean_s"] - 0.01) < 1e-9
    assert s["max_s"] == 0.01
    stats.reset()
    assert stats.summary() == {}


def test_latency_stats_streaming_percentiles():
    """p50/p95/p99 from the fixed log-spaced histogram: each estimate is
    the containing bucket's upper edge — within one bucket ratio
    (10^(1/5) ~ 1.58x) above the true quantile, never below it, and capped
    at the exact max."""
    stats = LatencyStats()
    # 100 distinct values spanning ~2 decades: true p50=0.00505, p99=0.01
    for i in range(1, 101):
        stats.record("op", i * 1e-4)
    s = stats.summary()["op"]
    for key, true_q in (("p50_s", 0.00505), ("p95_s", 0.0095),
                        ("p99_s", 0.0099)):
        assert true_q <= s[key] <= true_q * 1.585, (key, s[key])
    assert s["p50_s"] <= s["p95_s"] <= s["p99_s"] <= s["max_s"]


def test_latency_stats_percentiles_degenerate_and_extreme():
    stats = LatencyStats()
    stats.record("one", 0.01)  # single sample: all percentiles == max
    s = stats.summary()["one"]
    assert s["p50_s"] == s["p95_s"] == s["p99_s"] == 0.01
    # values beyond the bucket range clamp (no crash, capped at exact max)
    stats.record("huge", 1e9)
    stats.record("tiny", 1e-12)
    assert stats.summary()["huge"]["p99_s"] == 1e9
    assert stats.summary()["tiny"]["p99_s"] <= 1e-6


def test_traced_records_and_scopes():
    stats = LatencyStats()
    with traced("block", stats):
        time.sleep(0.02)
    s = stats.summary()["block"]
    assert s["count"] == 1
    assert s["mean_s"] >= 0.015


def test_profile_trace_writes(tmp_path):
    import glob

    from distributed_faiss_tpu.utils.tracing import profile_trace

    import jax.numpy as jnp

    d = str(tmp_path / "trace")
    with profile_trace(d):
        jnp.ones((32, 32)).sum().block_until_ready()
    # at least one real artifact file appears (the bare dir matching '/**'
    # would make this vacuous)
    assert glob.glob(d + "/**/*", recursive=True)
