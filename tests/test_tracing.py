"""Tracing/observability unit tests (SURVEY §5.1 — the reference has none)."""

import threading
import time

from distributed_faiss_tpu.utils.tracing import LatencyStats, traced


def test_latency_stats_concurrent():
    stats = LatencyStats()

    def worker():
        for _ in range(50):
            stats.record("op", 0.01)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = stats.summary()["op"]
    assert s["count"] == 400
    assert abs(s["mean_s"] - 0.01) < 1e-9
    assert s["max_s"] == 0.01
    stats.reset()
    assert stats.summary() == {}


def test_traced_records_and_scopes():
    stats = LatencyStats()
    with traced("block", stats):
        time.sleep(0.02)
    s = stats.summary()["block"]
    assert s["count"] == 1
    assert s["mean_s"] >= 0.015


def test_profile_trace_writes(tmp_path):
    import glob

    from distributed_faiss_tpu.utils.tracing import profile_trace

    import jax.numpy as jnp

    d = str(tmp_path / "trace")
    with profile_trace(d):
        jnp.ones((32, 32)).sum().block_until_ready()
    # at least one real artifact file appears (the bare dir matching '/**'
    # would make this vacuous)
    assert glob.glob(d + "/**/*", recursive=True)
