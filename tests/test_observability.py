"""Observability tier: distributed tracing, metrics export, dfstat CLI.

Covers the ISSUE 13 contract end to end: the trace-meta wire compat
matrix (traced mux client vs legacy untagged server, traced serial
client vs traced server, no-meta legacy frames), sampling-off
byte-identity with the pre-trace wire, SpanBuffer bound/eviction,
exemplar linkage from a p99 histogram row to a fetchable trace, the
Prometheus exporter (cumulative _bucket series over the real bounds +
the HTTP listener lifecycle), the shared LatencyStats.delta rate math,
stats-fan-out degradation with a dead rank, and a loopback-cluster
dfstat + ``--trace`` end-to-end drive whose merged timeline accounts for
the observed e2e latency across a replica failover.

Marked ``observability`` (own CI job, mirroring the scheduler tier); the
subprocess SIGKILL stats-degrade case is additionally ``slow``.
"""

import io
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_faiss_tpu import (
    Index,
    IndexCfg,
    IndexClient,
    IndexServer,
    IndexState,
)
from distributed_faiss_tpu.observability import dfstat, export, spans
from distributed_faiss_tpu.parallel import rpc
from distributed_faiss_tpu.utils.config import ReplicationCfg, TracingCfg
from distributed_faiss_tpu.utils.tracing import LatencyStats, bucket_bounds

pytestmark = pytest.mark.observability


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("localhost", port), timeout=1).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def write_discovery(tmp_path, ports, name="disc.txt"):
    p = tmp_path / name
    p.write_text("\n".join(
        [str(len(ports))] + [f"localhost,{port}" for port in ports]) + "\n")
    return str(p)


def make_trained_engine(storage, n=400, d=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    cfg = IndexCfg(index_builder_type="flat", dim=d, metric="l2",
                   train_num=64)
    cfg.index_storage_dir = str(storage)
    idx = Index(cfg)
    idx.add_batch(x, [("doc", i) for i in range(n)],
                  train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 60
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "train/drain timed out"
        time.sleep(0.05)
    return idx, x


def start_server(storage, engine=None, index_id="obs", tracing_cfg=None):
    port = free_port()
    srv = IndexServer(0, str(storage), tracing_cfg=tracing_cfg)
    if engine is not None:
        srv.indexes[index_id] = engine
        srv._wire_engine(engine)
    threading.Thread(target=srv.start_blocking, args=(port,),
                     name=f"obs-server:{port}", daemon=True).start()
    assert wait_listening(port)
    return srv, port


# ------------------------------------------------------------- span buffer


def test_span_buffer_bound_and_eviction():
    buf = spans.SpanBuffer(capacity=4, rank=3)
    for i in range(10):
        buf.record("t1" if i % 2 else "t2", f"stage{i}", 100.0 + i, 0.01,
                   window=i)
    st = buf.stats()
    assert st == {"capacity": 4, "size": 4, "recorded": 10, "evicted": 6}
    kept = buf.snapshot()
    assert [s["extra"]["window"] for s in kept] == [6, 7, 8, 9]
    assert all(s["rank"] == 3 for s in kept)
    # filtered read side (the get_trace_spans contract)
    assert all(s["trace_id"] == "t1" for s in buf.snapshot("t1"))
    buf.clear()
    assert buf.snapshot() == []


def test_span_buffer_capacity_from_env(monkeypatch):
    monkeypatch.setenv("DFT_TRACE_BUFFER", "7")
    assert spans.SpanBuffer().capacity == 7


def test_merge_timelines_dedupes_and_sorts():
    a = {"trace_id": "t", "name": "x", "start_s": 2.0, "dur_s": 0.1,
         "rank": 0}
    b = {"trace_id": "t", "name": "y", "start_s": 1.0, "dur_s": 0.2}
    merged = spans.merge_timelines([a, b], [dict(a)])  # exact dup dropped
    assert merged == [b, a]


def test_sampling_rate_knob(monkeypatch):
    monkeypatch.delenv("DFT_TRACE_SAMPLE", raising=False)
    assert spans.maybe_sample() is None  # default off: no RNG draw, no id
    monkeypatch.setenv("DFT_TRACE_SAMPLE", "1")
    tid = spans.maybe_sample()
    assert isinstance(tid, str) and len(tid) == 16
    monkeypatch.setenv("DFT_TRACE_SAMPLE", "0")
    assert spans.maybe_sample() is None


# -------------------------------------------------- exemplars + delta math


def test_exemplar_links_p99_bucket_to_trace():
    stats = LatencyStats()
    for _ in range(200):
        stats.record("op", 0.001)  # the body of the distribution, unsampled
    stats.record("op", 0.5, exemplar="tail-trace")
    s = stats.summary()["op"]
    assert s["p99_exemplar"] == "tail-trace"
    raw = stats.summary(raw=True)["op"]
    assert sum(raw["hist"]) == 201
    assert list(raw["exemplars"].values()) == ["tail-trace"]
    # an exemplar in the BODY must not masquerade as the tail's
    stats2 = LatencyStats()
    stats2.record("op", 0.001, exemplar="body-trace")
    for _ in range(200):
        stats2.record("op", 0.5)
    assert "p99_exemplar" not in stats2.summary()["op"]


def test_exemplar_ages_out(monkeypatch):
    """A tail exemplar older than EXEMPLAR_TTL_S stops being advertised
    — the span rings evicted its trace long ago, and a dead lead is
    worse than no lead."""
    import distributed_faiss_tpu.utils.tracing as tracing_mod

    stats = LatencyStats()
    stats.record("op", 0.5, exemplar="old-trace")
    assert stats.summary()["op"]["p99_exemplar"] == "old-trace"
    monkeypatch.setattr(tracing_mod, "EXEMPLAR_TTL_S", 0.0)
    s = stats.summary()["op"]
    assert "p99_exemplar" not in s
    assert stats.summary(raw=True)["op"]["exemplars"] == {}


def test_exemplars_absent_without_sampling():
    """Pre-trace output shape is unchanged when nothing passes an
    exemplar — the byte-identity contract's stats-surface half."""
    stats = LatencyStats()
    stats.record("op", 0.01)
    assert "p99_exemplar" not in stats.summary()["op"]
    assert stats.summary(raw=True)["op"]["exemplars"] == {}


def test_delta_shared_rate_math():
    stats = LatencyStats()
    stats.record("op", 0.1)
    prev = stats.summary(raw=True)
    stats.record("op", 0.3)
    stats.record("op", 0.5)
    cur = stats.summary(raw=True)
    d = LatencyStats.delta(prev, cur)["op"]
    assert d["count"] == 2
    assert abs(d["total_s"] - 0.8) < 1e-9
    assert abs(d["interval_mean_s"] - 0.4) < 1e-9
    assert sum(d["hist"]) == 2
    # no previous snapshot: totals ARE the interval
    assert LatencyStats.delta(None, cur)["op"]["count"] == 3
    # counter going backward (rank restarted) reports from zero, never
    # a negative rate
    fresh = LatencyStats()
    fresh.record("op", 0.1)
    d = LatencyStats.delta(cur, fresh.summary(raw=True))["op"]
    assert d["count"] == 1 and d["total_s"] > 0


# ------------------------------------------------------ wire compat matrix


class _LegacyServer:
    """A pre-trace, pre-mux peer: reads CALL frames, uses ONLY
    payload[:3] (unknown meta ignored — the legacy compat contract), and
    answers untagged, in order."""

    def __init__(self):
        self.port = free_port()
        self.metas = []
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("", self.port))
        self._lsock.listen(5)
        threading.Thread(target=self._loop, name="legacy-server",
                         daemon=True).start()

    def _loop(self):
        try:
            conn, _ = self._lsock.accept()
            while True:
                kind, payload = rpc.recv_frame(conn)
                if kind != rpc.KIND_CALL:
                    return
                fname = payload[0]
                self.metas.append(payload[3] if len(payload) > 3 else None)
                rpc.send_frame(conn, rpc.KIND_RESULT, f"legacy:{fname}")
        except (OSError, EOFError):
            pass


def test_traced_mux_client_vs_legacy_server():
    """A traced mux client against an untagged in-order server: the call
    completes FIFO, the unknown trace key is simply ignored, and the
    client still records its own spans."""
    srv = _LegacyServer()
    client = rpc.Client(0, "localhost", srv.port, mux=True)
    tid = spans.mint_trace_id()
    assert client.generic_fun("ping", trace_id=tid) == "legacy:ping"
    meta = srv.metas[0]
    assert meta["trace_id"] == tid and "req_id" in meta
    local = spans.local_buffer().snapshot(tid)
    assert {s["name"] for s in local} == {"client.pack", "client.rpc"}
    client.close()


def test_traced_serial_client_vs_traced_server(tmp_path):
    """DFT_RPC_MUX=0 stub with a trace: the frame grows the meta element
    (trace only — no req_id), the real server attributes queue/device
    spans to it on the legacy sync path."""
    idx, x = make_trained_engine(tmp_path / "s")
    srv, port = start_server(tmp_path, engine=idx)
    client = rpc.Client(1, "localhost", port, mux=False)
    tid = spans.mint_trace_id()
    client.generic_fun("search", ("obs", x[:3], 4), trace_id=tid)
    names = {s["name"] for s in srv.spans.snapshot(tid)}
    assert {"server.queue", "server.device", "server.write"} <= names
    client.close()


def test_no_meta_legacy_frames_still_served(tmp_path):
    """A hand-rolled 3-tuple CALL frame (the pre-deadline, pre-trace
    wire) against the current server: served unchanged."""
    idx, x = make_trained_engine(tmp_path / "s")
    srv, port = start_server(tmp_path, engine=idx)
    sock = socket.create_connection(("localhost", port), timeout=10)
    rpc.send_frame(sock, rpc.KIND_CALL, ("get_rank", (), {}))
    kind, payload = rpc.recv_frame(sock)
    assert (kind, payload) == (rpc.KIND_RESULT, 0)
    rpc.send_frame(sock, rpc.KIND_CLOSE, None)
    sock.close()


def _capture_one_frame(lsock, got):
    conn, _ = lsock.accept()
    buf = b""
    # header + skeleton length is enough to bound the frame (no tensors
    # in a no-arg call)
    while len(buf) < rpc._HDR.size:
        buf += conn.recv(4096)
    _magic, _kind, skel_len, narr = rpc._HDR.unpack(buf[:rpc._HDR.size])
    total = rpc._HDR.size + skel_len
    while len(buf) < total:
        buf += conn.recv(4096)
    got.append(buf[:total])
    rpc.send_frame(conn, rpc.KIND_RESULT, None)
    conn.close()


def test_sampling_off_byte_identity(monkeypatch):
    """The headline cost contract: with DFT_TRACE_SAMPLE=0 the serial
    stub's CALL frame is byte-for-byte the pre-trace wire."""
    monkeypatch.setenv("DFT_TRACE_SAMPLE", "0")
    got = []
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    t = threading.Thread(target=_capture_one_frame, args=(lsock, got),
                         name="frame-capture", daemon=True)
    t.start()
    client = rpc.Client(0, "localhost", port, mux=False)
    client.generic_fun("get_rank", trace_id=spans.maybe_sample())
    client.close()
    t.join(timeout=10)
    lsock.close()
    expected = b"".join(
        bytes(p) for p in rpc.pack_frame(rpc.KIND_CALL, ("get_rank", (), {})))
    assert got and got[0] == expected


# ------------------------------------------------------ prometheus export


def test_render_prometheus_histogram_and_gauges():
    stats = LatencyStats()
    stats.record("queue_wait_s", 2e-6)
    stats.record("queue_wait_s", 5e-6)
    tree = {"scheduler": {"counters": {"queued": 3, "shed_deadline": 1}},
            "ops": stats.summary(raw=True),
            "replication": {"shard_group": None, "note": "skipped"}}
    text = export.render_prometheus(tree, labels={"rank": 2})
    lines = text.splitlines()
    assert 'dft_scheduler_counters_queued{rank="2"} 3' in lines
    assert f'dft_ops_queue_wait_s_count{{rank="2"}} 2' in lines
    # cumulative over the REAL bounds: everything <= 2e-6 has count 1,
    # the +Inf bucket equals the count
    b = [ln for ln in lines if "dft_ops_queue_wait_s_bucket" in ln]
    le_2u = [ln for ln in b if f'le="{bucket_bounds()[3]:.6g}"' in ln]
    assert le_2u and le_2u[0].endswith(" 1")
    assert [ln for ln in b if 'le="+Inf"' in ln][0].endswith(" 2")
    # None / strings never render
    assert "shard_group" not in text and "note" not in text


def test_metrics_exporter_http_lifecycle(tmp_path):
    idx, x = make_trained_engine(tmp_path / "s")
    srv, port = start_server(tmp_path, engine=idx)
    exp = export.MetricsExporter(
        lambda: srv.get_perf_stats(raw=True), port=0, rank=0).start()
    idx.search_batched(x[:2], 3)
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{exp.port}/metrics", timeout=10).read().decode()
    assert 'dft_engine_obs_device_search_s_count{rank="0"}' in body
    assert "dft_tracing_capacity" in body
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/other", timeout=10)
    exp.stop()
    assert not exp._thread.is_alive()


def test_server_metrics_listener_via_env(tmp_path):
    """DFT_METRICS_PORT wiring: base + rank, started with the serving
    socket, surfaced in get_perf_stats, stopped in stop()."""
    base = free_port()
    srv, port = start_server(
        tmp_path, tracing_cfg=TracingCfg(metrics_port=base))
    deadline = time.time() + 10
    while srv._metrics is None and time.time() < deadline:
        time.sleep(0.05)
    assert srv._metrics is not None and srv._metrics.port == base
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{base}/metrics", timeout=10).read().decode()
    assert 'dft_rpc_workers{rank="0"}' in body
    assert srv.get_perf_stats()["tracing"]["metrics_port"] == base
    srv.stop()
    assert srv._metrics is None


# ------------------------------------------- loopback cluster end-to-end


@pytest.fixture
def cluster(tmp_path, monkeypatch):
    """Two ranks serving ONE replica group (R=2) + a client, tracing
    every request. Each rank sits behind a ChaosProxy so a test can kill
    it crash-shaped (connections torn, port refusing) without the
    graceful-stop handshake."""
    from distributed_faiss_tpu.testing.chaos import ChaosProxy

    monkeypatch.setenv("DFT_TRACE_SAMPLE", "1")
    idx_a, x = make_trained_engine(tmp_path / "a", seed=1)
    idx_b, _ = make_trained_engine(tmp_path / "b", seed=1)
    srv_a, port_a = start_server(tmp_path / "a", engine=idx_a)
    srv_b, port_b = start_server(tmp_path / "b", engine=idx_b)
    proxies = (ChaosProxy("127.0.0.1", port_a).start(),
               ChaosProxy("127.0.0.1", port_b).start())
    disc = write_discovery(tmp_path, [p.port for p in proxies])
    client = IndexClient(
        disc, replication_cfg=ReplicationCfg(replication=2, write_quorum=1))
    client.cfg = idx_a.cfg
    # dead-rank drills must fail fast, not burn the full redial budget
    for stub in client.sub_indexes:
        stub.RECONNECT_TIMEOUT = 0.3
    yield {"client": client, "servers": (srv_a, srv_b),
           "proxies": proxies, "ports": tuple(p.port for p in proxies),
           "disc": disc, "x": x}
    client.close()
    for p in proxies:
        p.stop()


def test_trace_end_to_end_accounts_for_latency(cluster):
    """The acceptance gate's core: a traced search's merged timeline
    carries client, queue-wait, device, and write spans whose server-side
    stages nest inside the client's rpc span — the stage sum accounts for
    the observed e2e latency (wire + interpreter overhead is the only
    remainder)."""
    client, x = cluster["client"], cluster["x"]
    tid = spans.mint_trace_id()
    client.search(x[:4], 5, "obs", trace_id=tid)
    timeline = client.get_trace_spans(tid)
    names = [s["name"] for s in timeline]
    for required in ("client.search", "client.pack", "client.rpc",
                     "server.queue", "server.device", "server.write"):
        assert required in names, (required, names)
    by = {}
    for s in timeline:
        by.setdefault(s["name"], []).append(s)
    e2e = by["client.search"][0]["dur_s"]
    rpc_dur = max(s["dur_s"] for s in by["client.rpc"])
    stage_sum = sum(max(s["dur_s"] for s in by[n])
                    for n in ("server.queue", "server.device",
                              "server.write"))
    assert stage_sum <= rpc_dur + 1e-3
    assert rpc_dur <= e2e + 1e-3
    # the stages ACCOUNT for the e2e latency: what's left is wire +
    # interpreter overhead, not an unattributed mystery
    assert e2e - stage_sum < max(0.5, 0.5 * e2e)
    # causality: queue precedes device precedes write on the wall clock
    q, d, w = (by[n][0]["start_s"] for n in ("server.queue",
                                             "server.device",
                                             "server.write"))
    assert q <= d <= w
    # window attribution: the queue span names its merge window/occupancy
    assert by["server.queue"][0]["extra"]["occupancy_rows"] >= 4


def test_trace_survives_rank_failover(cluster):
    """SIGKILL-shaped death of the preferred replica mid-storm: the
    traced search fails over, the timeline records the client.failover
    hop, and the trace fetch itself degrades past the dead rank."""
    client, x = cluster["client"], cluster["x"]
    client.search(x[:2], 3, "obs")  # pin a preferred replica
    with client._stats_lock:
        preferred = dict(client._preferred)
    victim_pos = preferred.get(0, 0)
    # crash-shaped: the proxy tears every connection down and the port
    # starts refusing — no graceful stop handshake
    cluster["proxies"][victim_pos].stop()
    tid = spans.mint_trace_id()
    out = client.search(x[:4], 5, "obs", trace_id=tid)
    assert out[0].shape == (4, 5)
    timeline = client.get_trace_spans(tid)
    names = [s["name"] for s in timeline]
    assert "client.failover" in names
    hop = next(s for s in timeline if s["name"] == "client.failover")
    assert hop["extra"]["replica"] == victim_pos
    # the surviving rank's server spans still made it into the merge
    assert "server.device" in names


def test_exemplar_yields_fetchable_trace(cluster):
    """get_perf_stats -> p99_exemplar -> get_trace_spans: the diagnosis
    loop closes without ever reading a log line."""
    client, x = cluster["client"], cluster["x"]
    for _ in range(4):
        client.search(x[:2], 3, "obs")
    exemplar = None
    for entry in client.get_perf_stats():
        if "error" in entry:
            continue
        exemplar = (entry.get("scheduler", {}).get("queues", {})
                    .get("e2e_s", {}).get("p99_exemplar")) or exemplar
    assert exemplar is not None
    timeline = client.get_trace_spans(exemplar)
    assert any(s["name"] == "server.device" for s in timeline)


def test_perf_stats_degrades_per_dead_rank(cluster):
    """Satellite bugfix: one dead rank must not fail the whole stats
    call — its entry becomes a structured error row, survivors intact."""
    client = cluster["client"]
    cluster["proxies"][1].stop()  # rank 1 dies crash-shaped
    stats = client.get_perf_stats()
    assert len(stats) == 2
    assert "error" not in stats[0] and "scheduler" in stats[0]
    assert "error" in stats[1]
    assert stats[1]["port"] == cluster["ports"][1]


def test_dfstat_stats_and_trace_views(cluster, capsys):
    """The ops CLI end to end over a live loopback cluster: the stats
    view renders per-rank rows + rates via the shared delta math, --json
    parses, and --trace prints the merged causal timeline."""
    client, x, disc = cluster["client"], cluster["x"], cluster["disc"]
    tid = spans.mint_trace_id()
    client.search(x[:4], 5, "obs", trace_id=tid)

    out = io.StringIO()
    assert dfstat.main(["--discovery", disc, "--count", "2",
                        "--interval", "0.2"], out=out) == 0
    text = out.getvalue()
    assert "rank" in text and "srch/s" in text
    assert "DEAD" not in text

    out = io.StringIO()
    assert dfstat.main(["--discovery", disc, "--count", "1", "--json"],
                       out=out) == 0
    doc = json.loads(out.getvalue())
    assert len(doc["ranks"]) == 2
    assert all("search_p99_ms" in r for r in doc["ranks"])

    out = io.StringIO()
    assert dfstat.main(["--discovery", disc, "--trace", tid], out=out) == 0
    trace_text = out.getvalue()
    assert tid in trace_text
    for stage in ("server.queue", "server.device", "server.write"):
        assert stage in trace_text
    # unknown trace: clear message + nonzero exit
    out = io.StringIO()
    assert dfstat.main(["--discovery", disc, "--trace", "deadbeef" * 2],
                       out=out) == 1
    assert "no spans" in out.getvalue()


def test_dfstat_redials_rank_that_came_back(tmp_path):
    """A rank unreachable when dfstat starts (mid-restart) must rejoin
    the view on a later poll — not render DEAD until the CLI restarts."""
    port = free_port()
    disc = write_discovery(tmp_path, [port])
    entries = dfstat._connect(disc, connect_timeout=0.2)
    pool = dfstat._fanout_pool(entries)
    assert entries[0][2] is None
    assert "error" in dfstat.poll(entries, pool)[0]  # still down
    srv = IndexServer(0, str(tmp_path))
    threading.Thread(target=srv.start_blocking, args=(port,),
                     name=f"obs-server:{port}", daemon=True).start()
    assert wait_listening(port)
    cur = dfstat.poll(entries, pool)[0]  # the rank came back: redialed
    assert "error" not in cur and "rpc" in cur
    pool.shutdown(wait=False)
    entries[0][2].close()


# ------------------------------------------------- SIGKILL degrade (slow)


@pytest.mark.slow
def test_perf_stats_degrade_with_sigkilled_rank(tmp_path):
    """The satellite's regression gate with a REAL SIGKILL: stats fan-out
    against a subprocess cluster where one rank dies -9 keeps the
    survivors' stats and reports the corpse as a structured error row."""
    from distributed_faiss_tpu.testing.chaos import ServerHarness

    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(2, disc, storage, base_port=free_port()) as harness:
        client = IndexClient(disc)
        cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                       train_num=32)
        client.create_index("obs", cfg)
        harness.kill(1)
        stats = client.get_perf_stats()
        assert len(stats) == 2
        # discovery order is registration order, not rank order: find the
        # corpse by its port
        dead_port = harness.port(1)
        by_port = {stub.port: entry
                   for stub, entry in zip(client.sub_indexes, stats)}
        assert "error" in by_port[dead_port]
        assert by_port[dead_port]["port"] == dead_port
        survivor = by_port[harness.port(0)]
        assert "error" not in survivor and "scheduler" in survivor
        client.close()
