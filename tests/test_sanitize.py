"""Sanitizer tier: GRAFT_SANITIZE=1 checkify runs (non-default marker).

Marked ``sanitize`` AND ``slow``: tier-1 (``-m 'not slow'``) never pays the
checkify re-trace cost; run explicitly with ``pytest -m sanitize``. The
subprocess test is the satellite the sanitizer exists for — the whole
engine/model suites re-run under NaN + OOB-gather runtime checks.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_faiss_tpu.models.flat import FlatIndex
from distributed_faiss_tpu.models.ivf import IVFFlatIndex, IVFPQIndex
from distributed_faiss_tpu.utils import sanitize

pytestmark = [pytest.mark.sanitize, pytest.mark.slow]


@pytest.fixture
def sanitized(monkeypatch):
    monkeypatch.setenv("GRAFT_SANITIZE", "1")


def _data(n=400, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, d)).astype(np.float32),
            rng.standard_normal((8, d)).astype(np.float32))


def test_enabled_reads_env(monkeypatch):
    monkeypatch.delenv("GRAFT_SANITIZE", raising=False)
    assert not sanitize.enabled()
    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    assert sanitize.enabled()


def test_flat_clean_data_matches_unsanitized(sanitized, monkeypatch):
    x, q = _data()
    idx = FlatIndex(16, "l2")
    idx.add(x)
    d1, i1 = idx.search(q, 5)
    monkeypatch.setenv("GRAFT_SANITIZE", "0")
    d0, i0 = idx.search(q, 5)
    np.testing.assert_array_equal(i1, i0)
    np.testing.assert_allclose(d1, d0)


def test_ivf_flat_clean_data_matches_unsanitized(sanitized, monkeypatch):
    x, q = _data()
    idx = IVFFlatIndex(16, 8, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    d1, i1 = idx.search(q, 5)
    monkeypatch.setenv("GRAFT_SANITIZE", "0")
    d0, i0 = idx.search(q, 5)
    np.testing.assert_array_equal(i1, i0)
    np.testing.assert_allclose(d1, d0)


def test_ivf_pq_clean_data_passes(sanitized):
    x, q = _data(n=600)
    idx = IVFPQIndex(16, 8, m=4)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    d, i = idx.search(q, 5)
    assert np.isfinite(d).all() and (i >= 0).all()


def test_nan_query_raises(sanitized):
    x, q = _data()
    idx = FlatIndex(16, "l2")
    idx.add(x)
    qb = q.copy()
    qb[0, 0] = np.nan
    with pytest.raises(Exception, match="(?i)nan"):
        idx.search(qb, 5)


def test_nan_query_raises_ivf(sanitized):
    x, q = _data()
    idx = IVFFlatIndex(16, 8, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    qb = q.copy()
    qb[0, 0] = np.nan
    with pytest.raises(Exception, match="(?i)nan"):
        idx.search(qb, 5)


def test_engine_and_models_suites_under_sanitizer():
    """The sanitizer-tier satellite: re-run test_engine.py + test_models.py
    with GRAFT_SANITIZE=1 — every jitted scan/search those suites drive
    runs under checkify NaN/OOB checks."""
    env = dict(os.environ, GRAFT_SANITIZE="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_engine.py",
         "tests/test_models.py", "-q", "-m", "not slow",
         "-p", "no:cacheprovider"],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, (
        f"sanitized suite failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
