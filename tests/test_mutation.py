"""Mutable corpora (mutation subsystem): tombstone deletes, upsert, the
crash-safe sidecar protocol, and background compaction — engine + model
layer. Fast tests run in tier-1; the marker mirrors the other subsystem
tiers (CI job ``mutation``)."""

import glob
import os
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.utils import racecheck

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.models.flat import FlatIndex
from distributed_faiss_tpu.models.ivf import IVFFlatIndex, IVFPQIndex
from distributed_faiss_tpu.mutation import compaction, tombstones
from distributed_faiss_tpu.mutation.tombstones import TombstoneSet
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import IndexCfg, MutationCfg

pytestmark = pytest.mark.mutation

DIM = 16


@pytest.fixture
def rng():
    return np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _no_background_compaction(monkeypatch):
    """Deterministic tests drive compaction explicitly; the watcher tier
    has its own test below."""
    monkeypatch.setenv("DFT_COMPACT", "0")


def flat_cfg(tmp_path, **kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", DIM)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 10)
    kw.setdefault("index_storage_dir", str(tmp_path / "shard"))
    return IndexCfg(**kw)


def build_engine(tmp_path, rng, n=200, **kw):
    cfg = flat_cfg(tmp_path, **kw)
    idx = Index(cfg)
    x = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(x, [(i,) for i in range(n)], train_async_if_triggered=False)
    wait_drained(idx, n)
    return idx, x


def wait_drained(idx, n, timeout=30.0):
    from distributed_faiss_tpu.utils.state import IndexState

    deadline = time.time() + timeout
    while time.time() < deadline:
        # wait for the ADD->TRAINED flip too: the drain worker zeroes
        # the buffer count BEFORE leaving ADD, and a test that then
        # forces/reads engine state would race the worker's final flip
        if (idx.get_idx_data_num() == (0, n)
                and idx.get_state() == IndexState.TRAINED):
            return
        time.sleep(0.02)
    raise AssertionError(f"engine never drained to {n} rows: "
                         f"{idx.get_idx_data_num()} ({idx.get_state()})")


# ------------------------------------------------------------ model layer


@pytest.mark.parametrize("metric", ["l2", "dot"])
def test_flat_delete_matches_rebuilt_index(rng, metric):
    """The delete-then-search byte-identity gate: a masked index answers
    exactly like an index freshly built over the surviving rows."""
    x = rng.standard_normal((300, DIM)).astype(np.float32)
    q = x[:6]
    idx = FlatIndex(DIM, metric)
    idx.train(x)
    idx.add(x)
    d_before, _ = idx.search(q, 8)
    dead = np.arange(0, 90)
    idx.remove_rows(dead)
    d, i = idx.search(q, 8)
    assert not np.intersect1d(i.ravel(), dead).size
    fresh = FlatIndex(DIM, metric)
    fresh.train(x)
    fresh.add(x[90:])
    d2, i2 = fresh.search(q, 8)
    np.testing.assert_array_equal(d, d2)
    np.testing.assert_array_equal(i - 90, i2)
    # idempotent: re-deleting changes nothing
    idx.remove_rows(dead[:10])
    d3, i3 = idx.search(q, 8)
    np.testing.assert_array_equal(d, d3)
    np.testing.assert_array_equal(i, i3)


def test_delete_nothing_is_byte_identical(rng):
    """remove_rows([]) leaves the live mask unmaterialized: the exact
    pre-mutation program serves, byte-identical results."""
    x = rng.standard_normal((200, DIM)).astype(np.float32)
    idx = FlatIndex(DIM, "l2")
    idx.train(x)
    idx.add(x)
    d0, i0 = idx.search(x[:4], 5)
    idx.remove_rows(np.zeros(0, np.int64))
    assert idx.store.live is None
    d1, i1 = idx.search(x[:4], 5)
    np.testing.assert_array_equal(d0, d1)
    np.testing.assert_array_equal(i0, i1)


def test_ivf_flat_delete_matches_rebuilt(rng):
    """IVF tombstones ride the device ids plane (-1 == padding to every
    scan entry): full-probe masked search equals a rebuild over the
    survivors with the same centroids."""
    x = rng.standard_normal((400, DIM)).astype(np.float32)
    q = x[:6]
    idx = IVFFlatIndex(DIM, 8, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    dead = np.arange(17, 140)
    idx.remove_rows(dead)
    d, i = idx.search(q, 10)
    assert not np.intersect1d(i.ravel(), dead).size
    keep = np.ones(400, bool)
    keep[dead] = False
    fresh = IVFFlatIndex(DIM, 8, "l2")
    fresh.train(x)  # same seeded k-means -> same centroids
    fresh.add(x[keep])
    fresh.set_nprobe(8)
    d2, _ = fresh.search(q, 10)
    np.testing.assert_array_equal(d, d2)


def test_ivf_pq_delete_never_surfaces(rng):
    x = rng.standard_normal((600, 32)).astype(np.float32)
    idx = IVFPQIndex(32, 4, m=8)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    dead = np.arange(0, 200)
    idx.remove_rows(dead)
    _, i = idx.search(x[:8], 10)
    assert not np.intersect1d(i.ravel(), dead).size


def test_k_exceeding_live_rows_returns_sentinels(rng):
    x = rng.standard_normal((10, DIM)).astype(np.float32)
    idx = FlatIndex(DIM, "l2")
    idx.train(x)
    idx.add(x)
    idx.remove_rows(np.arange(7))
    d, i = idx.search(x[:3], 8)
    # 3 live rows, k=8: the tail is -1/inf, no deleted id ever surfaces
    assert (i[:, 3:] == -1).all()
    assert not np.intersect1d(i.ravel(), np.arange(7)).size


def test_unsupported_kind_raises_cleanly():
    from distributed_faiss_tpu.models import base

    class Stub(base.TpuIndex):
        def __init__(self):
            super().__init__(4, "l2")

    with pytest.raises(RuntimeError, match="does not support remove"):
        Stub().remove_rows(np.arange(3))


def test_compact_state_filters_and_rejects(rng):
    x = rng.standard_normal((100, DIM)).astype(np.float32)
    idx = FlatIndex(DIM, "l2")
    idx.train(x)
    idx.add(x)
    keep = np.ones(100, bool)
    keep[::2] = False
    out = compaction.compact_state(idx.state_dict(), keep)
    assert out["ntotal"] == 50 and out["data"].shape[0] == 50
    with pytest.raises(compaction.CompactionUnsupported):
        compaction.compact_state({"kind": "hnswsq"}, keep)
    with pytest.raises(ValueError):
        compaction.compact_state(idx.state_dict(), keep[:10])


def test_tombstone_payload_round_trip():
    t = TombstoneSet({3: (3,), 7: None}, layout=5)
    p = t.to_payload()
    t2 = TombstoneSet.from_payload(p)
    assert sorted(t2.rows()) == [3, 7] and t2.layout == 5
    t2.merge_payload({"dead_rows": [7, 9], "dead_ids": ["x", "y"]})
    assert sorted(t2.rows()) == [3, 7, 9]
    assert 9 in t2 and len(t2) == 3
    # arbitrary ids survive the dump (tuples as JSON arrays, objects via
    # default=str), and json.loads round-trips the payload
    import json

    loaded = json.loads(tombstones.dump_payload(p))
    assert loaded["dead_rows"] == [3, 7] and loaded["dead_ids"] == [[3], None]


def test_mutation_cfg_validation(monkeypatch):
    assert MutationCfg().threshold == 0.25
    monkeypatch.setenv("DFT_COMPACT_THRESHOLD", "0.5")
    monkeypatch.setenv("DFT_COMPACT", "0")
    cfg = MutationCfg.from_env()
    assert cfg.threshold == 0.5 and not cfg.compact
    with pytest.raises(ValueError):
        MutationCfg(threshold=1.5)
    with pytest.raises(ValueError):
        MutationCfg(interval_s=0)


# ------------------------------------------------------------ engine layer


def test_engine_remove_ids_and_get_ids(tmp_path, rng):
    idx, x = build_engine(tmp_path, rng)
    assert idx.remove_ids([5, 6, 7]) == 3
    assert idx.remove_ids([5, 6]) == 0  # already dead
    assert idx.remove_ids([]) == 0
    d, m, _ = idx.search(x[5:8], 4)
    dead_meta = {(5,), (6,), (7,)}
    assert not any(mm in dead_meta for row in m for mm in row)
    assert idx.get_ids() == set(range(200)) - {5, 6, 7}
    st = idx.mutation_stats()
    assert st["tombstoned_rows"] == 3
    assert st["live_fraction"] == pytest.approx(197 / 200)
    # the delete was durable before remove_ids returned
    side = tombstones.load_sidecar(idx.cfg.index_storage_dir)
    assert sorted(side["dead_rows"]) == [5, 6, 7]


def test_engine_upsert_visibility_ordering(tmp_path, rng):
    """Old row stops serving when upsert returns; the new row serves after
    its drain — never both."""
    idx, x = build_engine(tmp_path, rng)
    q = x[42:43]
    _, m0, _ = idx.search(q, 1)
    assert m0[0][0] == (42,)
    new_vec = -x[42:43]  # far from the old one
    assert idx.upsert([42], new_vec, [(42,)]) == 1
    # from the moment upsert returns, the OLD row never serves again —
    # poll through the engine's transient mid-ADD rejection (the drain
    # window clients fail over across; see parallel/replication.py)
    saw_new = False
    deadline = time.time() + 30
    while time.time() < deadline and not saw_new:
        try:
            _, m1, _ = idx.search(np.concatenate([q, new_vec]), 1)
        except RuntimeError as e:
            assert "not trained" in str(e)
            time.sleep(0.01)
            continue
        assert m1[0][0] != (42,), "old row resurfaced after upsert"
        saw_new = m1[1][0] == (42,)
    assert saw_new, "new row never became visible"
    wait_drained(idx, 201)
    # exactly one live row carries the id
    d3, m3, _ = idx.search(np.concatenate([q, new_vec]), 3)
    flat = [mm for row in m3 for mm in row if mm == (42,)]
    assert len(flat) == 1


def test_engine_buffered_delete_never_serves(tmp_path, rng):
    """An id still in the add buffer at delete time is dropped when its
    chunk drains — it never serves."""
    cfg = flat_cfg(tmp_path, train_num=0)
    idx = Index(cfg)
    x = rng.standard_normal((50, DIM)).astype(np.float32)
    # NOT_TRAINED: everything sits in the buffer
    idx.add_batch(x, [(i,) for i in range(50)],
                  train_async_if_triggered=False)
    assert idx.remove_ids([3, 4]) == 2
    idx.cfg.train_num = 10
    idx.train()
    wait_drained(idx, 50)
    d, m, _ = idx.search(x[3:5], 3)
    assert not any(mm in {(3,), (4,)} for row in m for mm in row)
    assert idx.get_ids() == set(range(50)) - {3, 4}


def test_scheduler_window_sees_consistent_tombstone_snapshot(tmp_path, rng):
    """No torn mask mid-window: a batched window of IDENTICAL queries must
    return identical rows even while deletes land concurrently — the mask
    scatter and the device launch serialize on index_lock."""
    idx, x = build_engine(tmp_path, rng, n=150)
    q = np.tile(x[100:101], (24, 1))
    stop = threading.Event()
    bad = []

    def storm():
        while not stop.is_set():
            try:
                d, m, _ = idx.search_batched(q, 5)
            except Exception as e:  # pragma: no cover - fails the test below
                bad.append(repr(e))
                return
            for r in range(1, q.shape[0]):
                if m[r] != m[0] or not np.array_equal(d[r], d[0]):
                    bad.append((m[0], m[r]))
                    return

    threads = [threading.Thread(target=storm) for _ in range(3)]
    for t in threads:
        t.start()
    for victim in range(0, 40):
        idx.remove_ids([victim])
        time.sleep(0.005)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not bad, f"torn window observed: {bad[:2]}"


# -------------------------------------------------- persistence + fallback


def test_sidecar_survives_save_load(tmp_path, rng):
    idx, x = build_engine(tmp_path, rng)
    idx.remove_ids([1, 2, 3])
    d0, m0, _ = idx.search(x[:4], 5)
    idx.save()
    idx2 = Index.from_storage_dir(idx.cfg.index_storage_dir, ignore_buffer=False)
    d1, m1, _ = idx2.search(x[:4], 5)
    np.testing.assert_array_equal(d0, d1)
    assert m0 == m1
    assert idx2.mutation_stats()["tombstoned_rows"] == 3


def test_delete_after_save_survives_crash_without_new_save(tmp_path, rng):
    """The standalone sidecar alone carries deletes made after the last
    committed generation (the SIGKILL-right-after-remove_ids case)."""
    idx, x = build_engine(tmp_path, rng)
    idx.save()
    idx.remove_ids([10, 11])  # NO save afterwards — simulated crash
    idx2 = Index.from_storage_dir(idx.cfg.index_storage_dir, ignore_buffer=False)
    _, m, _ = idx2.search(x[10:12], 3)
    assert not any(mm in {(10,), (11,)} for row in m for mm in row)
    assert idx2.mutation_stats()["tombstoned_rows"] == 2


def test_sidecar_survives_torn_generation_fallback(tmp_path, rng):
    """Quarantine + fallback to the previous generation must keep every
    delete: same-layout positions apply directly; positions keyed to a
    layout that tore re-apply BY ID."""
    idx, x = build_engine(tmp_path, rng)
    idx.remove_ids([1, 2])
    idx.save()
    assert idx.compact()
    idx.remove_ids([30, 31])  # recorded against the compacted layout
    storage = idx.cfg.index_storage_dir
    gen, mpath = serialization.list_generations(storage)[0]
    manifest = serialization.load_manifest(mpath)
    with open(os.path.join(storage, manifest["files"]["index"]["name"]),
              "ab") as f:
        f.write(b"torn")
    idx2 = Index.from_storage_dir(storage, ignore_buffer=False)
    st = idx2.mutation_stats()
    assert st["load_fallbacks"] == 1
    assert st["tombstoned_rows"] == 4  # 2 positional + 2 by-id
    _, m, _ = idx2.search(x[[1, 2, 30, 31]], 3)
    deadm = {(1,), (2,), (30,), (31,)}
    assert not any(mm in deadm for row in m for mm in row)
    # the torn generation is evidence, not garbage
    assert os.path.isdir(os.path.join(storage, "quarantine"))


# ------------------------------------------------------------- compaction


def test_compaction_reclaims_and_preserves_results(tmp_path, rng):
    idx, x = build_engine(tmp_path, rng)
    dead = list(range(0, 60))
    idx.remove_ids(dead)
    d0, m0, _ = idx.search(x[:6], 8)
    assert idx.tombstone_fraction() == pytest.approx(0.3)
    assert idx.compact()
    assert idx.tombstone_fraction() == 0.0
    with idx.index_lock:  # white-box peek rides the pinned lock (racecheck)
        assert idx.tpu_index.ntotal == 140
    d1, m1, _ = idx.search(x[:6], 8)
    np.testing.assert_array_equal(d0, d1)
    assert m0 == m1
    st = idx.mutation_stats()
    assert st["compactions"] == 1 and st["layout_generation"] >= 1
    assert "compaction_s" in st
    # the compacted generation reloads byte-identically
    idx2 = Index.from_storage_dir(idx.cfg.index_storage_dir,
                                  ignore_buffer=False)
    d2, m2, _ = idx2.search(x[:6], 8)
    np.testing.assert_array_equal(d1, d2)
    assert m1 == m2
    assert idx2.mutation_stats()["tombstoned_rows"] == 0
    # compacting with nothing dead is a no-op
    assert not idx.compact()


def test_compaction_matches_freshly_built_index(tmp_path, rng):
    idx, x = build_engine(tmp_path, rng)
    idx.remove_ids(list(range(50, 120)))
    assert idx.compact()
    d, m, _ = idx.search(x[:5], 6)
    keep = [i for i in range(200) if not 50 <= i < 120]
    fresh = FlatIndex(DIM, "l2")
    fresh.train(x)
    fresh.add(x[keep])
    df, idf = fresh.search(x[:5], 6)
    np.testing.assert_array_equal(d, df)  # engine D == model D (l2)
    assert [[(keep[j],) for j in row] for row in idf.tolist()] == m


def test_compaction_composes_with_later_adds_and_deletes(tmp_path, rng):
    """Deletes and adds around a compaction keep positional integrity:
    the layout renumbers, metadata follows, later ids stay correct."""
    idx, x = build_engine(tmp_path, rng, n=100)
    extra = rng.standard_normal((20, DIM)).astype(np.float32)
    idx.remove_ids(list(range(0, 30)))
    idx.add_batch(extra, [(100 + i,) for i in range(20)],
                  train_async_if_triggered=False)
    wait_drained(idx, 120)
    idx.remove_ids([105])
    assert idx.compact()
    _, m, _ = idx.search(extra[5:6], 3)
    assert not any(mm == (105,) for row in m for mm in row)
    assert idx.get_ids() == (set(range(100, 120)) | set(range(30, 100))) - {105}


def test_background_watcher_compacts_over_threshold(tmp_path, rng, monkeypatch):
    monkeypatch.setenv("DFT_COMPACT", "1")
    monkeypatch.setenv("DFT_COMPACT_INTERVAL", "0.2")
    monkeypatch.setenv("DFT_COMPACT_THRESHOLD", "0.25")
    idx, x = build_engine(tmp_path, rng, n=100)
    assert idx.mutation_cfg.compact
    idx.remove_ids(list(range(40)))  # 0.4 > threshold
    deadline = time.time() + 30
    while idx.mutation_stats()["compactions"] < 1:
        assert time.time() < deadline, "watcher never compacted"
        time.sleep(0.05)
    assert idx.tombstone_fraction() == 0.0
    # retire stops the watcher (rides the same event as the save watcher)
    idx.retire()


def test_sigkill_mid_compaction_falls_back_with_tombstones(tmp_path, rng):
    """In-process stand-in for the chaos gate's crash window: a compaction
    that never reaches its commit leaves the previous generation + sidecar
    pair fully intact."""
    idx, x = build_engine(tmp_path, rng)
    idx.remove_ids(list(range(0, 60)))
    idx.save()
    storage = idx.cfg.index_storage_dir
    gens_before = serialization.list_generations(storage)
    # simulate the kill: run phases 1-2, then DON'T commit (the chaos test
    # kills the real process inside DFT_COMPACT_TEST_DELAY_S; here we just
    # never call compact). The on-disk state is exactly what a mid-phase-2
    # kill leaves: last generation + sidecar.
    idx2 = Index.from_storage_dir(storage, ignore_buffer=False)
    assert serialization.list_generations(storage)[0][0] == gens_before[0][0]
    assert idx2.mutation_stats()["tombstoned_rows"] == 60
    d, m, _ = idx2.search(x[:4], 5)
    dead = {(i,) for i in range(60)}
    assert not any(mm in dead for row in m for mm in row)


# ------------------------------------------- review regressions (PR 9)


def test_buffered_delete_on_unsupported_kind_rejected_up_front(
        tmp_path, rng, monkeypatch):
    """A delete whose rows are ALL still buffered must raise on an index
    kind without a tombstone mask — BEFORE any tombstone is recorded.
    Accepting it used to kill the drain worker at mask time (base-class
    remove_rows raise) and wedge the engine in ADD forever."""
    from distributed_faiss_tpu.models import hnsw

    # pretend the native graph is available so the hnswsq builder resolves
    # to the maskless HNSWSQIndex instead of the FlatIndex fallback
    monkeypatch.setattr(hnsw, "native_available", lambda: True)
    cfg = flat_cfg(tmp_path, index_builder_type="hnswsq", train_num=1000)
    idx = Index(cfg)
    x = rng.standard_normal((20, DIM)).astype(np.float32)
    idx.add_batch(x, [(i,) for i in range(20)],
                  train_async_if_triggered=False)
    assert idx.tpu_index is None  # below train_num: everything buffered
    with pytest.raises(RuntimeError, match="does not support remove"):
        idx.remove_ids([3, 5])
    with racecheck.peeking():  # white-box peek, reviewed
        assert len(idx.tombstones) == 0  # nothing recorded — drain stays safe


def test_trained_unsupported_kind_rejects_buffered_only_delete(
        tmp_path, rng):
    """Same contract on a TRAINED engine: even when every matching row is
    buffered (no device mask would happen in the call), an index instance
    without remove_rows support rejects up front."""
    from distributed_faiss_tpu.models import base

    idx, x = build_engine(tmp_path, rng, n=30)

    class Maskless(base.TpuIndex):
        def __init__(self, inner):
            super().__init__(inner.dim, inner.metric)
            self._inner = inner

        @property
        def ntotal(self):
            return self._inner.ntotal

    with idx.index_lock:
        idx.tpu_index = Maskless(idx.tpu_index)
    with pytest.raises(RuntimeError, match="does not support remove"):
        idx.remove_ids([0])
    with racecheck.peeking():  # white-box peek, reviewed
        assert len(idx.tombstones) == 0


def test_pretransform_delegates_tombstone_mask(rng):
    """PCA/OPQ wrappers pass the positional mask through to the inner
    index (the transform maps vectors, not row slots)."""
    from distributed_faiss_tpu.models.pretransform import PreTransformIndex

    x = rng.standard_normal((80, DIM)).astype(np.float32)
    inner = FlatIndex(8, "l2")
    idx = PreTransformIndex(inner, DIM, pca=True)
    idx.train(x)
    idx.add(x)
    assert idx.supports_remove_rows()
    idx.remove_rows(np.arange(10))
    _, i = idx.search(x[:5], 8)
    assert not np.intersect1d(np.asarray(i).ravel(), np.arange(10)).size


def test_drain_rejection_matches_failover_classifier(tmp_path, rng):
    """The replicated read path's drain-failover matcher is built from the
    SAME format string the engine raises with — this pins the two against
    drift (a reword used to silently disable failover)."""
    from distributed_faiss_tpu.parallel import replication, rpc
    from distributed_faiss_tpu.utils.state import IndexState

    idx, x = build_engine(tmp_path, rng, n=30)
    with idx.index_lock:
        idx.state = IndexState.ADD
    with pytest.raises(RuntimeError) as ei:
        idx.search_batched(x[:2], 3)
    assert replication.drain_failover_eligible(
        rpc.ServerException(str(ei.value)))
    with idx.index_lock:
        idx.state = IndexState.TRAINED


def test_sidecar_version_gate_keeps_last_writer_correct(tmp_path, rng):
    """The sidecar write happens OUTSIDE the serving locks; the version
    gate must drop a stale writer that lost the race to a newer payload
    (the newer one is always a superset)."""
    idx, x = build_engine(tmp_path, rng, n=40)
    idx.remove_ids([0])
    with idx.buffer_lock, idx.index_lock:
        idx.tombstones.add([1], [(1,)])
        p1, v1 = idx._tombstone_payload_locked()
        idx.tombstones.add([2], [(2,)])
        p2, v2 = idx._tombstone_payload_locked()
    idx._write_tombstone_sidecar(p2, v2)   # newer lands first
    idx._write_tombstone_sidecar(p1, v1)   # stale writer must skip
    side = tombstones.load_sidecar(idx.cfg.index_storage_dir)
    assert set(side["dead_rows"]) == {0, 1, 2}


def test_sidecar_still_durable_before_remove_returns(tmp_path, rng):
    """Moving the fsync off the serving locks must not move it past the
    ack: the sidecar on disk reflects the delete when remove_ids
    returns."""
    idx, x = build_engine(tmp_path, rng, n=40)
    idx.remove_ids([4, 7])
    side = tombstones.load_sidecar(idx.cfg.index_storage_dir)
    assert {4, 7} <= set(side["dead_rows"])


def test_sharded_remove_rows_masks_across_the_mesh(rng):
    """The sharded ids-plane mask (ShardedPaddedLists.mask_cells) splits
    the flat cell address into (chip, local position) host-side in int64
    — a global address over a big padded plane can exceed int32, and a
    silent wrap used to drop the delete on device."""
    from distributed_faiss_tpu.parallel.mesh import (
        ShardedIVFFlatIndex,
        make_mesh,
    )

    x = rng.standard_normal((600, DIM)).astype(np.float32)
    idx = ShardedIVFFlatIndex(DIM, 8, "l2", mesh=make_mesh(8))
    idx.train(x)
    idx.add(x)
    _, i0 = idx.search(x[:10], 5)
    assert (np.asarray(i0)[:, 0] == np.arange(10)).all()
    idx.remove_rows(np.arange(10))
    _, i1 = idx.search(x[:10], 5)
    assert not np.intersect1d(np.asarray(i1).ravel(), np.arange(10)).size
