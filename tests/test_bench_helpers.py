"""Numpy-only sanity checks for the benchmark harness helpers.

These pin the CPU baseline implementations (the denominators of every
vs_cpu_* ratio in benchmarks/RESULTS.md) without touching jax.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.baseline_configs import (  # noqa: E402
    cpu_exact_qps,
    cpu_ivf_qps,
    make_lowrank_corpus,
    recall_at_k,
)


def _exact_topk(x, q, k, metric):
    if metric == "l2":
        d2 = (x * x).sum(1)[None, :] - 2.0 * (q @ x.T)
    else:
        d2 = -(q @ x.T)
    part = np.argpartition(d2, k - 1, axis=1)[:, :k]
    pd = np.take_along_axis(d2, part, axis=1)
    return np.take_along_axis(part, np.argsort(pd, axis=1), axis=1)


def test_cpu_ivf_qps_runs_and_full_probe_is_positive():
    rng = np.random.default_rng(0)
    n, d, nlist, k = 5000, 16, 32, 5
    cents = rng.standard_normal((nlist, d)).astype(np.float32)
    x = rng.standard_normal((n, d)).astype(np.float32)
    assign = ((x[:, None, :] - cents[None]) ** 2).sum(2).argmin(1)
    q = rng.standard_normal((8, d)).astype(np.float32)
    for metric in ("l2", "dot"):
        assert cpu_ivf_qps(x, cents, assign, q, k, 4, metric) > 0
        assert cpu_ivf_qps(x, cents, assign, q, k, nlist, metric) > 0


def test_lowrank_corpus_shapes_and_rank():
    rng = np.random.default_rng(1)
    gen = make_lowrank_corpus(rng, d=64, r=8, n_latent_clusters=16)
    x = gen(500)
    assert x.shape == (500, 64) and x.dtype == np.float32
    # energy concentrates in ~r directions (ambient noise is 0.05)
    s = np.linalg.svd(x - x.mean(0), compute_uv=False)
    assert s[7] > 10 * s[8]


def test_recall_and_exact_helpers_agree():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2000, 8)).astype(np.float32)
    q = rng.standard_normal((16, 8)).astype(np.float32)
    gt = _exact_topk(x, q, 5, "l2")
    assert recall_at_k(gt, gt, 5) == 1.0
    assert cpu_exact_qps(x, q, 5, "l2") > 0


def test_bench_artifact_degraded_on_cpu_fallback():
    """A relay-death fallback must flag itself instead of printing a ratio
    that reads as a perf regression (BENCH_r02..r04 all showed ~1.0)."""
    import bench

    degraded = bench.format_result(
        backend="cpu-fallback(TPU relay unavailable)", rec=0.96, n=50_000,
        d=128, nprobe=8, build_s=12.0, tpu_qps=900.0, cpu_qps=910.0,
    )
    assert degraded["backend_degraded"] is True
    assert degraded["vs_baseline"] is None
    assert "degraded" in degraded["metric"]
    assert "0.99" in degraded["metric"]  # ratio stays inspectable

    healthy = bench.format_result(
        backend="tpu", rec=0.96, n=500_000, d=128, nprobe=8,
        build_s=30.0, tpu_qps=9000.0, cpu_qps=900.0,
    )
    assert "backend_degraded" not in healthy
    assert healthy["vs_baseline"] == 10.0
