"""Torn-snapshot-proof persistence: manifest write/verify helpers, engine
generation saves, checksum-verified loads with fallback to the previous
complete generation, quarantine (rename-never-delete), and a deterministic
corruption sweep standing in for kill -9 at every byte offset (the
real-SIGKILL loop lives in tests/test_chaos.py)."""

import os
import shutil
import time

import numpy as np
import pytest

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.state import IndexState


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", 16)
    kw.setdefault("metric", "l2")
    return IndexCfg(**kw)


def wait_state(idx, state, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if idx.get_state() == state:
            return True
        time.sleep(0.02)
    return False


def build_saved(tmp_path, rng, rows=60, saves=1):
    storage = str(tmp_path / "shard")
    idx = Index(flat_cfg(train_num=20, index_storage_dir=storage))
    x = rng.standard_normal((rows, 16)).astype(np.float32)
    idx.add_batch(x[:rows // 2], [("m", i) for i in range(rows // 2)],
                  train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    assert idx.save() is True
    for s in range(1, saves):
        lo = rows // 2 + (s - 1) * (rows // (2 * max(1, saves - 1)))
        hi = min(rows, lo + rows // (2 * max(1, saves - 1)))
        idx.add_batch(x[lo:hi], [("m", i) for i in range(lo, hi)],
                      train_async_if_triggered=False)
        deadline = time.time() + 60
        while idx.get_idx_data_num()[0] > 0:
            assert time.time() < deadline
            time.sleep(0.02)
        assert wait_state(idx, IndexState.TRAINED)
        assert idx.save() is True
    return storage, idx, x


# ----------------------------------------------------- serialization helpers


def test_atomic_write_returns_digest_of_published_bytes(tmp_path):
    p = str(tmp_path / "f.bin")
    digest = serialization.atomic_write(p, lambda f: f.write(b"payload"), "wb")
    assert digest == serialization.sha256_file(p)
    assert not os.path.exists(p + ".tmp")


def test_manifest_round_trip_and_verify(tmp_path):
    d = str(tmp_path)
    name = serialization.generation_filename("index", 3, "npz")
    assert name == "index-g00000003.npz"
    digest = serialization.atomic_write(
        os.path.join(d, name), lambda f: f.write(b"abc"), "wb")
    mpath = serialization.write_manifest(
        d, 3, {"index": {"name": name, "sha256": digest}}, extra={"ntotal": 9})
    manifest = serialization.load_manifest(mpath)
    assert manifest["generation"] == 3 and manifest["ntotal"] == 9
    assert serialization.verify_manifest(d, manifest) == []
    # flip one byte -> verify names the file and the mismatch
    with open(os.path.join(d, name), "r+b") as f:
        f.write(b"x")
    errors = serialization.verify_manifest(d, manifest)
    assert len(errors) == 1 and "sha256 mismatch" in errors[0]
    os.unlink(os.path.join(d, name))
    assert "missing" in serialization.verify_manifest(d, manifest)[0]


def test_list_generations_newest_first(tmp_path):
    d = str(tmp_path)
    for g in (1, 3, 2):
        serialization.write_manifest(d, g, {})
    assert [g for g, _ in serialization.list_generations(d)] == [3, 2, 1]
    assert serialization.list_generations(str(tmp_path / "nope")) == []


# ------------------------------------------------------------- engine saves


def test_save_writes_generation_and_manifest(tmp_path, rng):
    storage, idx, _ = build_saved(tmp_path, rng)
    gens = serialization.list_generations(storage)
    assert [g for g, _ in gens] == [1]
    manifest = serialization.load_manifest(gens[0][1])
    assert serialization.verify_manifest(storage, manifest) == []
    # the mutation tombstone sidecar is part of every committed set
    assert set(manifest["files"]) == {"index", "meta", "buffer", "cfg",
                                      "tombstones"}
    assert manifest["ntotal"] == idx.tpu_index.ntotal
    # unversioned cfg.json convenience copy for get_config_path readers
    assert os.path.isfile(os.path.join(storage, "cfg.json"))


def test_repeated_saves_prune_to_two_generations(tmp_path, rng):
    storage, idx, _ = build_saved(tmp_path, rng, rows=90, saves=3)
    gens = serialization.list_generations(storage)
    assert [g for g, _ in gens] == [3, 2]  # keep=2: newest + fallback
    # pruned generation-1 files are GONE (they were committed, not torn:
    # deletion, not quarantine)
    assert not any("g00000001" in n for n in os.listdir(storage))
    assert not os.path.isdir(os.path.join(storage, "quarantine"))
    loaded = Index.from_storage_dir(storage)
    assert loaded.tpu_index.ntotal == idx.tpu_index.ntotal
    assert loaded._generation == 3


def test_load_round_trip_newest_generation(tmp_path, rng):
    storage, idx, x = build_saved(tmp_path, rng, rows=80, saves=2)
    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded.get_state() == IndexState.TRAINED
    s0, m0, _ = idx.search(x[:3], 4)
    s1, m1, _ = loaded.search(x[:3], 4)
    np.testing.assert_allclose(s0, s1, rtol=1e-5)
    assert m0 == m1


# ------------------------------------------- fallback + quarantine semantics


def corrupt(path, offset=None):
    size = os.path.getsize(path)
    offset = size // 2 if offset is None else min(offset, size - 1)
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def newest_files(storage):
    gens = serialization.list_generations(storage)
    manifest = serialization.load_manifest(gens[0][1])
    return gens[0][0], gens[0][1], manifest


def test_corrupt_newest_falls_back_and_quarantines(tmp_path, rng):
    storage, idx, x = build_saved(tmp_path, rng, rows=80, saves=2)
    gen, mpath, manifest = newest_files(storage)
    assert gen == 2
    victim = manifest["files"]["index"]["name"]
    corrupt(os.path.join(storage, victim))

    loaded = Index.from_storage_dir(storage)
    assert loaded is not None, "fallback generation must load"
    assert loaded._generation == 1
    scores, meta, _ = loaded.search(x[:2], 3)
    assert all(m is not None for row in meta for m in row)
    # the torn set moved to quarantine/ — renamed, never deleted
    qdir = os.path.join(storage, "quarantine")
    quarantined = os.listdir(qdir)
    assert victim in quarantined
    assert os.path.basename(mpath) in quarantined
    assert not os.path.exists(os.path.join(storage, victim))


def test_missing_file_in_newest_falls_back(tmp_path, rng):
    storage, _, _ = build_saved(tmp_path, rng, rows=80, saves=2)
    _, _, manifest = newest_files(storage)
    os.unlink(os.path.join(storage, manifest["files"]["meta"]["name"]))
    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded._generation == 1


def test_unreadable_manifest_falls_back(tmp_path, rng):
    storage, _, _ = build_saved(tmp_path, rng, rows=80, saves=2)
    _, mpath, _ = newest_files(storage)
    with open(mpath, "w") as f:
        f.write('{"generation": 2, "files"')  # torn json
    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded._generation == 1


def test_both_generations_torn_returns_none(tmp_path, rng):
    storage, _, _ = build_saved(tmp_path, rng, rows=80, saves=2)
    for _, mpath in serialization.list_generations(storage):
        manifest = serialization.load_manifest(mpath)
        corrupt(os.path.join(storage, manifest["files"]["index"]["name"]))
    assert Index.from_storage_dir(storage) is None
    # nothing deleted: every generation is in quarantine for forensics
    qdir = os.path.join(storage, "quarantine")
    assert len([n for n in os.listdir(qdir) if "index-" in n]) == 2


def test_uncommitted_newer_files_are_quarantined_not_loaded(tmp_path, rng):
    """A crash between data writes and the manifest leaves generation-N+1
    data files with no manifest: load must serve generation N and sweep the
    orphans aside."""
    storage, idx, _ = build_saved(tmp_path, rng)
    orphan = serialization.generation_filename("index", 2, "npz")
    with open(os.path.join(storage, orphan), "wb") as f:
        f.write(b"partial write before crash")
    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded._generation == 1
    assert os.path.exists(os.path.join(storage, "quarantine", orphan))
    assert not os.path.exists(os.path.join(storage, orphan))


def test_save_after_fallback_recycles_generation_number(tmp_path, rng):
    """After loading the fallback (gen 1 of 2), the next save commits a
    fresh generation 2 even though a quarantined gen 2 existed."""
    storage, _, x = build_saved(tmp_path, rng, rows=80, saves=2)
    _, _, manifest = newest_files(storage)
    corrupt(os.path.join(storage, manifest["files"]["index"]["name"]))
    loaded = Index.from_storage_dir(storage)
    assert loaded._generation == 1
    loaded.add_batch(x[:10], [("n", i) for i in range(10)],
                     train_async_if_triggered=False)
    deadline = time.time() + 60
    while loaded.get_idx_data_num()[0] > 0:
        assert time.time() < deadline
        time.sleep(0.02)
    assert wait_state(loaded, IndexState.TRAINED)
    assert loaded.save() is True
    gen, _, manifest2 = newest_files(storage)
    assert gen == 2
    assert serialization.verify_manifest(storage, manifest2) == []
    again = Index.from_storage_dir(storage)
    assert again._generation == 2


def test_fresh_engine_over_existing_generations_numbers_past_disk(tmp_path, rng):
    """A fresh engine whose storage dir already holds generations (rank
    restarted without load, create_index on a rejoined rank) must number
    its first save PAST the newest on disk — recycling a low number would
    let prune delete the snapshot it just committed and loads would roll
    back to the stale newest-on-disk generation."""
    storage, _, x = build_saved(tmp_path, rng, rows=90, saves=3)
    assert [g for g, _ in serialization.list_generations(storage)] == [3, 2]

    fresh = Index(flat_cfg(train_num=20, index_storage_dir=storage))
    assert fresh._generation == 0  # never loaded: in-memory counter is cold
    fresh.add_batch(x[:30], [("f", i) for i in range(30)],
                    train_async_if_triggered=False)
    assert wait_state(fresh, IndexState.TRAINED)
    assert fresh.save() is True

    gens = serialization.list_generations(storage)
    assert [g for g, _ in gens] == [4, 3]  # committed past disk, then pruned
    manifest = serialization.load_manifest(gens[0][1])
    assert serialization.verify_manifest(storage, manifest) == []
    loaded = Index.from_storage_dir(storage)
    assert loaded._generation == 4
    assert loaded.tpu_index.ntotal == 30  # the fresh snapshot, not stale data


def test_stale_tmp_files_swept_to_quarantine(tmp_path, rng):
    """atomic_write leftovers (writer killed between open and rename) are
    quarantined at load — without the sweep a full-index-sized .tmp per
    crash accumulates forever."""
    storage, _, _ = build_saved(tmp_path, rng)
    for tmp_name in ("index-g00000002.npz.tmp", "cfg.json.tmp"):
        with open(os.path.join(storage, tmp_name), "wb") as f:
            f.write(b"abandoned mid-write")
    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded._generation == 1
    qdir = os.path.join(storage, "quarantine")
    assert set(os.listdir(qdir)) >= {"index-g00000002.npz.tmp", "cfg.json.tmp"}
    assert not any(n.endswith(".tmp") for n in os.listdir(storage))


def test_legacy_flat_layout_still_loads(tmp_path, rng):
    """Pre-manifest checkpoints (flat index.npz/meta.pkl/cfg.json) must
    keep loading through the legacy path."""
    storage, idx, x = build_saved(tmp_path, rng)
    gens = serialization.list_generations(storage)
    manifest = serialization.load_manifest(gens[0][1])
    # rewrite the generation as the old flat layout
    legacy = {"index": "index.npz", "meta": "meta.pkl",
              "buffer": "buffer.pkl", "cfg": "cfg.json"}
    for key, flat in legacy.items():
        src = os.path.join(storage, manifest["files"][key]["name"])
        os.replace(src, os.path.join(storage, flat))
    os.unlink(gens[0][1])

    loaded = Index.from_storage_dir(storage)
    assert loaded is not None and loaded.get_state() == IndexState.TRAINED
    assert loaded._generation == 0  # legacy load: next save commits gen 1
    s0, m0, _ = idx.search(x[:2], 3)
    s1, m1, _ = loaded.search(x[:2], 3)
    np.testing.assert_allclose(s0, s1, rtol=1e-5)
    assert m0 == m1


def test_corruption_sweep_never_loads_torn_set(tmp_path, rng):
    """Deterministic stand-in for kill -9 at any byte offset of a save:
    corrupt the newest generation's index file at a sweep of offsets (and
    truncate at several lengths); EVERY variant must load the previous
    complete generation — never a torn set, never an exception."""
    storage, idx, x = build_saved(tmp_path, rng, rows=80, saves=2)
    _, _, manifest = newest_files(storage)
    victim_rel = manifest["files"]["index"]["name"]
    pristine = str(tmp_path / "pristine")
    shutil.copytree(storage, pristine)
    size = os.path.getsize(os.path.join(storage, victim_rel))

    offsets = sorted({0, 1, size // 4, size // 2, 3 * size // 4, size - 1})
    for off in offsets:
        work = str(tmp_path / f"sweep-{off}")
        shutil.copytree(pristine, work)
        corrupt(os.path.join(work, victim_rel), offset=off)
        loaded = Index.from_storage_dir(work)
        assert loaded is not None, f"offset {off}: fallback must load"
        assert loaded._generation == 1, f"offset {off} served a torn set"
        shutil.rmtree(work)
    for trunc in (0, 1, size // 2, size - 1):
        work = str(tmp_path / f"trunc-{trunc}")
        shutil.copytree(pristine, work)
        with open(os.path.join(work, victim_rel), "r+b") as f:
            f.truncate(trunc)
        loaded = Index.from_storage_dir(work)
        assert loaded is not None and loaded._generation == 1, (
            f"truncation at {trunc} bytes served a torn set")
        shutil.rmtree(work)
