"""Fast unit tier for shard replication (ISSUE 8): quorum math, group
assignment, membership table, read-fanout planning, quorum writes with
under-replication + repair, and read failover ordering — all against
in-process fake stubs (mirroring tests/test_retry.py), so it runs in
tier-1 AND under the dedicated ``replication`` CI job. The live-cluster
SIGKILL-under-storm acceptance gate is in tests/test_replication_chaos.py.
"""

import random
import threading
from multiprocessing.dummy import Pool as ThreadPool

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import replication, rpc
from distributed_faiss_tpu.parallel.client import (
    REROUTE_LOG_LEN,
    IndexClient,
    QuorumError,
)
from distributed_faiss_tpu.parallel.replication import (
    MembershipTable,
    RepairQueue,
    assign_groups,
    plan_read_fanout,
    quorum_size,
)
from distributed_faiss_tpu.utils import lockdep
from distributed_faiss_tpu.utils.atomics import AtomicCounters
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg

pytestmark = pytest.mark.replication


# ------------------------------------------------------------- quorum math


def test_quorum_majority_default():
    assert quorum_size(1) == 1
    assert quorum_size(2) == 2
    assert quorum_size(3) == 2
    assert quorum_size(4) == 3
    assert quorum_size(5) == 3


def test_quorum_explicit_overrides_majority():
    assert quorum_size(3, 1) == 1
    assert quorum_size(3, 3) == 3


def test_quorum_validates():
    with pytest.raises(ValueError):
        quorum_size(0)
    with pytest.raises(ValueError):
        quorum_size(2, 3)


def test_replication_cfg_env_and_validation():
    cfg = ReplicationCfg.from_env({"DFT_REPLICATION": "2",
                                   "DFT_WRITE_QUORUM": "1"})
    assert cfg.replication == 2 and cfg.write_quorum == 1
    assert ReplicationCfg().replication == 1  # default: pre-replication
    with pytest.raises(ValueError):
        ReplicationCfg(replication=0)
    with pytest.raises(ValueError):
        ReplicationCfg(replication=2, write_quorum=3)
    with pytest.raises(TypeError):
        ReplicationCfg(bogus=1)


# ------------------------------------------------------- group assignment


def test_assign_groups_striping():
    assert assign_groups(4, 1) == [0, 1, 2, 3]     # R=1: one group per rank
    assert assign_groups(4, 2) == [0, 1, 0, 1]     # modular striping
    assert assign_groups(6, 3) == [0, 1, 0, 1, 0, 1]
    # remainder ranks land as extra replicas, never an under-replicated tail
    assert assign_groups(5, 2) == [0, 1, 0, 1, 0]


def test_assign_groups_clamps_oversized_factor():
    assert assign_groups(2, 5) == [0, 0]  # R > N: everyone replicates one shard


def test_membership_register_remove_and_snapshot():
    t = MembershipTable([0, 1, 0, 1])
    assert t.groups() == [0, 1]
    assert t.replicas(0) == [0, 2] and t.replicas(1) == [1, 3]
    assert t.group_of(3) == 1
    t.remove(2)
    assert t.replicas(0) == [0]
    t.register(2, 1)  # online join into the OTHER group
    assert t.replicas(1) == [1, 3, 2] and t.group_of(2) == 1
    t.register(2, 1)  # idempotent
    assert t.replicas(1) == [1, 3, 2]
    snap = t.snapshot()
    snap[0].append(99)  # snapshot is a copy
    assert t.replicas(0) == [0]


def test_plan_read_fanout_pins_and_rotates():
    t = MembershipTable([0, 1, 0, 1])
    plan = plan_read_fanout(t, {})
    assert plan == [(0, 0, [0, 2]), (1, 1, [1, 3])]
    # a pinned replica leads its group's failover ordering
    plan = plan_read_fanout(t, {0: 2})
    assert plan == [(0, 2, [2, 0]), (1, 1, [1, 3])]
    # a stale pin (position left the group) falls back to the head
    t.remove(2)
    plan = plan_read_fanout(t, {0: 2})
    assert plan == [(0, 0, [0]), (1, 1, [1, 3])]


def test_repair_queue_bounded_with_counters():
    q = RepairQueue(maxlen=3)
    for i in range(5):
        q.record({"batch": i})
    assert len(q) == 3
    s = q.stats()
    assert s["recorded"] == 5 and s["dropped"] == 2 and s["pending"] == 3
    items = q.drain()
    assert [it["batch"] for it in items] == [2, 3, 4]  # oldest dropped
    assert len(q) == 0
    q.mark_repaired(2)
    assert q.stats()["repaired"] == 2


# ----------------------------------------------------------- fake cluster


class FakeStub:
    """Quacks like rpc.Client for the replicated fan-out: scripted
    failures, per-call log, shard-group registration, and deterministic
    search results (score base = ``score``)."""

    def __init__(self, sid, score=None, always_fail=False, fail_first=0,
                 shard_group=None):
        self.id = sid
        self.host = "fake"
        self.port = 9000 + sid
        self.score = float(sid if score is None else score)
        self.always_fail = always_fail
        self.fail_first = fail_first
        self.shard_group = shard_group
        self.attempts = 0
        self.acked = []  # (fname, args) for every call that succeeded

    def generic_fun(self, fname, args=(), kwargs=None, **_kw):
        self.attempts += 1
        if self.always_fail:
            raise ConnectionRefusedError(f"rank {self.id} down")
        if self.fail_first > 0:
            self.fail_first -= 1
            raise ConnectionResetError(f"rank {self.id} blip")
        self.acked.append((fname, args))
        if fname == "search":
            _index_id, q, k, _emb = args
            nq = q.shape[0]
            d = self.score + np.arange(k, dtype=np.float32)
            scores = np.tile(d, (nq, 1))
            meta = [[(self.id, j) for j in range(k)] for _ in range(nq)]
            return (scores, meta, None)
        if fname == "get_shard_group":
            return self.shard_group
        if fname == "set_shard_group":
            self.shard_group = args[0]
            return self.shard_group
        return f"ok-{self.id}"


def make_client(stubs, rcfg=None, groups=None):
    c = object.__new__(IndexClient)
    c.sub_indexes = stubs
    c.num_indexes = len(stubs)
    c.pool = ThreadPool(max(len(stubs), 1))
    c.cur_server_ids = {}
    c._rng = random.Random(0)
    c.retry = rpc.RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0.0)
    c._stats_lock = lockdep.lock("IndexClient._stats_lock")
    from collections import deque
    c.reroutes = deque(maxlen=REROUTE_LOG_LEN)
    c.counters = AtomicCounters(
        ("reroutes", "failovers", "under_replicated", "quorum_failures"))
    c.rcfg = rcfg or ReplicationCfg()
    eff = min(c.rcfg.replication, max(len(stubs), 1))
    c.quorum = replication.quorum_size(eff, min(c.rcfg.write_quorum, eff))
    c.repair_queue = replication.RepairQueue(c.rcfg.repair_queue_len)
    c._preferred = {}
    c.membership = MembershipTable(
        groups if groups is not None
        else assign_groups(len(stubs), c.rcfg.replication))
    c.cfg = None
    return c


# ------------------------------------------------------------ quorum writes


def test_write_fans_out_to_every_replica_and_acks_on_full_quorum():
    a, b = FakeStub(0), FakeStub(1)
    client = make_client([a, b], rcfg=ReplicationCfg(replication=2))
    assert client.quorum == 2  # majority of 2
    client.cur_server_ids["idx"] = 0

    emb = np.zeros((4, 8), np.float32)
    client.add_index_data("idx", emb, [1, 2, 3, 4])

    # BOTH replicas got the batch, nothing under-replicated
    assert [f for f, _ in a.acked] == ["add_index_data"]
    assert [f for f, _ in b.acked] == ["add_index_data"]
    assert len(client.repair_queue) == 0
    assert client.counters.snapshot() == {
        "reroutes": 0, "failovers": 0,
        "under_replicated": 0, "quorum_failures": 0}


def test_write_quorum_reached_records_missed_replica_for_repair():
    """quorum=1, one replica dead: the write ACKS (the live replica has
    it) and the dead replica lands in the repair queue; once it heals,
    repair_under_replicated() re-sends and drains the queue."""
    live = FakeStub(0)
    dead = FakeStub(1, always_fail=True)
    client = make_client([live, dead],
                         rcfg=ReplicationCfg(replication=2, write_quorum=1))
    client.cur_server_ids["idx"] = 0

    client.add_index_data("idx", np.zeros((2, 8), np.float32), [1, 2])
    assert len(live.acked) == 1
    assert len(client.repair_queue) == 1
    assert client.counters["under_replicated"] == 1
    assert list(client.reroutes) == []  # quorum met: no reroute

    # still dead: repair keeps it queued
    out = client.repair_under_replicated()
    assert out == {"repaired": 0, "still_pending": 1}
    assert len(client.repair_queue) == 1

    dead.always_fail = False  # rank restarted
    out = client.repair_under_replicated()
    assert out == {"repaired": 1, "still_pending": 0}
    assert len(client.repair_queue) == 0
    assert [f for f, _ in dead.acked] == ["add_index_data"]
    assert client.repair_queue.stats()["repaired"] == 1


def test_write_below_quorum_with_partial_ack_raises_quorum_error():
    """Majority quorum of R=2 is 2: one dead replica means a PARTIAL
    placement — the batch must NOT reroute to another group (that would
    duplicate the minority replica's rows across shards) and must not
    report success."""
    live = FakeStub(0)
    dead = FakeStub(1, always_fail=True)
    client = make_client([live, dead], rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0

    with pytest.raises(QuorumError) as ei:
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert ei.value.acked == [0] and ei.value.needed == 2
    assert client.counters["quorum_failures"] == 1
    assert len(client.repair_queue) == 1  # partial placement recorded
    assert list(client.reroutes) == []    # never rerouted


def test_write_reroutes_to_next_group_when_whole_group_dead():
    # 4 ranks, R=2: groups {0: [0, 2], 1: [1, 3]}; group 0 fully dead
    stubs = [FakeStub(0, always_fail=True), FakeStub(1),
             FakeStub(2, always_fail=True), FakeStub(3)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0

    client.add_index_data("idx", np.zeros((2, 8), np.float32), [1, 2])
    # the batch landed on BOTH replicas of the next group
    assert len(stubs[1].acked) == 1 and len(stubs[3].acked) == 1
    # one reroute record per dead replica skipped, pointing at group 1
    assert {r["skipped_server"] for r in client.reroutes} == {0, 2}
    assert all(r["rerouted_to"] == 1 for r in client.reroutes)
    assert client.counters["reroutes"] == 2
    assert len(client.repair_queue) == 0  # nothing acked in the dead group


def test_write_quorum_clamps_to_shrunken_group():
    """After mark_rank_left shrinks a group to one replica, writes must
    keep acking on that replica — a majority-of-R quorum demanding acks
    from replicas that no longer exist would fail the shard forever."""
    a, b = FakeStub(0), FakeStub(1)
    client = make_client([a, b], rcfg=ReplicationCfg(replication=2))
    assert client.quorum == 2
    client.mark_rank_left(1)
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert len(a.acked) == 1 and b.acked == []
    assert client.counters["quorum_failures"] == 0
    assert len(client.repair_queue) == 0


def test_write_raises_when_every_group_dead():
    stubs = [FakeStub(i, always_fail=True) for i in range(4)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    with pytest.raises(RuntimeError, match="every rank"):
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert client.counters["reroutes"] == 4  # every replica skip recorded


def test_reroute_ring_is_bounded_but_counters_are_not():
    live = FakeStub(1)
    dead = FakeStub(0, always_fail=True)
    client = make_client([dead, live])  # R=1: two single-rank groups
    n = REROUTE_LOG_LEN + 7
    for i in range(n):
        client.cur_server_ids["idx"] = 0  # always place on the dead rank
        client.add_index_data("idx", np.zeros((1, 4), np.float32), [i])
    assert len(client.reroutes) == REROUTE_LOG_LEN  # ring capped
    assert client.counters["reroutes"] == n         # totals keep counting
    assert len(live.acked) == n                     # every batch still acked


# -------------------------------------------------------- read failover


def search_client(stubs, **kw):
    c = make_client(stubs, **kw)
    c.cfg = IndexCfg(metric="l2", dim=8)
    return c


def test_search_reads_one_replica_per_group_never_double_counts():
    """Two replicas of one shard (identical corpus): exactly one serves
    the read, so its rows appear ONCE in the merge — the old all-ranks
    fan-out would have returned each row twice."""
    a = FakeStub(0, score=0.0)
    b = FakeStub(1, score=0.0)
    client = search_client([a, b], rcfg=ReplicationCfg(replication=2))

    scores, meta = client.search(np.zeros((2, 8), np.float32), 4, "idx")
    searched = [s for s in (a, b)
                if any(f == "search" for f, _ in s.acked)]
    assert len(searched) == 1  # one replica per group
    # top-4 of one block [0,1,2,3] — duplicated replicas would give [0,0,1,1]
    assert scores[0].tolist() == [0.0, 1.0, 2.0, 3.0]
    assert [m[1] for m in meta[0]] == [0, 1, 2, 3]


def test_search_failover_skips_dead_replica_and_pins_next():
    dead = FakeStub(0, always_fail=True)
    live = FakeStub(1, score=5.0)
    client = search_client([dead, live], rcfg=ReplicationCfg(replication=2))

    scores, meta = client.search(np.zeros((1, 8), np.float32), 3, "idx")
    assert scores[0].tolist() == [5.0, 6.0, 7.0]
    assert meta[0][0][0] == 1  # served by the survivor
    assert client.counters["failovers"] == 1
    assert dead.attempts == 1

    # the survivor is PINNED: the dead replica is not even dialed again
    client.search(np.zeros((1, 8), np.float32), 3, "idx")
    assert dead.attempts == 1
    assert client.counters["failovers"] == 1  # no second failover


def test_search_failover_merges_across_groups_deterministically():
    # groups {0: [0, 2], 1: [1, 3]}; group 0's preferred replica is dead
    stubs = [FakeStub(0, score=0.0, always_fail=True),
             FakeStub(1, score=10.0),
             FakeStub(2, score=0.0),
             FakeStub(3, score=10.0)]
    client = search_client(stubs, rcfg=ReplicationCfg(replication=2))
    scores, meta = client.search(np.zeros((1, 8), np.float32), 4, "idx")
    # group 0 served by replica 2 (same shard content as 0): merged top-4
    # is group 0's block, identical to what a healthy cluster returns
    assert scores[0].tolist() == [0.0, 1.0, 2.0, 3.0]
    assert all(m[0] == 2 for m in meta[0])


def test_search_partial_reports_group_only_when_every_replica_dead():
    stubs = [FakeStub(0, always_fail=True), FakeStub(1, score=1.0),
             FakeStub(2, always_fail=True), FakeStub(3, score=7.0)]
    # groups {0: [0, 2], 1: [1, 3]} — group 0 fully dead, group 1 healthy
    client = search_client(stubs, rcfg=ReplicationCfg(replication=2))
    scores, meta, missing = client.search(
        np.zeros((1, 8), np.float32), 2, "idx", allow_partial=True)
    assert scores[0].tolist() == [1.0, 2.0]
    assert {m["server"] for m in missing} == {0, 2}  # every replica tried

    # strict mode: a shard with no live replica raises
    with pytest.raises(rpc.TRANSPORT_ERRORS):
        client.search(np.zeros((1, 8), np.float32), 2, "idx")


def test_search_application_error_propagates_without_failover():
    class RejectingStub(FakeStub):
        def generic_fun(self, fname, args=(), kwargs=None, **_kw):
            self.attempts += 1
            if fname == "search":
                raise rpc.ServerException("index not trained")
            return super().generic_fun(fname, args, kwargs, **_kw)

    rejecting = RejectingStub(0)
    other = FakeStub(1, score=1.0)
    client = search_client([rejecting, other],
                           rcfg=ReplicationCfg(replication=2))
    with pytest.raises(rpc.ServerException):
        client.search(np.zeros((1, 8), np.float32), 2, "idx")
    # a live rank REJECTING the request must not look like a dead one
    assert not any(f == "search" for f, _ in other.acked)


def test_get_ntotal_counts_groups_once_and_survives_dead_replica():
    class CountStub(FakeStub):
        def __init__(self, sid, ntotal, **kw):
            super().__init__(sid, **kw)
            self._ntotal = ntotal

        def generic_fun(self, fname, args=(), kwargs=None, **_kw):
            if fname == "get_ntotal" and not self.always_fail:
                self.attempts += 1
                return self._ntotal
            return super().generic_fun(fname, args, kwargs, **_kw)

    # groups {0: [0, 2], 1: [1, 3]}; replica 0 dead, 2 mid-repair (fewer
    # rows than its dead peer would have had); group 1 converged
    stubs = [CountStub(0, 100, always_fail=True), CountStub(1, 40),
             CountStub(2, 90), CountStub(3, 40)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    # per-group max over LIVE replicas, summed: 90 + 40 — rows never
    # counted once per replica, and a dead replica degrades to its peer
    assert client.get_ntotal("idx") == 130

    stubs[2].always_fail = True  # whole group dark -> the error surfaces
    with pytest.raises(rpc.TRANSPORT_ERRORS):
        client.get_ntotal("idx")


def test_retired_engine_never_autosaves_again(tmp_path):
    """A superseded engine (shard-transfer install, drop_index) must stop
    persisting: its save watcher exits and _maybe_save no-ops, so stale
    state can never land as the newest generation over the replacement's
    storage dir."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils import serialization

    storage = str(tmp_path / "shard")
    cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                   train_num=10, index_storage_dir=storage)
    idx = Index(cfg)
    rng = np.random.default_rng(0)
    idx.add_batch(rng.standard_normal((20, 8)).astype(np.float32),
                  [(i,) for i in range(20)], train_async_if_triggered=False)
    import time

    from distributed_faiss_tpu.utils.state import IndexState
    deadline = time.time() + 30
    # wait for the ADD->TRAINED flip too (the drain worker zeroes the
    # count first, and save() during ADD defers and returns None)
    while (idx.get_idx_data_num()[0] > 0
           or idx.get_state() != IndexState.TRAINED):
        assert time.time() < deadline
        time.sleep(0.02)
    assert idx.save()
    gens = serialization.list_generations(storage)
    idx.retire()
    # more rows arrive at the stale instance; save must now refuse
    idx.add_batch(rng.standard_normal((20, 8)).astype(np.float32),
                  [(20 + i,) for i in range(20)],
                  train_async_if_triggered=False)
    deadline = time.time() + 30
    while idx.get_idx_data_num()[0] > 0:
        assert time.time() < deadline
        time.sleep(0.02)
    assert not idx.save()
    assert serialization.list_generations(storage) == gens


# ----------------------------------------------- membership from the wire


def test_build_membership_honors_registered_groups_with_fallback():
    stubs = [FakeStub(0, shard_group=1), FakeStub(1, shard_group=0),
             FakeStub(2), FakeStub(3, always_fail=True)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    table = client._build_membership()
    # explicit registrations win; silent/dead ranks get derived striping
    # (derived for 4 ranks @ R=2 is [0, 1, 0, 1])
    assert table.group_of(0) == 1 and table.group_of(1) == 0
    assert table.group_of(2) == 0 and table.group_of(3) == 1


def test_register_groups_pushes_assignments():
    stubs = [FakeStub(0), FakeStub(1)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    client._register_groups()
    assert stubs[0].shard_group == 0 and stubs[1].shard_group == 0


def test_replication_stats_surface():
    client = make_client([FakeStub(0), FakeStub(1)],
                         rcfg=ReplicationCfg(replication=2, write_quorum=1))
    stats = client.get_replication_stats()
    assert stats["replication"] == 2 and stats["quorum"] == 1
    assert stats["groups"] == {0: [0, 1]}
    assert stats["counters"]["reroutes"] == 0
    assert stats["repair"]["pending"] == 0


# ------------------------------------------- shard transfer over the wire


def test_shard_transfer_over_the_wire(tmp_path):
    """End-to-end online join on loopback: rank B (empty) streams rank
    A's shard via the new KIND_SHARD_FETCH/KIND_SHARD_DATA frames
    (server.sync_shard_from -> rpc.Client.fetch_shard), commits it as a
    MANIFEST generation in ITS OWN storage dir, registers the group, and
    serves byte-identical results."""
    import socket
    import time

    from distributed_faiss_tpu.parallel.server import IndexServer
    from distributed_faiss_tpu.utils import serialization
    from distributed_faiss_tpu.utils.state import IndexState

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    pa = free_port()
    a = IndexServer(0, str(tmp_path / "a"))
    b = IndexServer(1, str(tmp_path / "b"))
    threading.Thread(target=a.start_blocking, args=(pa,), daemon=True).start()
    time.sleep(0.3)
    try:
        cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                       train_num=20)
        a.create_index("t", cfg)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((60, 16)).astype(np.float32)
        a.add_index_data("t", x, [("m", i) for i in range(60)])
        deadline = time.time() + 60
        while not (a.get_state("t") == IndexState.TRAINED
                   and a.get_aggregated_ntotal("t") == 0):
            assert time.time() < deadline, "source shard never drained"
            time.sleep(0.05)

        # a fetch for a missing index degrades to a structured error
        probe = rpc.Client(9, "localhost", pa, mux=False)
        with pytest.raises(rpc.ServerException):
            probe.fetch_shard("no-such-index")
        probe.close()

        out = b.sync_shard_from("t", "localhost", pa, shard_group=3)
        assert out["ntotal"] == 60 and out["buffered"] == 0
        assert b.get_shard_group() == 3

        sa = a.search("t", x[:5], 4)
        sb = b.search("t", x[:5], 4)
        np.testing.assert_array_equal(sa[0], sb[0])
        assert sa[1] == sb[1]

        # the transferred shard is durably committed on B's disk: a crash
        # right after the join restarts from this generation
        gens = serialization.list_generations(
            str(tmp_path / "b" / "t" / "1"))
        assert gens, "transfer was not committed as a MANIFEST generation"
    finally:
        a.stop()
        b.stop()


def test_repair_resend_is_versioned_noop_on_healed_replica(tmp_path):
    """ISSUE 12 satellite regression: ``repair_under_replicated()``
    re-sends used to DOUBLE-APPLY on a replica that already healed via
    anti-entropy. With per-id versions the re-send carries the batch's
    original stamp and the healed replica's LWW add gate no-ops it —
    over a real loopback server, ntotal and the digest stay put and the
    engine counts the no-op."""
    import socket
    import time

    from distributed_faiss_tpu.parallel.server import IndexServer
    from distributed_faiss_tpu.utils.config import VersioningCfg
    from distributed_faiss_tpu.utils.state import IndexState

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    port = free_port()
    srv = IndexServer(0, str(tmp_path / "a"))
    threading.Thread(target=srv.start_blocking, args=(port,),
                     daemon=True).start()
    time.sleep(0.3)
    stub = rpc.Client(0, "localhost", port)
    client = make_client([stub])
    client.vcfg = VersioningCfg()
    from distributed_faiss_tpu.mutation.versions import HLC

    client._hlc = HLC(writer_id=5)
    client._seeded = {"t"}
    client._last_write_version = {}
    client._unversioned_ranks = set()
    try:
        cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                       train_num=10)
        stub.generic_fun("create_index", ("t", cfg))
        x = np.random.default_rng(0).standard_normal((30, 8)).astype(
            np.float32)
        meta = [(i,) for i in range(30)]
        client.cur_server_ids["t"] = 0
        client.add_index_data("t", x, meta)
        deadline = time.time() + 60
        while not (stub.generic_fun("get_state", ("t",))
                   == IndexState.TRAINED
                   and stub.generic_fun("get_aggregated_ntotal",
                                        ("t",)) == 0):
            assert time.time() < deadline
            time.sleep(0.05)
        assert stub.generic_fun("get_ntotal", ("t",)) == 30
        # fabricate the outage's repair record: the SAME batch, the SAME
        # version, re-sent to a replica that (here: trivially) already
        # holds it — the pre-version behavior appended 30 duplicate rows
        client.repair_queue.record({
            "op": "add", "index_id": "t", "group": 0, "missing": [0],
            "failures": [], "embeddings": x, "metadata": meta,
            "version": client.last_write_version("t"),
        })
        out = client.repair_under_replicated()
        assert out == {"repaired": 1, "still_pending": 0}
        time.sleep(0.3)
        assert stub.generic_fun("get_ntotal", ("t",)) == 30
        assert stub.generic_fun("get_aggregated_ntotal", ("t",)) == 0
        mut = stub.generic_fun("get_perf_stats")["mutation"]["t"]
        assert mut["version_noop_adds"] == 30, mut
    finally:
        stub.close()
        srv.stop()


def test_mark_rank_left_removes_from_rotation():
    a, b = FakeStub(0, score=3.0), FakeStub(1, score=3.0)
    client = search_client([a, b], rcfg=ReplicationCfg(replication=2))
    client.search(np.zeros((1, 8), np.float32), 2, "idx")
    client.mark_rank_left(0)
    client.search(np.zeros((1, 8), np.float32), 2, "idx")
    # after the leave, only the remaining replica serves
    assert any(f == "search" for f, _ in b.acked)
    assert client.membership.replicas(0) == [1]
