"""Serving-scheduler unit tests (serving/scheduler.py): coalescing and
per-caller result routing, admission control (queue bound -> BUSY,
deadline shedding before device touch), group isolation, stop semantics,
config knobs, and the perf-stats surface. Pure threads + numpy — no
device work, so these run in tier-1."""

import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.serving import (
    DeadlineExpired,
    SchedulerBusy,
    SchedulerStopped,
    SearchScheduler,
)
from distributed_faiss_tpu.utils.config import SchedulerCfg

# fast (no device, no subprocess): these ALSO run in tier-1; the marker
# additionally pulls them into the dedicated scheduler CI job
pytestmark = pytest.mark.scheduler


class FakeEngine:
    """Deterministic per-row search: scores[i] = row-sum, ids[i] = arange.
    Records every launch (thread-safe) so tests can assert coalescing."""

    def __init__(self, delay=0.0, fail_index=None):
        self.calls = []
        self.lock = threading.Lock()
        self.delay = delay
        self.fail_index = fail_index

    def __call__(self, index_id, q, k, return_embeddings):
        with self.lock:
            self.calls.append((index_id, q.shape, k, return_embeddings))
        if self.delay:
            time.sleep(self.delay)
        if index_id == self.fail_index:
            raise RuntimeError(f"boom on {index_id}")
        scores = np.repeat(q.sum(axis=1, keepdims=True), k, axis=1)
        ids = np.tile(np.arange(k, dtype=np.int64), (q.shape[0], 1))
        meta = [[(index_id, float(row.sum()), j) for j in range(k)] for row in q]
        return scores, meta, None


def expected(q, k, index_id="idx"):
    scores = np.repeat(np.asarray(q, np.float32).sum(axis=1, keepdims=True), k, axis=1)
    meta = [[(index_id, float(row.sum()), j) for j in range(k)]
            for row in np.asarray(q, np.float32)]
    return scores, meta


def test_coalesces_concurrent_requests_and_routes_slices():
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(
        max_wait_ms=150.0, max_batch_rows=1024, max_queue=64))
    n_threads, rows = 6, 3
    queries = [np.full((rows, 4), float(t), np.float32) + np.arange(4)
               for t in range(n_threads)]
    results = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    def client(t):
        barrier.wait()
        results[t] = sched.submit("idx", queries[t], 5)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # every caller got ITS rows back, bit-identical to a solo launch
    for t in range(n_threads):
        scores, meta, embs = results[t]
        exp_scores, exp_meta = expected(queries[t], 5)
        np.testing.assert_array_equal(scores, exp_scores)
        assert meta == exp_meta
        assert embs is None
    # and the launches coalesced: far fewer calls than callers, total rows
    # conserved exactly once
    assert len(engine.calls) < n_threads
    assert sum(shape[0] for _, shape, _, _ in engine.calls) == n_threads * rows
    sched.stop()


def test_flushes_on_max_batch_rows_without_waiting():
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(
        max_wait_ms=5000.0, max_batch_rows=4, max_queue=64))
    t0 = time.monotonic()
    out = sched.submit("idx", np.ones((4, 2), np.float32), 3)
    assert time.monotonic() - t0 < 2.0  # row trigger, not the 5s window
    np.testing.assert_array_equal(out[0], expected(np.ones((4, 2)), 3)[0])
    sched.stop()


def test_incompatible_groups_never_share_a_launch():
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(
        max_wait_ms=100.0, max_batch_rows=1024, max_queue=64))
    outs = {}
    barrier = threading.Barrier(3)

    def client(name, index_id, k, dim):
        q = np.ones((2, dim), np.float32)
        barrier.wait()
        outs[name] = (sched.submit(index_id, q, k), q)

    ts = [threading.Thread(target=client, args=a) for a in
          [("a", "idx", 3, 4), ("b", "idx", 7, 4), ("c", "other", 3, 4)]]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # no launch mixed (index_id, k): every recorded call is homogeneous
    keys = {(iid, k) for iid, _shape, k, _re in engine.calls}
    assert keys == {("idx", 3), ("idx", 7), ("other", 3)}
    assert outs["a"][0][0].shape == (2, 3)
    assert outs["b"][0][0].shape == (2, 7)
    sched.stop()


def test_queue_full_rejects_with_busy():
    release = threading.Event()
    entered = threading.Event()

    def blocking_engine(index_id, q, k, re):
        entered.set()
        release.wait(10.0)
        return (np.zeros((q.shape[0], k), np.float32),)

    sched = SearchScheduler(blocking_engine, SchedulerCfg(
        max_wait_ms=0.0, max_batch_rows=1, max_queue=1))
    q = np.zeros((1, 2), np.float32)
    t1 = threading.Thread(target=lambda: sched.submit("idx", q, 1))
    t1.start()
    assert entered.wait(5.0)  # batcher is now blocked inside the launch
    t2 = threading.Thread(target=lambda: sched.submit("idx", q, 1))
    t2.start()
    deadline = time.time() + 5.0
    while sched.perf_stats()["counters"]["queued"] < 1:
        assert time.time() < deadline
        time.sleep(0.005)
    with pytest.raises(SchedulerBusy) as ei:
        sched.submit("idx", q, 1)
    assert ei.value.queue_depth == 1 and ei.value.max_queue == 1
    assert sched.perf_stats()["counters"]["rejected_busy"] == 1
    release.set()
    t1.join()
    t2.join()
    sched.stop()


def test_expired_deadline_rejected_before_device():
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(max_wait_ms=0.0))
    with pytest.raises(DeadlineExpired):
        sched.submit("idx", np.zeros((1, 2), np.float32), 1,
                     deadline=time.monotonic() - 0.1)
    assert engine.calls == []  # never touched the "device"
    assert sched.perf_stats()["counters"]["shed_deadline"] == 1
    sched.stop()


def test_deadline_expiring_in_queue_is_shed_at_flush():
    release = threading.Event()
    calls = []

    def blocking_engine(index_id, q, k, re):
        calls.append(q.shape)
        release.wait(10.0)
        return (np.zeros((q.shape[0], k), np.float32),)

    sched = SearchScheduler(blocking_engine, SchedulerCfg(
        max_wait_ms=0.0, max_batch_rows=1, max_queue=16))
    q = np.zeros((1, 2), np.float32)
    t1 = threading.Thread(target=lambda: sched.submit("idx", q, 1))
    t1.start()
    deadline = time.time() + 5.0
    while not calls:  # batcher is blocked serving the first request
        assert time.time() < deadline
        time.sleep(0.005)
    errs = []

    def doomed():
        try:
            sched.submit("idx", q, 1, deadline=time.monotonic() + 0.05)
        except Exception as e:
            errs.append(e)

    t2 = threading.Thread(target=doomed)
    t2.start()
    time.sleep(0.2)  # let the doomed request expire while queued
    release.set()
    t1.join()
    t2.join()
    assert len(errs) == 1 and isinstance(errs[0], DeadlineExpired)
    assert len(calls) == 1  # the expired request never reached the engine
    sched.stop()


def test_group_failure_isolated_to_its_callers():
    engine = FakeEngine(fail_index="bad")
    sched = SearchScheduler(engine, SchedulerCfg(max_wait_ms=50.0))
    out = {}
    barrier = threading.Barrier(2)

    def client(name, iid):
        barrier.wait()
        try:
            out[name] = sched.submit(iid, np.ones((1, 2), np.float32), 2)
        except Exception as e:
            out[name] = e

    ts = [threading.Thread(target=client, args=a)
          for a in [("ok", "good"), ("bad", "bad")]]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert isinstance(out["bad"], RuntimeError)
    assert "boom" in str(out["bad"])
    scores, _meta, _ = out["ok"]
    assert scores.shape == (1, 2)
    # the scheduler survives the failure and keeps serving
    again = sched.submit("good", np.ones((1, 2), np.float32), 2)
    assert again[0].shape == (1, 2)
    sched.stop()


def test_stop_fails_pending_and_future_submits():
    release = threading.Event()

    def blocking_engine(index_id, q, k, re):
        release.wait(10.0)
        return (np.zeros((q.shape[0], k), np.float32),)

    sched = SearchScheduler(blocking_engine, SchedulerCfg(
        max_wait_ms=0.0, max_batch_rows=1, max_queue=16))
    q = np.zeros((1, 2), np.float32)
    errs = []
    t1 = threading.Thread(target=lambda: sched.submit("idx", q, 1))
    t1.start()
    deadline = time.time() + 5.0
    while sched.perf_stats()["counters"]["submitted"] < 1:
        assert time.time() < deadline
        time.sleep(0.005)

    def queued():
        try:
            sched.submit("idx", q, 1)
        except Exception as e:
            errs.append(e)

    t2 = threading.Thread(target=queued)
    t2.start()
    while sched.perf_stats()["counters"]["queued"] < 1:
        assert time.time() < deadline
        time.sleep(0.005)
    # stop() drains the queue first (failing t2 with SchedulerStopped),
    # then joins the batcher — which is still blocked in t1's launch, so
    # release it once t2's rejection has landed
    stopper = threading.Thread(target=sched.stop)
    stopper.start()
    while not errs:
        assert time.time() < deadline
        time.sleep(0.005)
    release.set()  # in-flight launch completes normally for t1
    stopper.join()
    t1.join()
    t2.join()
    assert len(errs) == 1 and isinstance(errs[0], SchedulerStopped)
    with pytest.raises(SchedulerStopped):
        sched.submit("idx", q, 1)


def test_perf_stats_surface():
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(max_wait_ms=0.0))
    sched.submit("idx", np.ones((2, 3), np.float32), 4)
    stats = sched.perf_stats()
    assert stats["counters"]["submitted"] == 1
    assert stats["counters"]["batches"] == 1
    for metric in ("queue_wait_s", "e2e_s", "batch_requests", "batch_rows",
                   "queue_depth"):
        assert metric in stats["queues"], metric
        for key in ("count", "mean_s", "max_s", "p50_s", "p95_s", "p99_s"):
            assert key in stats["queues"][metric], (metric, key)
    assert stats["queues"]["batch_rows"]["max_s"] == 2.0
    sched.stop()


def test_eager_submit_skips_the_wait_window():
    """eager=True (the selector loop, which cannot overlap callers) must
    flush immediately instead of idling out the max-wait window."""
    engine = FakeEngine()
    sched = SearchScheduler(engine, SchedulerCfg(
        max_wait_ms=5000.0, max_batch_rows=1024, max_queue=16))
    t0 = time.monotonic()
    out = sched.submit("idx", np.ones((1, 2), np.float32), 3, eager=True)
    assert time.monotonic() - t0 < 2.0  # not the 5s window
    np.testing.assert_array_equal(out[0], expected(np.ones((1, 2)), 3)[0])
    sched.stop()


def test_rejects_non_2d_queries():
    sched = SearchScheduler(FakeEngine(), SchedulerCfg(max_wait_ms=0.0))
    with pytest.raises(ValueError, match="2-D"):
        sched.submit("idx", np.zeros(4, np.float32), 1)
    sched.stop()


# ------------------------------------------------------------- SchedulerCfg


def test_scheduler_cfg_defaults_and_validation():
    cfg = SchedulerCfg()
    assert cfg.enabled and cfg.max_batch_rows == 256
    assert cfg.max_wait_ms == 2.0 and cfg.max_queue == 512
    with pytest.raises(TypeError):
        SchedulerCfg(nope=1)
    with pytest.raises(ValueError):
        SchedulerCfg(max_batch_rows=0)
    with pytest.raises(ValueError):
        SchedulerCfg(max_queue=0)
    with pytest.raises(ValueError):
        SchedulerCfg(max_wait_ms=-1.0)


def test_scheduler_cfg_from_env():
    env = {"DFT_SCHEDULER": "0", "DFT_SCHED_MAX_BATCH": "32",
           "DFT_SCHED_MAX_WAIT_MS": "7.5", "DFT_SCHED_MAX_QUEUE": "9"}
    cfg = SchedulerCfg.from_env(env)
    assert cfg.enabled is False
    assert cfg.max_batch_rows == 32
    assert cfg.max_wait_ms == 7.5
    assert cfg.max_queue == 9
    assert SchedulerCfg.from_env({}).enabled is True
    assert SchedulerCfg.from_env({"DFT_SCHEDULER": "1"}).enabled is True
