"""Mutation x distributed layer (fast, fake-stub tier, mirroring
tests/test_replication.py): quorum delete fan-out, repair-queue deletes,
upsert routing, the ADD-drain read failover satellite, and the server's
``mutation`` perf key."""

import random
import threading
import time
from collections import deque
from multiprocessing.pool import ThreadPool

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import replication, rpc
from distributed_faiss_tpu.parallel.client import (
    IndexClient,
    QuorumError,
    REROUTE_LOG_LEN,
)
from distributed_faiss_tpu.parallel.replication import (
    MembershipTable,
    assign_groups,
)
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg
from distributed_faiss_tpu.utils import lockdep
from distributed_faiss_tpu.utils.atomics import AtomicCounters

pytestmark = [pytest.mark.mutation, pytest.mark.replication]

DRAIN_TB = ("Traceback...\nRuntimeError: Server index is not trained. "
            "state: IndexState.ADD")


class FakeStub:
    """rpc.Client stand-in: scripted transport failures, per-call ack log,
    integer remove_ids results, optional per-fname application errors."""

    def __init__(self, sid, score=None, always_fail=False, removed=1,
                 app_errors=None, shard_group=None):
        self.id = sid
        self.host = "fake"
        self.port = 9000 + sid
        self.score = float(sid if score is None else score)
        self.always_fail = always_fail
        self.removed = removed
        self.app_errors = dict(app_errors or {})
        self.shard_group = shard_group
        self.acked = []

    def generic_fun(self, fname, args=(), kwargs=None, **_kw):
        if self.always_fail:
            raise ConnectionRefusedError(f"rank {self.id} down")
        if fname in self.app_errors:
            raise self.app_errors[fname]
        self.acked.append((fname, args))
        if fname == "remove_ids":
            return self.removed
        if fname == "search":
            _index_id, q, k, _emb = args
            nq = q.shape[0]
            scores = np.tile(self.score + np.arange(k, dtype=np.float32),
                             (nq, 1))
            meta = [[(self.id, j) for j in range(k)] for _ in range(nq)]
            return (scores, meta, None)
        if fname == "get_shard_group":
            return self.shard_group
        return f"ok-{self.id}"


def make_client(stubs, rcfg=None, groups=None):
    c = object.__new__(IndexClient)
    c.sub_indexes = stubs
    c.num_indexes = len(stubs)
    c.pool = ThreadPool(max(len(stubs), 1))
    c.cur_server_ids = {}
    c._rng = random.Random(0)
    c.retry = rpc.RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0.0)
    c._stats_lock = lockdep.lock("IndexClient._stats_lock")
    c.reroutes = deque(maxlen=REROUTE_LOG_LEN)
    c.counters = AtomicCounters(
                  ("reroutes", "failovers", "under_replicated", "quorum_failures"))
    c.rcfg = rcfg or ReplicationCfg()
    eff = min(c.rcfg.replication, max(len(stubs), 1))
    c.quorum = replication.quorum_size(eff, min(c.rcfg.write_quorum, eff))
    c.repair_queue = replication.RepairQueue(c.rcfg.repair_queue_len)
    c._preferred = {}
    c.membership = MembershipTable(
        groups if groups is not None
        else assign_groups(len(stubs), c.rcfg.replication))
    c.cfg = IndexCfg(metric="l2")
    return c


# ----------------------------------------------------------- quorum deletes


def test_remove_ids_fans_to_every_replica_of_every_group():
    stubs = [FakeStub(i) for i in range(4)]  # R=2 -> groups {0:[0,2], 1:[1,3]}
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    removed = client.remove_ids("idx", [7, 8])
    # every replica of every group saw the delete exactly once
    for s in stubs:
        assert [f for f, _ in s.acked] == ["remove_ids"]
    # max per group, summed over groups
    assert removed == 2
    assert len(client.repair_queue) == 0


def test_remove_ids_quorum_records_missed_replica_and_repairs():
    """quorum=1, one replica dead: the delete ACKS, the dead replica lands
    in the repair queue as an op=remove_ids record; once it heals,
    repair_under_replicated re-sends the DELETE (not an add)."""
    a, b = FakeStub(0), FakeStub(1, always_fail=True)
    client = make_client(
        [a, b], rcfg=ReplicationCfg(replication=2, write_quorum=1),
        groups=[0, 0])
    assert client.remove_ids("idx", [1, 2, 3]) == 1
    assert client.counters["under_replicated"] == 1
    item = list(client.repair_queue._items)[0]
    assert item["op"] == "remove_ids" and item["ids"] == [1, 2, 3]
    assert item["missing"] == [1]

    b.always_fail = False
    out = client.repair_under_replicated()
    assert out == {"repaired": 1, "still_pending": 0}
    assert [f for f, _ in b.acked] == ["remove_ids"]
    assert b.acked[0][1] == ("idx", [1, 2, 3])


def test_remove_ids_below_quorum_raises_never_reroutes():
    """A whole dead group raises QuorumError AFTER the other groups were
    still attempted; the dead group's delete is recorded for repair and
    never re-sent to another group."""
    stubs = [FakeStub(0, always_fail=True), FakeStub(1),
             FakeStub(2, always_fail=True), FakeStub(3)]
    # groups: {0: [0, 2] both dead, 1: [1, 3] alive}
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2),
                         groups=[0, 1, 0, 1])
    with pytest.raises(QuorumError) as exc:
        client.remove_ids("idx", [5])
    assert exc.value.group == 0
    # the LIVE group still processed the delete (deletes are per-group
    # data: no cross-group reroute could substitute)
    assert [f for f, _ in stubs[1].acked] == ["remove_ids"]
    assert [f for f, _ in stubs[3].acked] == ["remove_ids"]
    assert client.counters["quorum_failures"] == 1
    item = list(client.repair_queue._items)[0]
    assert item["op"] == "remove_ids" and set(item["missing"]) == {0, 2}


def test_remove_ids_application_error_propagates():
    err = rpc.ServerException("no tombstone support for this index kind")
    stubs = [FakeStub(0, app_errors={"remove_ids": err}), FakeStub(1)]
    client = make_client(stubs, groups=[0, 1])
    with pytest.raises(rpc.ServerException):
        client.remove_ids("idx", [1])


def test_upsert_deletes_everywhere_then_places_once():
    stubs = [FakeStub(i) for i in range(2)]
    client = make_client(stubs, groups=[0, 1])
    client.cur_server_ids["idx"] = 0
    emb = np.zeros((1, 8), np.float32)
    removed = client.upsert("idx", [9], emb)
    assert removed == 2  # both groups reported a tombstoned row
    # delete hit both; the add landed on exactly one group
    assert [f for f, _ in stubs[0].acked][0] == "remove_ids"
    adds = [s for s in stubs
            if any(f == "add_index_data" for f, _ in s.acked)]
    assert len(adds) == 1
    # default metadata carries the id at position 0
    fname, args = adds[0].acked[-1]
    assert args[2] == [(9,)]


def test_upsert_validates_shapes():
    client = make_client([FakeStub(0)], groups=[0])
    with pytest.raises(RuntimeError, match="match the batch size"):
        client.upsert("idx", [1, 2], np.zeros((1, 4), np.float32))


# ------------------------------------------- ADD-drain read failover (sat.)


def drain_exc():
    return rpc.ServerException(DRAIN_TB)


def test_drain_failover_eligibility_is_narrow():
    assert replication.drain_failover_eligible(drain_exc())
    assert not replication.drain_failover_eligible(
        rpc.ServerException("Server index is not trained. state: "
                            "IndexState.NOT_TRAINED"))
    assert not replication.drain_failover_eligible(
        RuntimeError(DRAIN_TB))  # only wire-level ServerException


def test_search_fails_over_past_draining_replica_and_pins():
    """The regression for the slow-draining victim: an R=2 group keeps
    serving while one replica is mid-ADD."""
    draining = FakeStub(0, app_errors={"search": drain_exc()})
    peer = FakeStub(1, score=1.0)
    client = make_client([draining, peer],
                         rcfg=ReplicationCfg(replication=2), groups=[0, 0])
    scores, meta = client.search(np.zeros((2, 4), np.float32), 3, "idx")
    assert meta[0][0] == (1, 0)  # served by the peer
    assert client.counters["failovers"] == 1
    assert client._preferred[0] == 1  # pinned for subsequent calls


def test_search_raises_when_whole_group_is_draining():
    stubs = [FakeStub(i, app_errors={"search": drain_exc()})
             for i in range(2)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2),
                         groups=[0, 0])
    with pytest.raises(rpc.ServerException):
        client.search(np.zeros((1, 4), np.float32), 3, "idx")


def test_search_other_application_errors_never_fail_over():
    bad = FakeStub(0, app_errors={"search": rpc.ServerException("boom")})
    peer = FakeStub(1)
    client = make_client([bad, peer], rcfg=ReplicationCfg(replication=2),
                         groups=[0, 0])
    with pytest.raises(rpc.ServerException, match="boom"):
        client.search(np.zeros((1, 4), np.float32), 3, "idx")
    assert client.counters["failovers"] == 0


def test_partial_search_drain_failover():
    draining = FakeStub(0, app_errors={"search": drain_exc()})
    peer = FakeStub(1, score=1.0)
    client = make_client([draining, peer],
                         rcfg=ReplicationCfg(replication=2), groups=[0, 0])
    scores, meta, missing = client.search(
        np.zeros((1, 4), np.float32), 3, "idx", allow_partial=True)
    assert missing == []  # the group served; nothing degraded
    assert meta[0][0] == (1, 0)


# ----------------------------------------------------- server perf surface


def test_server_perf_stats_grows_mutation_key(tmp_path, monkeypatch):
    monkeypatch.setenv("DFT_COMPACT", "0")
    from distributed_faiss_tpu.parallel.server import IndexServer

    srv = IndexServer(0, str(tmp_path))
    cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                   train_num=5)
    srv.create_index("m", cfg)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((40, 8)).astype(np.float32)
    srv.add_index_data("m", x, [(i,) for i in range(40)],
                       train_async_if_triggered=False)
    deadline = time.time() + 30
    while srv.get_ntotal("m") < 40:
        assert time.time() < deadline
        time.sleep(0.02)
    assert srv.remove_ids("m", [1, 2]) == 2
    stats = srv.get_perf_stats()
    mu = stats["mutation"]["m"]
    assert mu["tombstoned_rows"] == 2
    assert mu["live_fraction"] == pytest.approx(38 / 40)
    assert mu["compactions"] == 0
    assert srv.compact_index("m") is True
    assert srv.get_perf_stats()["mutation"]["m"]["compactions"] == 1
    srv.stop()


def test_upsert_without_cfg_requires_explicit_metadata():
    """A cfg-less client cannot know custom_meta_id_idx: synthesizing
    (id,) metadata could put the id in the wrong slot, creating rows no
    later remove_ids could ever match — it must raise instead."""
    client = make_client([FakeStub(0)], groups=[0])
    client.cfg = None
    with pytest.raises(RuntimeError, match="needs the client"):
        client.upsert("idx", [1], np.zeros((1, 4), np.float32))
    # explicit metadata keeps working without a cfg
    client.upsert("idx", [1], np.zeros((1, 4), np.float32),
                  metadata=[("doc", 1)])
