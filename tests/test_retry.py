"""Fast unit tier for the self-healing write path: RetryPolicy backoff
math, MultiRankError contents, add_index_data rerouting, and broadcast
aggregation — all against in-process fake stubs (no sockets), so this
runs in tier-1; the live-cluster versions are in tests/test_chaos.py."""

import random
import threading
from collections import deque
from multiprocessing.dummy import Pool as ThreadPool

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import replication, rpc
from distributed_faiss_tpu.parallel.client import (
    REROUTE_LOG_LEN,
    IndexClient,
    MultiRankError,
)
from distributed_faiss_tpu.utils.config import ReplicationCfg
from distributed_faiss_tpu.utils import lockdep
from distributed_faiss_tpu.utils.atomics import AtomicCounters


# ------------------------------------------------------------- RetryPolicy


def test_backoff_math_exact_without_jitter():
    p = rpc.RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                        max_delay=10.0, jitter=0.0)
    assert p.delay(0) == pytest.approx(0.1)
    assert p.delay(1) == pytest.approx(0.2)
    assert p.delay(2) == pytest.approx(0.4)
    assert p.delay(3) == pytest.approx(0.8)


def test_backoff_caps_at_max_delay():
    p = rpc.RetryPolicy(base_delay=0.1, multiplier=10.0, max_delay=0.5,
                        jitter=0.0)
    assert p.delay(0) == pytest.approx(0.1)
    assert p.delay(1) == pytest.approx(0.5)  # 1.0 capped
    assert p.delay(7) == pytest.approx(0.5)


def test_backoff_jitter_bounds():
    p = rpc.RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=10.0,
                        jitter=0.5)
    for attempt in range(4):
        nominal = min(10.0, 0.1 * 2.0 ** attempt)
        for _ in range(50):
            d = p.delay(attempt)
            assert nominal * 0.5 <= d <= nominal * 1.5


def test_backoff_jitter_uses_private_rng():
    p = rpc.RetryPolicy(jitter=0.5)
    random.seed(99)
    state = random.getstate()
    for _ in range(20):
        p.delay(0)
    assert random.getstate() == state


def test_policy_validates_params():
    with pytest.raises(ValueError):
        rpc.RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        rpc.RetryPolicy(jitter=1.5)


def test_run_retries_transport_then_succeeds():
    p = rpc.RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("boom")
        return "ok"

    assert p.run(flaky) == "ok"
    assert len(calls) == 3


def test_run_gives_up_after_max_attempts():
    p = rpc.RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    calls = []

    def dead():
        calls.append(1)
        raise EOFError("connection closed mid-frame")

    with pytest.raises(EOFError):
        p.run(dead)
    assert len(calls) == 3


@pytest.mark.parametrize("exc", [
    rpc.ServerException("remote traceback"),
    ValueError("bad argument"),
    RuntimeError("client to h:1 is closed"),
])
def test_run_does_not_retry_application_errors(exc):
    """Transport errors only: a live rank rejecting the request (or a local
    programming error) must propagate on the FIRST attempt."""
    p = rpc.RetryPolicy(max_attempts=5, base_delay=0.001, jitter=0.0)
    calls = []

    def app_error():
        calls.append(1)
        raise exc

    with pytest.raises(type(exc)):
        p.run(app_error)
    assert len(calls) == 1
    assert not p.is_retryable(exc)
    assert p.is_retryable(ConnectionRefusedError("down"))


def test_stream_corruption_is_retryable():
    """A garbled RESPONSE surfaces client-side as FrameError (bad magic) or
    UnpicklingError; generic_fun has already dropped the connection, so the
    write path must treat both as transport and retry on a clean redial."""
    import pickle

    p = rpc.RetryPolicy()
    assert p.is_retryable(rpc.FrameError("bad frame magic b'xxxx'"))
    assert p.is_retryable(pickle.UnpicklingError("corrupt skeleton"))
    # plain RuntimeError (e.g. "client is closed") stays non-retryable
    assert not p.is_retryable(RuntimeError("client to h:1 is closed"))


# ----------------------------------------------------------- fake cluster


class FakeStub:
    """Quacks like rpc.Client for the fan-out helpers: scripted per-call
    behaviors, records every (fname, args) it acks."""

    def __init__(self, sid, behaviors=None):
        self.id = sid
        self.host = "fake"
        self.port = 9000 + sid
        self.behaviors = list(behaviors or [])  # exceptions to raise, in order
        self.acked = []

    def generic_fun(self, fname, args=(), kwargs=None, timeout=None):
        if self.behaviors:
            b = self.behaviors.pop(0)
            if isinstance(b, BaseException):
                raise b
        self.acked.append((fname, args))
        return f"ok-{self.id}"


def make_client(stubs, retry=None, replication_cfg=None):
    c = object.__new__(IndexClient)
    c.sub_indexes = stubs
    c.num_indexes = len(stubs)
    c.pool = ThreadPool(len(stubs))
    c.cur_server_ids = {}
    c._rng = random.Random(0)
    c.retry = retry or rpc.RetryPolicy(max_attempts=2, base_delay=0.001,
                                       jitter=0.0)
    c._stats_lock = lockdep.lock("IndexClient._stats_lock")
    c.reroutes = deque(maxlen=REROUTE_LOG_LEN)
    c.counters = AtomicCounters(
                  ("reroutes", "failovers", "under_replicated", "quorum_failures"))
    c.rcfg = replication_cfg or ReplicationCfg()
    eff = min(c.rcfg.replication, max(len(stubs), 1))
    c.quorum = replication.quorum_size(eff, min(c.rcfg.write_quorum, eff))
    c.repair_queue = replication.RepairQueue(c.rcfg.repair_queue_len)
    c._preferred = {}
    c.membership = replication.MembershipTable(
        replication.assign_groups(len(stubs), c.rcfg.replication))
    c.cfg = None
    return c


def test_add_index_data_reroutes_to_next_live_rank():
    dead = FakeStub(0, behaviors=[ConnectionRefusedError("down")] * 10)
    live = FakeStub(1)
    client = make_client([dead, live])
    client.cur_server_ids["idx"] = 0  # force first placement on the dead rank

    emb = np.zeros((4, 8), np.float32)
    client.add_index_data("idx", emb, [1, 2, 3, 4])

    assert len(live.acked) == 1  # the batch landed exactly once, on rank 1
    assert live.acked[0][0] == "add_index_data"
    assert len(client.reroutes) == 1
    skip = client.reroutes[0]
    assert skip["skipped_server"] == 0 and skip["index_id"] == "idx"
    assert skip["rerouted_to"] == 1
    # round-robin resumes AFTER the rank that actually acked
    assert client.cur_server_ids["idx"] == 0


def test_add_index_data_transient_failure_retries_same_rank():
    flaky = FakeStub(0, behaviors=[ConnectionResetError("blip")])
    other = FakeStub(1)
    client = make_client([flaky, other])
    client.cur_server_ids["idx"] = 0

    client.add_index_data("idx", np.zeros((2, 8), np.float32), [1, 2])
    assert len(flaky.acked) == 1  # retry healed in place: no reroute
    assert list(client.reroutes) == []
    assert client.cur_server_ids["idx"] == 1


def test_add_index_data_raises_when_every_rank_dead():
    stubs = [FakeStub(i, behaviors=[OSError("down")] * 10) for i in range(3)]
    client = make_client(stubs)
    with pytest.raises(RuntimeError, match="every rank"):
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert len(client.reroutes) == 3  # every skip recorded


def test_add_index_data_application_error_propagates():
    """A live rank REJECTING the batch (index not created, bad args) must
    raise immediately — rerouting it would hide a misconfigured shard."""
    rejecting = FakeStub(0, behaviors=[rpc.ServerException("no such index")])
    other = FakeStub(1)
    client = make_client([rejecting, other])
    client.cur_server_ids["idx"] = 0
    with pytest.raises(rpc.ServerException):
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert other.acked == [] and list(client.reroutes) == []


def test_broadcast_success_returns_rank_ordered_results():
    client = make_client([FakeStub(0), FakeStub(1), FakeStub(2)])
    assert client._broadcast("save_index", ("idx",)) == ["ok-0", "ok-1", "ok-2"]


def test_broadcast_collects_every_rank_outcome():
    """One dead rank + one rejecting rank: the op still runs everywhere
    else, and MultiRankError carries all three outcomes."""
    ok = FakeStub(0)
    dead = FakeStub(1, behaviors=[ConnectionRefusedError("down")] * 10)
    reject = FakeStub(2, behaviors=[rpc.ServerException("not trained")])
    client = make_client([ok, dead, reject])

    with pytest.raises(MultiRankError) as ei:
        client._broadcast("sync_train", ("idx",))
    err = ei.value
    assert err.op == "sync_train"
    assert len(err.outcomes) == 3
    assert [o["ok"] for o in err.outcomes] == [True, False, False]
    assert err.results == ["ok-0"]
    assert [o["server"] for o in err.failures] == [1, 2]
    assert "ConnectionRefusedError" in err.failures[0]["error"]
    assert isinstance(err.failures[1]["exception"], rpc.ServerException)
    # the healthy rank DID run the op (no first-error abort)
    assert ok.acked == [("sync_train", ("idx",))]
    # operator-facing message names every failing rank with host:port
    msg = str(err)
    assert "rank 1 (fake:9001)" in msg and "rank 2 (fake:9002)" in msg


def test_broadcast_retry_heals_transient_rank():
    flaky = FakeStub(0, behaviors=[ConnectionResetError("blip")])
    client = make_client([flaky, FakeStub(1)],
                         retry=rpc.RetryPolicy(max_attempts=3,
                                               base_delay=0.001, jitter=0.0))
    assert client._broadcast("set_nprobe", ("idx", 8)) == ["ok-0", "ok-1"]


def test_broadcast_is_thread_safe_under_concurrent_ops():
    stubs = [FakeStub(i) for i in range(4)]
    client = make_client(stubs)
    errors = []

    def worker():
        try:
            for _ in range(10):
                client._broadcast("save_index", ("idx",))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert all(len(s.acked) == 40 for s in stubs)
