"""Scheduler result-identity and ordering guarantees, end to end.

The serving scheduler must be invisible in results: N concurrent clients
through the scheduler get BYTE-IDENTICAL (scores, meta) to direct
(scheduler-off) serving, in both serving loops. Under faults (rank
SIGKILL mid-batch) callers may see transport errors or BUSY — never
another caller's rows.

Marked ``scheduler`` (own CI job, mirroring the chaos job); the
subprocess chaos case is additionally ``slow``.
"""

import socket
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.utils import compilecheck, racecheck

from distributed_faiss_tpu import (
    IndexCfg,
    IndexClient,
    IndexServer,
    IndexState,
    SchedulerCfg,
)
from distributed_faiss_tpu.parallel import rpc

pytestmark = pytest.mark.scheduler


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("localhost", port), timeout=1).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def write_discovery(tmp_path, ports, name):
    p = tmp_path / name
    p.write_text("\n".join(
        [str(len(ports))] + [f"localhost,{port}" for port in ports]) + "\n")
    return str(p)


def start_server(storage, mode, sched_cfg):
    port = free_port()
    srv = IndexServer(0, str(storage), scheduler_cfg=sched_cfg)
    target = srv.start_blocking if mode == "blocking" else srv.start
    threading.Thread(target=target, args=(port,), daemon=True).start()
    assert wait_listening(port)
    return srv, port


def flat_cfg():
    return IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                    train_num=64)


def build_corpus(rng_seed=0, n=600, d=16):
    rng = np.random.default_rng(rng_seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    meta = [("doc", i) for i in range(n)]
    queries = [rng.standard_normal((4, d)).astype(np.float32)
               for _ in range(8)]
    return x, meta, queries


def fill_and_train(disc, index_id, x, meta):
    client = IndexClient(disc)
    client.create_index(index_id, flat_cfg())
    for s in range(0, x.shape[0], 100):
        client.add_index_data(index_id, x[s:s + 100], meta[s:s + 100])
    client.sync_train(index_id)
    deadline = time.time() + 60
    while client.get_state(index_id) != IndexState.TRAINED:
        assert time.time() < deadline, "train timed out"
        time.sleep(0.1)
    # wait for the async add drain so both clusters serve the full corpus
    while client.get_buffer_depth(index_id) > 0:
        assert time.time() < deadline, "add drain timed out"
        time.sleep(0.1)
    return client


@pytest.mark.parametrize("mode", ["blocking", "selector"])
def test_concurrent_clients_identical_to_direct_serving(tmp_path, mode):
    """8 concurrent clients x 5 searches through the scheduler vs direct
    serving: every (scores, meta) pair must match exactly."""
    x, meta, queries = build_corpus()
    index_id = f"ident_{mode}"
    setups = {}
    for arm, enabled in (("on", True), ("off", False)):
        cfg = SchedulerCfg(enabled=enabled, max_wait_ms=3.0)
        srv, port = start_server(tmp_path / arm, mode, cfg)
        disc = write_discovery(tmp_path, [port], f"{arm}.txt")
        admin = fill_and_train(disc, index_id, x, meta)
        admin.close()
        setups[arm] = (srv, disc)
    assert setups["on"][0].scheduler is not None
    assert setups["off"][0].scheduler is None

    results = {"on": {}, "off": {}}
    errors = []

    def client_thread(arm, tid):
        try:
            c = IndexClient(setups[arm][1], None)
            c.cfg = flat_cfg()
            out = []
            for _ in range(5):
                scores, m = c.search(queries[tid], 3, index_id)
                out.append((scores.copy(), m))
            results[arm][tid] = out
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append((arm, tid, e))

    for arm in ("on", "off"):
        ts = [threading.Thread(target=client_thread, args=(arm, t))
              for t in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors[:2]

    for tid in range(8):
        for (s_on, m_on), (s_off, m_off) in zip(
                results["on"][tid], results["off"][tid]):
            assert s_on.dtype == s_off.dtype
            np.testing.assert_array_equal(s_on, s_off)
            assert m_on == m_off
    # the scheduler actually served these (not silently bypassed), and the
    # new observability fields travel through the get_perf_stats RPC
    stats = setups["on"][0].get_perf_stats()
    assert stats["scheduler"]["counters"]["submitted"] >= 40
    assert "p99_s" in stats["scheduler"]["queues"]["queue_wait_s"]
    for arm in setups:
        setups[arm][0].stop()


def test_return_embeddings_identical_through_scheduler(tmp_path):
    x, meta, queries = build_corpus()
    index_id = "ident_embs"
    arms = {}
    for arm, enabled in (("on", True), ("off", False)):
        srv, port = start_server(
            tmp_path / arm, "blocking", SchedulerCfg(enabled=enabled))
        disc = write_discovery(tmp_path, [port], f"{arm}.txt")
        admin = fill_and_train(disc, index_id, x, meta)
        arms[arm] = (srv, admin)
    out = {}
    for arm, (_srv, client) in arms.items():
        out[arm] = client.search(queries[0], 3, index_id,
                                 return_embeddings=True)
    s_on, m_on, e_on = out["on"]
    s_off, m_off, e_off = out["off"]
    np.testing.assert_array_equal(s_on, s_off)
    assert m_on == m_off
    for row_on, row_off in zip(e_on, e_off):
        for a, b in zip(row_on, row_off):
            np.testing.assert_array_equal(a, b)
    for arm, (srv, client) in arms.items():
        client.close()
        srv.stop()


def test_busy_backpressure_and_client_retry(tmp_path):
    """A saturated 1-deep queue rejects with a structured BUSY frame; a raw
    stub surfaces rpc.BusyError, while IndexClient's RetryPolicy backoff
    rides it out and still gets the right answer."""
    x, meta, queries = build_corpus()
    index_id = "busy"
    srv, port = start_server(
        tmp_path / "srv", "blocking",
        SchedulerCfg(max_wait_ms=0.0, max_batch_rows=1, max_queue=1))
    disc = write_discovery(tmp_path, [port], "srv.txt")
    admin = fill_and_train(disc, index_id, x, meta)
    golden = admin.search(queries[0], 3, index_id)

    # slow every scheduled launch so the queue saturates deterministically
    engine = srv.indexes[index_id]
    orig = engine.search_batched

    def slow_search(*a, **k):
        time.sleep(0.4)
        return orig(*a, **k)

    engine.search_batched = slow_search
    try:
        stubs = [rpc.Client(i, "localhost", port) for i in range(3)]
        outcomes = []

        def one(stub):
            try:
                outcomes.append(
                    ("ok", stub.generic_fun(
                        "search", (index_id, queries[0], 3))))
            except rpc.BusyError as e:
                outcomes.append(("busy", e))

        ts = []
        for stub in stubs:  # stagger: launch-occupant, queued, rejected
            t = threading.Thread(target=one, args=(stub,))
            t.start()
            ts.append(t)
            time.sleep(0.1)
        for t in ts:
            t.join()
        kinds = sorted(k for k, _ in outcomes)
        assert kinds == ["busy", "ok", "ok"], outcomes
        busy = next(e for k, e in outcomes if k == "busy")
        assert busy.info["reason"] == "queue_full"
        # the successes returned the exact direct-serving answer
        for k, v in outcomes:
            if k == "ok":
                np.testing.assert_array_equal(v[0], golden[0])
                assert v[1] == golden[1]
        assert srv.scheduler.perf_stats()["counters"]["rejected_busy"] >= 1

        # IndexClient with a patient RetryPolicy absorbs BUSY transparently
        patient = IndexClient(disc, None, retry_policy=rpc.RetryPolicy(
            max_attempts=8, base_delay=0.1, jitter=0.0))
        patient.cfg = flat_cfg()
        blocker = threading.Thread(target=one, args=(stubs[0],))
        filler = threading.Thread(target=one, args=(stubs[1],))
        blocker.start()
        time.sleep(0.1)
        filler.start()
        time.sleep(0.05)
        scores, m = patient.search(queries[0], 3, index_id)
        np.testing.assert_array_equal(scores, golden[0])
        assert m == golden[1]
        blocker.join()
        filler.join()
        for stub in stubs:
            stub.close()
        patient.close()
    finally:
        engine.search_batched = orig
    admin.close()
    srv.stop()


def test_deadline_shed_serverside_without_touching_device(tmp_path):
    """A request whose stamped deadline expires while queued is shed by the
    scheduler: the engine never sees it, and the shed counter records it."""
    x, meta, queries = build_corpus()
    index_id = "shed"
    srv, port = start_server(
        tmp_path / "srv", "blocking",
        SchedulerCfg(max_wait_ms=0.0, max_batch_rows=1, max_queue=8))
    disc = write_discovery(tmp_path, [port], "srv.txt")
    admin = fill_and_train(disc, index_id, x, meta)

    engine = srv.indexes[index_id]
    orig = engine.search_batched
    launches = []

    def slow_search(*a, **k):
        launches.append(a[0].shape)
        time.sleep(0.5)
        return orig(*a, **k)

    engine.search_batched = slow_search
    try:
        c1 = rpc.Client(1, "localhost", port)
        c2 = rpc.Client(2, "localhost", port)
        t1 = threading.Thread(target=lambda: c1.generic_fun(
            "search", (index_id, queries[0], 3)))
        t1.start()
        time.sleep(0.15)  # c1's launch is in flight; c2 queues behind it
        with pytest.raises(rpc.DeadlineExceeded):
            # 0.2s budget < the 0.35s left of c1's launch: expires queued,
            # the server sheds it at flush and its structured BUSY(deadline)
            # frame arrives within the client's grace window
            c2.generic_fun("search", (index_id, queries[0], 3),
                           deadline=time.time() + 0.2)
        t1.join()
        deadline = time.time() + 5
        while not srv.scheduler.perf_stats()["counters"]["shed_deadline"]:
            assert time.time() < deadline, "request was never shed"
            time.sleep(0.05)
        time.sleep(0.2)  # would-be second launch window
        assert len(launches) == 1  # c2's rows never reached the engine
        c1.close()
        c2.close()
    finally:
        engine.search_batched = orig
    admin.close()
    srv.stop()


@pytest.mark.mesh
def test_mesh_backed_clients_identical_and_one_launch_per_window(tmp_path):
    """ISSUE 6 acceptance: a mesh-backed rank (flat corpus sharded over the
    virtual 8-device mesh) serving 8 concurrent clients through the
    scheduler is byte-identical to scheduler-off serving, AND every merged
    window costs exactly ONE device launch (the new engine perf counters
    pin it)."""
    x, meta, queries = build_corpus()
    index_id = "mesh_ident"
    mesh_cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                        train_num=64, mesh_shards=True)
    setups = {}
    for arm, enabled in (("on", True), ("off", False)):
        cfg = SchedulerCfg(enabled=enabled, max_wait_ms=3.0)
        srv, port = start_server(tmp_path / arm, "blocking", cfg)
        disc = write_discovery(tmp_path, [port], f"mesh_{arm}.txt")
        admin = IndexClient(disc)
        admin.create_index(index_id, mesh_cfg)
        for s in range(0, x.shape[0], 100):
            admin.add_index_data(index_id, x[s:s + 100], meta[s:s + 100])
        admin.sync_train(index_id)
        deadline = time.time() + 120
        while (admin.get_state(index_id) != IndexState.TRAINED
               or admin.get_buffer_depth(index_id) > 0):
            assert time.time() < deadline, "mesh train/drain timed out"
            time.sleep(0.1)
        admin.close()
        setups[arm] = (srv, disc)
    from distributed_faiss_tpu.parallel.mesh import ShardedFlatIndex

    for arm in setups:
        with racecheck.peeking():  # white-box peek, reviewed
            assert isinstance(setups[arm][0].indexes[index_id].tpu_index,
                              ShardedFlatIndex)

    results = {"on": {}, "off": {}}
    errors = []

    def client_thread(arm, tid):
        try:
            c = IndexClient(setups[arm][1], None)
            c.cfg = mesh_cfg
            out = []
            for _ in range(5):
                scores, m = c.search(queries[tid], 3, index_id)
                out.append((scores.copy(), m))
            results[arm][tid] = out
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append((arm, tid, e))

    for arm in ("on", "off"):
        ts = [threading.Thread(target=client_thread, args=(arm, t))
              for t in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors[:2]

    for tid in range(8):
        for (s_on, m_on), (s_off, m_off) in zip(
                results["on"][tid], results["off"][tid]):
            np.testing.assert_array_equal(s_on, s_off)
            assert m_on == m_off

    # launch-count assertion: one device launch per merged window — every
    # scheduler flush became exactly one dispatch on the mesh
    stats = setups["on"][0].get_perf_stats()
    eng = stats["engine"][index_id]
    sched = stats["scheduler"]["counters"]
    assert sched["submitted"] >= 40
    assert eng["device_launches"]["max_s"] == 1.0, eng["device_launches"]
    assert eng["device_launches"]["count"] == sched["batches"], (
        eng["device_launches"], sched)
    assert eng["rows_per_launch"]["max_s"] >= 4.0  # windows really merged rows
    for arm in setups:
        setups[arm][0].stop()


@pytest.mark.mesh
@pytest.mark.compilecheck
def test_mesh_serving_compiles_nothing_after_warmup(tmp_path):
    """Steady-state compile budget (graftlint 0.5 runtime witness): after
    warming every pow2 query bucket an 8-client storm can reach (windows
    merge 1..8 four-row requests -> 4..32 rows -> buckets 8/16/32), the
    storm itself must compile ZERO new XLA programs — each retrace is a
    multi-hundred-ms stall on the serving path, so a compile here means
    the bucketing leaked a fresh abstract signature. The compile-count
    witness (utils/compilecheck.py, DFT_COMPILECHECK) supplies the tally;
    this test force-installs it so the budget is pinned in tier-1 too."""
    x, meta, queries = build_corpus()
    index_id = "mesh_budget"
    mesh_cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                        train_num=64, mesh_shards=True)
    installed_here = not compilecheck._installed
    compilecheck.install()
    try:
        srv, port = start_server(tmp_path / "srv", "blocking",
                                 SchedulerCfg(enabled=True, max_wait_ms=3.0))
        disc = write_discovery(tmp_path, [port], "budget.txt")
        admin = IndexClient(disc)
        admin.create_index(index_id, mesh_cfg)
        for s in range(0, x.shape[0], 100):
            admin.add_index_data(index_id, x[s:s + 100], meta[s:s + 100])
        admin.sync_train(index_id)
        deadline = time.time() + 120
        while (admin.get_state(index_id) != IndexState.TRAINED
               or admin.get_buffer_depth(index_id) > 0):
            assert time.time() < deadline, "mesh train/drain timed out"
            time.sleep(0.1)

        # warmup: touch every reachable query bucket through the real
        # serving path (single client -> one window per request)
        rng = np.random.default_rng(7)
        for rows in (4, 8, 16, 32):
            q = rng.standard_normal((rows, 16)).astype(np.float32)
            admin.search(q, 3, index_id)
        assert compilecheck.counts(), (
            "compile witness saw no compilations at all — the "
            "log_compiles hook is not wired")
        snap = compilecheck.snapshot()

        errors = []

        def client_thread(tid):
            try:
                c = IndexClient(disc, None)
                c.cfg = mesh_cfg
                for _ in range(5):
                    c.search(queries[tid], 3, index_id)
                c.close()
            except Exception as e:  # pragma: no cover
                errors.append((tid, e))

        ts = [threading.Thread(target=client_thread, args=(t,))
              for t in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors, errors[:2]
        fresh = compilecheck.new_since(snap)
        assert not fresh, (
            f"steady-state serving window compiled new XLA programs "
            f"after warmup: {fresh}")
        sched = srv.get_perf_stats()["scheduler"]["counters"]
        assert sched["submitted"] >= 40  # the storm really went through
        admin.close()
        srv.stop()
    finally:
        if installed_here:
            compilecheck.uninstall()


@pytest.mark.slow
def test_rank_sigkill_mid_batch_never_crosses_results(tmp_path):
    """Chaos case: SIGKILL the rank while 6 clients hammer the scheduler.
    Every outcome must be either the exact golden answer for THAT client's
    query or a transport/BUSY/deadline error — never another caller's
    rows."""
    from distributed_faiss_tpu.testing.chaos import ServerHarness

    x, meta, queries = build_corpus()
    index_id = "chaos"
    disc = str(tmp_path / "disc.txt")
    harness = ServerHarness(1, disc, str(tmp_path / "storage"),
                            base_port=free_port())
    with harness:
        admin = fill_and_train(disc, index_id, x, meta)
        goldens = {t: admin.search(queries[t], 3, index_id)
                   for t in range(6)}
        admin.close()

        bad = []
        stop = threading.Event()

        def storm(tid):
            c = IndexClient(disc, None)
            c.cfg = flat_cfg()
            while not stop.is_set():
                try:
                    scores, m = c.search(queries[tid], 3, index_id)
                except (rpc.TRANSPORT_ERRORS + (
                        rpc.BusyError, rpc.DeadlineExceeded)):
                    continue  # shed/killed: acceptable, results withheld
                if not (np.array_equal(scores, goldens[tid][0])
                        and m == goldens[tid][1]):
                    bad.append((tid, scores, m))  # pragma: no cover
            c.close()

        ts = [threading.Thread(target=storm, args=(t,)) for t in range(6)]
        for t in ts:
            t.start()
        time.sleep(1.0)   # storm against the live rank
        harness.kill(0)   # mid-batch SIGKILL
        time.sleep(1.0)   # storm against the corpse
        stop.set()
        for t in ts:
            t.join()
    assert not bad, f"cross-caller results surfaced: {bad[:1]}"
