"""Mesh parallelism tests on the virtual 8-device CPU mesh.

Validates the multi-chip design without TPU hardware: corpus-sharded exact
search with distributed top-k merge, psum-reduced sharded k-means, and the
ivf_tpu builder end-to-end.
"""

import jax
import numpy as np
import pytest

from distributed_faiss_tpu.parallel import mesh as meshmod
from distributed_faiss_tpu.models.flat import FlatIndex


def np_topk(q, x, k, metric):
    if metric == "dot":
        s = q @ x.T
    else:
        s = -((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    ids = np.argsort(-s, axis=1)[:, :k]
    return np.take_along_axis(s, ids, 1), ids


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_sharded_knn_golden(rng, metric):
    m = meshmod.make_mesh()
    S = m.shape["shard"]
    per = 64
    x = rng.standard_normal((S * per, 24)).astype(np.float32)
    q = rng.standard_normal((6, 24)).astype(np.float32)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    xs = jax.device_put(jnp.asarray(x), NamedSharding(m, P("shard", None)))
    ntot = jax.device_put(jnp.full((S,), per, jnp.int32), NamedSharding(m, P("shard")))
    vals, ids = meshmod.sharded_knn(m, jnp.asarray(q), xs, ntot, 10, metric)
    ws, wi = np_topk(q, x, 10, metric)
    np.testing.assert_array_equal(np.asarray(ids), wi)
    np.testing.assert_allclose(np.asarray(vals), ws, rtol=1e-4, atol=1e-4)


def test_sharded_kmeans_recovers_blobs(rng):
    m = meshmod.make_mesh()
    centers = np.array([[0, 0], [12, 12], [-12, 12], [0, -12]], dtype=np.float32)
    x = np.concatenate(
        [c + rng.standard_normal((200, 2)).astype(np.float32) * 0.4 for c in centers]
    )
    rng.shuffle(x)
    cent = np.asarray(meshmod.sharded_kmeans(m, x, 4, iters=15, chunk=128))
    d = np.linalg.norm(centers[:, None, :] - cent[None, :, :], axis=-1)
    assert d.min(axis=1).max() < 0.5


def test_sharded_kmeans_matches_single_device_quality(rng):
    from distributed_faiss_tpu.ops import kmeans as km

    m = meshmod.make_mesh()
    x = rng.standard_normal((2000, 16)).astype(np.float32)

    def inertia(cent):
        d = ((x[:, None, :] - cent[None, :, :]) ** 2).sum(-1)
        return d.min(axis=1).mean()

    sharded = np.asarray(meshmod.sharded_kmeans(m, x, 16, iters=12))
    single = np.asarray(km.kmeans(x, 16, iters=12))
    assert inertia(sharded) < inertia(single) * 1.25


def test_sharded_flat_index_matches_flat(rng):
    x = rng.standard_normal((1000, 16)).astype(np.float32)
    q = rng.standard_normal((5, 16)).astype(np.float32)
    flat = FlatIndex(16, "l2")
    flat.add(x)
    sharded = meshmod.ShardedFlatIndex(16, "l2")
    sharded.add(x[:500])
    sharded.add(x[500:])
    D0, I0 = flat.search(q, 8)
    D1, I1 = sharded.search(q, 8)
    np.testing.assert_array_equal(I0, I1)
    np.testing.assert_allclose(D0, D1, rtol=1e-4, atol=1e-4)
    rec = sharded.reconstruct_batch(I1[0])
    np.testing.assert_allclose(rec, x[I1[0]], rtol=1e-6)


def test_sharded_flat_state_round_trip(rng, tmp_path):
    from distributed_faiss_tpu.models.factory import index_from_state_dict
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    x = rng.standard_normal((300, 8)).astype(np.float32)
    q = rng.standard_normal((3, 8)).astype(np.float32)
    idx = meshmod.ShardedFlatIndex(8, "dot")
    idx.add(x)
    D0, I0 = idx.search(q, 5)
    p = str(tmp_path / "s.npz")
    save_state(p, idx.state_dict())
    idx2 = index_from_state_dict(load_state(p))
    D1, I1 = idx2.search(q, 5)
    np.testing.assert_array_equal(I0, I1)


def test_flat_mesh_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                   mesh_shards=True, mesh_devices=4)
    idx = build_index(cfg)
    assert isinstance(idx, meshmod.ShardedFlatIndex)
    assert idx.nshards == 4
    x = rng.standard_normal((200, 8)).astype(np.float32)
    idx.add(x)
    D, I = idx.search(x[:3], 4)
    assert (I[:, 0] == np.arange(3)).all()


def test_ivf_tpu_builder(rng):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=16, metric="l2",
                   centroids=8, nprobe=8)
    idx = build_index(cfg)
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(x[:4], 5)
    assert (I[:, 0] == np.arange(4)).all()  # self-hit with full probe
    assert idx.get_centroids().shape == (8, 16)
