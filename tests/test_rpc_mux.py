"""RPC multiplexing: pipelined, out-of-order calls per connection.

Covers the mux protocol end to end: tagged responses route by req_id and
complete out of order; legacy peers interop in BOTH directions (a mux
client degrades to FIFO attribution against an untagged in-order server;
a no-meta legacy client is served unchanged by a mux server); a single
IndexClient's in-flight window reaches the serving scheduler as one
merged device batch with byte-identical results; transport failures fail
every in-flight call (no hang) and the demux thread shuts down cleanly.

Marked ``rpcmux`` (own CI job, mirroring the scheduler tier); the
subprocess SIGKILL case is additionally ``slow``.
"""

import socket
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.utils import racecheck

from distributed_faiss_tpu import (
    Index,
    IndexCfg,
    IndexClient,
    IndexServer,
    IndexState,
    SchedulerCfg,
)
from distributed_faiss_tpu.parallel import rpc

pytestmark = pytest.mark.rpcmux


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("localhost", port), timeout=1).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def write_discovery(tmp_path, ports, name="disc.txt"):
    p = tmp_path / name
    p.write_text("\n".join(
        [str(len(ports))] + [f"localhost,{port}" for port in ports]) + "\n")
    return str(p)


def flat_cfg(dim=16):
    return IndexCfg(index_builder_type="flat", dim=dim, metric="l2",
                    train_num=64)


def make_trained_engine(storage, n=600, d=16, seed=0):
    """An in-process trained engine Index (injected into servers so RPC
    tests don't pay the over-the-wire ingest)."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    meta = [("doc", i) for i in range(n)]
    cfg = flat_cfg(d)
    cfg.index_storage_dir = str(storage)
    idx = Index(cfg)
    idx.add_batch(x, meta, train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 60
    while idx.get_state() != IndexState.TRAINED:
        assert time.time() < deadline, "train timed out"
        time.sleep(0.05)
    while idx.get_idx_data_num()[0] > 0:
        assert time.time() < deadline, "add drain timed out"
        time.sleep(0.05)
    queries = [rng.standard_normal((4, d)).astype(np.float32)
               for _ in range(8)]
    return idx, queries


def start_server(storage, mode, sched_cfg=None, engine=None,
                 index_id="mux"):
    port = free_port()
    srv = IndexServer(0, str(storage), scheduler_cfg=sched_cfg)
    if engine is not None:
        srv.indexes[index_id] = engine
    target = srv.start_blocking if mode == "blocking" else srv.start
    threading.Thread(target=target, args=(port,), daemon=True).start()
    assert wait_listening(port)
    return srv, port


# --------------------------------------------------------- protocol-level


class _TaggedScriptServer:
    """One-connection server that reads N tagged calls, then answers them
    in an explicit req-arrival order (e.g. second request first) with
    req_id-tagged frames — the out-of-order shape only a mux client can
    demultiplex."""

    def __init__(self, n_calls, answer_order):
        self.n_calls = n_calls
        self.answer_order = answer_order
        self.frames = []
        self.port = free_port()
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("", self.port))
        self._lsock.listen(5)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        try:
            conn, _ = self._lsock.accept()
            for _ in range(self.n_calls):
                kind, payload = rpc.recv_frame(conn)
                assert kind == rpc.KIND_CALL
                self.frames.append(payload)
            for arrival_idx in self.answer_order:
                fname, args, _kw, meta = self.frames[arrival_idx]
                rpc._send_parts(conn, rpc.pack_tagged_response(
                    rpc.KIND_RESULT, ("answer", fname, args),
                    meta["req_id"]))
                time.sleep(0.05)  # keep completion order observable
        except (EOFError, OSError):
            pass

    def close(self):
        self._lsock.close()


def test_pipelined_out_of_order_completion():
    """Two calls in flight on ONE connection; the server answers the
    SECOND first. The demux must route each tagged response to its own
    caller — and the second caller finishes before the first."""
    srv = _TaggedScriptServer(n_calls=2, answer_order=[1, 0])
    c = rpc.Client(0, "localhost", srv.port)
    done = {}
    order = []

    def call(name, delay):
        time.sleep(delay)
        done[name] = c.generic_fun(name, (name,))
        order.append(name)

    t1 = threading.Thread(target=call, args=("first", 0.0))
    t2 = threading.Thread(target=call, args=("second", 0.1))
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert done["first"] == ("answer", "first", ("first",))
    assert done["second"] == ("answer", "second", ("second",))
    assert order == ["second", "first"]  # completed out of send order
    c.close()
    srv.close()


class _LegacyServer:
    """The pre-mux serve loop: one frame at a time, in order, untagged
    responses, meta element ignored. Records max concurrently-received-
    but-unanswered depth (always 1 here: it cannot pipeline)."""

    def __init__(self):
        self.calls = 0
        self.port = free_port()
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("", self.port))
        self._lsock.listen(5)
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        try:
            while True:
                conn, _ = self._lsock.accept()
                threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True).start()
        except OSError:
            pass

    def _serve(self, conn):
        try:
            while True:
                kind, payload = rpc.recv_frame(conn)
                if kind == rpc.KIND_CLOSE:
                    break
                fname, args, kwargs = payload[:3]
                self.calls += 1
                rpc.send_frame(conn, rpc.KIND_RESULT, ("echo", args))
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    def close(self):
        self._lsock.close()


def test_mux_client_against_legacy_server_degrades_to_fifo():
    """Interop direction 1: a mux client against an untagged in-order
    server. The demux attributes untagged responses FIFO (exact, because
    a legacy server answers one frame at a time in order) — every one of
    6 threads x 10 pipelined calls gets ITS OWN result back."""
    srv = _LegacyServer()
    c = rpc.Client(0, "localhost", srv.port)
    assert c._mux
    bad = []

    def worker(i):
        for j in range(10):
            got = c.generic_fun("echo", ((i, j),))
            if got != ("echo", ((i, j),)):
                bad.append((i, j, got))  # pragma: no cover

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not bad, bad[:3]
    assert srv.calls == 60
    c.close()
    srv.close()


def test_legacy_no_meta_client_against_mux_server(tmp_path):
    """Interop direction 2: a no-meta legacy peer against a mux server.
    Raw 3-tuple frames (no meta element at all) must be served on the
    unchanged synchronous path with untagged responses, and a serial
    (DFT_RPC_MUX=0) Client must round-trip fully."""
    srv, port = start_server(tmp_path, "blocking")

    # raw legacy frames, no meta element
    raw = socket.create_connection(("localhost", port))
    rpc.send_frame(raw, rpc.KIND_CALL, ("get_rank", (), {}))
    kind, payload = rpc.recv_frame(raw)
    assert kind == rpc.KIND_RESULT and payload == 0  # untagged response
    rpc.send_frame(raw, rpc.KIND_CLOSE, None)
    raw.close()

    serial = rpc.Client(0, "localhost", port, mux=False)
    assert serial.get_rank() == 0
    assert serial.ping()["rank"] == 0
    serial.close()

    stats = srv.get_perf_stats()["rpc"]
    assert stats["legacy_calls"] >= 3
    assert stats["mux_calls"] == 0
    srv.stop()


# ------------------------------------------------------------- real server


def test_out_of_order_completion_on_real_server(tmp_path):
    """A slow scheduled search and a fast get_rank in flight on the SAME
    stub: the fast call must complete while the search still runs —
    impossible before mux (the stub lock serialized the round trips, the
    server one frame per connection)."""
    engine, queries = make_trained_engine(tmp_path / "shard")
    srv, port = start_server(tmp_path, "blocking",
                             SchedulerCfg(max_wait_ms=1.0), engine)
    orig = engine.search_batched

    def slow_search(*a, **k):
        time.sleep(0.6)
        return orig(*a, **k)

    engine.search_batched = slow_search
    try:
        c = rpc.Client(0, "localhost", port)
        events = []
        search_done = threading.Event()

        def do_search():
            c.generic_fun("search", ("mux", queries[0], 3))
            events.append("search")
            search_done.set()

        t = threading.Thread(target=do_search)
        t.start()
        time.sleep(0.2)  # search is in flight on the wire
        assert c.generic_fun("get_rank", ()) == 0
        events.append("get_rank")
        assert not search_done.is_set()  # answered while search in flight
        t.join()
        assert events == ["get_rank", "search"]
        c.close()
    finally:
        engine.search_batched = orig
    srv.stop()


@pytest.mark.parametrize("mode", ["blocking", "selector"])
def test_single_client_window_coalesces_with_identical_results(
        tmp_path, mode):
    """The acceptance case, in both serving loops: 8 concurrent callers
    through ONE IndexClient (one stub, one connection) are byte-identical
    to sequential serving, AND their in-flight window reaches the
    scheduler as merged device batches (batch_requests > 1 from a single
    client — impossible pre-mux)."""
    engine, queries = make_trained_engine(tmp_path / "shard")
    srv, port = start_server(tmp_path, mode,
                             SchedulerCfg(max_wait_ms=25.0), engine)
    disc = write_discovery(tmp_path, [port])
    client = IndexClient(disc)
    client.cfg = flat_cfg()

    golden = [client.search(q, 3, "mux") for q in queries]
    srv.scheduler.stats.reset()  # only count the concurrent storm below

    results = {}
    errors = []
    barrier = threading.Barrier(8)

    def caller(tid):
        try:
            barrier.wait()
            out = []
            for _ in range(5):
                out.append(client.search(queries[tid], 3, "mux"))
            results[tid] = out
        except Exception as e:  # pragma: no cover
            errors.append((tid, e))

    ts = [threading.Thread(target=caller, args=(t,)) for t in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors[:2]
    for tid in range(8):
        g_scores, g_meta = golden[tid]
        assert len(results[tid]) == 5
        for scores, meta in results[tid]:
            assert scores.dtype == g_scores.dtype
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta

    sched = srv.get_perf_stats()["scheduler"]
    merged_max = sched["queues"]["batch_requests"]["max_s"]
    assert merged_max > 1, (
        f"single client's window never merged (max batch_requests="
        f"{merged_max})")

    # the observability satellite: per-stub client-side view rides the
    # same get_perf_stats surface
    stats = client.get_perf_stats()
    view = stats[0]["rpc"]["client"]
    assert view["mux"] is True
    assert view["in_flight_peak"] > 1
    assert view["round_trip_s"]["count"] >= 40
    assert "p99_s" in view["round_trip_s"]
    client.close()
    srv.stop()


def test_close_with_calls_in_flight_unblocks_and_demux_exits(tmp_path):
    """close() with a call still in flight: the caller is failed promptly
    (no hang) and the demux reader thread exits cleanly."""
    engine, queries = make_trained_engine(tmp_path / "shard")
    srv, port = start_server(tmp_path, "blocking",
                             SchedulerCfg(max_wait_ms=1.0), engine)
    orig = engine.search_batched

    def slow_search(*a, **k):
        time.sleep(1.0)
        return orig(*a, **k)

    engine.search_batched = slow_search
    try:
        c = rpc.Client(0, "localhost", port)
        outcome = []

        def do_search():
            try:
                outcome.append(("ok", c.generic_fun(
                    "search", ("mux", queries[0], 3))))
            except Exception as e:
                outcome.append(("err", e))

        t = threading.Thread(target=do_search)
        t.start()
        time.sleep(0.2)  # call is on the wire
        reader = c._reader
        t0 = time.time()
        c.close()
        t.join(timeout=5.0)
        assert not t.is_alive(), "in-flight caller hung through close()"
        assert time.time() - t0 < 5.0
        assert outcome and outcome[0][0] == "err"
        assert not reader.is_alive(), "demux thread survived close()"
        # close is terminal: no redial
        with pytest.raises(RuntimeError):
            c.generic_fun("get_rank", ())
    finally:
        engine.search_batched = orig
    srv.stop()


def test_percall_timeout_on_tagged_peer_abandons_only_that_call():
    """A per-call timeout against a peer that is demonstrably alive
    (tagged responses still flowing) abandons ONLY the timed-out slot:
    other in-flight calls complete, the connection survives, and the
    late response is dropped by req_id instead of misrouted."""
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", 0))
    lsock.listen(5)
    port = lsock.getsockname()[1]

    def serve():
        try:
            conn, _ = lsock.accept()
            frames = [rpc.recv_frame(conn)[1] for _ in range(2)]
            by_name = {f[0]: f[3]["req_id"] for f in frames}
            # answer the companion call, never the one that will time out
            rpc._send_parts(conn, rpc.pack_tagged_response(
                rpc.KIND_RESULT, "companion-ok", by_name["companion"]))
            # the connection must still serve AFTER the timeout
            kind, payload = rpc.recv_frame(conn)
            rpc._send_parts(conn, rpc.pack_tagged_response(
                rpc.KIND_RESULT, "after-ok", payload[3]["req_id"]))
        except (EOFError, OSError):
            pass

    threading.Thread(target=serve, daemon=True).start()
    c = rpc.Client(0, "localhost", port)
    outcomes = {}

    def doomed():
        try:
            outcomes["doomed"] = c.generic_fun("doomed", (), timeout=0.8)
        except OSError as e:  # socket.timeout
            outcomes["doomed"] = e

    def companion():
        time.sleep(0.1)  # send after doomed, so its response proves life
        outcomes["companion"] = c.generic_fun("companion", ())

    ts = [threading.Thread(target=doomed), threading.Thread(target=companion)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert isinstance(outcomes["doomed"], OSError)
    assert outcomes["companion"] == "companion-ok"  # NOT collaterally failed
    # same connection, no redial: the window survived the timeout
    with racecheck.peeking():  # white-box peek, reviewed
        assert not c._closed
    assert c.generic_fun("after", ()) == "after-ok"
    c.close()
    lsock.close()


def test_transport_failure_fails_all_inflight_calls():
    """A torn connection fails EVERY in-flight call with a TRANSPORT
    error (so retry/reroute/partial-search machinery sees the rank as
    dead), and the stub redials cleanly on the next call."""
    # script a server that answers nothing, then dies mid-window
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", 0))
    lsock.listen(5)
    port = lsock.getsockname()[1]
    conns = []

    def accept_loop():
        try:
            while True:
                conn, _ = lsock.accept()
                conns.append(conn)
        except OSError:
            pass

    threading.Thread(target=accept_loop, daemon=True).start()
    c = rpc.Client(0, "localhost", port)
    errs = []

    def call(i):
        try:
            c.generic_fun("never_answered", (i,))
        except rpc.TRANSPORT_ERRORS as e:
            errs.append(e)

    ts = [threading.Thread(target=call, args=(i,)) for i in range(5)]
    for t in ts:
        t.start()
    deadline = time.time() + 5
    while len(c._pending) < 5 and time.time() < deadline:
        time.sleep(0.01)
    assert len(c._pending) == 5  # the whole window is in flight
    for conn in conns:
        conn.close()  # RST/EOF mid-window
    for t in ts:
        t.join(timeout=5.0)
        assert not t.is_alive(), "caller hung past connection teardown"
    assert len(errs) == 5  # every caller saw a transport error
    lsock.close()
    c.close()


@pytest.mark.slow
def test_sigkill_with_mux_window_bounded_and_reroutes(tmp_path):
    """Chaos interplay: SIGKILL a rank while W mux calls are in flight on
    one stub — every caller gets a transport error within the deadline
    bound (no hang), ingest reroutes to the surviving rank (acked batches
    never lost), and the demux threads shut down cleanly on close()."""
    from distributed_faiss_tpu.testing.chaos import ServerHarness

    index_id = "chaos_mux"
    disc = str(tmp_path / "disc.txt")
    harness = ServerHarness(2, disc, str(tmp_path / "storage"),
                            base_port=free_port())
    with harness:
        client = IndexClient(disc)
        client.create_index(index_id, flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((600, 16)).astype(np.float32)
        meta = [("doc", i) for i in range(600)]
        for s in range(0, 600, 100):
            client.add_index_data(index_id, x[s:s + 100], meta[s:s + 100])
        client.sync_train(index_id)
        deadline = time.time() + 60
        while client.get_state(index_id) != IndexState.TRAINED:
            assert time.time() < deadline, "train timed out"
            time.sleep(0.1)
        q = rng.standard_normal((4, 16)).astype(np.float32)

        # ranks register in the discovery file in racy order: pin the
        # storm to the stub actually wired to the rank we will SIGKILL
        stub = next(s for s in client.sub_indexes
                    if s.port == harness.port(0))
        stop = threading.Event()
        outcomes = []

        def storm(tid):
            while not stop.is_set():
                t0 = time.time()
                try:
                    stub.generic_fun("search", (index_id, q, 3),
                                     timeout=5.0)
                except rpc.RETRYABLE_ERRORS as e:
                    # transport classified AND bounded: no caller waits
                    # past its own timeout + teardown slack
                    outcomes.append(("err", time.time() - t0, e))
                    time.sleep(0.05)
                else:
                    outcomes.append(("ok", time.time() - t0, None))

        ts = [threading.Thread(target=storm, args=(t,)) for t in range(6)]
        for t in ts:
            t.start()
        time.sleep(0.8)   # storm the live rank with a full window
        harness.kill(0)   # SIGKILL with W calls in flight
        time.sleep(1.5)   # storm the corpse: failures must stay bounded
        stop.set()
        for t in ts:
            t.join(timeout=15.0)
            assert not t.is_alive(), "storm caller hung after SIGKILL"
        errs = [o for o in outcomes if o[0] == "err"]
        assert errs, "SIGKILL produced no transport errors?"
        assert max(o[1] for o in outcomes) < 8.0  # timeout + slack, no hang

        # retry/reroute still works: ingest lands on the surviving rank
        before = len(client.reroutes)
        client.add_index_data(index_id, x[:50], meta[:50])
        assert len(client.reroutes) >= before  # acked by SOME rank

        readers = [s._reader for s in client.sub_indexes
                   if s._reader is not None]
        client.close()
        for r in readers:
            r.join(timeout=5.0)
            assert not r.is_alive(), "demux thread survived close()"
