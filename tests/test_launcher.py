"""Launcher + bulk loader + observability tests.

Models the reference's L5 surface (scripts/server_launcher.py,
scripts/load_data.py) using the local-subprocess backend.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import launcher


def test_discovery_append_locking(tmp_path):
    path = str(tmp_path / "disc.txt")
    launcher.write_discovery_header(path, 16)
    threads = [
        threading.Thread(target=launcher.append_discovery_entry, args=(path, f"h{i}", 1000 + i))
        for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = open(path).read().strip().splitlines()
    assert lines[0] == "16"
    entries = sorted(lines[1:])
    assert len(entries) == 16 and len(set(entries)) == 16


def test_file_lock_contention(tmp_path):
    path = str(tmp_path / "f.txt")
    open(path, "w").close()
    lock = launcher.acquire_file_lock(path)
    with pytest.raises(TimeoutError):
        launcher.acquire_file_lock(path, timeout=0.3)
    launcher.release_file_lock(lock)
    lock2 = launcher.acquire_file_lock(path, timeout=1)
    launcher.release_file_lock(lock2)


def test_slurm_launch_with_mocked_submitit(tmp_path, monkeypatch):
    """launch_slurm never runs in this image (submitit absent), so exercise it
    against a mock: verify the executor parameters mirror the reference's
    AutoExecutor setup (server_launcher.py:111-122) and that the submitted
    task derives rank from global_rank and port from base_port+local_rank
    (reference :59-68)."""
    import types

    recorded = {}

    class FakeJobEnvironment:
        global_rank = 5
        local_rank = 2

    class FakeAutoExecutor:
        def __init__(self, folder):
            recorded["folder"] = folder

        def update_parameters(self, **kw):
            recorded["params"] = kw

        def submit(self, fn):
            recorded["task"] = fn
            return "fake-job"

    fake = types.ModuleType("submitit")
    fake.JobEnvironment = FakeJobEnvironment
    fake.AutoExecutor = FakeAutoExecutor
    monkeypatch.setitem(sys.modules, "submitit", fake)

    disc = str(tmp_path / "disc.txt")
    job = launcher.launch_slurm(
        num_servers=6, num_servers_per_node=2, discovery_path=disc,
        storage_dir=str(tmp_path / "st"), base_port=14000, partition="learnlab",
    )
    assert job == "fake-job"
    assert open(disc).readline().strip() == "6"
    p = recorded["params"]
    assert p["nodes"] == 3 and p["tasks_per_node"] == 2
    assert p["slurm_partition"] == "learnlab"

    served = {}
    monkeypatch.setattr(
        launcher, "run_server",
        lambda rank, port, dp, sd, load: served.update(
            rank=rank, port=port, disc=dp, storage=sd, load=load),
    )
    recorded["task"]()  # what submitit would run on the SLURM task
    assert served["rank"] == 5 and served["port"] == 14002
    assert served["disc"] == disc and served["load"] is False


def test_slurm_launch_without_submitit_raises(monkeypatch, tmp_path):
    monkeypatch.setitem(sys.modules, "submitit", None)
    with pytest.raises(RuntimeError, match="submitit is not installed"):
        launcher.launch_slurm(1, 1, str(tmp_path / "d.txt"), str(tmp_path / "s"))


@pytest.mark.slow
def test_degraded_mode_search_with_dead_rank(tmp_path):
    """Kill 1 of 4 ranks: search(allow_partial=True) serves top-k from the
    3 survivors and names the dead rank; the default strict mode raises.
    Completes the hook the reference stubbed (client.py:69-76)."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    base_port = 13551
    procs = launcher.launch_local(4, disc, storage, base_port=base_port, env=env)
    try:
        from distributed_faiss_tpu import IndexClient, IndexCfg, IndexState

        cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                       train_num=100)
        client = IndexClient(disc)
        client.create_index("pidx", cfg)
        rng = np.random.default_rng(3)
        x = rng.standard_normal((400, 16)).astype(np.float32)
        for s in range(0, 400, 50):
            # tuple metadata: get_ids extracts the id at position 0
            client.add_index_data("pidx", x[s:s + 50],
                                  [(i,) for i in range(s, s + 50)])
        t0 = time.time()
        while (client.get_state("pidx") != IndexState.TRAINED
               or client.get_buffer_depth("pidx") > 0):
            assert time.time() - t0 < 120, "index never drained"
            time.sleep(0.2)
        client.add_buffer_to_index("pidx")
        t0 = time.time()
        while client.get_ntotal("pidx") < 400:
            assert time.time() - t0 < 120, "adds never indexed"
            time.sleep(0.2)

        client.save_index("pidx")  # restart-from-storage needs a checkpoint
        # which ids each rank owns (stub order == discovery order); ports
        # are base_port + rank, so map the victim stub back to its process
        ids_per_stub = [stub.get_ids("pidx") for stub in client.sub_indexes]
        victim = 2
        victim_port = client.sub_indexes[victim].port
        procs[victim_port - base_port].kill()  # SIGKILL
        procs[victim_port - base_port].wait()

        q = x[:40]
        with pytest.raises(Exception):
            client.search(q, 5, "pidx")  # strict mode: dead rank raises

        scores, metas, missing = client.search(
            q, 5, "pidx", allow_partial=True, partial_timeout=15.0)
        assert len(missing) == 1 and missing[0]["port"] == victim_port
        assert scores.shape == (40, 5)
        surviving_ids = set().union(
            *(s for i, s in enumerate(ids_per_stub) if i != victim))
        dead_ids = ids_per_stub[victim]
        flat_meta = [m[0] for row in metas for m in row]
        assert flat_meta and all(m in surviving_ids for m in flat_meta)
        assert not any(m in dead_ids for m in flat_meta)
        # queries whose vector lives on a survivor still self-hit at top-1
        for i in range(40):
            if i in surviving_ids:
                assert metas[i][0] == (i,)
        # still down on the next call: partial mode keeps skipping it
        scores2, metas2, missing2 = client.search(
            q, 5, "pidx", allow_partial=True, partial_timeout=15.0)
        assert len(missing2) == 1

        # restart the victim rank on the SAME port: the stub redials on the
        # next call (rpc.Client auto-reconnect) and, after a load_index
        # broadcast restores its shard from storage, the cluster converges
        # back to complete results on the ORIGINAL client
        vrank = [r for r, i in client.index_rank_to_id.items() if i == victim][0]
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "distributed_faiss_tpu.parallel.server",
             "--rank", str(vrank), "--port", str(victim_port),
             "--storage-dir", storage],
            env={**os.environ, **env},
        ))
        from distributed_faiss_tpu.parallel.client import MultiRankError

        t0 = time.time()
        while True:
            try:
                assert client.load_index("pidx", cfg, force_reload=False)
                break
            except (OSError, MultiRankError):
                # broadcast ops now aggregate per-rank failures into a
                # structured MultiRankError instead of leaking the first
                # OSError out of the pool
                assert time.time() - t0 < 60, "restarted rank never came up"
                time.sleep(0.3)
        scores3, metas3, missing3 = client.search(
            q, 5, "pidx", allow_partial=True, partial_timeout=15.0)
        assert missing3 == []
        for i in range(40):  # full corpus served again, incl. old dead ids
            assert metas3[i][0] == (i,)
        client.search(q, 5, "pidx")  # strict mode healthy again
        client.close()
    finally:
        for p in procs:
            p.kill()


@pytest.mark.slow
def test_crash_recovery_kill9_mid_add_and_mid_save(tmp_path):
    """Fault injection for the atomic-checkpoint design (engine.py tmp+rename;
    fixes the reference's acknowledged torn-write TODO, index.py:443-446):
    SIGKILL a rank mid-add-stream and later mid-save, restart from storage,
    and assert the reload invariant — the last successful save is never
    torn, reload works, metadata join stays consistent, and data loss is
    bounded by the unsaved window."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    procs = launcher.launch_local(2, disc, storage, base_port=13571, env=env)
    procs2 = []
    try:
        from distributed_faiss_tpu import IndexClient, IndexCfg, IndexState

        cfg = IndexCfg(index_builder_type="ivf_simple", dim=16, metric="l2",
                       train_num=200, centroids=4, nprobe=4)
        client = IndexClient(disc)
        client.create_index("cr", cfg)
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2000, 16)).astype(np.float32)
        for s in range(0, 400, 50):
            client.add_index_data("cr", x[s:s + 50], list(range(s, s + 50)))
        t0 = time.time()
        while client.get_state("cr") != IndexState.TRAINED:
            assert time.time() - t0 < 120, "train timeout"
            time.sleep(0.2)
        client.add_buffer_to_index("cr")
        t0 = time.time()
        while client.get_buffer_depth("cr") > 0:
            assert time.time() - t0 < 120, "buffer drain timeout"
            time.sleep(0.2)
        client.save_index("cr")
        saved_ntotal = client.get_ntotal("cr")
        assert saved_ntotal == 400

        # stream more adds and SIGKILL rank 1 mid-stream
        threading.Timer(0.05, procs[1].kill).start()
        added = 400
        try:
            for s in range(400, 2000, 50):
                client.add_index_data("cr", x[s:s + 50], list(range(s, s + 50)))
                added = s + 50
        except Exception:
            pass
        procs[0].kill()  # survivor dies too (whole-cluster crash)
        for p in procs:
            p.wait()
        client.close()

        # restart from the same storage; mid-add SIGKILL must not have torn
        # anything the last save persisted
        disc2 = str(tmp_path / "disc2.txt")
        procs2 = launcher.launch_local(2, disc2, storage, base_port=13581, env=env)
        client2 = IndexClient(disc2)
        assert client2.load_index("cr", cfg, force_reload=False)
        nt = client2.get_ntotal("cr")
        # one batch may have been applied but never acked (killed before
        # the ack): loss AND overshoot are both bounded by one batch window
        assert saved_ntotal <= nt <= added + 50, (saved_ntotal, nt, added)
        scores, metas = client2.search(x[:10], 3, "cr")
        for i in range(10):  # saved prefix must still self-hit, meta intact
            assert metas[i][0] == i
        assert all(isinstance(m, int) and 0 <= m < added + 50
                   for row in metas for m in row)

        # now SIGKILL mid-save: the previous good save must survive a torn
        # writer (atomic tmp+fsync+rename, ordered renames)
        client2.save_index("cr")
        nt_saved2 = client2.get_ntotal("cr")
        for s in range(added, min(added + 600, 2000), 50):
            client2.add_index_data("cr", x[s:s + 50], list(range(s, s + 50)))
        saver = threading.Thread(
            target=lambda: client2.save_index("cr"), daemon=True)
        threading.Timer(0.02, procs2[0].kill).start()
        saver.start()
        saver.join(timeout=60)
        procs2[1].kill()
        for p in procs2:
            p.wait()
        client2.close()

        disc3 = str(tmp_path / "disc3.txt")
        procs3 = launcher.launch_local(2, disc3, storage, base_port=13591, env=env)
        procs2 = procs2 + procs3  # ensure cleanup
        client3 = IndexClient(disc3)
        assert client3.load_index("cr", cfg, force_reload=False)
        nt3 = client3.get_ntotal("cr")
        assert nt3 >= nt_saved2, (nt3, nt_saved2)  # last good save intact
        scores, metas = client3.search(x[:10], 3, "cr")
        for i in range(10):
            assert metas[i][0] == i
        client3.close()
    finally:
        for p in procs + procs2:
            try:
                p.kill()
            except Exception:
                pass


@pytest.mark.slow
def test_local_launch_end_to_end(tmp_path):
    """Full L5 path: launch_local subprocesses -> client -> ingest -> search,
    plus the bulk loader CLI against the same cluster."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    procs = launcher.launch_local(2, disc, storage, base_port=13501, env=env)
    try:
        from distributed_faiss_tpu import IndexClient, IndexCfg, IndexState

        # bulk loader CLI against the live cluster (memmap fp16 ingest)
        mmap_path = str(tmp_path / "data.mmap")
        rows, dim = 600, 16
        rng = np.random.default_rng(0)
        data = rng.standard_normal((rows, dim)).astype(np.float16)
        np.memmap(mmap_path, dtype=np.float16, mode="w+", shape=(rows, dim))[:] = data

        cfg = IndexCfg(index_builder_type="flat", dim=dim, metric="l2", train_num=100)
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        out = subprocess.run(
            [sys.executable, "scripts/load_data.py", "--data", mmap_path,
             "--dtype", "fp16", "--dim", str(dim), "--bs", "100",
             "--discovery", disc, "--index-id", "bulk", "--cfg", cfg_path],
            env={**os.environ, **env}, cwd=repo_root,
            capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]

        client = IndexClient(disc)
        client.cfg = cfg
        assert client.get_ntotal("bulk") == rows
        scores, meta = client.search(np.asarray(data[:3], np.float32), 4, "bulk")
        assert meta[0][0] == 0 and meta[1][0] == 1  # integer-id metadata
        # observability: per-RPC latency counters
        stats = client.get_perf_stats()
        assert len(stats) == 2
        assert stats[0]["search"]["count"] >= 1
        assert stats[0]["add_index_data"]["mean_s"] > 0
        client.close()
    finally:
        for p in procs:
            p.kill()


@pytest.mark.slow
def test_slurm_task_body_via_fake_srun(tmp_path):
    """Real-ish SLURM execution (VERDICT r4 #10): a fake-srun harness spawns
    one subprocess per task with srun's rank env vars (SLURM_PROCID /
    SLURM_LOCALID); each subprocess reconstructs (rank, port) through a
    submitit-compatible JobEnvironment — exactly what launch_slurm's task
    closure does (launcher.py:132-136) — and runs the REAL run_server,
    including its gethostname discovery registration. The client then
    drives the cluster end-to-end."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_port = 13601
    # one "node" hosts all tasks: local ranks 0..n-1 give distinct ports on
    # this single machine (multi-node port reuse needs distinct hosts, which
    # a one-container harness cannot provide)
    num_servers = 3

    # the launch_slurm submit-side step the harness replays (launcher.py:130)
    launcher.write_discovery_header(disc, num_servers)

    # worker = the task body from launch_slurm, verbatim, against a
    # JobEnvironment that reads the same SLURM variables submitit's does
    worker = str(tmp_path / "slurm_task.py")
    with open(worker, "w") as f:
        f.write(
            "import os, sys, types\n"
            f"sys.path.insert(0, {repo_root!r})\n"
            "submitit = types.ModuleType('submitit')\n"
            "class JobEnvironment:\n"
            "    def __init__(self):\n"
            "        self.global_rank = int(os.environ['SLURM_PROCID'])\n"
            "        self.local_rank = int(os.environ['SLURM_LOCALID'])\n"
            "submitit.JobEnvironment = JobEnvironment\n"
            "sys.modules['submitit'] = submitit\n"
            "from distributed_faiss_tpu.parallel.launcher import run_server\n"
            f"base_port, disc, storage = {base_port}, {disc!r}, {storage!r}\n"
            "env = submitit.JobEnvironment()\n"
            "rank = env.global_rank\n"
            "port = base_port + env.local_rank\n"
            "run_server(rank, port, disc, storage, False)\n"
        )

    procs = []
    try:
        for rank in range(num_servers):
            procs.append(subprocess.Popen(
                [sys.executable, worker],
                env={**os.environ, "JAX_PLATFORMS": "cpu",
                     "PYTHONPATH": repo_root,
                     # what srun exports per task
                     "SLURM_PROCID": str(rank),
                     "SLURM_LOCALID": str(rank),
                     "SLURM_NTASKS": str(num_servers),
                     "SLURM_NODEID": "0"},
            ))

        # every rank registered itself (gethostname, like real SLURM tasks)
        deadline = time.time() + 60
        lines = []
        while time.time() < deadline:
            with open(disc) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            if len(lines) >= 1 + num_servers:
                break
            time.sleep(0.2)
        assert len(lines) >= 1 + num_servers, lines

        # end-to-end drive through the discovery file the tasks populated
        from distributed_faiss_tpu import IndexClient, IndexCfg, IndexState

        rng = np.random.default_rng(0)
        x = rng.standard_normal((900, 16)).astype(np.float32)
        cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2",
                       train_num=50)
        client = IndexClient(disc)
        client.create_index("srun", cfg)
        for s in range(0, 900, 300):
            client.add_index_data(
                "srun", x[s:s + 300],
                [(i, f"m{i}") for i in range(s, s + 300)])
        deadline = time.time() + 60
        while time.time() < deadline:
            if (client.get_state("srun") == IndexState.TRAINED
                    and client.get_ntotal("srun") == 900):
                break
            time.sleep(0.2)
        assert client.get_ntotal("srun") == 900
        scores, metas = client.search(x[:5], 3, "srun")
        for i in range(5):
            assert metas[i][0] == (i, f"m{i}")
        client.close()
    finally:
        for p in procs:
            p.kill()
