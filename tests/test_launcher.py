"""Launcher + bulk loader + observability tests.

Models the reference's L5 surface (scripts/server_launcher.py,
scripts/load_data.py) using the local-subprocess backend.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import launcher


def test_discovery_append_locking(tmp_path):
    path = str(tmp_path / "disc.txt")
    launcher.write_discovery_header(path, 16)
    threads = [
        threading.Thread(target=launcher.append_discovery_entry, args=(path, f"h{i}", 1000 + i))
        for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = open(path).read().strip().splitlines()
    assert lines[0] == "16"
    entries = sorted(lines[1:])
    assert len(entries) == 16 and len(set(entries)) == 16


def test_file_lock_contention(tmp_path):
    path = str(tmp_path / "f.txt")
    open(path, "w").close()
    lock = launcher.acquire_file_lock(path)
    with pytest.raises(TimeoutError):
        launcher.acquire_file_lock(path, timeout=0.3)
    launcher.release_file_lock(lock)
    lock2 = launcher.acquire_file_lock(path, timeout=1)
    launcher.release_file_lock(lock2)


def test_slurm_launch_with_mocked_submitit(tmp_path, monkeypatch):
    """launch_slurm never runs in this image (submitit absent), so exercise it
    against a mock: verify the executor parameters mirror the reference's
    AutoExecutor setup (server_launcher.py:111-122) and that the submitted
    task derives rank from global_rank and port from base_port+local_rank
    (reference :59-68)."""
    import types

    recorded = {}

    class FakeJobEnvironment:
        global_rank = 5
        local_rank = 2

    class FakeAutoExecutor:
        def __init__(self, folder):
            recorded["folder"] = folder

        def update_parameters(self, **kw):
            recorded["params"] = kw

        def submit(self, fn):
            recorded["task"] = fn
            return "fake-job"

    fake = types.ModuleType("submitit")
    fake.JobEnvironment = FakeJobEnvironment
    fake.AutoExecutor = FakeAutoExecutor
    monkeypatch.setitem(sys.modules, "submitit", fake)

    disc = str(tmp_path / "disc.txt")
    job = launcher.launch_slurm(
        num_servers=6, num_servers_per_node=2, discovery_path=disc,
        storage_dir=str(tmp_path / "st"), base_port=14000, partition="learnlab",
    )
    assert job == "fake-job"
    assert open(disc).readline().strip() == "6"
    p = recorded["params"]
    assert p["nodes"] == 3 and p["tasks_per_node"] == 2
    assert p["slurm_partition"] == "learnlab"

    served = {}
    monkeypatch.setattr(
        launcher, "run_server",
        lambda rank, port, dp, sd, load: served.update(
            rank=rank, port=port, disc=dp, storage=sd, load=load),
    )
    recorded["task"]()  # what submitit would run on the SLURM task
    assert served["rank"] == 5 and served["port"] == 14002
    assert served["disc"] == disc and served["load"] is False


def test_slurm_launch_without_submitit_raises(monkeypatch, tmp_path):
    monkeypatch.setitem(sys.modules, "submitit", None)
    with pytest.raises(RuntimeError, match="submitit is not installed"):
        launcher.launch_slurm(1, 1, str(tmp_path / "d.txt"), str(tmp_path / "s"))


@pytest.mark.slow
def test_local_launch_end_to_end(tmp_path):
    """Full L5 path: launch_local subprocesses -> client -> ingest -> search,
    plus the bulk loader CLI against the same cluster."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    procs = launcher.launch_local(2, disc, storage, base_port=13501, env=env)
    try:
        from distributed_faiss_tpu import IndexClient, IndexCfg, IndexState

        # bulk loader CLI against the live cluster (memmap fp16 ingest)
        mmap_path = str(tmp_path / "data.mmap")
        rows, dim = 600, 16
        rng = np.random.default_rng(0)
        data = rng.standard_normal((rows, dim)).astype(np.float16)
        np.memmap(mmap_path, dtype=np.float16, mode="w+", shape=(rows, dim))[:] = data

        cfg = IndexCfg(index_builder_type="flat", dim=dim, metric="l2", train_num=100)
        cfg_path = str(tmp_path / "cfg.json")
        cfg.save(cfg_path)

        out = subprocess.run(
            [sys.executable, "scripts/load_data.py", "--data", mmap_path,
             "--dtype", "fp16", "--dim", str(dim), "--bs", "100",
             "--discovery", disc, "--index-id", "bulk", "--cfg", cfg_path],
            env={**os.environ, **env}, cwd=repo_root,
            capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]

        client = IndexClient(disc)
        client.cfg = cfg
        assert client.get_ntotal("bulk") == rows
        scores, meta = client.search(np.asarray(data[:3], np.float32), 4, "bulk")
        assert meta[0][0] == 0 and meta[1][0] == 1  # integer-id metadata
        # observability: per-RPC latency counters
        stats = client.get_perf_stats()
        assert len(stats) == 2
        assert stats[0]["search"]["count"] >= 1
        assert stats[0]["add_index_data"]["mean_s"] > 0
        client.close()
    finally:
        for p in procs:
            p.kill()
