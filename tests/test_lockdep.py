"""Runtime lockdep witness tests (marker ``lockdep``; the subprocess
tier re-runs are additionally ``slow``).

Unit layer: the DFT_LOCKDEP=1 factories detect cycle-forming
acquisition edges (ABBA and longer), self-deadlocks, keep per-thread
held-sets isolated, release Condition keys across ``wait``, and are
plain threading primitives when disabled.

Tier layer (``pytest -m lockdep``, mirrored by the ci.yml ``lockdep``
job): re-run the scheduler, rpc-mux, and mesh-serving suites with the
witness on — every pinned lock in those paths is instrumented, so any
dynamic lock-order inversion the static lock-order checker cannot see
fails loudly instead of hanging a rank.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from distributed_faiss_tpu.utils import lockdep

pytestmark = pytest.mark.lockdep


@pytest.fixture
def witness(monkeypatch):
    monkeypatch.setenv("DFT_LOCKDEP", "1")
    lockdep.reset()
    yield
    lockdep.reset()


# ------------------------------------------------------------------ factories

def test_disabled_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("DFT_LOCKDEP", raising=False)
    assert not lockdep.enabled()
    lk = lockdep.lock("X.lk")
    assert type(lk) is type(threading.Lock())
    assert type(lockdep.rlock("X.rlk")) is type(threading.RLock())
    assert isinstance(lockdep.condition("X.cond"), threading.Condition)


def test_enabled_reads_env(witness):
    assert lockdep.enabled()
    lk = lockdep.lock("X.lk")
    assert isinstance(lk, lockdep._DepLock)
    with lk:
        assert lockdep.held() == ("X.lk",)
    assert lockdep.held() == ()


# ------------------------------------------------------------ cycle detection

def test_abba_cycle_raises(witness):
    a, b = lockdep.lock("T.a"), lockdep.lock("T.b")
    with a:
        with b:
            pass
    assert ("T.a", "T.b") in lockdep.edges()
    with b:
        with pytest.raises(lockdep.LockOrderError, match="T.a"):
            a.acquire()


def test_abba_across_threads_is_caught(witness):
    """The deliberate ABBA-deadlock fixture: thread 1 records a->b,
    thread 2 attempts b->a and must get LockOrderError instead of a
    deadlock (the check runs BEFORE blocking)."""
    a, b = lockdep.lock("AB.a"), lockdep.lock("AB.b")

    def t1():
        with a:
            with b:
                pass

    errors = []

    def t2():
        try:
            with b:
                with a:
                    pass  # pragma: no cover - must raise before here
        except lockdep.LockOrderError as e:
            errors.append(e)

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()
    assert len(errors) == 1
    msg = str(errors[0])
    assert "AB.a" in msg and "AB.b" in msg and "cycle" in msg


def test_three_lock_cycle_chain_in_message(witness):
    a, b, c = (lockdep.lock("C.a"), lockdep.lock("C.b"), lockdep.lock("C.c"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(lockdep.LockOrderError) as exc:
            a.acquire()
    msg = str(exc.value)
    assert "C.a -> C.b" in msg and "C.b -> C.c" in msg


def test_self_deadlock_raises(witness):
    a = lockdep.lock("S.a")
    with a:
        with pytest.raises(lockdep.LockOrderError, match="re-acquires"):
            a.acquire()
    # the failed acquire must not corrupt the held list
    assert lockdep.held() == ()


def test_rlock_reentry_is_legal(witness):
    r = lockdep.rlock("R.r")
    with r:
        with r:
            assert lockdep.held() == ("R.r",)
    assert lockdep.held() == ()


def test_consistent_order_never_raises(witness):
    a, b = lockdep.lock("OK.a"), lockdep.lock("OK.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockdep.edges().keys() == {("OK.a", "OK.b")}


# ------------------------------------------------------- held-set bookkeeping

def test_held_sets_are_per_thread(witness):
    a = lockdep.lock("H.a")
    seen = {}

    def other():
        seen["held"] = lockdep.held()

    with a:
        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert lockdep.held() == ("H.a",)
    assert seen["held"] == ()


def test_condition_wait_releases_key(witness):
    cond = lockdep.condition("W.cond")
    with cond:
        assert lockdep.held() == ("W.cond",)
        # wait() releases the underlying lock: the key must leave the
        # held set for the duration and come back after the timeout
        assert cond.wait(0.01) is False
        assert lockdep.held() == ("W.cond",)
    assert lockdep.held() == ()


def test_condition_wait_unowned_does_not_corrupt_held(witness):
    """wait() without holding the condition must raise threading's own
    RuntimeError and leave the held list untouched (regression: the old
    finally re-added a phantom key, poisoning every later acquisition
    on the thread)."""
    cond = lockdep.condition("W.unowned")
    with pytest.raises(RuntimeError):
        cond.wait(0.01)
    assert lockdep.held() == ()
    with cond:  # the witness stays usable afterwards
        assert lockdep.held() == ("W.unowned",)
    assert lockdep.held() == ()


def test_reset_clears_edges(witness):
    a, b = lockdep.lock("RS.a"), lockdep.lock("RS.b")
    with a:
        with b:
            pass
    assert lockdep.edges()
    lockdep.reset()
    assert lockdep.edges() == {}


def test_error_in_one_thread_leaves_witness_usable(witness):
    a, b = lockdep.lock("E.a"), lockdep.lock("E.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(lockdep.LockOrderError):
            a.acquire()
    # the consistent order still works afterwards
    with a:
        with b:
            pass


# -------------------------------------------------------------- integrations

def test_scheduler_runs_clean_under_witness(witness):
    """The serving scheduler's real lock choreography (condition +
    per-request events + batcher thread) must record no cycle."""
    from distributed_faiss_tpu.serving.scheduler import SearchScheduler
    from distributed_faiss_tpu.utils.config import SchedulerCfg

    def search_fn(index_id, q, k, emb):
        return (np.zeros((q.shape[0], k), np.float32),
                [[None] * k for _ in range(q.shape[0])], None)

    sched = SearchScheduler(search_fn, SchedulerCfg(max_wait_ms=1.0))
    try:
        q = np.zeros((2, 4), np.float32)
        out = sched.submit("idx", q, 3)
        assert out[0].shape == (2, 3)
    finally:
        sched.stop()
    es = set(lockdep.edges())
    assert not [e for e in es if (e[1], e[0]) in es]  # no 2-cycles recorded


def test_engine_locks_are_instrumented(witness):
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg

    idx = Index(IndexCfg(dim=8, index_builder_type="flat"))
    assert isinstance(idx.buffer_lock, lockdep._DepLock)
    assert isinstance(idx.index_lock, lockdep._DepLock)
    idx.add_batch(np.zeros((4, 8), np.float32), None,
                  train_async_if_triggered=False)
    # buffer_lock and index_lock both taken; only the designed
    # buffer->index edge (or none) may exist — never the reverse
    assert ("Index.index_lock", "Index.buffer_lock") not in lockdep.edges()


# ------------------------------------------------------------------ the tier

@pytest.mark.slow
def test_scheduler_and_rpcmux_suites_under_witness():
    """The lockdep-tier satellite: re-run the scheduler + rpc-mux +
    versions suites with DFT_LOCKDEP=1 — every pinned lock in the
    serving path (including the new version-watermark / pinned-snapshot
    / HLC locks) runs instrumented, so a dynamic lock-order inversion
    fails the suite."""
    env = dict(os.environ, DFT_LOCKDEP="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_scheduler.py",
         "tests/test_scheduler_identity.py", "tests/test_rpc_mux.py",
         "tests/test_versions.py",
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, (
        f"lockdep scheduler/rpcmux tier failed:\n{proc.stdout[-4000:]}\n"
        f"{proc.stderr[-2000:]}"
    )


@pytest.mark.slow
def test_mesh_serving_suite_under_witness():
    """Mesh-serving under the witness, on the virtual 8-device CPU mesh
    (the scheduler->engine->mesh one-launch path holds index_lock around
    the pjit dispatch by design — the witness proves it stays acyclic)."""
    env = dict(os.environ, DFT_LOCKDEP="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q",
         "-m", "mesh and not slow", "-p", "no:cacheprovider"],
        env=env, capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, (
        f"lockdep mesh tier failed:\n{proc.stdout[-4000:]}\n"
        f"{proc.stderr[-2000:]}"
    )
