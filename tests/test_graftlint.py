"""graftlint test tier (marked ``lint``, runs under tier-1).

Three layers:
- golden fixtures: each checker fires on its known-bad fixture at EXACT
  (rule, line) locations and stays silent on its known-clean twin;
- the self-enforcing repo lint: ``distributed_faiss_tpu/`` + ``tools/``
  must produce zero findings — a regression that re-introduces a host
  sync, an unlocked access, an unguarded kernel route, or a bare
  ``pickle.loads`` fails the ordinary test run;
- the CLI: exit codes and ``--format=json`` shape.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.graftlint import lint_paths

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join("tests", "fixtures", "lint")


def _lint(relpath):
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        return lint_paths([relpath])
    finally:
        os.chdir(cwd)


def _locs(findings):
    return sorted((f.rule, f.line) for f in findings)


# ---------------------------------------------------------------- fixtures

def test_host_sync_bad_fixture():
    assert _locs(_lint(f"{FIX}/host_sync_bad.py")) == [
        ("host-sync", 17),  # float(s.max())
        ("host-sync", 18),  # .item()
        ("host-sync", 19),  # np.asarray over a jitted call
        ("host-sync", 20),  # jax.device_get
    ]


def test_host_sync_clean_fixture():
    assert _lint(f"{FIX}/host_sync_clean.py") == []


def test_recompile_bad_fixture():
    assert _locs(_lint(f"{FIX}/recompile_bad.py")) == [
        ("recompile-hazard", 10),  # non-static scalar param
        ("recompile-hazard", 17),  # branch on traced param
        ("recompile-hazard", 23),  # inline jax.jit
    ]


def test_recompile_clean_fixture():
    assert _lint(f"{FIX}/recompile_clean.py") == []


def test_dtype_bad_fixture():
    assert _locs(_lint(f"{FIX}/ops/dtype_bad.py")) == [
        ("dtype-discipline", 8),   # einsum, implicit accumulation
        ("dtype-discipline", 13),  # bf16 dot_general, implicit accumulation
    ]


def test_dtype_clean_fixture():
    assert _lint(f"{FIX}/ops/dtype_clean.py") == []


def test_locks_bad_fixture():
    assert _locs(_lint(f"{FIX}/locks_bad.py")) == [
        ("lock-discipline", 25),  # unlocked minority access
    ]


def test_locks_clean_fixture():
    assert _lint(f"{FIX}/locks_clean.py") == []


def test_pallas_guard_bad_fixture():
    assert _locs(_lint(f"{FIX}/pallas_bad.py")) == [
        ("pallas-guard", 13),  # pallas_call outside ops/*_pallas.py
        ("pallas-guard", 19),  # unguarded public route into the kernel
    ]


def test_pallas_guard_clean_fixture():
    assert _lint(f"{FIX}/ops/clean_pallas.py") == []


def test_pickle_bad_fixture():
    assert _locs(_lint(f"{FIX}/parallel/pickle_bad.py")) == [
        ("pickle-safety", 5),   # module-level pickle.loads
        ("pickle-safety", 9),   # pickle.loads
        ("pickle-safety", 13),  # pickle.load
        ("pickle-safety", 18),  # pickle.loads under a module-level if
    ]


def test_pickle_clean_fixture():
    assert _lint(f"{FIX}/parallel/pickle_clean.py") == []


def test_lock_order_bad_fixture():
    assert _locs(_lint(f"{FIX}/lock_order_bad.py")) == [
        ("lock-order", 16),  # Pair.a -> Pair.b vs Pair.b -> Pair.a
        ("lock-order", 37),  # CrossPair cycle through a call edge
    ]


def test_lock_order_clean_fixture():
    assert _lint(f"{FIX}/lock_order_clean.py") == []


def test_lock_order_finding_carries_full_chain():
    findings = _lint(f"{FIX}/lock_order_bad.py")
    msg = next(f.message for f in findings if f.line == 16)
    assert "Pair.a -> Pair.b" in msg and "Pair.b -> Pair.a" in msg
    assert "lock_order_bad.py:21" in msg  # the closing edge's provenance
    msg = next(f.message for f in findings if f.line == 37)
    assert "calls CrossPair._locked_y" not in msg  # chain names locks, not calls
    assert "CrossPair.x -> CrossPair.y" in msg


def test_blocking_bad_fixture():
    assert _locs(_lint(f"{FIX}/blocking_bad.py")) == [
        ("blocking-under-lock", 29),  # sendall under lock
        ("blocking-under-lock", 33),  # untimed Event.wait under lock
        ("blocking-under-lock", 37),  # unbounded Thread.join under lock
        ("blocking-under-lock", 41),  # time.sleep under lock
        ("blocking-under-lock", 45),  # sendall hidden one helper down
        ("blocking-under-lock", 49),  # jitted launch under lock
    ]


def test_blocking_clean_fixture():
    assert _lint(f"{FIX}/blocking_clean.py") == []


def test_blocking_flags_later_with_item(tmp_path):
    """Multi-item withs evaluate later context expressions AFTER earlier
    locks are acquired: `with self.lock, sock.accept():` blocks under
    the lock and must be flagged (regression for the walker passing the
    pre-with held set to later items)."""
    src = (
        "import threading\n"
        "\n"
        "class C:\n"
        "    def __init__(self, sock):\n"
        "        self.lock = threading.Lock()\n"
        "        self.sock = sock\n"
        "\n"
        "    def acc(self):\n"
        "        with self.lock, self.sock.accept() as conn:\n"
        "            return conn\n"
    )
    p = tmp_path / "m.py"
    p.write_text(src)
    findings = lint_paths([str(p)])
    assert [(f.rule, f.line) for f in findings] == [
        ("blocking-under-lock", 9)]


def test_frameproto_bad_fixture():
    locs = sorted((f.rule, os.path.basename(f.path), f.line)
                  for f in _lint(f"{FIX}/frameproto_bad"))
    assert locs == [
        ("frame-protocol", "rpc.py", 11),     # duplicate wire value
        ("frame-protocol", "rpc.py", 13),     # unregistered tagged kind
        ("frame-protocol", "rpc.py", 14),     # dead kind
        ("frame-protocol", "rpc.py", 41),     # meta key 'req_id' unread
        ("frame-protocol", "rpc.py", 42),     # meta key 'trace' unread
        ("frame-protocol", "server.py", 15),  # CALL arity over-unpack
        ("frame-protocol", "server.py", 23),  # KIND_BUSY unhandled by client
        ("frame-protocol", "server.py", 27),  # KIND_PROGRESS unhandled
    ]


def test_frameproto_clean_fixture():
    assert _lint(f"{FIX}/frameproto_clean") == []


def test_frameproto_wire_bad_fixture():
    """The ISSUE 14 binary-wire contract rules: flag-bit collision,
    unserved binary-encodable op, pickle decode outside
    restricted_loads."""
    locs = sorted((f.rule, os.path.basename(f.path), f.line)
                  for f in _lint(f"{FIX}/frameproto_wire_bad"))
    assert locs == [
        ("frame-protocol", "rpc.py", 11),  # KIND value collides with flag
        ("frame-protocol", "rpc.py", 13),  # BINARY_CALL_OPS op unserved
        ("frame-protocol", "rpc.py", 25),  # pickle.loads outside the pin
    ]
    msgs = {f.line: f.message for f in _lint(f"{FIX}/frameproto_wire_bad")}
    assert "WIRE_BINARY_FLAG" in msgs[11]
    assert "export_all" in msgs[13]
    assert "restricted_loads" in msgs[25]


def test_stale_pins_fail_the_repo_lint(monkeypatch):
    """The frame-protocol stale-pin audit: drift in the reviewed PINS map
    (class gone, attribute gone, lock gone) turns into findings anchored
    at the checks/locks.py entry."""
    from tools.graftlint.checks import locks as locks_mod

    doctored = dict(locks_mod.PINS)
    doctored[("GhostClass", "x")] = "lck"              # class missing
    doctored[("Index", "phantom_attr")] = "index_lock"  # attr missing
    doctored[("IndexServer", "phantom2")] = "phantom_lock"  # attr AND lock
    monkeypatch.setattr(locks_mod, "PINS", doctored)
    stale = [f for f in _lint("distributed_faiss_tpu")
             if "stale pin" in f.message]
    assert len(stale) == 4
    assert {f.rule for f in stale} == {"frame-protocol"}
    assert all(f.path.endswith("checks/locks.py") for f in stale)


def test_pins_all_resolve_today():
    """The PR 7 audit result, pinned: every hand-maintained PINS entry
    currently resolves (no findings from the stale-pin audit)."""
    assert [f for f in _lint("distributed_faiss_tpu")
            if "stale pin" in f.message] == []


def test_suppression_silences_bad_fixture(tmp_path):
    src = open(os.path.join(REPO, FIX, "parallel", "pickle_bad.py")).read()
    sub = tmp_path / "parallel"
    sub.mkdir()
    patched = src.replace(
        "return pickle.loads(raw)  # line 9: bare loads on wire bytes",
        "return pickle.loads(raw)  # graftlint: ok(pickle-safety): test",
    )
    (sub / "pickle_bad.py").write_text(patched)
    findings = lint_paths([str(sub / "pickle_bad.py")])
    assert _locs(findings) == [
        ("pickle-safety", 5), ("pickle-safety", 13), ("pickle-safety", 18)]


def test_threads_bad_fixture():
    assert _locs(_lint(f"{FIX}/threads_bad")) == [
        ("thread-lifecycle", 15),  # unnamed
        ("thread-lifecycle", 20),  # daemon status implicit
        ("thread-lifecycle", 25),  # chained start, untracked
        ("thread-lifecycle", 29),  # tracked but no join path from stop()
        ("thread-lifecycle", 35),  # raw _thread.start_new_thread
    ]


def test_threads_clean_fixture():
    # exercises: helper-resident join reachable from stop(), tuple-swap
    # drain aliasing, container tracking, escaping factory thread
    assert _lint(f"{FIX}/threads_clean") == []


def test_durability_bad_fixture():
    assert _locs(_lint(f"{FIX}/durability_bad")) == [
        ("generation-commit", 15),  # open(..., 'w') into storage
        ("generation-commit", 22),  # raw rename inside storage
        ("generation-commit", 27),  # json.dump straight into storage...
        ("generation-commit", 27),  # ...via an inline open('w')
        ("generation-commit", 34),  # MANIFEST outside _commit_generation
        ("generation-commit", 41),  # data file written after the MANIFEST
        ("generation-commit", 49),  # tmp+rename without fsync
    ]


def test_durability_clean_fixture():
    # post-manifest cfg.json convenience copy and a hand-rolled
    # tmp+fsync+rename are both sanctioned
    assert _lint(f"{FIX}/durability_clean") == []


def test_knobs_bad_fixture():
    locs = sorted((f.rule, os.path.basename(f.path), f.line)
                  for f in _lint(f"{FIX}/knobs_bad"))
    assert locs == [
        ("env-knob-drift", "OPERATIONS.md", 6),  # default drift (7 vs 5)
        ("env-knob-drift", "OPERATIONS.md", 7),  # stale doc knob
        ("env-knob-drift", "config.py", 7),      # undocumented code knob
        ("env-knob-drift", "mod.py", 8),         # ad-hoc env read
    ]


def test_knobs_clean_fixture():
    # schema + envutil knobs documented, computed default skipped
    assert _lint(f"{FIX}/knobs_clean") == []


def test_exceptions_bad_fixture():
    assert _locs(_lint(f"{FIX}/exceptions_bad")) == [
        ("exception-classification", 12),  # silent broad swallow
        ("exception-classification", 21),  # broad except driving a retry
        ("exception-classification", 30),  # bare except
        ("exception-classification", 40),  # hot-path swallow-and-pass
    ]


def test_exceptions_clean_fixture():
    # narrow classes, RETRYABLE_ERRORS-gated retry, re-classification,
    # outcome recording, logged guards, narrow hot-path pass
    assert _lint(f"{FIX}/exceptions_clean") == []


def test_races_bad_fixture():
    assert _locs(_lint(f"{FIX}/races_bad")) == [
        ("shared-state-race", 12),  # in-class stale atomic() marker
        ("shared-state-race", 21),  # thread-root write vs lock-free api read
        ("shared-state-race", 28),  # api write vs lock-free thread-root read
        ("shared-state-race", 34),  # outside-class stale atomic() marker
    ]


def test_races_finding_carries_per_root_provenance():
    msgs = {f.line: f.message for f in _lint(f"{FIX}/races_bad")}
    # both sides named with root + file:line, and the lock-guarded attr
    # (jobs, under self.lock on every root) is NOT among the findings
    assert "thread:Worker._run" in msgs[21] and "`api`" in msgs[21]
    assert "races_bad/mod.py:32" in msgs[21]  # the read side's provenance
    assert "races_bad/mod.py:19" in msgs[28]  # the read side's provenance
    assert "stale atomic(phantom)" in msgs[12]
    assert "stale atomic(ghost)" in msgs[34]
    assert not any("jobs" in m for m in msgs.values())


def test_races_clean_fixture():
    # locked on every root / atomic()-waived counter / reasoned ok():
    # all three escape hatches, zero findings, zero rot
    assert _lint(f"{FIX}/races_clean") == []


def test_races_rule_gates_off_on_subset_lints():
    """--changed subsets cannot see the thread roots in OTHER modules, so
    the whole rule (findings AND the atomic-rot audit) gates off."""
    assert lint_paths(
        [os.path.join(FIX, "races_bad", "mod.py")], subset=True) == []


# ------------------------------------------------------- suppression audit

def test_stale_suppression_is_flagged(tmp_path):
    """The rot audit: an ok() that suppresses nothing is itself a
    finding; one that earns its keep is not."""
    p = tmp_path / "parallel"
    p.mkdir()
    (p / "mod.py").write_text(
        "import pickle\n"
        "\n"
        "\n"
        "def live(raw):\n"
        "    # graftlint: ok(pickle-safety): fixture waiver\n"
        "    return pickle.loads(raw)\n"
        "\n"
        "\n"
        "def stale(x):\n"
        "    return x + 1  # graftlint: ok(host-sync): nothing here\n"
    )
    findings = lint_paths([str(p / "mod.py")])
    assert [(f.rule, f.line) for f in findings] == [
        ("unused-suppression", 10)]
    assert "ok(host-sync)" in findings[0].message


def test_unknown_rule_suppression_is_flagged(tmp_path):
    (tmp_path / "m.py").write_text(
        "X = 1  # graftlint: ok(pickel-safety): typo'd rule\n")
    findings = lint_paths([str(tmp_path / "m.py")])
    assert [(f.rule, f.line) for f in findings] == [
        ("unused-suppression", 1)]
    assert "unknown rule" in findings[0].message


def test_dormant_waiver_opt_out(tmp_path):
    """ok(unused-suppression) beside a deliberately-dormant waiver
    silences the audit for it — and is itself counted as used."""
    (tmp_path / "m.py").write_text(
        "# graftlint: ok(unused-suppression): version-gated path below\n"
        "X = 1  # graftlint: ok(host-sync): fires only on jax<0.4\n")
    assert lint_paths([str(tmp_path / "m.py")]) == []


def test_orphaned_dormant_waiver_marker_is_flagged(tmp_path):
    """The opt-out marker is itself audited: one whose waived neighbor
    was deleted is rot too — the audit's escape hatch cannot be the one
    place rot accumulates."""
    (tmp_path / "m.py").write_text(
        "# graftlint: ok(unused-suppression): covered a waiver, now gone\n"
        "X = 1\n")
    findings = lint_paths([str(tmp_path / "m.py")])
    assert [(f.rule, f.line) for f in findings] == [
        ("unused-suppression", 1)]
    assert "orphaned" in findings[0].message


def test_subset_lint_skips_cross_artifact_rules():
    """subset=True (the --changed path) gates off the rot audit and the
    knob/doc cross-check: a config.py-only changed set must not flag
    every knob whose reader wasn't in the subset as a stale doc row, and
    a suppression whose finding resolves through unlinted modules must
    not read as stale."""
    config = os.path.join("distributed_faiss_tpu", "utils", "config.py")
    engine = os.path.join("distributed_faiss_tpu", "engine.py")
    assert lint_paths([config], subset=True) == []
    assert lint_paths([engine], subset=True) == []


def test_docstring_mentions_are_not_suppressions(tmp_path):
    """The ok()/hot syntax quoted inside a string literal neither
    suppresses nor trips the audit (comment-token scanning)."""
    (tmp_path / "m.py").write_text(
        '"""Docs: use ``# graftlint: ok(host-sync)`` to waive."""\n'
        "X = 1\n")
    assert lint_paths([str(tmp_path / "m.py")]) == []


# ---------------------------------------------------------- self-enforcing

def test_repo_is_lint_clean():
    findings = _lint("distributed_faiss_tpu") + _lint("tools")
    assert findings == [], "\n".join(str(f) for f in findings)


# ----------------------------------------------------------------- the CLI

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_clean_repo_exits_zero():
    proc = _cli("distributed_faiss_tpu", "tools")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_bad_fixture_exits_one_with_json():
    proc = _cli("--format=json", f"{FIX}/parallel/pickle_bad.py")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["count"] == 4
    assert {f["rule"] for f in payload["findings"]} == {"pickle-safety"}
    assert all(
        set(f) == {"rule", "path", "line", "col", "message"}
        for f in payload["findings"]
    )


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("host-sync", "recompile-hazard", "dtype-discipline",
                 "lock-discipline", "lock-order", "blocking-under-lock",
                 "frame-protocol", "pallas-guard", "pickle-safety",
                 "thread-lifecycle", "generation-commit", "env-knob-drift",
                 "exception-classification", "shared-state-race"):
        assert rule in proc.stdout


def test_all_fourteen_checkers_registered():
    from tools.graftlint import checks

    assert len(checks.ALL) == 14
    assert len(checks.RULES) == 14


def test_cli_changed_mode(tmp_path):
    """--changed lints exactly the files touched vs HEAD (plus
    untracked) under the default paths, in a scratch git repo."""
    import shutil

    repo = tmp_path / "repo"
    pkg = repo / "distributed_faiss_tpu"
    pkg.mkdir(parents=True)
    (pkg / "clean.py").write_text("X = 1\n")
    env = dict(os.environ, PYTHONPATH=REPO,
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, env=env, check=True,
                       capture_output=True)

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    def changed(*args, cwd=repo):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--changed", *args],
            cwd=cwd, env=env, capture_output=True, text=True, timeout=120)

    proc = changed()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no changed files" in proc.stdout

    # an untracked bad file under the default paths is picked up (in
    # parallel/ so the path-scoped pickle-safety rule applies to it)
    (pkg / "parallel").mkdir()
    shutil.copy(os.path.join(REPO, FIX, "parallel", "pickle_bad.py"),
                pkg / "parallel" / "bad.py")
    proc = changed()
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "pickle-safety" in proc.stdout

    # ...from a subdirectory too: git emits repo-root-relative names, so
    # a cwd-relative resolve would silently lint nothing and false-pass
    proc = changed(cwd=pkg)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "pickle-safety" in proc.stdout

    # ...and removing it returns to exit 0
    (pkg / "parallel" / "bad.py").unlink()
    proc = changed()
    assert proc.returncode == 0, proc.stdout + proc.stderr
