"""graftlint test tier (marked ``lint``, runs under tier-1).

Three layers:
- golden fixtures: each checker fires on its known-bad fixture at EXACT
  (rule, line) locations and stays silent on its known-clean twin;
- the self-enforcing repo lint: ``distributed_faiss_tpu/`` + ``tools/``
  must produce zero findings — a regression that re-introduces a host
  sync, an unlocked access, an unguarded kernel route, or a bare
  ``pickle.loads`` fails the ordinary test run;
- the CLI: exit codes and ``--format=json`` shape.
"""

import json
import os
import subprocess
import sys

import pytest

from tools.graftlint import lint_paths

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join("tests", "fixtures", "lint")


def _lint(relpath):
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        return lint_paths([relpath])
    finally:
        os.chdir(cwd)


def _locs(findings):
    return sorted((f.rule, f.line) for f in findings)


# ---------------------------------------------------------------- fixtures

def test_host_sync_bad_fixture():
    assert _locs(_lint(f"{FIX}/host_sync_bad.py")) == [
        ("host-sync", 17),  # float(s.max())
        ("host-sync", 18),  # .item()
        ("host-sync", 19),  # np.asarray over a jitted call
        ("host-sync", 20),  # jax.device_get
    ]


def test_host_sync_clean_fixture():
    assert _lint(f"{FIX}/host_sync_clean.py") == []


def test_recompile_bad_fixture():
    assert _locs(_lint(f"{FIX}/recompile_bad.py")) == [
        ("recompile-hazard", 10),  # non-static scalar param
        ("recompile-hazard", 17),  # branch on traced param
        ("recompile-hazard", 23),  # inline jax.jit
    ]


def test_recompile_clean_fixture():
    assert _lint(f"{FIX}/recompile_clean.py") == []


def test_dtype_bad_fixture():
    assert _locs(_lint(f"{FIX}/ops/dtype_bad.py")) == [
        ("dtype-discipline", 8),   # einsum, implicit accumulation
        ("dtype-discipline", 13),  # bf16 dot_general, implicit accumulation
    ]


def test_dtype_clean_fixture():
    assert _lint(f"{FIX}/ops/dtype_clean.py") == []


def test_locks_bad_fixture():
    assert _locs(_lint(f"{FIX}/locks_bad.py")) == [
        ("lock-discipline", 25),  # unlocked minority access
    ]


def test_locks_clean_fixture():
    assert _lint(f"{FIX}/locks_clean.py") == []


def test_pallas_guard_bad_fixture():
    assert _locs(_lint(f"{FIX}/pallas_bad.py")) == [
        ("pallas-guard", 13),  # pallas_call outside ops/*_pallas.py
        ("pallas-guard", 19),  # unguarded public route into the kernel
    ]


def test_pallas_guard_clean_fixture():
    assert _lint(f"{FIX}/ops/clean_pallas.py") == []


def test_pickle_bad_fixture():
    assert _locs(_lint(f"{FIX}/parallel/pickle_bad.py")) == [
        ("pickle-safety", 5),   # module-level pickle.loads
        ("pickle-safety", 9),   # pickle.loads
        ("pickle-safety", 13),  # pickle.load
        ("pickle-safety", 18),  # pickle.loads under a module-level if
    ]


def test_pickle_clean_fixture():
    assert _lint(f"{FIX}/parallel/pickle_clean.py") == []


def test_suppression_silences_bad_fixture(tmp_path):
    src = open(os.path.join(REPO, FIX, "parallel", "pickle_bad.py")).read()
    sub = tmp_path / "parallel"
    sub.mkdir()
    patched = src.replace(
        "return pickle.loads(raw)  # line 9: bare loads on wire bytes",
        "return pickle.loads(raw)  # graftlint: ok(pickle-safety): test",
    )
    (sub / "pickle_bad.py").write_text(patched)
    findings = lint_paths([str(sub / "pickle_bad.py")])
    assert _locs(findings) == [
        ("pickle-safety", 5), ("pickle-safety", 13), ("pickle-safety", 18)]


# ---------------------------------------------------------- self-enforcing

def test_repo_is_lint_clean():
    findings = _lint("distributed_faiss_tpu") + _lint("tools")
    assert findings == [], "\n".join(str(f) for f in findings)


# ----------------------------------------------------------------- the CLI

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_clean_repo_exits_zero():
    proc = _cli("distributed_faiss_tpu", "tools")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_bad_fixture_exits_one_with_json():
    proc = _cli("--format=json", f"{FIX}/parallel/pickle_bad.py")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["count"] == 4
    assert {f["rule"] for f in payload["findings"]} == {"pickle-safety"}
    assert all(
        set(f) == {"rule", "path", "line", "col", "message"}
        for f in payload["findings"]
    )


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("host-sync", "recompile-hazard", "dtype-discipline",
                 "lock-discipline", "pallas-guard", "pickle-safety"):
        assert rule in proc.stdout
