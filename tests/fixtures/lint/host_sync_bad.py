"""host-sync known-bad fixture: every flagged line is a hot-path sync."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _score(q, x):
    return jnp.dot(q, x.T, preferred_element_type=jnp.float32)


# graftlint: hot
def serve(q, x):
    s = _score(q, x)
    best = s.max()            # line 16: not flagged (no coercion wrapper)
    peak = float(s.max())     # line 17: host-sync (float over reduction)
    one = s[0, 0].item()      # line 18: host-sync (.item())
    host = np.asarray(_score(q, x))   # line 19: host-sync (np over jitted)
    dev = jax.device_get(s)   # line 20: host-sync (device_get)
    return best, peak, one, host, dev
