"""blocking-under-lock known-bad fixture: direct socket/join/wait/sleep
blocking under a held lock, an indirect (helper-hidden) socket write,
and a jitted device launch under a lock."""

import threading
import time

import jax


@jax.jit
def _scan(x):
    return x


def _push(sock, data):
    sock.sendall(data)


class Conn:
    def __init__(self, sock, thread):
        self.sock = sock
        self.thread = thread
        self.lock = threading.Lock()
        self.done = threading.Event()

    def send_locked(self, data):
        with self.lock:
            self.sock.sendall(data)  # line 29: socket op under lock

    def wait_locked(self):
        with self.lock:
            self.done.wait()  # line 33: untimed wait under lock

    def join_locked(self):
        with self.lock:
            self.thread.join()  # line 37: unbounded join under lock

    def sleep_locked(self):
        with self.lock:
            time.sleep(0.1)  # line 41: sleep under lock

    def indirect_locked(self, data):
        with self.lock:
            _push(self.sock, data)  # line 45: sendall via helper

    def launch_locked(self, x):
        with self.lock:
            return _scan(x)  # line 49: jitted launch under lock
