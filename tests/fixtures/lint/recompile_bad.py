"""recompile-hazard known-bad fixture."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def topk_scan(x, k: int, chunk: int = 512):  # line 10: chunk not static
    del chunk
    return jax.lax.top_k(x, k)


@jax.jit
def branchy(x, threshold):
    if threshold > 0:  # line 17: Python branch on traced param
        return x * threshold
    return x


def dispatch(x):
    fn = jax.jit(lambda v: v * 2)  # line 23: inline jit per call
    return fn(x)
