"""host-sync known-clean fixture: hot path with one explicit np host op."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _score(q, x):
    return jnp.dot(q, x.T, preferred_element_type=jnp.float32)


# graftlint: hot
def serve(q, x):
    vals = _score(q, x)
    out = np.empty(vals.shape, np.float32)
    out[:] = vals  # buffer-protocol fetch, the designed one-per-block sync
    peak = np.max(out, initial=0.0)  # explicit np.* host reduction: clean
    return out, peak


def cold_path(q, x):
    # not hot, not annotated: coercions here are off the serving path
    return float(_score(q, x).max())
