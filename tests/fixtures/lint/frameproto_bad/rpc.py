"""frame-protocol known-bad fixture (protocol module): a duplicated
wire value, an unregistered tagged kind, a dead kind, a client pack
site whose arity the paired server over-unpacks, and CALL meta keys
the paired server never reads."""

KIND_CALL = 0
KIND_RESULT = 1
KIND_ERROR = 2
KIND_CLOSE = 3
KIND_BUSY = 4
KIND_PROGRESS = 4  # line 10: reuses wire value 4 (KIND_BUSY)
KIND_RESULT_MUX = 5
KIND_ERROR_MUX = 6  # line 12: tagged kind missing from MUX_RESPONSE_KINDS
KIND_PING = 7  # line 13: defined, never sent/dispatched/registered

MUX_RESPONSE_KINDS = {KIND_RESULT: KIND_RESULT_MUX}
_MUX_TO_BASE = {v: k for k, v in MUX_RESPONSE_KINDS.items()}


def pack_frame(kind, obj=None):
    return [bytes([kind])]


def send_frame(sock, kind, obj=None):
    for part in pack_frame(kind, obj):
        sock.sendall(part)


def recv_frame(sock):
    return sock.recv(1)[0], None


class Client:
    def call(self, fname, args):
        # 2-element CALL payload; the server unpacks three
        send_frame(self.sock, KIND_CALL, (fname, args))
        kind, payload = recv_frame(self.sock)
        return self._interpret(kind, payload)

    def call_traced(self, fname, args):
        meta = {"req_id": 1}  # meta keys the server's _one_call never
        meta["trace"] = "t"   # reads (.get) — dead on the wire
        send_frame(self.sock, KIND_CALL, (fname, args, meta))

    def close(self):
        send_frame(self.sock, KIND_CLOSE, None)

    def _reader_loop(self, sock):
        while True:
            kind, payload = recv_frame(sock)
            base = _MUX_TO_BASE.get(kind)
            if base is not None:
                kind = base

    def _interpret(self, kind, payload):
        if kind == KIND_RESULT:
            return payload
        if kind == KIND_ERROR:
            raise RuntimeError(payload)
        # KIND_BUSY and KIND_PROGRESS fall through: unexpected frame kind
        raise RuntimeError(f"unexpected frame kind {kind}")
