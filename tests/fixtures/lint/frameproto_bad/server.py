"""frame-protocol known-bad fixture (paired server): sends kinds the
client never interprets and unpacks more CALL payload elements than the
client packs."""

from tests.fixtures.lint.frameproto_bad import rpc


class Server:
    def _one_call(self, conn):
        kind, payload = rpc.recv_frame(conn)
        if kind == rpc.KIND_CLOSE:
            raise SystemExit
        if kind != rpc.KIND_CALL:
            raise RuntimeError(f"unexpected frame kind {kind}")
        fname, args, kwargs = payload  # line 15: 3-way unpack of a 2-tuple
        try:
            ret = getattr(self, fname)(*args, **kwargs)
            rpc.send_frame(conn, rpc.KIND_RESULT, ret)
        except Exception as e:
            rpc.send_frame(conn, rpc.KIND_ERROR, str(e))

    def shed(self, conn):
        rpc.send_frame(conn, rpc.KIND_BUSY, {})  # line 23: client lacks BUSY

    def notify(self, conn):
        # line 26-27: client never handles PROGRESS at all
        rpc.send_frame(conn, rpc.KIND_PROGRESS, {"pct": 50})
