"""recompile-hazard known-clean fixture."""

import functools

import jax
import jax.numpy as jnp

_double = jax.jit(lambda v: v * 2)  # module-level: one cache entry


@functools.partial(jax.jit, static_argnames=("k", "chunk", "metric"))
def topk_scan(x, k: int, chunk: int = 512, metric: str = "l2"):
    del chunk, metric
    return jax.lax.top_k(x, k)


@jax.jit
def masked(x, vmin=None):
    if vmin is None:  # structural `is None` branch: clean
        return x
    return jnp.maximum(x, vmin)


def dispatch(x):
    return _double(x)
