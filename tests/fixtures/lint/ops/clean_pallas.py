"""pallas-guard known-clean fixture: the kernel lives in an ops/*_pallas.py
module and the public entry point routes through pallas_guarded."""

import jax
from jax.experimental import pallas as pl


def pallas_guarded(index, call):
    """Stand-in guard with the real contract's shape (kernel -> XLA oracle)."""
    try:
        return call(True)
    except Exception:
        return call(False)


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def double_pallas(x):
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
    )(x)


def _double_xla(x):
    return x * 2


def serve(index, x):
    return pallas_guarded(
        index, lambda p: double_pallas(x) if p else _double_xla(x))
