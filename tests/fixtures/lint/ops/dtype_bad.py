"""dtype-discipline known-bad fixture (lives under ops/ to be in scope)."""

import jax
import jax.numpy as jnp


def scores(q, x):
    ip = jnp.einsum("qd,nd->qn", q, x)  # line 8: no preferred_element_type
    return ip


def scan_bf16(q, x):
    return jax.lax.dot_general(  # line 13: bf16 operands, implicit accum
        q.astype(jnp.bfloat16), x.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
    )
