"""dtype-discipline known-clean fixture."""

import jax
import jax.numpy as jnp


def scores(q, x):
    return jnp.einsum("qd,nd->qn", q, x,
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)


def scan_bf16(q, x):
    return jax.lax.dot_general(
        q.astype(jnp.bfloat16), x.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
