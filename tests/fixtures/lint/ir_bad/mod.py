"""Doctored IR-tier fixture: every jaxpr rule fires, with pinned lines.

Loaded by tests/test_graftlint_ir.py via importlib (never imported by the
package) and fed to ``lint_ir`` through fixture rows carrying the
callables directly.  Line numbers are asserted exactly — keep the layout
stable or update the golden expectations.
"""

import numpy as np

import jax
import jax.numpy as jnp

# 513 * 512 * 4 = 1,050,624 bytes: just over the 1 MiB const limit
_BIG = np.ones((513, 512), np.float32)


def _leak(x):
    return np.asarray(x).sum()


def _host_norm(x):
    return np.linalg.norm(np.asarray(x), axis=-1)


@jax.jit
def residency_bad(x):
    jax.debug.callback(_leak, x)  # line 28: host callback inside the program
    return x * 2.0


@jax.jit
def callback_bad(x):
    # line 35: pure_callback whose target is not in the allowlist
    return jax.pure_callback(
        _host_norm, jax.ShapeDtypeStruct((4,), jnp.float32), x)


@jax.jit
def dtype_bad(a, b):
    return jnp.dot(a, b)  # line 41: bf16 x bf16 accumulating in bf16


@jax.jit
def const_bad(x):  # anchored at the @jax.jit line above (44): def-site rule
    return x + _BIG  # the weight-sized array is baked in as a const


@jax.jit
def unregistered(x):  # line 50: module-level jit entry with no registry row
    return x * 3.0
