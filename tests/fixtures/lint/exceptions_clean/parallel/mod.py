"""exception-classification clean fixture: the sanctioned patterns."""

import logging

logger = logging.getLogger()

TRANSPORT_ERRORS = (OSError, EOFError)
RETRYABLE_ERRORS = TRANSPORT_ERRORS + (TimeoutError,)


class ServerException(RuntimeError):
    pass


def narrow_swallow(sock):
    try:
        return sock.recv(4)
    except OSError:
        return None  # narrow class: a reviewed decision


def gated_retry(call):
    while True:
        try:
            return call()
        except RETRYABLE_ERRORS:
            continue  # classified: only transport-ish failures retry


def classify(call):
    try:
        return call()
    except Exception as e:
        raise ServerException(str(e))  # re-classified into the taxonomy


def record_outcome(call, outcomes):
    try:
        return call()
    except Exception as e:
        outcomes.append(e)  # recorded: the caller dispatches on it
        return None


def logged_guard(call):
    try:
        return call()
    except Exception:
        logger.exception("background pass failed")  # at minimum, logged
        return None


# graftlint: hot
def hot_scan(rows, out):
    for r in rows:
        try:
            out.append(r.decode())
        except UnicodeDecodeError:
            pass  # narrow pass on the hot path is fine
