"""generation-commit bad fixture: one violation class per function."""

import json
import os

from distributed_faiss_tpu.utils.serialization import (
    atomic_write,
    generation_filename,
    write_manifest,
)


def direct_write(storage_dir, payload):
    # line 15: open(..., 'w') straight into the storage dir
    with open(os.path.join(storage_dir, "meta.json"), "w") as f:
        f.write(payload)


def sneaky_rename(index_storage_dir):
    tmp = os.path.join(index_storage_dir, "x.tmp")
    # line 22: un-fsync'd rename inside the storage dir
    os.replace(tmp, os.path.join(index_storage_dir, "x.bin"))


def dump_straight(storage_dir, obj):
    # line 27: serializer (and an open-for-write) straight into storage
    json.dump(obj, open(os.path.join(storage_dir, "cfg.json"), "w"))


def rogue_commit(storage_dir, state):
    name = generation_filename("index", 1, "npz")
    atomic_write(os.path.join(storage_dir, name), state, "wb")
    # line 33: a MANIFEST written outside _commit_generation
    write_manifest(storage_dir, 1, {})


def _commit_generation(storage_dir, state):
    name = generation_filename("index", 2, "npz")
    write_manifest(storage_dir, 2, {})
    # line 40: generation data file written AFTER the manifest
    atomic_write(os.path.join(storage_dir, name), state, "wb")


def hand_rolled(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    # line 48: rename with no fsync between write and publish
    os.replace(tmp, path)
