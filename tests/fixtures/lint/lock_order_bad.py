"""lock-order known-bad fixture: two ABBA cycles — one purely lexical
(nested withs in opposite orders), one through a cross-function call
edge (a method that acquires under the hood)."""

import threading


class Pair:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.items = []

    def ab(self):
        with self.a:
            with self.b:  # line 16: edge Pair.a -> Pair.b
                return list(self.items)

    def ba(self):
        with self.b:
            with self.a:  # line 21: edge Pair.b -> Pair.a — cycle
                self.items.append(1)


class CrossPair:
    def __init__(self):
        self.x = threading.Lock()
        self.y = threading.Lock()
        self.n = 0

    def _locked_y(self):
        with self.y:
            self.n += 1

    def xy(self):
        with self.x:
            self._locked_y()  # line 37: call edge CrossPair.x -> CrossPair.y

    def yx(self):
        with self.y:
            with self.x:  # line 41: edge CrossPair.y -> CrossPair.x — cycle
                self.n += 1
