"""thread-lifecycle clean fixture: every discipline pattern in one class.

Covers: direct self-attr tracking with a helper-resident join (the
call-graph propagation from stop()), container tracking with the
snapshot-and-swap drain idiom, local-append tracking, and a factory
whose thread escapes to its caller.
"""

import threading


def make_worker(target):
    # escapes: the caller owns tracking/joining
    t = threading.Thread(target=target, name="made", daemon=True)
    return t


class Crew:
    def __init__(self):
        self._lock = threading.Lock()
        self._pool = []
        self._loop = threading.Thread(target=self._run, name="crew-loop",
                                      daemon=True)
        self._loop.start()

    def hire(self):
        t = threading.Thread(target=self._run, name="crew-worker",
                             daemon=True)
        with self._lock:
            self._pool.append(t)
        t.start()

    def _run(self):
        pass

    def _drain(self):
        # join lives in a helper: reachable from stop() through the
        # class call graph, and the locals alias through a tuple swap
        with self._lock:
            pool, self._pool = self._pool, []
        for t in pool:
            t.join(timeout=1.0)
        loop = self._loop
        loop.join(timeout=1.0)

    def stop(self):
        self._drain()
