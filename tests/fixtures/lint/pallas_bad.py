"""pallas-guard known-bad fixture: a kernel outside ops/*_pallas.py and a
public entry point reaching it without pallas_guarded."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2


def _double(x):  # line 13: pallas_call outside ops/*_pallas.py
    return pl.pallas_call(
        _kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
    )(x)


def serve(x):  # line 19: public route into the kernel, no guard
    return _double(x)
