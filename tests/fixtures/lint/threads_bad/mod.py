"""thread-lifecycle bad fixture: one violation class per site."""

import _thread
import threading


class Orphanage:
    def __init__(self):
        self._named = None
        self._implicit = None
        self._unjoined = None

    def spawn_unnamed(self):
        # line 16: named missing (daemon explicit, tracked, joined)
        self._named = threading.Thread(target=self._work, daemon=True)
        self._named.start()

    def spawn_implicit_daemon(self):
        # line 21: daemon status implicit (named, tracked, joined)
        self._implicit = threading.Thread(target=self._work, name="w")
        self._implicit.start()

    def spawn_chained(self):
        # line 26: started and dropped — untracked orphan
        threading.Thread(target=self._work, name="x", daemon=True).start()

    def spawn_unjoined(self):
        # line 30: tracked in self._unjoined but stop() never joins it
        self._unjoined = threading.Thread(target=self._work, name="y",
                                          daemon=True)
        self._unjoined.start()

    def spawn_raw(self):
        # line 36: raw _thread spawn
        _thread.start_new_thread(self._work, ())

    def _work(self):
        pass

    def stop(self):
        for t in (self._named, self._implicit):
            if t is not None:
                t.join(timeout=1.0)
