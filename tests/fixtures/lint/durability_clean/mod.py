"""generation-commit clean fixture: the sanctioned write patterns."""

import json
import os

from distributed_faiss_tpu.utils.serialization import (
    atomic_write,
    generation_filename,
    write_manifest,
)


def read_ok(storage_dir):
    # reads are free
    with open(os.path.join(storage_dir, "meta.json")) as f:
        return f.read()


def atomic_ok(storage_dir, payload):
    # writes ride atomic_write (tmp+fsync+rename inside)
    atomic_write(os.path.join(storage_dir, "meta.json"),
                 lambda f: f.write(payload), "w")


def _commit_generation(storage_dir, state, meta):
    # data files first, MANIFEST last — the commit point
    entries = {}
    for key, blob in (("index", state), ("meta", meta)):
        name = generation_filename(key, 3, "bin")
        digest = atomic_write(os.path.join(storage_dir, name),
                              lambda f: f.write(blob), "wb")
        entries[key] = {"name": name, "sha256": digest}
    write_manifest(storage_dir, 3, entries)
    # the unversioned convenience copy is NOT a generation data file:
    # writing it after the manifest is legal
    atomic_write(os.path.join(storage_dir, "cfg.json"),
                 lambda f: f.write(json.dumps({})), "w")


def hand_rolled_ok(path, data):
    # tmp + fsync + rename by hand is honest (atomic_write preferred)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
