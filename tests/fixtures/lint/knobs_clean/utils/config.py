"""env-knob-drift clean fixture: schema home."""

_FIX_SCHEMA = {
    "alpha": (int, "DFT_FIX_ALPHA", 5),
}
