"""env-knob-drift clean fixture: ad-hoc knobs ride envutil helpers."""

from distributed_faiss_tpu.utils import envutil


def gamma_enabled():
    return envutil.env_flag("DFT_FIX_GAMMA", False)


def delta_budget():
    # no literal default: the fallback is computed, so the doc cell is
    # free-form and default-drift comparison skips it
    raw = envutil.env_int("DFT_FIX_DELTA")
    return raw if raw else 2 * 4
