"""env-knob-drift bad fixture: ad-hoc env read."""

import os


def read_adhoc():
    # line 8: raw read outside utils/config.py / utils/envutil.py
    return os.environ.get("DFT_FIX_ADHOC", "0")
