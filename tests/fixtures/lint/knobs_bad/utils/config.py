"""env-knob-drift bad fixture: the schema home."""

_FIX_SCHEMA = {
    # line 5: documented with the wrong default
    "alpha": (int, "DFT_FIX_ALPHA", 5),
    # line 7: no doc row at all
    "beta": (bool, "DFT_FIX_BETA", True),
}
