"""blocking-under-lock known-clean fixture: bounded waits/joins are
fine anywhere; blocking ops happen after the lock is released; the
launch runs outside the critical section."""

import threading
import time

import jax


@jax.jit
def _scan(x):
    return x


class Conn:
    def __init__(self, sock, thread):
        self.sock = sock
        self.thread = thread
        self.lock = threading.Lock()
        self.done = threading.Event()
        self.queue = []

    def send_unlocked(self, data):
        with self.lock:
            payload = bytes(data)  # snapshot under the lock ...
        self.sock.sendall(payload)  # ... blocking write outside it

    def wait_bounded(self):
        with self.lock:
            self.done.wait(0.5)  # timeout: bounded, legal under the lock

    def join_bounded(self):
        with self.lock:
            self.thread.join(timeout=1.0)

    def sleep_outside(self):
        time.sleep(0.1)
        with self.lock:
            return len(self.queue)

    def launch_outside(self, x):
        with self.lock:
            arg = x
        return _scan(arg)
