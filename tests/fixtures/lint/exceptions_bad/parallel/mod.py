"""exception-classification bad fixture: one violation class per site."""

import logging

logger = logging.getLogger()


def silent_swallow(sock):
    try:
        return sock.recv(4)
    # line 12: broad except, nothing raised/logged/recorded/classified
    except Exception:
        return None


def ungated_retry(call):
    while True:
        try:
            return call()
        # line 21: broad except driving a retry loop
        except Exception as e:
            logger.warning("retrying after %s", e)
            continue


def bare_teardown(sock):
    try:
        sock.close()
    # line 29: bare except eats SystemExit/KeyboardInterrupt
    except:  # noqa: E722
        pass


# graftlint: hot
def hot_scan(rows, out):
    for r in rows:
        try:
            out.append(r.decode())
        # line 38: swallow-and-pass on a hot-path function
        except Exception:
            pass
