"""races_bad: shared state written on one thread root and touched on
another with no common lock (shared-state-race golden fixture)."""
import threading


class Worker:
    def __init__(self):
        self.lock = threading.Lock()
        self.jobs = []
        self.done = 0
        self.flag = False
        # graftlint: atomic(phantom): waives nothing -> in-class rot
        self.t = threading.Thread(target=self._run, name="w", daemon=True)

    def _run(self):
        while True:
            with self.lock:
                self.jobs.pop()
            if self.flag:
                return
            self.done += 1

    def submit(self, job):
        with self.lock:
            self.jobs.append(job)

    def stop(self):
        self.flag = True
        self.t.join(timeout=1.0)

    def stats(self):
        return self.done

    # graftlint: atomic(ghost): no such attribute -> the marker is rot
