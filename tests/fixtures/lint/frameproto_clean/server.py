"""frame-protocol known-clean fixture (paired server): dispatches every
client-sent kind, answers only kinds the client interprets, and slices
the CALL payload within the packed arity."""

from tests.fixtures.lint.frameproto_clean import rpc


class Server:
    def _one_call(self, conn):
        kind, payload = rpc.recv_frame(conn)
        if kind == rpc.KIND_CLOSE:
            raise SystemExit
        if kind != rpc.KIND_CALL:
            raise RuntimeError(f"unexpected frame kind {kind}")
        fname, args, kwargs = payload[:3]  # meta element stays optional
        meta = payload[3] if len(payload) > 3 else None
        req_id = meta.get("req_id") if isinstance(meta, dict) else None
        assert req_id is None or isinstance(req_id, int)
        try:
            ret = getattr(self, fname)(*args, **kwargs)
            rpc.send_frame(conn, rpc.KIND_RESULT, ret)
        except Exception as e:
            rpc.send_frame(conn, rpc.KIND_ERROR, str(e))

    def search(self, index_id, query, top_k):
        return (query, [], None)
