"""frame-protocol known-clean fixture (protocol module): unique wire
values, a complete mux registration, every kind produced by one side
consumed by the other, and pack/unpack arities that agree."""

KIND_CALL = 0
KIND_RESULT = 1
KIND_ERROR = 2
KIND_CLOSE = 3
KIND_RESULT_MUX = 4
KIND_ERROR_MUX = 5

MUX_RESPONSE_KINDS = {KIND_RESULT: KIND_RESULT_MUX, KIND_ERROR: KIND_ERROR_MUX}
_MUX_TO_BASE = {v: k for k, v in MUX_RESPONSE_KINDS.items()}


def pack_frame(kind, obj=None):
    return [bytes([kind])]


def send_frame(sock, kind, obj=None):
    for part in pack_frame(kind, obj):
        sock.sendall(part)


def recv_frame(sock):
    return sock.recv(1)[0], None


class Client:
    def call(self, fname, args, kwargs):
        meta = {"req_id": 0}  # every meta key is read by _one_call
        send_frame(self.sock, KIND_CALL, (fname, args, kwargs, meta))
        kind, payload = recv_frame(self.sock)
        return self._interpret(kind, payload)

    def close(self):
        send_frame(self.sock, KIND_CLOSE, None)

    def _reader_loop(self, sock):
        while True:
            kind, payload = recv_frame(sock)
            base = _MUX_TO_BASE.get(kind)
            if base is not None:
                kind = base

    def _interpret(self, kind, payload):
        if kind == KIND_RESULT:
            return payload
        if kind == KIND_ERROR:
            raise RuntimeError(payload)
        raise RuntimeError(f"unexpected frame kind {kind}")


# ------------------------------------------------- binary wire (clean)

WIRE_BINARY_FLAG = 0x80  # no KIND_* value carries this bit

BINARY_CALL_OPS = ("search",)  # served by the paired Server.search


def restricted_loads(data):
    import pickle

    return pickle.loads(data)  # the ONE sanctioned pickle decode site
