"""pickle-safety known-bad fixture (lives under parallel/ to be in scope)."""

import pickle

DEFAULTS = pickle.loads(b"\x80\x04N.")  # line 5: module-level bare loads


def recv_payload(raw: bytes):
    return pickle.loads(raw)  # line 9: bare loads on wire bytes


def recv_stream(fileobj):
    return pickle.load(fileobj)  # line 13: bare load on a socket file


if True:  # version-gate pattern: still visible to the checker
    def recv_gated(raw: bytes):
        return pickle.loads(raw)  # line 18: bare loads under an if block
