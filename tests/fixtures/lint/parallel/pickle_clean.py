"""pickle-safety known-clean fixture: the allowlisted Unpickler pattern."""

import io
import pickle


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if module == "builtins" and name in ("set", "frozenset"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(f"disallowed {module}.{name}")


def restricted_loads(data):
    return _RestrictedUnpickler(io.BytesIO(bytes(data))).load()


def recv_payload(raw: bytes):
    return restricted_loads(raw)
