"""races_clean: every cross-root access shares a lock, is reviewed-
benign (atomic marker), or carries a reasoned ok() waiver."""
import threading


class Worker:
    def __init__(self):
        self.lock = threading.Lock()
        self.jobs = []
        # graftlint: atomic(ticks): benign monotonic heartbeat counter
        self.ticks = 0
        self.t = threading.Thread(target=self._run, name="w", daemon=True)

    def _run(self):
        while True:
            with self.lock:
                if not self.jobs:
                    return
                self.jobs.pop()
            self.ticks += 1
            # graftlint: ok(shared-state-race): reviewed - best-effort progress echo, staleness acceptable
            self.echo = self.ticks

    def submit(self, job):
        with self.lock:
            self.jobs.append(job)

    def stop(self):
        with self.lock:
            self.jobs.append(None)
        self.t.join(timeout=1.0)

    def stats(self):
        return (self.ticks, self.echo)
