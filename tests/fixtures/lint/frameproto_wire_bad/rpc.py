"""frame-protocol known-bad fixture (binary wire): a kind whose value
collides with the binary-skeleton flag bit, a binary-encodable op the
paired server does not serve, and a pickle decode outside
restricted_loads."""

WIRE_BINARY_FLAG = 0x80

KIND_CALL = 0
KIND_RESULT = 1
KIND_CLOSE = 2
KIND_BULK = 0x84  # line 11: collides with the flag bit

BINARY_CALL_OPS = ("search", "export_all")  # line 13: export_all unserved


def restricted_loads(data):
    import pickle

    return pickle.loads(data)


def raw_loads(data):
    import pickle

    return pickle.loads(data)  # line 25: decode outside restricted_loads


def send_frame(sock, kind, obj=None):
    sock.sendall(bytes([kind]))


def recv_frame(sock):
    return sock.recv(1)[0], restricted_loads(sock.recv(64))


class Client:
    def call(self, fname, args, kwargs):
        send_frame(self.sock, KIND_CALL, (fname, args, kwargs))
        kind, payload = recv_frame(self.sock)
        return self._interpret(kind, payload)

    def bulk(self):
        send_frame(self.sock, KIND_BULK, None)

    def close(self):
        send_frame(self.sock, KIND_CLOSE, None)

    def _interpret(self, kind, payload):
        if kind == KIND_RESULT:
            return payload
        raise RuntimeError(f"unexpected frame kind {kind}")
