"""frame-protocol known-bad fixture (binary wire): the paired server —
serves ``search`` but not the other op the protocol module advertises
as binary-encodable."""

from tests.fixtures.lint.frameproto_wire_bad import rpc


class Server:
    def _one_call(self, conn):
        kind, payload = rpc.recv_frame(conn)
        if kind == rpc.KIND_CLOSE:
            raise SystemExit
        if kind == rpc.KIND_BULK:
            return
        if kind != rpc.KIND_CALL:
            raise RuntimeError(f"unexpected frame kind {kind}")
        fname, args, kwargs = payload
        ret = getattr(self, fname)(*args, **kwargs)
        rpc.send_frame(conn, rpc.KIND_RESULT, ret)

    def search(self, index_id, query, top_k):
        return (query, [], None)
