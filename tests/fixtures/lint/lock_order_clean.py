"""lock-order known-clean fixture: one global acquisition order
(a before b, everywhere), sequential (non-nested) acquisitions, and a
call edge consistent with the lexical order."""

import threading


class Ordered:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.items = []

    def _locked_b(self):
        with self.b:
            self.items.append(0)

    def both(self):
        with self.a, self.b:  # a -> b, the one global order
            return list(self.items)

    def via_call(self):
        with self.a:
            self._locked_b()  # a -> b again: same direction, no cycle

    def sequential(self):
        with self.b:
            n = len(self.items)
        with self.a:  # not nested: b released before a — no b -> a edge
            return n
