"""lock-discipline known-clean fixture: every guarded access under its
lock; immutable attributes and inline lambdas stay lock-free."""

import threading


class Registry:
    def __init__(self, name):
        self.name = name  # written only in __init__: immutable, lock-free
        self.entries = {}
        self.lock = threading.Lock()

    def put(self, key, value):
        with self.lock:
            self.entries[key] = value

    def size(self):
        with self.lock:
            return len(self.entries)

    def snapshot_sorted(self):
        with self.lock:
            # inline lambda inherits the lock context (runs inline)
            return sorted(self.entries.items(), key=lambda kv: len(self.entries) and kv[0])

    def label(self):
        return self.name
