"""Clean twins of the doctored IR-tier fixture cases: same program
shapes, written the way the policy wants them — no findings."""

import numpy as np

import jax
import jax.numpy as jnp


def _host_norm(x):
    return np.linalg.norm(np.asarray(x), axis=-1)


@jax.jit
def residency_clean(x):
    return x * 2.0  # no host primitive: the program stays on-device


@jax.jit
def callback_clean(x):
    # the same pure_callback, but its target is allowlisted by the test
    return jax.pure_callback(
        _host_norm, jax.ShapeDtypeStruct((4,), jnp.float32), x)


@jax.jit
def dtype_clean(a, b):
    # bf16 operands, fp32 accumulation: the policy-conforming contraction
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


@jax.jit
def const_clean(x, big):
    return x + big  # the weight-sized array arrives as an argument
