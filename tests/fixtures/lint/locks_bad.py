"""lock-discipline known-bad fixture: majority-vote inference catches the
one unlocked access of an otherwise locked attribute."""

import threading


class Registry:
    def __init__(self):
        self.entries = {}
        self.lock = threading.Lock()

    def put(self, key, value):
        with self.lock:
            self.entries[key] = value

    def get_one(self, key):
        with self.lock:
            return self.entries.get(key)

    def drop(self, key):
        with self.lock:
            self.entries.pop(key, None)

    def size_racy(self):
        return len(self.entries)  # line 25: unlocked minority access
