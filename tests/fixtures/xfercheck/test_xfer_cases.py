"""Doctored implicit-transfer cases for the DFT_XFERCHECK e2e tests.

Driven by tests/test_xfercheck.py in a subprocess with DFT_XFERCHECK=1 +
DFT_XFERCHECK_E2E=1: the seeded case feeds a raw numpy block straight
into a jit dispatch inside a guarded section on a worker thread that
SWALLOWS the raise (serving loops catch broadly by design) — only the
conftest fixture's post-test check can fail it, which proves the real
wiring. The explicit twin moves the same data the designed way
(device_put feed, explicit() fetch scope) and must pass. The env guard
keeps every normal tier from running them: without the driver variables
they skip.
"""

import os
import threading

import numpy as np
import pytest

import jax

from distributed_faiss_tpu.utils import xfercheck

pytestmark = pytest.mark.skipif(
    os.environ.get("DFT_XFERCHECK_E2E") != "1",
    reason="doctored case: driven by tests/test_xfercheck.py subprocess")


def _double(x):
    return x * 2.0


def test_seeded_implicit_feed_fails_via_the_fixture():
    """A numpy operand at jit dispatch inside guarded() is an implicit
    host-to-device upload; the worker swallows the raise, the conftest
    fixture must still fail this test."""
    fn = jax.jit(_double)
    q = np.ones((8, 4), np.float32)

    def doctored_serve():
        try:
            with xfercheck.guarded("doctored merge-window flush"):
                fn(q)  # implicit h2d: numpy straight into the dispatch
        except xfercheck.ImplicitTransferError:
            pass  # swallowed on purpose: the fixture must still fail us

    t = threading.Thread(target=doctored_serve, name="doctored-server",
                         daemon=True)
    t.start()
    t.join(30.0)


def test_explicit_twin_is_clean():
    """The same program with the designed moves: an explicit device_put
    feed and an explicit() fetch scope — nothing to witness."""
    fn = jax.jit(_double)
    q = jax.device_put(np.ones((8, 4), np.float32))

    def clean_serve():
        with xfercheck.guarded("doctored merge-window flush"):
            out = fn(q)  # device operand: no transfer at dispatch
            with xfercheck.explicit("doctored result fetch"):
                np.asarray(out)

    t = threading.Thread(target=clean_serve, name="doctored-server",
                         daemon=True)
    t.start()
    t.join(30.0)
