"""Doctored thread-lifecycle cases for the DFT_THREADCHECK e2e tests.

Driven by tests/test_threadcheck.py in a subprocess with
DFT_THREADCHECK=1 + DFT_THREADCHECK_E2E=1: the leak case must FAIL under
the conftest witness fixture (proving the real wiring — install at
collection, snapshot/check around each test — catches it), the daemon
and joined cases must pass. The env guard keeps every normal tier from
running them: without the driver variables they skip.
"""

import os
import threading
import time

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("DFT_THREADCHECK_E2E") != "1",
    reason="doctored case: driven by tests/test_threadcheck.py subprocess")

# long enough to outlive the (driver-shortened) grace join, short enough
# that the non-daemon thread cannot hold the subprocess interpreter
# hostage for more than a few seconds after pytest finishes
_LINGER_S = 3.0


def test_leaks_a_nondaemon_thread():
    threading.Thread(target=time.sleep, args=(_LINGER_S,),
                     name="doctored-leak", daemon=False).start()


def test_daemon_thread_is_exempt():
    hold = threading.Event()
    threading.Thread(target=hold.wait, name="doctored-daemon",
                     daemon=True).start()


def test_tracked_and_joined_is_clean():
    done = threading.Event()
    t = threading.Thread(target=done.set, name="doctored-joined",
                         daemon=False)
    t.start()
    assert done.wait(5.0)
    t.join(5.0)
    assert not t.is_alive()
