"""Doctored shared-state-race cases for the DFT_RACECHECK e2e tests.

Driven by tests/test_racecheck.py in a subprocess with DFT_RACECHECK=1 +
DFT_RACECHECK_E2E=1: the seeded-race case must FAIL under the conftest
witness fixture even though the racing thread SWALLOWS its in-thread
SharedStateRaceError (proving the real wiring — install at collection,
drain/check around each test — catches swallowed raises), and the
locked twin must pass. The env guard keeps every normal tier from
running them: without the driver variables they skip.
"""

import os
import threading

import pytest

from distributed_faiss_tpu.utils import lockdep, racecheck

pytestmark = pytest.mark.skipif(
    os.environ.get("DFT_RACECHECK_E2E") != "1",
    reason="doctored case: driven by tests/test_racecheck.py subprocess")


def _shared_class():
    class Shared:
        def __init__(self):
            self.lock = lockdep.lock("Shared.lock")
            self.value = 0

    return racecheck.instrument(Shared)


def test_seeded_race_fails_via_the_fixture():
    """The racing write happens on a worker thread that swallows the
    raise (serving loops catch broadly by design) — only the conftest
    fixture's post-test check can fail this test."""
    obj = _shared_class()()

    def doctored_racy_write():
        try:
            obj.value = 1  # lock-free write from a second thread
        except racecheck.SharedStateRaceError:
            pass  # swallowed on purpose: the fixture must still fail us

    t = threading.Thread(target=doctored_racy_write,
                         name="doctored-racer", daemon=True)
    t.start()
    t.join(5.0)


def test_locked_twin_is_clean():
    obj = _shared_class()()

    def locked_write():
        with obj.lock:
            obj.value = 1

    t = threading.Thread(target=locked_write, name="doctored-locked",
                         daemon=True)
    t.start()
    t.join(5.0)
    with obj.lock:
        obj.value = 2
