"""Native C++ HNSW-SQ tests (builds the shared library with g++ on first run)."""

import numpy as np
import pytest

from distributed_faiss_tpu.models import hnsw

pytestmark = pytest.mark.skipif(
    not hnsw.native_available(), reason="no C++ toolchain for native hnsw"
)


def brute_l2_ids(q, x, k):
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return np.argsort(d, axis=1)[:, :k]


@pytest.fixture
def built(rng):
    x = rng.standard_normal((3000, 24)).astype(np.float32)
    idx = hnsw.HNSWSQIndex(24, "l2", M=16, ef_construction=80)
    assert not idx.is_trained
    idx.train(x[:1000])
    idx.add(x[:1500])
    idx.add(x[1500:])
    return idx, x


def test_build_and_recall(built, rng):
    idx, x = built
    assert idx.ntotal == 3000
    q = rng.standard_normal((20, 24)).astype(np.float32)
    idx.set_nprobe(128)  # efSearch
    D, I = idx.search(q, 10)
    gt = brute_l2_ids(q, x, 10)
    recall = np.mean([len(set(I[i]) & set(gt[i])) / 10 for i in range(20)])
    assert recall > 0.85, recall
    assert np.all(np.diff(D, axis=1) >= 0)  # ascending l2 distances


def test_ef_tradeoff(built, rng):
    idx, x = built
    q = rng.standard_normal((20, 24)).astype(np.float32)
    gt = brute_l2_ids(q, x, 10)

    def recall(ef):
        idx.set_nprobe(ef)
        _, I = idx.search(q, 10)
        return np.mean([len(set(I[i]) & set(gt[i])) / 10 for i in range(20)])

    assert recall(256) >= recall(10) - 0.05  # more ef never meaningfully worse


def test_self_query(built):
    idx, x = built
    idx.set_nprobe(64)
    D, I = idx.search(x[:8], 1)
    assert (I[:, 0] == np.arange(8)).sum() >= 7  # SQ8 noise may miss one
    rec = idx.reconstruct_batch(np.arange(4))
    assert np.max(np.abs(rec - x[:4])) < 0.1  # sq8 quantization error


def test_state_round_trip(built, rng, tmp_path):
    from distributed_faiss_tpu.models.factory import index_from_state_dict
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    idx, x = built
    q = rng.standard_normal((5, 24)).astype(np.float32)
    idx.set_nprobe(100)
    D0, I0 = idx.search(q, 6)
    p = str(tmp_path / "h.npz")
    save_state(p, idx.state_dict())
    idx2 = index_from_state_dict(load_state(p))
    assert idx2.ntotal == 3000
    D1, I1 = idx2.search(q, 6)
    np.testing.assert_array_equal(I0, I1)  # identical graph -> identical walk
    np.testing.assert_allclose(D0, D1, rtol=1e-6)


def test_untrained_add_raises():
    idx = hnsw.HNSWSQIndex(8, "l2")
    with pytest.raises(RuntimeError):
        idx.add(np.zeros((3, 8), np.float32))


def test_engine_integration(rng):
    """hnswsq through the full engine lifecycle (nprobe -> efSearch)."""
    from distributed_faiss_tpu import Index, IndexCfg, IndexState
    import time

    cfg = IndexCfg(index_builder_type="hnswsq", dim=16, metric="l2",
                   train_num=300, nprobe=64)
    idx = Index(cfg)
    x = rng.standard_normal((800, 16)).astype(np.float32)
    idx.add_batch(x, [("d", i) for i in range(800)], train_async_if_triggered=False)
    t0 = time.time()
    while idx.get_state() != IndexState.TRAINED:
        assert time.time() - t0 < 60
        time.sleep(0.05)
    D, M, _ = idx.search(x[:4], 5)
    assert sum(M[i][0] == ("d", i) for i in range(4)) >= 3


def test_threaded_build_recall(rng):
    """Graph built with 8 forced construction threads (max lock contention on
    any core count) must reach the same recall grade as a serial build."""
    if not hnsw.native_available():
        pytest.skip("no native toolchain")
    n, d = 8000, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((50, d)).astype(np.float32)
    idx = hnsw.HNSWSQIndex(d, "l2", M=16, ef_construction=80)
    idx.set_threads(8)
    idx.train(x[:2000])
    for s in range(0, n, 2000):  # several batches -> several parallel phases
        idx.add(x[s:s + 2000])
    assert idx.ntotal == n
    d2 = (q ** 2).sum(1)[:, None] - 2 * q @ x.T + (x ** 2).sum(1)[None, :]
    gt = np.argsort(d2, axis=1)[:, :10]
    idx.nprobe = 128
    _, I = idx.search(q, 10)
    rec = np.mean([len(set(I[i]) & set(gt[i])) / 10 for i in range(50)])
    assert rec > 0.8, rec


def test_concurrent_searches_consistent(rng):
    """Concurrent search() calls on ONE instance are safe (pooled visited
    tables) and agree with the serial answer."""
    import threading

    if not hnsw.native_available():
        pytest.skip("no native toolchain")
    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((64, 16)).astype(np.float32)
    idx = hnsw.HNSWSQIndex(16, "l2", M=16, ef_construction=60)
    idx.train(x)
    idx.add(x)
    idx.nprobe = 64
    D0, I0 = idx.search(q, 5)
    outs = [None] * 6
    def worker(t):
        outs[t] = idx.search(q, 5)
    ts = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for D, I in outs:
        np.testing.assert_array_equal(I, I0)
        np.testing.assert_allclose(D, D0, rtol=1e-6)


def test_refine_lifts_recall_past_095(rng):
    """The SQ8 codec alone plateaus ~0.90 recall (codec ceiling, shared with
    the reference's IndexHNSWSQ); refine_k_factor's exact-fp16 rescore of
    the shortlist must clear 0.95 at the same efSearch (VERDICT r4 #7)."""
    n, d = 8000, 48
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((30, d)).astype(np.float32)
    gt = brute_l2_ids(q, x, 10)

    def build(rf):
        idx = hnsw.HNSWSQIndex(d, "l2", M=24, ef_construction=100,
                               refine_k_factor=rf)
        idx.train(x[:2000])
        idx.add(x)
        idx.set_nprobe(128)
        return idx

    def recall(idx):
        _, ids = idx.search(q, 10)
        return np.mean([len(set(ids[i]) & set(gt[i])) / 10 for i in range(len(q))])

    plain, refined = build(0), build(8)
    r_plain, r_ref = recall(plain), recall(refined)
    assert r_ref >= 0.95, (r_plain, r_ref)
    assert r_ref > r_plain, (r_plain, r_ref)

    # distances from the refined path are exact fp16 L2, ascending
    D, I = refined.search(q, 10)
    assert np.all(np.diff(D, axis=1) >= 0)

    # round-trip keeps the refine store and the recall grade
    idx2 = hnsw.HNSWSQIndex.from_state_dict(refined.state_dict())
    idx2.set_nprobe(128)
    assert recall(idx2) >= 0.95


def test_refine_keeps_k_columns_on_tiny_corpus(rng):
    """With refine on (the factory default), ntotal < k must still return
    (nq, k) padded with inf/-1 — the shape contract every family keeps
    (r5 review)."""
    d = 16
    idx = hnsw.HNSWSQIndex(d, "l2", M=8, ef_construction=40, refine_k_factor=8)
    x = rng.standard_normal((5, d)).astype(np.float32)
    idx.train(rng.standard_normal((100, d)).astype(np.float32))
    idx.add(x)
    D, I = idx.search(rng.standard_normal((3, d)).astype(np.float32), 10)
    assert D.shape == (3, 10) and I.shape == (3, 10)
    assert (I >= 0).sum(axis=1).tolist() == [5, 5, 5]
    assert np.isinf(D[:, 5:]).all()
