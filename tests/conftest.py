"""Test harness configuration.

Tests run on a *virtual 8-device CPU mesh* so the multi-chip sharding paths
(parallel/mesh.py) execute without TPU hardware, mirroring how the reference
fakes a cluster with in-process threads + loopback sockets
(reference: tests/test_integration.py:51-115).

The env vars must be set before jax initializes its backends, hence the
module-level assignment in conftest (imported by pytest before any test
module).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
