"""Test harness configuration.

Tests run on a *virtual 8-device CPU mesh* so the multi-chip sharding paths
(parallel/mesh.py) execute without TPU hardware, mirroring how the reference
fakes a cluster with in-process threads + loopback sockets
(reference: tests/test_integration.py:51-115).

The env vars must be set before jax initializes its backends, hence the
module-level assignment in conftest (imported by pytest before any test
module).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's axon sitecustomize calls jax.config.update(
# "jax_platforms", "axon,cpu") at interpreter start, which OUTRANKS the
# JAX_PLATFORMS env var — with a live relay the whole suite would silently
# run on the single real TPU chip (and test_virtual_mesh_has_8_devices
# fails 1 != 8). Re-assert cpu through the same config channel; safe
# because no backend has initialized yet at conftest import time.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
