"""Test harness configuration.

Tests run on a *virtual 8-device CPU mesh* so the multi-chip sharding paths
(parallel/mesh.py) execute without TPU hardware, mirroring how the reference
fakes a cluster with in-process threads + loopback sockets
(reference: tests/test_integration.py:51-115).

The env vars must be set before jax initializes its backends, hence the
module-level assignment in conftest (imported by pytest before any test
module).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's axon sitecustomize calls jax.config.update(
# "jax_platforms", "axon,cpu") at interpreter start, which OUTRANKS the
# JAX_PLATFORMS env var — with a live relay the whole suite would silently
# run on the single real TPU chip (and test_virtual_mesh_has_8_devices
# fails 1 != 8). Re-assert cpu through the same config channel; safe
# because no backend has initialized yet at conftest import time.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from distributed_faiss_tpu.utils import (compilecheck, racecheck, threadcheck,
                                         xfercheck)

# DFT_THREADCHECK=1: wrap Thread.start once, at collection time, so every
# thread started anywhere in the suite carries creation provenance
if threadcheck.enabled():
    threadcheck.install()

# DFT_RACECHECK=1: instrument the lockdep-factory-locked classes once, at
# collection time, so every instance the suite creates is witnessed from
# birth (utils/racecheck.py; implies lockdep's held-lockset tracking)
if racecheck.enabled():
    racecheck.install()

# DFT_COMPILECHECK=1: hook jax's lowering logger once, at collection time,
# so every XLA compilation anywhere in the suite lands in the per-entry
# tally (utils/compilecheck.py; the zero-new-compiles-after-warmup
# assertions read it via snapshot()/new_since())
if compilecheck.enabled():
    compilecheck.install()


@pytest.fixture(autouse=True)
def _thread_leak_witness():
    """DFT_THREADCHECK=1 runtime witness (utils/threadcheck.py): snapshot
    the live-thread set around each test; a NON-DAEMON thread created
    during the test that outlives it (past a bounded grace join) fails
    the test with its name and creation site. Threads owned by
    broader-scoped fixtures are in the `before` snapshot (higher-scope
    fixtures set up first) and are exempt, which scopes the witness to
    exactly what this test created. No-op when the knob is off."""
    if not threadcheck.enabled():
        yield
        return
    before = threadcheck.snapshot()
    yield
    threadcheck.check(before)


@pytest.fixture(autouse=True)
def _shared_state_race_witness():
    """DFT_RACECHECK=1 runtime witness (utils/racecheck.py): any
    shared-state race recorded during this test fails it — including
    races whose in-thread SharedStateRaceError a serving loop swallowed
    (batcher/connection threads catch broadly by design, so the raise
    alone cannot be the only failure path). Violations from earlier
    tests are drained up front so blame lands on the test that provoked
    the race. No-op when the knob is off."""
    if not racecheck.enabled():
        yield
        return
    racecheck.drain()
    yield
    racecheck.check()


@pytest.fixture(autouse=True)
def _implicit_transfer_witness():
    """DFT_XFERCHECK=1 runtime witness (utils/xfercheck.py): any implicit
    host<->device transfer recorded inside a guarded serving section
    during this test fails it — including violations whose in-thread
    ImplicitTransferError the scheduler's broad per-request error
    routing swallowed. Earlier tests' violations are drained up front so
    blame lands on the test that provoked the transfer. No-op when the
    knob is off."""
    if not xfercheck.enabled():
        yield
        return
    xfercheck.drain()
    yield
    xfercheck.check()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
