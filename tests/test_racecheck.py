"""Runtime shared-state race witness tests (marker ``racecheck``; the
subprocess tier re-run is additionally ``slow``).

Unit layer: the DFT_RACECHECK=1 witness (utils/racecheck.py) runs the
Eraser state machine per (instance, attribute) — a cross-thread write
with an empty candidate lockset raises SharedStateRaceError with thread
+ file:line provenance for both sides; lockset refinement keeps a
consistently-held lock from false-flagging; RLock reentry and
Condition.wait's release/re-acquire are handled; read-only sharing and
construction-time publishes never report; EXEMPT pairs, the peeking()
suspension, and the DFT_RACECHECK_SAMPLE read-sampling knob are honored.

E2e layer: a subprocess pytest run over the doctored cases in
tests/fixtures/racecheck/ proves the REAL wiring — conftest instruments
at collection, the autouse fixture drains/checks around each test —
fails a seeded race whose in-thread raise was SWALLOWED, and passes the
locked twin.

Tier layer (``pytest -m racecheck``, mirrored by the ci.yml
``racecheck`` job): re-run the scheduler, rpc-mux, replication,
anti-entropy, mutation, and versions suites with the witness on — the
dynamic complement of graftlint's static shared-state-race checker,
exactly as lockdep is to lock-order and threadcheck to thread-lifecycle.
"""

import os
import subprocess
import sys
import threading

import pytest

from distributed_faiss_tpu.utils import lockdep, racecheck

pytestmark = pytest.mark.racecheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def witness(monkeypatch):
    """DFT_RACECHECK=1 (which also flips the lockdep factories on, so
    ``held()`` tracks the locks the candidate sets intersect). Classes
    are instrumented per test via racecheck.instrument; recorded
    violations are drained on the way out so a deliberate race here can
    never leak into another test's check."""
    monkeypatch.setenv("DFT_RACECHECK", "1")
    monkeypatch.delenv("DFT_RACECHECK_SAMPLE", raising=False)
    yield
    racecheck.reset()


def _fresh(name="Shared", lock_factory=lockdep.lock, lock_name=None):
    """A new instrumented class owning one lockdep-factory lock."""

    class Shared:
        def __init__(self):
            self.lock = lock_factory(lock_name or f"{name}.lock")
            self.value = 0

    Shared.__name__ = name
    return racecheck.instrument(Shared)


def _run_in_thread(fn, name="racer"):
    box = {}

    def run():
        try:
            fn()
        except BaseException as e:  # captured for assertions
            box["exc"] = e

    t = threading.Thread(target=run, name=name, daemon=True)
    t.start()
    t.join(10.0)
    assert not t.is_alive()
    return box.get("exc")


# ------------------------------------------------------------------ switch

def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DFT_RACECHECK", raising=False)
    assert not racecheck.enabled()


def test_enabled_implies_lockdep(witness, monkeypatch):
    monkeypatch.delenv("DFT_LOCKDEP", raising=False)
    assert racecheck.enabled()
    assert lockdep.enabled()  # held-lockset tracking is the witness's input


# ------------------------------------------------------------- the machine

def test_two_thread_empty_lockset_raises_with_provenance(witness):
    obj = _fresh("ProvCls")()
    obj.value = 2  # creator-thread write: exclusive, constrains nothing

    def locked_write():
        with obj.lock:
            obj.value = 3  # transition: candidate = {ProvCls.lock}

    assert _run_in_thread(locked_write) is None
    with pytest.raises(racecheck.SharedStateRaceError) as exc:
        obj.value = 4  # lock-free: candidate empties with a write -> raise
    msg = str(exc.value)
    assert "ProvCls.value" in msg
    assert "test_racecheck.py:" in msg          # this access's site
    assert "last write by" in msg               # the other side's site
    assert "MainThread" in msg
    racecheck.reset()


def test_lockset_refinement_keeps_common_lock_quiet(witness):
    cls = _fresh("Refined")
    obj = cls()
    extra = lockdep.lock("Refined.extra")

    def writer():
        for _ in range(50):
            with obj.lock:
                obj.value += 1
        with extra:
            with obj.lock:  # extra locks refine but never empty the set
                obj.value += 1

    assert _run_in_thread(writer, "w1") is None
    assert _run_in_thread(writer, "w2") is None
    with obj.lock:
        obj.value += 1
    assert racecheck.drain() == []


def test_rlock_reentry_is_not_a_violation(witness):
    cls = _fresh("Reent", lock_factory=lockdep.rlock)
    obj = cls()

    def writer():
        with obj.lock:
            with obj.lock:  # legal RLock reentry: still one held key
                obj.value += 1

    assert _run_in_thread(writer) is None
    with obj.lock:
        obj.value += 1
    assert racecheck.drain() == []


def test_condition_wait_release_is_handled(witness):
    class Queue:
        def __init__(self):
            self.cond = lockdep.condition("Queue.cond")
            self.items = 0

    racecheck.instrument(Queue)
    q = Queue()
    started = threading.Event()

    def consumer():
        with q.cond:
            started.set()
            while q.items == 0:
                q.cond.wait(timeout=5.0)  # drops the key for the wait
            q.items -= 1

    t = threading.Thread(target=consumer, name="consumer", daemon=True)
    t.start()
    assert started.wait(5.0)
    with q.cond:
        q.items += 1  # under the condition: candidate stays {Queue.cond}
        q.cond.notify_all()
    t.join(10.0)
    assert not t.is_alive()
    assert racecheck.drain() == []


def test_read_only_sharing_never_reports(witness):
    obj = _fresh("ReadOnly")()
    obj.value = 7  # construction-time publish by the creator

    def reader():
        for _ in range(20):
            assert obj.value == 7  # lock-free reads: Shared, not Modified

    assert _run_in_thread(reader, "r1") is None
    assert _run_in_thread(reader, "r2") is None
    assert racecheck.drain() == []


def test_exempt_pairs_are_never_tracked(witness):
    class Index:  # matches the EXEMPT ("Index", "cfg") pair by name
        def __init__(self):
            self.lock = lockdep.lock("ExemptIndex.lock")
            self.cfg = None

    racecheck.instrument(Index)
    obj = Index()

    def racy_cfg_write():
        obj.cfg = object()  # would violate, but the pair is exempt

    assert ("Index", "cfg") in racecheck.EXEMPT
    assert _run_in_thread(racy_cfg_write) is None
    obj.cfg = object()
    assert racecheck.drain() == []


def test_swallowed_raise_is_still_recorded_for_check(witness):
    obj = _fresh("Swallowed")()
    obj.value = 1

    def racy():
        try:
            obj.value = 2  # second-thread lock-free write -> raises
        except racecheck.SharedStateRaceError:
            pass  # a serving loop would swallow it exactly like this

    assert _run_in_thread(racy) is None
    with pytest.raises(racecheck.SharedStateRaceError, match="Swallowed"):
        racecheck.check()
    assert racecheck.drain() == []  # check() drained


def test_peeking_suspends_the_witness_on_this_thread(witness):
    obj = _fresh("Peeked")()
    obj.value = 1

    def racy():
        with racecheck.peeking():
            obj.value = 2  # a reviewed white-box poke: not witnessed

    assert _run_in_thread(racy) is None
    assert racecheck.drain() == []


def test_sample_knob_gates_reads_but_never_writes(witness, monkeypatch):
    monkeypatch.setenv("DFT_RACECHECK_SAMPLE", "0")
    calls = []
    real = racecheck._witness

    def counting(obj, cls_name, attr, is_write, depth=3):
        calls.append((attr, is_write))
        return real(obj, cls_name, attr, is_write, depth + 1)

    monkeypatch.setattr(racecheck, "_witness", counting)
    obj = _fresh("Sampled")()
    obj.value = 1          # write: always witnessed
    _ = obj.value          # read: sampled out at rate 0
    assert ("value", True) in calls
    assert ("value", False) not in calls
    racecheck.reset()


def test_instrument_is_idempotent_and_deinstrument_restores(witness):
    class C:
        def __init__(self):
            self.lock = lockdep.lock("C.lock")

    orig_set = C.__setattr__
    racecheck.instrument(C)
    wrapped = C.__setattr__
    assert wrapped is not orig_set
    racecheck.instrument(C)  # second instrument must not double-wrap
    assert C.__setattr__ is wrapped
    racecheck.deinstrument(C)
    assert C.__setattr__ is orig_set
    racecheck.deinstrument(C)  # idempotent too
    assert C.__setattr__ is orig_set


def test_registry_resolves_and_uninstall_restores():
    """Every INSTRUMENTED (module, class) entry must import and resolve —
    the registry is a hand-maintained mirror of the lockdep-factory
    classes, and a renamed class must fail HERE, not silently evaporate
    the witness's coverage."""
    import importlib

    for mod_name, cls_name in racecheck.INSTRUMENTED:
        cls = getattr(importlib.import_module(mod_name), cls_name)
        assert isinstance(cls, type), (mod_name, cls_name)
    was_empty = not racecheck._installed
    racecheck.install()
    try:
        for cls in racecheck._installed:
            assert cls.__dict__.get("__racecheck_orig__")
    finally:
        if was_empty:
            racecheck.uninstall()


# ----------------------------------------------------------------------- e2e

def _run_doctored(case: str):
    env = dict(os.environ, DFT_RACECHECK="1", DFT_RACECHECK_E2E="1",
               JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pytest",
         f"tests/fixtures/racecheck/test_race_cases.py::{case}",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)


def test_e2e_conftest_fixture_fails_seeded_race():
    proc = _run_doctored("test_seeded_race_fails_via_the_fixture")
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "SharedStateRaceError" in proc.stdout
    assert "Shared.value" in proc.stdout
    assert "test_race_cases.py:" in proc.stdout  # access provenance


def test_e2e_locked_twin_passes():
    proc = _run_doctored("test_locked_twin_is_clean")
    assert proc.returncode == 0, (
        f"locked twin failed under the witness:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}")


def test_e2e_cases_skip_without_driver_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DFT_RACECHECK_E2E", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/fixtures/racecheck/test_race_cases.py",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 skipped" in proc.stdout


# ------------------------------------------------------------------ the tier

@pytest.mark.slow
def test_concurrent_suites_under_witness():
    """The racecheck-tier satellite (mirrors the lockdep/threadcheck
    tiers): re-run the scheduler, rpc-mux, replication, anti-entropy,
    mutation, and versions fast suites with DFT_RACECHECK=1 — every
    cross-thread empty-lockset access fails its test with provenance."""
    env = dict(os.environ, DFT_RACECHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_scheduler.py", "tests/test_scheduler_identity.py",
         "tests/test_rpc.py", "tests/test_rpc_mux.py",
         "tests/test_replication.py", "tests/test_antientropy.py",
         "tests/test_mutation.py", "tests/test_mutation_cluster.py",
         "tests/test_versions.py",
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert proc.returncode == 0, (
        f"racecheck tier failed:\n{proc.stdout[-6000:]}\n"
        f"{proc.stderr[-2000:]}")
