"""Anti-entropy acceptance gate (ISSUE 10): R=2 cluster with the repair
queue shrunk so under-replicated records are PROVABLY dropped, a replica
SIGKILLed and restarted mid-ingest, and **zero** client-driven repair
calls — the server-side sweep alone must converge both replicas to
byte-identical digests and byte-identical search results under a live
mux query storm, with deleted ids never resurrected; a second SIGKILL
mid-heal must fall back cleanly (no torn generation); and the compaction
lease must sit on exactly one replica of the group."""

import os
import socket
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import antientropy, rpc
from distributed_faiss_tpu.parallel.client import IndexClient
from distributed_faiss_tpu.testing.chaos import QueryStorm, ServerHarness
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg
from distributed_faiss_tpu.utils.state import IndexState

pytestmark = [pytest.mark.antientropy, pytest.mark.chaos, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# fast sweeps so convergence lands inside the test budget; compaction
# watcher off to keep the gate focused on repair (the lease has its own
# assertion via get_health)
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT,
       "DFT_ANTIENTROPY_INTERVAL": "0.5", "DFT_COMPACT": "0"}

DIM = 16


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg():
    return IndexCfg(index_builder_type="flat", dim=DIM, metric="l2",
                    train_num=50)


def wait_drained(client, index_id, n, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (client.get_state(index_id) == IndexState.TRAINED
                and client.get_buffer_depth(index_id) == 0
                and client.get_ntotal(index_id) >= n):
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster never drained to {n} indexed rows")


def rank_digest(port, index_id, timeout=5.0):
    """This rank's replica digest for one index, over the wire (the same
    KIND_DIGEST exchange the sweepers use)."""
    resp = antientropy_exchange(port, timeout)
    return resp["digests"].get(index_id)


def antientropy_exchange(port, timeout=5.0):
    return rpc.digest_exchange(
        "localhost", port, {"rank": None, "group": None, "want": None},
        timeout=timeout)


def wait_converged(ports, index_id, timeout=90.0):
    """Poll both ranks' wire digests until byte-identical (and present)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            digs = [rank_digest(p, index_id) for p in ports]
        except Exception as e:  # a rank mid-restart: keep polling
            last = e
            time.sleep(0.3)
            continue
        if all(d is not None for d in digs) and all(d == digs[0]
                                                    for d in digs):
            return digs[0]
        last = digs
        time.sleep(0.3)
    raise AssertionError(f"replicas never converged: {last}")


def test_sweeper_converges_dropped_repairs_under_storm_gate(tmp_path):
    """The gate, end to end:

    1. healthy R=2 group (2 ranks), 300 rows ingested + saved, repair
       queue shrunk to ONE slot;
    2. SIGKILL replica 1; delete 12 ids (acks at quorum 1; replica 1
       misses them); golden = post-delete search;
    3. mux query storm; ingest 4 more batches through the outage — the
       1-slot queue provably DROPS records (degraded=true) and the
       client NEVER calls repair_under_replicated();
    4. restart replica 1 from its (pre-delete, pre-ingest) storage: the
       server-side sweep alone pulls the missing rows, applies the
       deletes, and both replicas converge to byte-identical wire
       digests;
    5. SIGKILL replica 1 again mid-heal, restart: no torn generation —
       it loads, re-heals, re-converges;
    6. zero storm errors, every storm result byte-identical to golden,
       no deleted id ever served; reads pinned onto the healed replica
       serve golden on the SAME client; the compaction lease sits on
       exactly one live replica of the group.
    """
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(2, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(
            disc, replication_cfg=ReplicationCfg(
                replication=2, write_quorum=1, repair_queue_len=1))
        group = client.membership.group_of(0)
        assert client.membership.replicas(group) == [0, 1]
        client.create_index("gidx", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((300, DIM)).astype(np.float32)
        acked = set()
        for s in range(0, 300, 50):
            ids = [(i,) for i in range(s, s + 50)]
            client.add_index_data("gidx", x[s:s + 50], ids)
            acked.update(i for (i,) in ids)
        wait_drained(client, "gidx", 300)
        client.save_index("gidx")

        victim_pos = 1
        victim_rank = client.sub_indexes[victim_pos].port - h.base_port
        victim_port = client.sub_indexes[victim_pos].port
        survivor_port = client.sub_indexes[0].port
        victim_dir = os.path.join(storage, "gidx", str(victim_rank))
        assert serialization.list_generations(victim_dir)

        # ---- kill the victim, then mutate while it is down (mid-ingest)
        h.kill(victim_rank)
        doomed = list(range(0, 12))
        removed = client.remove_ids("gidx", doomed)
        assert removed == len(doomed)
        acked -= set(doomed)
        dead_meta = {(i,) for i in doomed}

        # ingest through the outage: every batch acks at quorum 1 on the
        # survivor; the 1-slot repair queue PROVABLY drops records (the
        # delete record + 4 add records -> >= 3 dropped). Ingest runs
        # BEFORE the storm window (a lone live replica draining its
        # buffer is legitimately in ADD and rejects searches — an engine
        # contract, not an anti-entropy gap; the replication gate makes
        # the same split). The far rows sit far from every query, so the
        # golden top-5 is invariant under them.
        far = (rng.standard_normal((200, DIM)) + 50.0).astype(np.float32)
        for s in range(0, 200, 50):
            ids = [(300 + s + i,) for i in range(50)]
            client.add_index_data("gidx", far[s:s + 50], ids)
            acked.update(i for (i,) in ids)
        repl = client.get_replication_stats()
        assert repl["repair"]["dropped"] >= 3, repl["repair"]
        assert repl["degraded"] is True
        assert len(client.repair_queue) == 1  # only ONE record survives
        survivor = client.sub_indexes[0]
        deadline = time.time() + 120
        while survivor.generic_fun("get_aggregated_ntotal", ("gidx",)) > 0:
            assert time.time() < deadline, "survivor never drained"
            time.sleep(0.2)

        # golden AFTER the mutations (served by the survivor via failover)
        q = np.ascontiguousarray(x[50:58])
        g_scores, g_meta = client.search(q, 5, "gidx")
        assert not any(m in dead_meta for row in g_meta for m in row)

        def reload_gidx():
            # restart mechanics only (NOT a repair call): point the
            # restarted process back at its on-disk gidx generation —
            # stale by 12 deletes and 200 rows, which the sweep must heal
            deadline = time.time() + 60
            while True:
                try:
                    client.sub_indexes[victim_pos].generic_fun(
                        "load_index", ("gidx", None), timeout=30.0)
                    return
                except Exception:
                    assert time.time() < deadline, "victim never reloaded"
                    time.sleep(0.3)

        with QueryStorm(client, "gidx", q, 5, threads=4) as storm:
            time.sleep(0.5)  # storm baseline against the degraded group

            # ---- restart from (stale) storage; ZERO client repair calls:
            # the sweepers alone must converge the group
            h.restart(victim_rank,
                      extra_env={"DFT_SHARD_GROUP": str(group)})
            h.wait_port(victim_rank)
            reload_gidx()
            wait_converged([survivor_port, victim_port], "gidx")

            # ---- SIGKILL again mid-heal window, restart: the heal's
            # commits ride the generation protocol — no torn state
            h.kill(victim_rank)
            time.sleep(0.3)
            h.restart(victim_rank,
                      extra_env={"DFT_SHARD_GROUP": str(group)})
            h.wait_port(victim_rank)
            reload_gidx()
            final_digest = wait_converged([survivor_port, victim_port],
                                          "gidx")
            time.sleep(1.0)  # storm keeps sampling the converged group
        results, errors = storm.stop()

        assert errors == [], f"storm saw search errors: {errors[:3]}"
        assert len(results) >= 10, "storm produced too few samples"
        for scores, meta in results:
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta
            assert not any(m in dead_meta for row in meta for m in row)

        # digests converged byte-identically and carry the deletes
        assert final_digest["dead_n"] >= len(doomed)

        # the victim really drained its pulled rows, then serves golden
        # on the SAME client when reads pin onto it
        deadline = time.time() + 120
        while client.get_buffer_depth("gidx") > 0:
            assert time.time() < deadline, "healed rank never drained"
            time.sleep(0.2)
        with client._stats_lock:
            client._preferred[group] = victim_pos
        scores2, meta2 = client.search(q, 5, "gidx")
        np.testing.assert_array_equal(scores2, g_scores)
        assert meta2 == g_meta
        served = client.sub_indexes[victim_pos].generic_fun("get_perf_stats")
        assert served.get("search", {}).get("count", 0) >= 1, (
            "pinned search was not served by the healed rank")
        # the healed rank repaired rows server-side (its own counters)
        ae = served["antientropy"]
        assert ae["enabled"] and (ae["rows_repaired"] > 0
                                  or ae["full_syncs"] > 0)

        # no acked id lost, no deleted id resurrected, cluster-wide
        present = set(client.get_ids("gidx"))
        lost = acked - present
        assert not lost, f"{len(lost)} acked ids lost: {sorted(lost)[:10]}"
        assert not (set(doomed) & present), "deleted ids resurrected"

        # ---- compaction lease: exactly one live replica holds it
        held = []
        for port in (survivor_port, victim_port):
            health = rpc.Client(9, "localhost", port,
                                mux=False).generic_fun("get_health")
            held.append(bool(health["compaction"]["held"]))
        assert held.count(True) == 1, held
        client.close()
