"""Engine tests: state machine, threshold training, persistence.

Models the reference's integration assertions (tests/test_integration.py:
train_num honored :117-146, config persistence :332-385, centroids :387-416)
at the single-shard level.
"""

import time

import numpy as np
import pytest

from distributed_faiss_tpu.engine import Index, infer_n_centroids
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.state import IndexState


def wait_state(idx, state, timeout=30.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if idx.get_state() == state:
            return True
        time.sleep(0.05)
    return False


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", 16)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 100)
    return IndexCfg(**kw)


def test_train_num_honored(rng):
    idx = Index(flat_cfg())
    x = rng.standard_normal((99, 16)).astype(np.float32)
    idx.add_batch(x, [("m", i) for i in range(99)], train_async_if_triggered=False)
    assert idx.get_state() == IndexState.NOT_TRAINED
    idx.add_batch(x[:1], [("m", 99)], train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    buf, indexed = idx.get_idx_data_num()
    assert buf == 0 and indexed == 100


def test_search_with_metadata(rng):
    idx = Index(flat_cfg(train_num=50))
    x = rng.standard_normal((200, 16)).astype(np.float32)
    meta = [("doc", i) for i in range(200)]
    idx.add_batch(x, meta, train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    q = x[:3] + 1e-6
    scores, results_meta, embs = idx.search(q, 5)
    assert scores.shape == (3, 5)
    assert embs is None
    # nearest neighbor of x[i] is x[i] itself -> metadata joins positionally
    for i in range(3):
        assert results_meta[i][0] == ("doc", i)


def test_search_return_embeddings(rng):
    idx = Index(flat_cfg(train_num=10))
    x = rng.standard_normal((50, 16)).astype(np.float32)
    idx.add_batch(x, None, train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    scores, meta, embs = idx.search(x[:2], 3, return_embeddings=True)
    assert len(embs) == 2 and len(embs[0]) == 3
    np.testing.assert_allclose(embs[0][0], x[0], rtol=1e-5)


def test_search_untrained_raises(rng):
    idx = Index(flat_cfg())
    with pytest.raises(RuntimeError):
        idx.search(rng.standard_normal((1, 16)).astype(np.float32), 3)


def test_async_add_path(rng):
    idx = Index(flat_cfg(train_num=50, buffer_bsz=64))
    x = rng.standard_normal((50, 16)).astype(np.float32)
    idx.add_batch(x, None, train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    # more data after training: flows through ADD back to TRAINED
    for _ in range(4):
        idx.add_batch(rng.standard_normal((100, 16)).astype(np.float32), None)
    assert wait_state(idx, IndexState.TRAINED)
    deadline = time.time() + 30
    while time.time() < deadline:
        buf, indexed = idx.get_idx_data_num()
        if buf == 0 and indexed == 450:
            break
        time.sleep(0.05)
    assert (buf, indexed) == (0, 450)


def test_save_load_round_trip(rng, tmp_path):
    storage = str(tmp_path / "shard")
    idx = Index(flat_cfg(train_num=20, index_storage_dir=storage))
    x = rng.standard_normal((120, 16)).astype(np.float32)
    meta = [("m", i) for i in range(120)]
    idx.add_batch(x, meta, train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    assert idx.save() is True

    loaded = Index.from_storage_dir(storage)
    assert loaded is not None
    assert loaded.get_state() == IndexState.TRAINED
    q = x[:2]
    s0, m0, _ = idx.search(q, 4)
    s1, m1, _ = loaded.search(q, 4)
    np.testing.assert_allclose(s0, s1, rtol=1e-5)
    assert m0 == m1
    # cfg persisted alongside
    assert loaded.cfg.train_num == 20


def test_save_rename_order(rng, tmp_path, monkeypatch):
    """Pin the checkpoint crash-point invariant: every data file of a
    snapshot generation must be renamed into place BEFORE that generation's
    MANIFEST — the manifest is the commit point, so a crash at any rename
    leaves either the previous committed generation or an uncommitted
    (quarantinable) partial set, never a loadable torn one."""
    import os as _os

    order = []
    real_replace = _os.replace

    def spy(src, dst):
        # every checkpoint rename goes through serialization.atomic_write;
        # record only this shard's files, not unrelated library activity
        if str(tmp_path) in str(dst):
            order.append(_os.path.basename(dst))
        return real_replace(src, dst)

    monkeypatch.setattr(
        "distributed_faiss_tpu.utils.serialization.os.replace", spy)
    storage = str(tmp_path / "ord")
    idx = Index(flat_cfg(train_num=10, index_storage_dir=storage))
    idx.add_batch(rng.standard_normal((20, 16)).astype(np.float32), None,
                  train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    assert idx.save()
    manifest = "MANIFEST-g00000001.json"
    assert manifest in order, order
    for data in ("index-g00000001.npz", "meta-g00000001.pkl",
                 "buffer-g00000001.pkl", "cfg-g00000001.json"):
        assert order.index(data) < order.index(manifest), order


def test_load_missing_returns_none(tmp_path):
    assert Index.from_storage_dir(str(tmp_path / "nope")) is None


def test_ivf_engine_centroids(rng, tmp_path):
    cfg = IndexCfg(
        index_builder_type="ivf_simple", dim=16, metric="dot",
        train_num=300, centroids=8, nprobe=8,
    )
    idx = Index(cfg)
    x = rng.standard_normal((400, 16)).astype(np.float32)
    idx.add_batch(x, None, train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    cents = idx.get_centroids()
    assert cents.shape == (8, 16)
    idx.set_nprobe(4)
    assert idx.tpu_index.nprobe == 4


def test_train_ratio_when_train_num_zero(rng, caplog):
    """train_num=0 + train_ratio<1 -> ratio x buffered rows used for training
    (reference index.py:199-206); trigger must then come via sync_train."""
    import logging

    idx = Index(flat_cfg(train_num=0, train_ratio=0.5))
    x = rng.standard_normal((100, 16)).astype(np.float32)
    idx.add_batch(x, None, train_async_if_triggered=False)
    # train_num == 0 never auto-triggers (reference: `0 < train_num <= total`)
    assert idx.get_state() == IndexState.NOT_TRAINED
    with caplog.at_level(logging.INFO):
        idx.sync_train()
    assert wait_state(idx, IndexState.TRAINED)
    buf, indexed = idx.get_idx_data_num()
    assert (buf, indexed) == (0, 100)
    # the ratio must be observable: exactly 50 of 100 rows went to training
    assert any("(50, 16)" in r.getMessage() for r in caplog.records), \
        [r.getMessage() for r in caplog.records][:5]


def test_infer_centroids_tiers():
    assert infer_n_centroids(10000) == int(2 * 100)
    assert infer_n_centroids(2_000_000) == 65536
    assert infer_n_centroids(20_000_000) == 262144
    assert infer_n_centroids(200_000_000) == 1048576


def test_get_ids_custom_idx(rng):
    idx = Index(flat_cfg(train_num=10, custom_meta_id_idx=1))
    x = rng.standard_normal((20, 16)).astype(np.float32)
    idx.add_batch(x, [("a", 100 + i) for i in range(20)], train_async_if_triggered=False)
    assert idx.get_ids() == set(range(100, 120))


def test_trained_but_empty_return_embeddings(rng):
    """Pin the trained-but-empty window semantics (engine.py search):
    search on a trained index with ntotal==0 returns all-(-1) ids, None
    metadata, and ZERO-filled embeddings — a documented divergence from
    FAISS search_and_reconstruct (reference index.py:246-260), which never
    exposes this window because its add is synchronous."""
    from distributed_faiss_tpu.models.factory import build_index

    cfg = flat_cfg(train_num=10)
    idx = Index(cfg)
    # construct the window directly: trained engine whose async add has not
    # drained yet (tpu_index exists, ntotal == 0)
    idx.tpu_index = build_index(cfg)
    idx.tpu_index.train(rng.standard_normal((10, 16)).astype(np.float32))
    idx.state = IndexState.TRAINED

    q = rng.standard_normal((2, 16)).astype(np.float32)
    scores, meta, embs = idx.search(q, 3, return_embeddings=True)
    assert all(m is None for row in meta for m in row)
    assert len(embs) == 2 and len(embs[0]) == 3
    for row in embs:
        for e in row:
            np.testing.assert_array_equal(e, np.zeros(16, np.float32))


def test_drop_index(rng):
    idx = Index(flat_cfg(train_num=10))
    idx.add_batch(rng.standard_normal((20, 16)).astype(np.float32), None,
                  train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    idx.drop_index()
    assert idx.get_state() == IndexState.NOT_TRAINED
    assert idx.get_idx_data_num() == (0, 0)


def test_dim_inferred_when_zero(rng):
    idx = Index(IndexCfg(index_builder_type="flat", dim=0, metric="l2", train_num=10))
    idx.add_batch(rng.standard_normal((20, 24)).astype(np.float32), None,
                  train_async_if_triggered=False)
    assert wait_state(idx, IndexState.TRAINED)
    assert idx.cfg.dim == 24
