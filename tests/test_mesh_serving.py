"""Mesh-sharded serving path: one pjit launch per merged batch (ISSUE 6).

Covers the serving contract on the virtual 8-device CPU mesh
(tests/conftest.py): every sharded index's search issues exactly ONE
device dispatch per call (single-block direct, multi-block fused), the
engine routes merged windows through ``TpuIndex.search_batched`` and
reports ``device_launches`` / ``rows_per_launch``, the env-backed mesh
knobs (DFT_MESH_DEVICES / DFT_MESH_MODE) resolve through the factory,
and a mesh-backed rank round-trips the generation/MANIFEST persistence
machinery including checksum-verified fallback.

Marked ``mesh`` (own CI job with the forced 8-device platform); these
tests are fast and also run in tier-1.
"""

import json
import os
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel.mesh import (
    ShardedFlatIndex,
    ShardedIVFFlatIndex,
    ShardedIVFPQIndex,
    make_mesh,
    sharded_knn,
)

pytestmark = pytest.mark.mesh


def brute_ids(q, x, k, metric="l2"):
    if metric == "dot":
        s = q @ x.T
    else:
        s = -((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return np.argsort(-s, axis=1)[:, :k]


# ------------------------------------------------------- one-launch contract


def test_sharded_flat_single_block_one_launch(rng):
    x = rng.standard_normal((900, 16)).astype(np.float32)
    q = rng.standard_normal((24, 16)).astype(np.float32)
    idx = ShardedFlatIndex(16, "l2")
    idx.add(x)
    assert idx.launches == 0
    D, I = idx.search(q, 5)
    assert idx.launches == 1
    np.testing.assert_array_equal(I, brute_ids(q, x, 5))
    idx.search(q[:3], 5)
    assert idx.launches == 2  # one per call, not per block/row


def test_sharded_flat_multi_block_fused_one_launch(rng):
    """A batch spanning several query blocks must ride the fused lax.map
    entry: ONE dispatch, results byte-identical to the per-block launches
    it replaced (the old query_blocks loop round-tripped per block)."""
    import jax.numpy as jnp

    from distributed_faiss_tpu.models import base
    from distributed_faiss_tpu.ops import distance

    x = rng.standard_normal((3000, 16)).astype(np.float32)
    q = rng.standard_normal((2100, 16)).astype(np.float32)
    idx = ShardedFlatIndex(16, "l2")
    idx.add(x)
    D, I = idx.search(q, 5)
    assert idx.launches == 1

    # reference: the pre-fused shape — one sharded_knn launch per block
    idx._sync()
    block = base.pick_query_block(65536 * 4)
    ref_s = np.empty((q.shape[0], 5), np.float32)
    ref_i = np.empty((q.shape[0], 5), np.int64)
    for s, n, chunk in base.query_blocks(q, block):
        vals, ids = sharded_knn(idx.mesh, jnp.asarray(chunk), idx._dev,
                                idx._ntotals, 5, "l2")
        ref_s[s:s + n] = np.asarray(vals)[:n]
        ref_i[s:s + n] = np.asarray(ids)[:n]
    ref_s, ref_i = base.finalize_results(ref_s, ref_i, "l2")
    np.testing.assert_array_equal(D, ref_s)
    np.testing.assert_array_equal(I, ref_i)


@pytest.mark.parametrize("routing", [False, True])
def test_sharded_ivf_flat_one_launch_exact(rng, routing):
    x = rng.standard_normal((1200, 16)).astype(np.float32)
    q = rng.standard_normal((20, 16)).astype(np.float32)
    idx = ShardedIVFFlatIndex(16, 8, "l2", probe_routing=routing)
    idx.train(x[:600])
    idx.add(x)
    idx.set_nprobe(8)  # full probe: exact by construction
    assert idx.launches == 0
    D, I = idx.search(q, 5)
    # masked: one dispatch; routed: one per (block, bucket-retry) — with
    # uniform full probe there are no drops, so still exactly one
    assert idx.launches == 1
    np.testing.assert_array_equal(I, brute_ids(q, x, 5))


def test_sharded_ivf_pq_counts_launches(rng):
    x = rng.standard_normal((800, 16)).astype(np.float32)
    q = rng.standard_normal((12, 16)).astype(np.float32)
    idx = ShardedIVFPQIndex(16, 4, m=4, metric="l2")
    idx.train(x[:400])
    idx.add(x)
    idx.set_nprobe(4)
    idx.search(q, 5)
    assert idx.launches == 1
    idx.search(q, 5)
    assert idx.launches == 2


def test_search_batched_default_matches_search(rng):
    """TpuIndex.search_batched (the scheduler's launch target) is plain
    search for every model; mesh models guarantee one launch behind it."""
    x = rng.standard_normal((500, 8)).astype(np.float32)
    q = rng.standard_normal((9, 8)).astype(np.float32)
    idx = ShardedFlatIndex(8, "dot")
    idx.add(x)
    a = idx.search(q, 4)
    b = idx.search_batched(q, 4)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    assert idx.launches == 2


# ------------------------------------------------- engine routing + counters


def _trained_mesh_engine(tmp_path, rng, n=600, d=16, extra=None):
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    kw = dict(index_builder_type="flat", dim=d, metric="l2", train_num=64,
              index_storage_dir=str(tmp_path), mesh_shards=True)
    kw.update(extra or {})
    idx = Index(IndexCfg(**kw))
    x = rng.standard_normal((n, d)).astype(np.float32)
    idx.add_batch(x, [("m", i) for i in range(n)],
                  train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 120
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "train/add drain timed out"
        time.sleep(0.02)
    return idx, x


def test_engine_merged_window_is_one_launch(tmp_path, rng):
    """engine.search_batched (what the scheduler calls per flush) must cost
    exactly one device dispatch on a mesh index, and the new perf rows
    must say so."""
    idx, x = _trained_mesh_engine(tmp_path, rng)
    assert isinstance(idx.tpu_index, ShardedFlatIndex)
    q = rng.standard_normal((32, 16)).astype(np.float32)  # 8x4-row window
    for _ in range(3):
        idx.search_batched(q, 3)
    s = idx.perf.summary()
    assert s["device_launches"]["count"] == 3
    assert s["device_launches"]["max_s"] == 1.0  # ONE launch per window
    assert s["rows_per_launch"]["max_s"] == 32.0
    assert s["device_search_rows"]["max_s"] == 32.0


def test_engine_search_and_batched_identical_on_mesh(tmp_path, rng):
    idx, x = _trained_mesh_engine(tmp_path, rng)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    s1, m1, _ = idx.search(q, 3)
    s2, m2, _ = idx.search_batched(q, 3)
    np.testing.assert_array_equal(s1, s2)
    assert m1 == m2


# ------------------------------------------------------------ env mesh knobs


def test_mesh_cfg_env_parsing():
    from distributed_faiss_tpu.utils.config import MeshCfg

    cfg = MeshCfg.from_env({})
    assert cfg.devices == 0 and cfg.mode == "masked"
    cfg = MeshCfg.from_env({"DFT_MESH_DEVICES": "4", "DFT_MESH_MODE": "routed"})
    assert cfg.devices == 4 and cfg.mode == "routed"
    with pytest.raises(ValueError, match="mode"):
        MeshCfg.from_env({"DFT_MESH_MODE": "zigzag"})
    with pytest.raises(ValueError, match="devices"):
        MeshCfg(devices=-1)
    with pytest.raises(TypeError, match="unknown"):
        MeshCfg(chips=8)


def test_factory_resolves_env_mesh_knobs(monkeypatch):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    monkeypatch.setenv("DFT_MESH_DEVICES", "4")
    monkeypatch.setenv("DFT_MESH_MODE", "routed")
    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                   centroids=4, shard_lists=True)
    idx = build_index(cfg)
    assert isinstance(idx, ShardedIVFFlatIndex)
    assert idx.mesh.devices.size == 4  # DFT_MESH_DEVICES
    assert idx.probe_routing is True   # DFT_MESH_MODE=routed

    # explicit cfg.extra pins win over the host env defaults
    cfg2 = IndexCfg(index_builder_type="ivf_tpu", dim=8, metric="l2",
                    centroids=4, shard_lists=True, mesh_devices=2,
                    probe_routing=False)
    idx2 = build_index(cfg2)
    assert idx2.mesh.devices.size == 2
    assert idx2.probe_routing is False


def test_restore_honors_host_mesh_devices(monkeypatch, rng):
    """from_state_dict builds with mesh=None -> make_mesh(None), which must
    apply the per-host DFT_MESH_DEVICES default: a restored rank may not
    silently spread onto chips the operator excluded."""
    from distributed_faiss_tpu.models.factory import index_from_state_dict

    x = rng.standard_normal((300, 8)).astype(np.float32)
    q = rng.standard_normal((5, 8)).astype(np.float32)
    idx = ShardedFlatIndex(8, "l2")  # built on all 8 virtual devices
    idx.add(x)
    golden = idx.search(q, 3)
    state = idx.state_dict()

    monkeypatch.setenv("DFT_MESH_DEVICES", "2")
    restored = index_from_state_dict(state)
    assert restored.nshards == 2  # host env applied on restore
    got = restored.search(q, 3)
    np.testing.assert_array_equal(golden[0], got[0])
    np.testing.assert_array_equal(golden[1], got[1])


def test_factory_env_devices_apply_to_flat_mesh(monkeypatch):
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    monkeypatch.setenv("DFT_MESH_DEVICES", "2")
    idx = build_index(IndexCfg(index_builder_type="flat", dim=8, metric="l2",
                               mesh_shards=True))
    assert isinstance(idx, ShardedFlatIndex)
    assert idx.nshards == 2


# ------------------------------------------------------- persistence (engine)


def test_mesh_engine_generation_round_trip(tmp_path, rng):
    """A mesh-backed rank rides the generation/MANIFEST machinery: save,
    restore, byte-identical serving."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils import serialization

    idx, x = _trained_mesh_engine(tmp_path / "shard", rng)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    golden = idx.search_batched(q, 3)
    assert idx.save()
    assert serialization.list_generations(str(tmp_path / "shard"))

    loaded = Index.from_storage_dir(str(tmp_path / "shard"))
    assert isinstance(loaded.tpu_index, ShardedFlatIndex)
    got = loaded.search_batched(q, 3)
    np.testing.assert_array_equal(golden[0], got[0])
    assert golden[1] == got[1]


def test_mesh_engine_torn_generation_falls_back(tmp_path, rng):
    """Corrupting the newest committed generation of a mesh-backed shard
    must quarantine it and serve the previous one (the checksum-verified
    fallback the chaos gate relies on)."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils import serialization
    from distributed_faiss_tpu.utils.state import IndexState

    storage = tmp_path / "shard"
    idx, x = _trained_mesh_engine(storage, rng)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    golden = idx.search_batched(q, 3)
    assert idx.save()

    # a second, newer generation... (wait for the state flip too: the
    # drain worker zeroes the buffer count BEFORE leaving ADD, and
    # save() during ADD defers to add-completion and returns None)
    extra = rng.standard_normal((40, 16)).astype(np.float32)
    idx.add_batch(extra, [("m", 600 + i) for i in range(40)],
                  train_async_if_triggered=False)
    deadline = time.time() + 60
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline
        time.sleep(0.02)
    assert idx.save()
    gen, mpath = serialization.list_generations(str(storage))[0]
    with open(mpath) as f:
        manifest = json.load(f)
    victim = os.path.join(str(storage), manifest["files"]["index"]["name"])
    with open(victim, "r+b") as f:  # ...torn at a random-ish byte offset
        f.seek(12)
        f.write(b"\xde\xad\xbe\xef")

    loaded = Index.from_storage_dir(str(storage))
    assert loaded is not None
    assert loaded.tpu_index.ntotal == 600  # previous generation
    got = loaded.search_batched(q, 3)
    np.testing.assert_array_equal(golden[0], got[0])
    assert golden[1] == got[1]
    qdir = os.path.join(str(storage), "quarantine")
    assert os.path.isdir(qdir) and os.listdir(qdir)


def test_sharded_ivf_engine_round_trip(tmp_path, rng):
    """ivf_tpu + shard_lists (sharded inverted lists) through the same
    engine save/restore path: the gather-to-host state dict must stream
    the mesh-resident lists back bit-identically."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    cfg = IndexCfg(index_builder_type="ivf_tpu", dim=16, metric="l2",
                   train_num=64, centroids=8, nprobe=8,
                   index_storage_dir=str(tmp_path), shard_lists=True)
    idx = Index(cfg)
    x = rng.standard_normal((500, 16)).astype(np.float32)
    idx.add_batch(x, [("m", i) for i in range(500)],
                  train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 300
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "train/add drain timed out"
        time.sleep(0.05)
    assert isinstance(idx.tpu_index, ShardedIVFFlatIndex)
    q = rng.standard_normal((10, 16)).astype(np.float32)
    golden = idx.search_batched(q, 3)
    assert idx.save()

    from distributed_faiss_tpu.engine import Index as Engine

    loaded = Engine.from_storage_dir(str(tmp_path))
    assert isinstance(loaded.tpu_index, ShardedIVFFlatIndex)
    got = loaded.search_batched(q, 3)
    np.testing.assert_array_equal(golden[0], got[0])
    assert golden[1] == got[1]
    # the restored rank still serves one launch per merged window
    s = loaded.perf.summary()
    assert s["device_launches"]["max_s"] == 1.0
