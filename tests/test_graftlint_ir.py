"""graftlint IR tier: golden doctored fixtures, clean twins, drift
detection, and the self-enforcing repo-wide jaxpr lint.

The bad fixture (tests/fixtures/lint/ir_bad/mod.py) is loaded via
importlib — never imported by the package — and fed to ``lint_ir``
through rows that carry the callables directly (the harness's
``fn``/``spec_fn``/``buckets_fn`` override path).  Rule AND line are
asserted exactly, so the lint cannot silently rot into a no-op.
"""

import importlib.util
import os
import subprocess
import sys

import pytest

import jax
import jax.numpy as jnp

from tools.graftlint.ir.harness import (
    RULE_BUDGET,
    RULE_CONST,
    RULE_DTYPE,
    RULE_RESIDENCY,
    RULE_TRACE,
    lint_ir,
)

pytestmark = [pytest.mark.lint, pytest.mark.ir]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BAD = "tests/fixtures/lint/ir_bad/mod.py"
CLEAN = "tests/fixtures/lint/ir_clean/mod.py"


def _load(relpath, name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO_ROOT, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _bf16(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.bfloat16)


def _row(path, qualname, fn, spec, **extra):
    row = {
        "path": path,
        "import": "<fixture>",
        "qualname": qualname,
        "trace": True,
        "budget": None,
        "buckets": None,
        "fn": fn,
        "spec_fn": lambda: spec,
    }
    row.update(extra)
    return row


@pytest.fixture
def repo_cwd(monkeypatch):
    """Findings anchor paths relative to cwd; pin it to the repo root."""
    monkeypatch.chdir(REPO_ROOT)


def _bad_rows():
    mod = _load(BAD, "ir_bad_fixture")
    return [
        _row(BAD, "residency_bad", mod.residency_bad, [((_f32(4),), {})]),
        _row(BAD, "callback_bad", mod.callback_bad, [((_f32(4, 8),), {})]),
        _row(BAD, "dtype_bad", mod.dtype_bad,
             [((_bf16(4, 8), _bf16(8, 4)), {})]),
        _row(BAD, "const_bad", mod.const_bad, [((_f32(513, 512),), {})]),
        # budget drift: enumerator reaches 2 buckets, registry declares 3
        {"path": BAD, "import": "<fixture>", "qualname": "budget_bad",
         "trace": False, "budget": 3, "buckets": None,
         "buckets_fn": lambda: {8, 16}},
        # stale row: resolves through the real import path and misses
        {"path": BAD, "import": "tests.fixtures.lint.ir_bad.mod",
         "qualname": "stale_row", "trace": True,
         "budget": None, "buckets": None},
    ]


def test_bad_fixture_findings_exact(repo_cwd):
    """Every IR rule fires on the doctored fixture, at the pinned line."""
    findings = lint_ir(entries=_bad_rows(), callback_allowlist=())
    got = [(f.rule, f.path, f.line) for f in findings]
    assert got == [
        (RULE_BUDGET, BAD, 1),       # budget_bad: 2 buckets vs declared 3
        (RULE_TRACE, BAD, 1),        # stale_row fails to resolve
        (RULE_RESIDENCY, BAD, 28),   # residency_bad: debug_callback
        (RULE_RESIDENCY, BAD, 35),   # callback_bad: unallowed pure_callback
        (RULE_DTYPE, BAD, 41),       # dtype_bad: bf16 accumulation
        (RULE_CONST, BAD, 44),       # const_bad: >1MiB baked const
        (RULE_BUDGET, BAD, 50),      # unregistered: registry drift
    ]

    by_line = {f.line: f.message for f in findings if f.rule != RULE_BUDGET}
    assert "`debug_callback`" in by_line[28]
    assert "`_host_norm` not in PURE_CALLBACK_ALLOWLIST" in by_line[35]
    assert "accumulates in bfloat16" in by_line[41]
    assert "1050624-byte array" in by_line[44]

    budget_msgs = {f.line: f.message for f in findings
                   if f.rule == RULE_BUDGET}
    assert "reaches 2 shape buckets but the registry declares 3" \
        in budget_msgs[1]
    assert "unregistered jit entry `unregistered`" in budget_msgs[50]
    trace_msg = next(f.message for f in findings if f.rule == RULE_TRACE)
    assert "stale registry row" in trace_msg
    assert "stale_row" in trace_msg


def test_clean_twins_no_findings(repo_cwd):
    """The policy-conforming twins of every bad case lint clean."""
    mod = _load(CLEAN, "ir_clean_fixture")
    rows = [
        _row(CLEAN, "residency_clean", mod.residency_clean,
             [((_f32(4),), {})]),
        _row(CLEAN, "callback_clean", mod.callback_clean,
             [((_f32(4, 8),), {})]),
        _row(CLEAN, "dtype_clean", mod.dtype_clean,
             [((_bf16(4, 8), _bf16(8, 4)), {})]),
        _row(CLEAN, "const_clean", mod.const_clean,
             [((_f32(513, 512), _f32(513, 512)), {})]),
        # exact budget match: declared 3, enumerator reaches 3
        {"path": CLEAN, "import": "<fixture>", "qualname": "budget_clean",
         "trace": False, "budget": 3, "buckets": None,
         "buckets_fn": lambda: {8, 16, 32}},
    ]
    findings = lint_ir(entries=rows, callback_allowlist={"_host_norm"})
    assert findings == []


def test_budget_check_is_exact_both_directions(repo_cwd):
    """Budget drift fires when the enumeration over- OR under-shoots the
    declared count — exact equality, not an upper bound."""
    for buckets in ({8, 16}, {8, 16, 32, 64}):
        row = {"path": CLEAN, "import": "<fixture>",
               "qualname": "budget_clean", "trace": False, "budget": 3,
               "buckets": None, "buckets_fn": lambda b=buckets: b}
        # ignore the drift findings this lone row leaves behind in the
        # covered file — this test pins only the budget-equality check
        mismatch = [f for f in lint_ir(entries=[row], callback_allowlist=())
                    if f.rule == RULE_BUDGET and "shape buckets" in f.message]
        assert len(mismatch) == 1
        assert f"reaches {len(buckets)} shape buckets" in mismatch[0].message


def test_registry_drift_flags_each_missing_def(repo_cwd):
    """Dropping rows from a covered file surfaces every unregistered
    module-level jit def at its own def line."""
    mod = _load(CLEAN, "ir_clean_fixture_drift")
    rows = [_row(CLEAN, "residency_clean", mod.residency_clean,
                 [((_f32(4),), {})])]
    findings = lint_ir(entries=rows, callback_allowlist=())
    drift = [(f.line, f.message) for f in findings if f.rule == RULE_BUDGET]
    assert [ln for ln, _ in drift] == [20, 27, 33]
    assert all("unregistered jit entry" in msg for _, msg in drift)
    assert "`callback_clean`" in drift[0][1]
    assert "`dtype_clean`" in drift[1][1]
    assert "`const_clean`" in drift[2][1]


def test_unregistered_nonjit_helpers_are_exempt(repo_cwd):
    """Plain (non-jit) module-level defs in a covered file never count as
    drift — only jitted launch targets need rows."""
    mod = _load(CLEAN, "ir_clean_fixture_full")
    rows = [
        _row(CLEAN, "residency_clean", mod.residency_clean,
             [((_f32(4),), {})]),
        _row(CLEAN, "callback_clean", mod.callback_clean,
             [((_f32(4, 8),), {})]),
        _row(CLEAN, "dtype_clean", mod.dtype_clean,
             [((_bf16(4, 8), _bf16(8, 4)), {})]),
        _row(CLEAN, "const_clean", mod.const_clean,
             [((_f32(513, 512), _f32(513, 512)), {})]),
    ]
    findings = lint_ir(entries=rows, callback_allowlist={"_host_norm"})
    # _host_norm is a module-level def but not jitted: no drift finding
    assert findings == []


def test_missing_covered_file_is_a_finding(repo_cwd):
    row = {"path": "tests/fixtures/lint/ir_bad/no_such_file.py",
           "import": "<fixture>", "qualname": "ghost", "trace": False,
           "budget": None, "buckets": None}
    findings = lint_ir(entries=[row], callback_allowlist=())
    assert [f.rule for f in findings] == [RULE_BUDGET]
    assert "missing file" in findings[0].message


def test_non_jitted_registered_callable_is_a_finding(repo_cwd):
    """A registered entry that is not actually jitted (no .trace) is an
    unverified entry, not a silent skip."""
    rows = [_row(BAD, "residency_bad", lambda x: x, [((_f32(4),), {})])]
    findings = lint_ir(entries=rows, callback_allowlist=())
    trace = [f for f in findings if f.rule == RULE_TRACE]
    assert len(trace) == 1
    assert "not a jitted callable" in trace[0].message


def test_repo_ir_lint_is_clean(repo_cwd):
    """Self-enforcement: the real registry traces every entry on today's
    repo with zero findings (drift, budgets, residency, dtype, consts)."""
    from distributed_faiss_tpu.utils import jitreg

    findings = lint_ir()
    assert findings == [], "\n".join(
        f"{f.rule} {f.path}:{f.line} {f.message}" for f in findings)
    # and the registry actually covers a real fleet of entries
    assert len(jitreg.rows()) >= 30


@pytest.mark.slow
def test_cli_ir_only_exits_zero():
    """End-to-end CLI: `python -m tools.graftlint --ir-only` on the repo."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--ir-only"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: 0 finding(s)" in proc.stdout
