"""Binary wire path tests (ISSUE 14, marker ``wire``).

Covers the binary skeleton codec (golden vectors, round trips, schema
fallback), the per-connection capability negotiation (handshake matrix:
binary<->binary, binary<->legacy both directions, DFT_RPC_WIRE=pickle
override), per-frame pickle fallback for non-schema payloads,
malformed-binary-header isolation on BOTH serving loops, mux
out-of-order completion under binary skeletons, the no-pickle-bytes
frame scan on a negotiated connection, and a chaos garble case proving
the retry/redial machinery is unchanged under the new encoding."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu import (
    Index,
    IndexCfg,
    IndexClient,
    IndexServer,
    IndexState,
    SchedulerCfg,
    WireCfg,
)
from distributed_faiss_tpu.parallel import rpc, wire

pytestmark = pytest.mark.wire

PICKLE_PROTO4 = b"\x80\x04"  # pickle.dumps(protocol=4) frame prefix


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection(("localhost", port), timeout=1).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def write_discovery(tmp_path, ports, name="disc.txt"):
    p = tmp_path / name
    p.write_text("\n".join(
        [str(len(ports))] + [f"localhost,{port}" for port in ports]) + "\n")
    return str(p)


def make_trained_engine(storage, n=400, d=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    cfg = IndexCfg(index_builder_type="flat", dim=d, metric="l2",
                   train_num=64)
    cfg.index_storage_dir = str(storage)
    idx = Index(cfg)
    idx.add_batch(x, [("doc", i) for i in range(n)],
                  train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 60
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "train/drain timed out"
        time.sleep(0.05)
    queries = [rng.standard_normal((4, d)).astype(np.float32)
               for _ in range(8)]
    return idx, queries


def start_server(storage, mode, engine=None, index_id="wire",
                 wire_cfg=None):
    port = free_port()
    srv = IndexServer(0, str(storage),
                      scheduler_cfg=SchedulerCfg(max_wait_ms=1.0),
                      wire_cfg=wire_cfg)
    if engine is not None:
        srv.indexes[index_id] = engine
        srv._wire_engine(engine)
    target = srv.start_blocking if mode == "blocking" else srv.start
    threading.Thread(target=target, args=(port,), daemon=True).start()
    assert wait_listening(port)
    return srv, port


# ------------------------------------------------------------------- codec


def test_binary_call_golden_vector():
    """Pin the CALL skeleton byte layout: a wire-format change that moves
    these bytes breaks live peers mid-rolling-upgrade and MUST be a
    conscious, versioned decision (bump wire._VERSION, extend decode)."""
    q = np.arange(6, dtype=np.float32).reshape(2, 3)
    skel, arrays = wire.encode_call(
        "search", ("idx", q, 7, True),
        {}, {"req_id": 9, "deadline_s": 2.0, "trace_id": "ab", "wire": 1})
    assert len(arrays) == 1 and arrays[0].dtype == np.float32
    expected = (
        b"\x01"                  # version
        b"\x00"                  # op_id: search
        b"\x07"                  # meta flags: req_id | deadline | trace
        + struct.pack("<Q", 9)   # req_id
        + struct.pack("<d", 2.0)  # deadline_s
        + struct.pack("<I", 2) + b"ab"    # trace_id
        + struct.pack("<I", 3) + b"idx"   # index_id
        + struct.pack("<I", 0)   # query plane ref
        + struct.pack("<I", 7)   # top_k
        + b"\x01"                # return_embeddings
    )
    assert skel == expected
    fname, args, kwargs, meta = wire.decode_call(skel, arrays)
    assert fname == "search" and kwargs == {}
    assert args[0] == "idx" and args[2] == 7 and args[3] is True
    np.testing.assert_array_equal(args[1], q)
    assert meta == {"wire": 1, "req_id": 9, "deadline_s": 2.0,
                    "trace_id": "ab"}
    # the skeleton is NOT pickle
    assert not skel.startswith(PICKLE_PROTO4)


def test_binary_result_roundtrip_exact_types():
    """Byte-identity depends on the labels round-tripping EXACT Python
    types (tuple vs list, int vs float vs str vs None vs bool)."""
    scores = np.random.default_rng(1).standard_normal((3, 4)).astype(np.float32)
    labels = [
        [(1,), ("doc", 2), None, True],
        [(-5, "x"), (3.25,), False, ("nested", (1, 2))],
        [[], (0,), ("s",), (2 ** 62,)],
    ]
    skel, arrays = wire.encode_result((scores, labels, None))
    out = wire.decode_result(skel, arrays)
    np.testing.assert_array_equal(out[0], scores)
    assert out[1] == labels and out[2] is None
    for got, want in zip(out[1], labels):
        assert [type(g) for g in got] == [type(w) for w in want]

    # embeddings variant: per-hit ndarray leaves ride tensor planes
    embs = [[np.full(4, i, np.float32) for i in range(2)] for _ in range(2)]
    skel, arrays = wire.encode_result((scores[:2, :2], labels[:2][:2], embs))
    out = wire.decode_result(skel, arrays)
    for row_got, row_want in zip(out[2], embs):
        for g, w in zip(row_got, row_want):
            np.testing.assert_array_equal(g, w)


def test_binary_error_and_busy_roundtrip():
    skel, arr = wire.encode_error("Traceback: boom")
    assert wire.decode_error(skel, arr) == "Traceback: boom"
    for payload in ({"reason": "stopping"},
                    {"reason": "queue_full", "queue_depth": 3,
                     "max_queue": 8}):
        skel, arr = wire.encode_busy(payload)
        assert wire.decode_busy(skel, arr) == payload


def test_encode_schema_misses_fall_back():
    """Anything outside the schema must raise WireEncodeError (the
    per-frame pickle fallback signal) — never encode lossily."""
    q = np.zeros((1, 4), np.float32)
    with pytest.raises(wire.WireEncodeError):  # unknown op
        wire.encode_call("get_rank", (), {}, {})
    with pytest.raises(wire.WireEncodeError):  # non-schema kwarg
        wire.encode_call("search", ("i", q, 3), {"min_version": (1, 2, 3)},
                         {})
    with pytest.raises(wire.WireEncodeError):  # future meta key
        wire.encode_call("search", ("i", q, 3), {}, {"baggage": "x"})
    with pytest.raises(wire.WireEncodeError):  # np scalar metadata
        wire.encode_result((q, [[(np.int64(3),)]], None))
    with pytest.raises(wire.WireEncodeError):  # non-search result shape
        wire.encode_result(42)
    # and the rpc-level helpers return None instead of raising
    assert rpc.pack_binary_call("get_rank", (), {}, {}) is None
    assert rpc.pack_binary_response(rpc.KIND_RESULT, 42, req_id=1) is None
    assert rpc.pack_binary_response(rpc.KIND_SHARD_DATA, {}, None) is None


def test_binary_decode_rejects_garbage():
    """Truncation, trailing bytes, bad tags, out-of-range plane refs and
    wrong query dtype all raise (WireDecodeError at the codec,
    FrameError at the frame layer) — a garbled binary stream is
    transport-classified, never garbage results."""
    q = np.zeros((2, 3), np.float32)
    skel, arrays = wire.encode_call("search", ("i", q, 3, False), {},
                                    {"req_id": 1})
    with pytest.raises(wire.WireDecodeError):
        wire.decode_call(skel[:-2], arrays)          # truncated
    with pytest.raises(wire.WireDecodeError):
        wire.decode_call(skel + b"xx", arrays)       # trailing bytes
    with pytest.raises(wire.WireDecodeError):
        wire.decode_call(skel, [])                   # plane ref dangling
    with pytest.raises(wire.WireDecodeError):
        wire.decode_call(skel, [q.astype(np.float64)])  # dtype violation
    with pytest.raises(wire.WireDecodeError):
        wire.decode_result(b"\x01\x00" + struct.pack("<I", 0) + b"\xff",
                           [q])                      # unknown value tag
    # frame layer: a binary-flagged frame with a garbled skeleton is a
    # FrameError (TRANSPORT_ERRORS)
    a, b = socket.socketpair()
    hdr = rpc._HDR.pack(rpc.MAGIC, rpc.KIND_CALL | rpc.WIRE_BINARY_FLAG,
                        4, 0)
    a.sendall(hdr + b"\xde\xad\xbe\xef")
    with pytest.raises(rpc.FrameError):
        rpc.recv_frame_ex(b)
    a.close()
    b.close()


# ------------------------------------------------------------- negotiation


@pytest.mark.parametrize("mode", ["blocking", "selector"])
def test_negotiation_and_identity_both_loops(tmp_path, mode):
    """binary<->binary: the first search rides pickle + advert, the
    server answers binary immediately, the second search CALL goes out
    binary — and every result is byte-identical regardless of which
    encoding carried it. Works on BOTH serving loops."""
    idx, queries = make_trained_engine(tmp_path / "eng")
    srv, port = start_server(tmp_path, mode, engine=idx)
    disc = write_discovery(tmp_path, [port])
    client = IndexClient(disc)
    client.cfg = idx.cfg
    try:
        first = client.search(queries[0], 5, "wire")
        stub = client.sub_indexes[0]
        assert stub.rpc_stats()["peer_wire"] is True  # negotiated on reply 1
        second = client.search(queries[0], 5, "wire")
        np.testing.assert_array_equal(first[0], second[0])
        assert first[1] == second[1]
        # embeddings variant over the binary path
        d, m, e = client.search(queries[1], 3, "wire",
                                return_embeddings=True)
        assert len(e) == queries[1].shape[0]
        # non-search ops on the same negotiated connection keep working
        # (their responses fall back to pickle per frame)
        assert client.sub_indexes[0].get_rank() == 0
    finally:
        client.close()
        srv.stop()


def test_handshake_matrix_legacy_interop(tmp_path):
    """Legacy interop both directions with ZERO configuration:
    a binary-capable client against a pickle-only server and a
    pickle-only client against a binary-capable server both serve
    byte-identical results on plain pickle frames."""
    idx, queries = make_trained_engine(tmp_path / "eng")

    # golden from a binary<->binary pair
    srv, port = start_server(tmp_path / "s1", "blocking", engine=idx)
    disc = write_discovery(tmp_path, [port], "d1.txt")
    c = IndexClient(disc)
    c.cfg = idx.cfg
    c.search(queries[0], 5, "wire")  # negotiate
    golden = c.search(queries[0], 5, "wire")
    assert c.sub_indexes[0].rpc_stats()["peer_wire"] is True
    c.close()
    srv._stopping.set()
    srv.socket.close()
    srv.scheduler.stop()

    # binary client vs pickle-only server
    srv, port = start_server(tmp_path / "s2", "blocking", engine=idx,
                             wire_cfg=WireCfg(encoding="pickle"))
    disc = write_discovery(tmp_path, [port], "d2.txt")
    c = IndexClient(disc)
    c.cfg = idx.cfg
    r = [c.search(queries[0], 5, "wire") for _ in range(3)][-1]
    assert c.sub_indexes[0].rpc_stats()["peer_wire"] is False
    np.testing.assert_array_equal(r[0], golden[0])
    assert r[1] == golden[1]
    c.close()
    srv._stopping.set()
    srv.socket.close()
    srv.scheduler.stop()

    # pickle-only client (DFT_RPC_WIRE=pickle) vs binary server
    srv, port = start_server(tmp_path / "s3", "blocking", engine=idx)
    disc = write_discovery(tmp_path, [port], "d3.txt")
    stub = rpc.Client(0, "localhost", port, wire_binary=False)
    out = [stub.generic_fun("search", ("wire", queries[0], 5))
           for _ in range(3)][-1]
    assert stub.rpc_stats()["peer_wire"] is False
    np.testing.assert_array_equal(out[0], golden[0])
    assert out[1] == golden[1]
    # and a SERIAL (legacy-dialect) client against the binary server
    serial = rpc.Client(1, "localhost", port, mux=False)
    out = serial.generic_fun("search", ("wire", queries[0], 5))
    np.testing.assert_array_equal(out[0], golden[0])
    stub.close()
    serial.close()
    srv._stopping.set()
    srv.socket.close()
    srv.scheduler.stop()


def test_env_override_pins_pickle(tmp_path, monkeypatch):
    """DFT_RPC_WIRE=pickle on the client side keeps frames free of even
    the capability advert — byte-identical to the pre-wire client."""
    monkeypatch.setenv("DFT_RPC_WIRE", "pickle")
    assert rpc.wire_binary_by_env() is False
    c = rpc.Client.__new__(rpc.Client)  # no dial needed for the flag
    assert WireCfg.from_env().encoding == "pickle"
    monkeypatch.setenv("DFT_RPC_WIRE", "binary")
    assert rpc.wire_binary_by_env() is True
    with pytest.raises(ValueError):
        WireCfg(encoding="msgpack")


def test_per_frame_fallback_on_negotiated_connection(tmp_path):
    """A search whose kwargs fall outside the binary schema
    (min_version) must transparently ride a pickle skeleton on an
    otherwise-binary connection — same connection, no error, correct
    structured rejection semantics."""
    idx, queries = make_trained_engine(tmp_path / "eng")
    srv, port = start_server(tmp_path, "blocking", engine=idx)
    stub = rpc.Client(0, "localhost", port)
    try:
        stub.generic_fun("search", ("wire", queries[0], 5))
        assert stub.rpc_stats()["peer_wire"] is True
        # min_version demands a watermark this replica does not have:
        # the structured stale-read rejection must come back intact
        # (ServerException — an application error, not a wire error)
        with pytest.raises(rpc.ServerException) as ei:
            stub.generic_fun("search", ("wire", queries[0], 5),
                             {"min_version": (1, 0, "w")})
        assert "stale read" in str(ei.value).lower() or "version" in str(
            ei.value).lower()
        # the connection survived the fallback frame
        assert stub.generic_fun("get_rank", ()) == 0
    finally:
        stub.close()
        srv._stopping.set()
        srv.socket.close()
        srv.scheduler.stop()


def test_negotiated_search_frames_contain_no_pickle(tmp_path):
    """The acceptance scan: capture every frame both directions on a
    negotiated connection; after negotiation the search CALL and RESULT
    frames are binary-flagged and their skeletons contain no pickle."""
    captured = []
    real_send = rpc._send_parts

    def tap(sock, parts):
        captured.append(b"".join(bytes(p) for p in parts))
        return real_send(sock, parts)

    idx, queries = make_trained_engine(tmp_path / "eng")
    srv, port = start_server(tmp_path, "blocking", engine=idx)
    stub = rpc.Client(0, "localhost", port)
    try:
        stub.generic_fun("search", ("wire", queries[0], 5))  # negotiate
        rpc._send_parts = tap
        try:
            for q in queries[:4]:
                stub.generic_fun("search", ("wire", q, 5))
        finally:
            rpc._send_parts = real_send
    finally:
        stub.close()
        srv._stopping.set()
        srv.socket.close()
        srv.scheduler.stop()

    calls = results = 0
    for buf in captured:
        magic, kind, skel_len, _narr = rpc._HDR.unpack(buf[:rpc._HDR.size])
        if magic != rpc.MAGIC:
            continue
        skel = buf[rpc._HDR.size:rpc._HDR.size + skel_len]
        base = kind & ~rpc.WIRE_BINARY_FLAG
        if base in (rpc.KIND_CALL, rpc.KIND_RESULT_MUX):
            # every search-family frame after negotiation is binary and
            # pickle-free
            assert kind & rpc.WIRE_BINARY_FLAG, f"pickle frame kind {kind}"
            assert PICKLE_PROTO4 not in skel
            calls += base == rpc.KIND_CALL
            results += base == rpc.KIND_RESULT_MUX
    assert calls == 4 and results == 4


# ---------------------------------------------------------------- serving


@pytest.mark.parametrize("mode", ["blocking", "selector"])
def test_malformed_binary_header_drops_only_that_connection(tmp_path, mode):
    """A binary-flagged frame with a garbled skeleton kills ITS
    connection (FrameError) — the server keeps serving everyone else, in
    both serving loops (the pickle-era malformed-frame contract)."""
    srv, port = start_server(tmp_path, mode)
    # well-formed header, binary flag, garbage skeleton
    bad = socket.create_connection(("localhost", port))
    bad.sendall(rpc._HDR.pack(rpc.MAGIC,
                              rpc.KIND_CALL | rpc.WIRE_BINARY_FLAG, 8, 0)
                + b"\xff" * 8)
    time.sleep(0.2)
    bad.close()
    # a binary-flagged frame claiming an unknown kind dies the same way
    bad = socket.create_connection(("localhost", port))
    bad.sendall(rpc._HDR.pack(rpc.MAGIC,
                              rpc.KIND_DIGEST | rpc.WIRE_BINARY_FLAG, 2, 0)
                + b"\x01\x00")
    time.sleep(0.2)
    bad.close()
    c = rpc.Client(0, "localhost", port)
    assert c.get_rank() == 0
    c.close()
    srv.stop()


def test_mux_out_of_order_tagged_binary_responses():
    """Out-of-order completion under BINARY tagged skeletons: the demux
    routes by req_id exactly as with pickle frames, and the first binary
    response flips the stub's peer_wire."""
    port = free_port()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", port))
    lsock.listen(1)
    frames = []
    scores = {"a": np.full((1, 2), 1.0, np.float32),
              "b": np.full((1, 2), 2.0, np.float32)}

    def serve():
        conn, _ = lsock.accept()
        for _ in range(2):
            kind, payload = rpc.recv_frame(conn)
            assert kind == rpc.KIND_CALL
            frames.append(payload)
        # answer in REVERSE arrival order, binary-tagged
        for payload in reversed(frames):
            fname, args, _kw, meta = payload
            body = (scores[args[0]], [[("hit", args[0])]], None)
            rpc._send_parts(conn, rpc.pack_binary_response(
                rpc.KIND_RESULT, body, meta["req_id"]))
            time.sleep(0.05)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    c = rpc.Client(0, "localhost", port)
    done = []

    def call(iid):
        out = c.generic_fun("search", (iid, np.zeros((1, 2), np.float32), 1))
        done.append((iid, out))

    threads = [threading.Thread(target=call, args=(i,)) for i in ("a", "b")]
    for th in threads:
        th.start()
        time.sleep(0.05)  # deterministic arrival order a, b
    for th in threads:
        th.join(timeout=10)
    assert len(done) == 2
    by_id = dict(done)
    np.testing.assert_array_equal(by_id["a"][0], scores["a"])
    np.testing.assert_array_equal(by_id["b"][0], scores["b"])
    assert by_id["a"][1] == [[("hit", "a")]]
    assert c.rpc_stats()["peer_wire"] is True
    c.close()
    lsock.close()


def test_garble_on_negotiated_connection_retries_unchanged(tmp_path):
    """Chaos: garble the byte window of a binary-negotiated connection —
    the demux fails all in-flight calls with TRANSPORT_ERRORS and the
    NEXT call redials cleanly, exactly the pickle-era contract."""
    from distributed_faiss_tpu.testing.chaos import ChaosProxy, Fault

    idx, queries = make_trained_engine(tmp_path / "eng")
    srv, port = start_server(tmp_path, "blocking", engine=idx)
    proxy = ChaosProxy("localhost", port,
                       plan=[Fault(Fault.GARBLE, after_bytes=6000,
                                   nbytes=64, direction="down")]).start()
    try:
        stub = rpc.Client(0, "localhost", proxy.port)
        stub.generic_fun("search", ("wire", queries[0], 5))
        assert stub.rpc_stats()["peer_wire"] is True
        # keep searching until the garble window hits: the failure MUST
        # be transport-classified (retry/reroute machinery unchanged)
        saw_transport = False
        for _ in range(200):
            try:
                stub.generic_fun("search", ("wire", queries[0], 5),
                                 timeout=5.0)
            except rpc.TRANSPORT_ERRORS:
                saw_transport = True
                break
            except socket.timeout:
                saw_transport = True
                break
        assert saw_transport, "garble never surfaced as a transport error"
        # the next call redials (connection 2 of the plan: clean) and
        # renegotiates binary from scratch
        deadline = time.time() + 10
        while True:
            try:
                out = stub.generic_fun("search", ("wire", queries[0], 5))
                break
            except rpc.TRANSPORT_ERRORS + (ConnectionRefusedError,):
                assert time.time() < deadline
                time.sleep(0.3)
        assert out[0].shape == (4, 5)
        stub.close()
    finally:
        proxy.stop()
        srv._stopping.set()
        srv.socket.close()
        srv.scheduler.stop()


# ------------------------------------------------- buffered frame reader


class _ScriptedSock:
    """A fake socket over a fixed byte script, counting syscalls. ``recv``
    returns up to ``n`` bytes (everything queued is 'available', like a
    kernel buffer after a pipelined burst); ``recv_into`` fills the view
    from the same stream."""

    def __init__(self, data, chunk=None):
        self._data = memoryview(bytes(data))
        self._ofs = 0
        self._chunk = chunk  # cap per-recv bytes (exercises short reads)
        self.recv_calls = 0

    def _grab(self, n):
        self.recv_calls += 1
        if self._chunk is not None:
            n = min(n, self._chunk)
        take = self._data[self._ofs:self._ofs + n]
        self._ofs += len(take)
        return take

    def recv(self, n):
        return bytes(self._grab(n))

    def recv_into(self, view, n):
        take = self._grab(min(n, len(view)))
        view[:len(take)] = take
        return len(take)


def _sample_frames():
    """A mixed pipelined burst: pickle CALL with tensor planes, binary
    CALL, tagged binary RESULT with planes, pickle BUSY."""
    q = np.arange(24, dtype=np.float32).reshape(3, 8)
    frames = []
    frames.append(rpc.pack_frame(
        rpc.KIND_CALL, ("add_index_data", ("idx", q, [("m", i) for i in
                                                      range(3)]), {})))
    frames.append(rpc.pack_binary_call(
        "search", ("idx", q, 5, False), {}, {"req_id": 7, "wire": 1}))
    scores = np.linspace(0.0, 1.0, 15, dtype=np.float32).reshape(3, 5)
    labels = [[(i, j) for j in range(5)] for i in range(3)]
    frames.append(rpc.pack_binary_response(
        rpc.KIND_RESULT, (scores, labels, None), req_id=7))
    frames.append(rpc.pack_frame(rpc.KIND_BUSY, {"reason": "queue_full",
                                                 "queue_depth": 9,
                                                 "max_queue": 9}))
    return frames


def _deep_equal(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.dtype == b.dtype and a.shape == b.shape
                and bool((a == b).all()))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_deep_equal(x, y) for x, y in zip(a, b)))
    if isinstance(a, dict) and isinstance(b, dict):
        return (set(a) == set(b)
                and all(_deep_equal(a[k], b[k]) for k in a))
    return a == b


def test_buffered_reader_byte_identity_vs_unbuffered():
    """The buffered FrameReader decodes a pipelined burst to EXACTLY what
    the unbuffered one-shot reader produces frame by frame — kinds,
    binary flags, payload structure, and tensor bytes."""
    frames = _sample_frames()
    blob = b"".join(bytes(p) for f in frames for p in f)

    buffered = rpc.FrameReader(_ScriptedSock(blob))
    got_buf = [buffered.recv_frame_ex() for _ in frames]

    unbuffered_sock = _ScriptedSock(blob)
    got_unbuf = [rpc.recv_frame_ex(unbuffered_sock) for _ in frames]

    assert len(got_buf) == len(got_unbuf) == len(frames)
    for (k1, p1, b1), (k2, p2, b2) in zip(got_buf, got_unbuf):
        assert k1 == k2 and b1 == b2
        assert _deep_equal(p1, p2)
    # and the exact-mode reader consumed the stream to the same offset
    # (no byte lost or double-read between the two implementations)
    assert unbuffered_sock._ofs == len(blob)


def test_buffered_reader_survives_short_reads():
    """recv returning tiny chunks (a trickling peer) never desyncs the
    buffered reader."""
    frames = _sample_frames()
    blob = b"".join(bytes(p) for f in frames for p in f)
    reader = rpc.FrameReader(_ScriptedSock(blob, chunk=7))
    ref_sock = _ScriptedSock(blob)
    for _ in frames:
        k1, p1, b1 = reader.recv_frame_ex()
        k2, p2, b2 = rpc.recv_frame_ex(ref_sock)
        assert k1 == k2 and b1 == b2 and _deep_equal(p1, p2)


def test_buffered_reader_cuts_recv_syscalls_and_reports_pending():
    """The point of the buffer: a burst that the kernel delivers in one
    recv costs ONE syscall for every header/skeleton/plane-header field
    (bulk plane data still recv_into's directly), where the unbuffered
    reader pays one per field. ``pending`` flags buffered follower
    frames so a selector loop serves them before blocking."""
    frames = _sample_frames()
    blob = b"".join(bytes(p) for f in frames for p in f)

    greedy_sock = _ScriptedSock(blob)
    reader = rpc.FrameReader(greedy_sock)
    reader.recv_frame_ex()
    assert reader.pending  # follower frames already buffered
    for _ in frames[1:]:
        reader.recv_frame_ex()
    assert not reader.pending

    unbuf_sock = _ScriptedSock(blob)
    for _ in frames:
        rpc.recv_frame_ex(unbuf_sock)

    # everything after the first recv is buffered: the greedy reader does
    # ONE recv for the whole burst (plane data was buffered too, since
    # the single recv grabbed the full blob)
    assert greedy_sock.recv_calls == 1
    # the unbuffered reader pays per header/skeleton/plane-header field
    assert unbuf_sock.recv_calls > 4 * len(frames)


def test_buffered_reader_eof_messages_match_unbuffered():
    frames = _sample_frames()
    blob = b"".join(bytes(p) for p in frames[0])
    # clean EOF before any byte
    with pytest.raises(EOFError, match="connection closed$"):
        rpc.FrameReader(_ScriptedSock(b"")).recv_frame_ex()
    # EOF mid-frame
    with pytest.raises(EOFError, match="mid-frame|mid-tensor"):
        rpc.FrameReader(_ScriptedSock(blob[:20])).recv_frame_ex()
