"""State lattice unit tests (model: reference tests/test_index_state.py:13-22)."""

import pytest

from distributed_faiss_tpu import IndexState


def agg(*states):
    return IndexState.get_aggregated_states(list(states))


def test_uniform():
    for s in IndexState:
        assert agg(s, s, s) == s


def test_training_dominates():
    assert agg(IndexState.TRAINED, IndexState.TRAINING) == IndexState.TRAINING
    assert agg(IndexState.NOT_TRAINED, IndexState.TRAINING, IndexState.ADD) == IndexState.TRAINING


def test_not_trained_next():
    assert agg(IndexState.TRAINED, IndexState.NOT_TRAINED) == IndexState.NOT_TRAINED
    assert agg(IndexState.ADD, IndexState.NOT_TRAINED) == IndexState.NOT_TRAINED


def test_add_then_trained():
    assert agg(IndexState.TRAINED, IndexState.ADD) == IndexState.ADD
    assert agg(IndexState.TRAINED, IndexState.TRAINED) == IndexState.TRAINED


def test_empty_raises():
    with pytest.raises(ValueError):
        IndexState.get_aggregated_states([])
