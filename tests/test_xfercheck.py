"""Runtime implicit-transfer witness tests (marker ``xfercheck``; the
subprocess tier re-run is additionally ``slow``).

Unit layer: the DFT_XFERCHECK=1 witness (utils/xfercheck.py) arms
``jax.transfer_guard("disallow")`` around guarded() sections — a numpy
operand at jit dispatch inside one raises ImplicitTransferError with
label + thread + scope in the message and is recorded for the conftest
check; explicit() re-allows a designed fetch/feed region; explicit-API
moves (device_put) are fine under the guard; nested sections record the
violation once; non-transfer exceptions pass through untouched;
DFT_XFERCHECK_SCOPE picks the guarded directions.

E2e layer: a subprocess pytest run over the doctored cases in
tests/fixtures/xfercheck/ proves the REAL wiring — the autouse conftest
fixture drains/checks around each test — fails a seeded implicit feed
whose in-thread raise was SWALLOWED, and passes the explicit twin.

Tier layer (``pytest -m xfercheck``, mirrored by the ci.yml
``xfercheck`` job): re-run the scheduler, mesh-serving, and wire suites
with DFT_XFERCHECK=1 + DFT_COMPILECHECK=1 — the dynamic complement of
the IR tier's static device-residency rule, exactly as racecheck is to
the shared-state checker.
"""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from distributed_faiss_tpu.utils import xfercheck

pytestmark = pytest.mark.xfercheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _double(x):
    return x * 2.0


@pytest.fixture
def witness(monkeypatch):
    """DFT_XFERCHECK=1 with the default (all) scope; recorded violations
    are drained on the way out so a deliberate implicit transfer here
    never leaks into another test's conftest check."""
    monkeypatch.setenv("DFT_XFERCHECK", "1")
    monkeypatch.delenv("DFT_XFERCHECK_SCOPE", raising=False)
    yield
    xfercheck.reset()


# ------------------------------------------------------------------ switch

def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DFT_XFERCHECK", raising=False)
    assert not xfercheck.enabled()
    # guarded() is a transparent no-op: the implicit feed sails through
    with xfercheck.guarded("off"):
        assert not xfercheck.armed()
        jax.jit(_double)(np.ones((4,), np.float32))
    assert xfercheck.drain() == []


def test_scope_default_and_validation(monkeypatch):
    monkeypatch.delenv("DFT_XFERCHECK_SCOPE", raising=False)
    assert xfercheck.scope() == "all"
    monkeypatch.setenv("DFT_XFERCHECK_SCOPE", "d2h")
    assert xfercheck.scope() == "d2h"
    monkeypatch.setenv("DFT_XFERCHECK_SCOPE", "h2d")
    assert xfercheck.scope() == "h2d"
    monkeypatch.setenv("DFT_XFERCHECK_SCOPE", "bogus")
    assert xfercheck.scope() == "all"


# ------------------------------------------------------------- the witness

def test_implicit_feed_raises_with_label_and_is_recorded(witness):
    fn = jax.jit(_double)
    with pytest.raises(xfercheck.ImplicitTransferError) as exc:
        with xfercheck.guarded("unit merge-window flush"):
            fn(np.ones((8, 4), np.float32))  # implicit h2d at dispatch
    msg = str(exc.value)
    assert "'unit merge-window flush'" in msg
    assert "MainThread" in msg
    assert "scope 'all'" in msg
    leaks = xfercheck.drain()
    assert len(leaks) == 1 and "unit merge-window flush" in leaks[0]


def test_device_operand_and_device_put_are_clean(witness):
    fn = jax.jit(_double)
    with xfercheck.guarded("unit clean launch"):
        assert xfercheck.armed()
        x = jax.device_put(np.ones((8, 4), np.float32))  # explicit: allowed
        fn(x)
    assert not xfercheck.armed()
    assert xfercheck.drain() == []


def test_explicit_scope_allows_a_designed_feed(witness):
    fn = jax.jit(_double)
    with xfercheck.guarded("unit flush"):
        with xfercheck.explicit("designed host feed"):
            out = fn(np.ones((8, 4), np.float32))  # re-allowed inside
            np.asarray(out)
    assert xfercheck.drain() == []


def test_explicit_is_a_noop_when_nothing_is_armed(monkeypatch):
    monkeypatch.delenv("DFT_XFERCHECK", raising=False)
    with xfercheck.explicit("cold path"):
        pass  # no guard armed: must not even import-touch jax config


def test_nested_guarded_records_exactly_once(witness):
    fn = jax.jit(_double)
    with pytest.raises(xfercheck.ImplicitTransferError):
        with xfercheck.guarded("outer scheduler flush"):
            with xfercheck.guarded("inner engine span"):
                fn(np.ones((8, 4), np.float32))
    leaks = xfercheck.drain()
    assert len(leaks) == 1  # the innermost section converts; outer re-raises
    assert "inner engine span" in leaks[0]
    assert not xfercheck.armed()


def test_non_transfer_exceptions_pass_through(witness):
    with pytest.raises(ValueError, match="unrelated"):
        with xfercheck.guarded("unit flush"):
            raise ValueError("unrelated serving failure")
    assert xfercheck.drain() == []


def test_swallowed_raise_still_fails_check(witness):
    fn = jax.jit(_double)

    def serve():
        try:
            with xfercheck.guarded("swallowing serve loop"):
                fn(np.ones((8, 4), np.float32))
        except xfercheck.ImplicitTransferError:
            pass  # the serving loop's broad except, in miniature

    t = threading.Thread(target=serve, name="swallower", daemon=True)
    t.start()
    t.join(30.0)
    assert not t.is_alive()
    with pytest.raises(xfercheck.ImplicitTransferError,
                       match="swallowing serve loop"):
        xfercheck.check()
    assert xfercheck.drain() == []  # check() drained


def test_d2h_scope_leaves_host_feeds_unguarded(witness, monkeypatch):
    """Scope plumbing: with only the device-to-host direction guarded,
    the implicit h2d feed is out of scope and must not raise."""
    monkeypatch.setenv("DFT_XFERCHECK_SCOPE", "d2h")
    fn = jax.jit(_double)
    with xfercheck.guarded("unit d2h-only flush"):
        fn(np.ones((8, 4), np.float32))
    assert xfercheck.drain() == []


# ----------------------------------------------------------------------- e2e

def _run_doctored(case: str):
    env = dict(os.environ, DFT_XFERCHECK="1", DFT_XFERCHECK_E2E="1",
               JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pytest",
         f"tests/fixtures/xfercheck/test_xfer_cases.py::{case}",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)


def test_e2e_conftest_fixture_fails_seeded_implicit_feed():
    proc = _run_doctored("test_seeded_implicit_feed_fails_via_the_fixture")
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "ImplicitTransferError" in proc.stdout
    assert "doctored merge-window flush" in proc.stdout


def test_e2e_explicit_twin_passes():
    proc = _run_doctored("test_explicit_twin_is_clean")
    assert proc.returncode == 0, (
        f"explicit twin failed under the witness:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}")


def test_e2e_cases_skip_without_driver_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DFT_XFERCHECK_E2E", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/fixtures/xfercheck/test_xfer_cases.py",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 skipped" in proc.stdout


# ------------------------------------------------------------------ the tier

@pytest.mark.slow
def test_serving_suites_under_witness():
    """The xfercheck-tier satellite (mirrors the lockdep/threadcheck/
    racecheck tiers): re-run the scheduler, mesh-serving, and wire fast
    suites with BOTH runtime witnesses armed — every implicit transfer
    on a hot path fails its test with provenance, and the compile tally
    backs the steady-state budget assertions."""
    env = dict(os.environ, DFT_XFERCHECK="1", DFT_COMPILECHECK="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_scheduler.py", "tests/test_scheduler_identity.py",
         "tests/test_mesh_serving.py", "tests/test_wire.py",
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert proc.returncode == 0, (
        f"xfercheck tier failed:\n{proc.stdout[-6000:]}\n"
        f"{proc.stderr[-2000:]}")
