"""Runtime compile-count witness tests (marker ``compilecheck``).

The DFT_COMPILECHECK=1 witness (utils/compilecheck.py) hooks jax's
lowering logger at DEBUG and tallies ``Compiling <name> with global
shapes`` records per entry. These tests pin the mechanics: install /
uninstall idempotence with logger-level restore, the tally actually
counting a fresh XLA compilation, cache hits counting nothing,
snapshot/new_since window semantics, and the jit(...) name
normalization the registry qualnames rely on. The serving-side budget
assertion itself (zero new compiles after warmup under the 8-client
mux storm) lives in tests/test_scheduler_identity.py.
"""

import logging

import numpy as np
import pytest

import jax

from distributed_faiss_tpu.utils import compilecheck

pytestmark = pytest.mark.compilecheck


@pytest.fixture
def tally():
    """A clean installed tally, restored afterwards even when the
    surrounding run (DFT_COMPILECHECK=1 tiers) already installed one."""
    installed_here = not compilecheck._installed
    compilecheck.install()
    compilecheck.reset()
    yield
    compilecheck.reset()
    if installed_here:
        compilecheck.uninstall()


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DFT_COMPILECHECK", raising=False)
    assert not compilecheck.enabled()


def test_install_is_idempotent_and_uninstall_restores_level():
    logger = logging.getLogger(compilecheck._LOGGER_NAME)
    if compilecheck._installed:  # an outer tier owns the hook: stand down
        pytest.skip("compilecheck already installed by the surrounding run")
    prev_level = logger.level
    prev_handlers = list(logger.handlers)
    compilecheck.install()
    compilecheck.install()  # second install must not double-hook
    assert len(compilecheck._installed) == 1
    assert logger.level == logging.DEBUG
    added = [h for h in logger.handlers if h not in prev_handlers]
    assert len(added) == 1
    compilecheck.uninstall()
    assert not compilecheck._installed
    assert logger.level == prev_level
    assert logger.handlers == prev_handlers
    compilecheck.uninstall()  # idempotent too


def test_normalize_strips_jit_wrapper():
    assert compilecheck._normalize("jit(_probe)") == "_probe"
    assert compilecheck._normalize("_probe") == "_probe"


def test_fresh_compile_is_tallied_and_cache_hits_are_not(tally):
    def _tally_probe(x):
        return x * 3.0 + 1.0

    fn = jax.jit(_tally_probe)
    fn(jax.device_put(np.ones((5, 7), np.float32)))  # fresh: compiles
    counts = compilecheck.counts()
    assert counts.get("_tally_probe", 0) >= 1
    before = counts["_tally_probe"]
    fn(jax.device_put(np.zeros((5, 7), np.float32)))  # cache hit
    assert compilecheck.counts()["_tally_probe"] == before


def test_snapshot_new_since_window_semantics(tally):
    def _window_probe(x):
        return x - 0.5

    fn = jax.jit(_window_probe)
    fn(jax.device_put(np.ones((3, 3), np.float32)))  # warmup compile
    snap = compilecheck.snapshot()
    fn(jax.device_put(np.full((3, 3), 2.0, np.float32)))  # same bucket
    assert compilecheck.new_since(snap) == {}
    fn(jax.device_put(np.ones((6, 3), np.float32)))  # new abstract shape
    assert compilecheck.new_since(snap) == {"_window_probe": 1}


def test_reset_clears_the_tally(tally):
    def _reset_probe(x):
        return x + 2.0

    jax.jit(_reset_probe)(jax.device_put(np.ones((2,), np.float32)))
    assert compilecheck.counts()
    compilecheck.reset()
    assert compilecheck.counts() == {}


def test_hostile_log_records_never_raise(tally):
    class _Hostile(logging.LogRecord):
        def getMessage(self):
            raise RuntimeError("malformed record")

    handler = compilecheck._installed[0][1]
    handler.emit(_Hostile("x", logging.DEBUG, "f", 1, "m", (), None))
    assert compilecheck.counts() == {}  # swallowed, nothing tallied
