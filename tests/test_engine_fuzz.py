"""Randomized op-sequence fuzz of the engine state machine.

Drives random interleavings of the public engine surface (add / train /
search / save / load / drop) and asserts the invariants the reference's
state machine promises (index.py:138-343): state only moves through the
lattice, search works iff TRAINED, ntotal-vs-metadata accounting stays
positional, and a save/load round-trip at any point reproduces state.
"""

import time

import numpy as np
import pytest

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.state import IndexState


def wait_trained(idx, timeout=60):
    deadline = time.time() + timeout
    while idx.get_state() not in (IndexState.TRAINED, IndexState.NOT_TRAINED):
        assert time.time() < deadline, f"stuck in {idx.get_state()}"
        time.sleep(0.02)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_engine_random_op_sequence(seed, tmp_path):
    rng = np.random.default_rng(seed)
    d = 8
    cfg = IndexCfg(index_builder_type="ivf_simple", dim=d, metric="l2",
                   train_num=150, centroids=4, nprobe=4)
    idx = Index(cfg)
    total = 0
    storage = str(tmp_path / f"fuzz{seed}")

    for step in range(30):
        op = rng.choice(["add", "search", "save_load", "state"])
        if op == "add":
            n = int(rng.integers(1, 80))
            x = rng.standard_normal((n, d)).astype(np.float32)
            idx.add_batch(x, list(range(total, total + n)))
            total += n
            if total >= cfg.train_num:
                # settle to TRAINED so later ops see a deterministic state
                deadline = time.time() + 60
                while idx.get_state() != IndexState.TRAINED:
                    assert time.time() < deadline
                    time.sleep(0.02)
            else:
                assert idx.get_state() == IndexState.NOT_TRAINED
        elif op == "search":
            q = rng.standard_normal((2, d)).astype(np.float32)
            if idx.get_state() == IndexState.TRAINED:
                scores, meta, _ = idx.search(q, 3)
                assert scores.shape == (2, 3) and len(meta) == 2
                # positional metadata: every non-None hit is a real id
                for row in meta:
                    for m in row:
                        assert m is None or 0 <= m < total
            else:
                with pytest.raises(RuntimeError):
                    idx.search(q, 3)
        elif op == "save_load" and idx.get_state() == IndexState.TRAINED:
            idx.cfg.index_storage_dir = storage
            idx.save()
            idx2 = Index.from_storage_dir(storage)
            wait_trained(idx2)
            assert idx2.get_state() == IndexState.TRAINED
            buf, nidx = idx.get_idx_data_num()
            buf2, nidx2 = idx2.get_idx_data_num()
            assert buf + nidx == buf2 + nidx2 == total
            q = rng.standard_normal((1, d)).astype(np.float32)
            s1, m1, _ = idx.search(q, 3)
            s2, m2, _ = idx2.search(q, 3)
            np.testing.assert_allclose(s1, s2, rtol=1e-5)
            assert m1 == m2
        else:
            st = idx.get_state()
            assert st in (IndexState.NOT_TRAINED, IndexState.TRAINING,
                          IndexState.ADD, IndexState.TRAINED)
            if total >= cfg.train_num:
                # the async train thread may not have flipped the state yet
                # (NOT_TRAINED -> TRAINING is itself asynchronous), so poll
                # to TRAINED rather than treating NOT_TRAINED as terminal
                deadline = time.time() + 60
                while idx.get_state() != IndexState.TRAINED:
                    assert time.time() < deadline, "threshold crossed but never trained"
                    time.sleep(0.02)
            buf, nidx = idx.get_idx_data_num()
            assert buf + nidx == total
