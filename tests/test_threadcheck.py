"""Runtime thread-leak witness tests (marker ``threadcheck``; the
subprocess tier re-runs are additionally ``slow``).

Unit layer: the DFT_THREADCHECK=1 witness (utils/threadcheck.py)
detects a leaked non-daemon thread and names its creation site, exempts
daemon threads, passes tracked-and-joined workers, grants a bounded
grace join to winding-down workers, and is a no-op when disabled.

E2e layer: a subprocess pytest run over the doctored cases in
tests/fixtures/threadcheck/ proves the REAL wiring — conftest installs
the wrapper at collection, the autouse fixture snapshots/checks around
each test — fails a leaking test and passes the daemon/joined ones.

Tier layer (``pytest -m threadcheck``, mirrored by the ci.yml
``threadcheck`` job): re-run the scheduler, replication, anti-entropy,
and mutation suites with the witness on — the dynamic complement of
graftlint's static thread-lifecycle checker, exactly as lockdep is to
the static lock-order checker.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from distributed_faiss_tpu.utils import threadcheck

pytestmark = pytest.mark.threadcheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def witness(monkeypatch):
    """DFT_THREADCHECK=1 with the start-wrapper installed; restores the
    unwrapped Thread.start afterwards unless an outer tier (the
    threadcheck CI job runs this file with the env set globally) already
    owned the installation."""
    monkeypatch.setenv("DFT_THREADCHECK", "1")
    owned = threadcheck._ORIG_START is None
    threadcheck.install()
    yield
    if owned:
        threadcheck.uninstall()


# ------------------------------------------------------------------ switch

def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DFT_THREADCHECK", raising=False)
    assert not threadcheck.enabled()


def test_enabled_reads_env(witness):
    assert threadcheck.enabled()


def test_install_is_idempotent_and_uninstall_restores():
    was_installed = threadcheck._ORIG_START is not None
    threadcheck.uninstall()  # clean slate even under the global tier
    orig = threading.Thread.start
    try:
        threadcheck.install()
        wrapped = threading.Thread.start
        assert wrapped is not orig
        threadcheck.install()  # second install must not double-wrap
        assert threading.Thread.start is wrapped
        threadcheck.uninstall()
        assert threading.Thread.start is orig
        threadcheck.uninstall()  # idempotent too
        assert threading.Thread.start is orig
    finally:
        if was_installed:
            threadcheck.install()


# --------------------------------------------------------------- leak check

def test_leak_detected_with_name_and_site(witness):
    """A non-daemon thread created after the snapshot that outlives the
    grace join raises ThreadLeakError naming the thread AND the
    file:line that started it."""
    before = threadcheck.snapshot()
    hold = threading.Event()
    t = threading.Thread(target=hold.wait, name="leaky-worker",
                         daemon=False)
    t.start()
    with pytest.raises(threadcheck.ThreadLeakError) as exc:
        threadcheck.check(before, grace_s=0.2)
    msg = str(exc.value)
    assert "leaky-worker" in msg
    assert "test_threadcheck.py:" in msg
    hold.set()
    t.join(5.0)
    assert not t.is_alive()


def test_daemon_threads_are_exempt(witness):
    before = threadcheck.snapshot()
    hold = threading.Event()
    t = threading.Thread(target=hold.wait, name="daemon-worker",
                         daemon=True)
    t.start()
    threadcheck.check(before, grace_s=0.2)  # must not raise
    hold.set()
    t.join(5.0)


def test_tracked_and_joined_is_clean(witness):
    before = threadcheck.snapshot()
    done = threading.Event()
    t = threading.Thread(target=done.set, name="joined-worker",
                         daemon=False)
    t.start()
    assert done.wait(5.0)
    t.join(5.0)
    threadcheck.check(before)  # must not raise
    assert threadcheck.leaked(before, grace_s=0.0) == []


def test_grace_join_absorbs_winding_down_worker(witness):
    """A non-daemon worker that finishes within the grace window is not
    a leak: stop()-then-return teardown patterns must not flake."""
    before = threadcheck.snapshot()
    t = threading.Thread(target=time.sleep, args=(0.3,),
                         name="winding-down", daemon=False)
    t.start()
    threadcheck.check(before, grace_s=5.0)  # joins it inside the grace
    assert not t.is_alive()


def test_preexisting_threads_are_exempt(witness):
    """Threads already alive at snapshot time (session-scoped fixtures,
    executors owned by a broader scope) are never this scope's leak."""
    hold = threading.Event()
    t = threading.Thread(target=hold.wait, name="outer-scope",
                         daemon=False)
    t.start()
    before = threadcheck.snapshot()
    assert threadcheck.leaked(before, grace_s=0.1) == []
    hold.set()
    t.join(5.0)


def test_grace_default_comes_from_env(witness, monkeypatch):
    monkeypatch.setenv("DFT_THREADCHECK_GRACE_S", "0.25")
    assert threadcheck._default_grace() == 0.25
    before = threadcheck.snapshot()
    hold = threading.Event()
    t = threading.Thread(target=hold.wait, name="env-grace",
                         daemon=False)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(threadcheck.ThreadLeakError):
        threadcheck.check(before)  # grace resolved from the env knob
    assert time.monotonic() - t0 < 3.0
    hold.set()
    t.join(5.0)


# ---------------------------------------------------------------- provenance

def test_provenance_recorded_for_wrapped_start(witness):
    t = threading.Thread(target=lambda: None, name="prov", daemon=True)
    t.start()
    t.join(5.0)
    assert threadcheck.provenance(t).startswith("test_threadcheck.py:")


def test_unwitnessed_start_has_placeholder_provenance():
    was_installed = threadcheck._ORIG_START is not None
    threadcheck.uninstall()
    try:
        t = threading.Thread(target=lambda: None, name="bare", daemon=True)
        t.start()
        t.join(5.0)
        assert threadcheck.provenance(t) == "<unwitnessed start>"
    finally:
        if was_installed:  # the threadcheck tier installs globally at
            threadcheck.install()  # collection: leave it as we found it


# ----------------------------------------------------------------------- e2e

def _run_doctored(case: str):
    """Run one doctored case under the real conftest wiring with a short
    grace so the leak case fails fast."""
    env = dict(os.environ, DFT_THREADCHECK="1", DFT_THREADCHECK_E2E="1",
               DFT_THREADCHECK_GRACE_S="0.5", JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "pytest",
         f"tests/fixtures/threadcheck/test_leak_cases.py::{case}",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)


def test_e2e_conftest_fixture_fails_leaking_test():
    proc = _run_doctored("test_leaks_a_nondaemon_thread")
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "ThreadLeakError" in proc.stdout
    assert "doctored-leak" in proc.stdout
    assert "test_leak_cases.py:" in proc.stdout  # creation provenance


def test_e2e_daemon_and_joined_cases_pass():
    for case in ("test_daemon_thread_is_exempt",
                 "test_tracked_and_joined_is_clean"):
        proc = _run_doctored(case)
        assert proc.returncode == 0, (
            f"{case} failed under the witness:\n"
            f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}")


def test_e2e_cases_skip_without_driver_env(monkeypatch):
    """The doctored file must never run in normal tiers: without the
    driver env its tests skip (so a plain `pytest tests/` cannot trip
    over a deliberate leak)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DFT_THREADCHECK_E2E", None)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/fixtures/threadcheck/test_leak_cases.py",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "3 skipped" in proc.stdout


# ------------------------------------------------------------------ the tier

@pytest.mark.slow
def test_threaded_suites_under_witness():
    """The threadcheck-tier satellite (mirrors the lockdep tier): re-run
    the scheduler, replication, anti-entropy, mutation, and versions
    fast suites with DFT_THREADCHECK=1 — every test that starts a
    non-daemon thread and does not join it fails with the thread's
    creation site."""
    env = dict(os.environ, DFT_THREADCHECK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_scheduler.py", "tests/test_scheduler_identity.py",
         "tests/test_replication.py", "tests/test_mutation.py",
         "tests/test_antientropy.py", "tests/test_versions.py",
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert proc.returncode == 0, (
        f"threadcheck tier failed:\n{proc.stdout[-6000:]}\n"
        f"{proc.stderr[-2000:]}")
