"""Pallas flat-scan kernel golden tests (interpreter mode on CPU — same
kernel code path the TPU runs compiled) plus the guard/demotion ladder the
IVF-Flat models wrap it in."""

import numpy as np
import pytest

from distributed_faiss_tpu.ops import flat_pallas


@pytest.fixture
def problem(rng):
    nq, d, nlist, cap, g = 5, 24, 12, 128, 3
    q = rng.standard_normal((nq, d)).astype(np.float32)
    data = rng.standard_normal((nlist, cap, d)).astype(np.float16)
    ids = rng.integers(-1, 60, (nlist, cap)).astype(np.int32)
    sizes = rng.integers(0, cap + 1, (nlist,)).astype(np.int32)
    li = rng.integers(0, nlist, (nq, g)).astype(np.int32)
    return q, data, ids, sizes, li


def np_reference(q, data, ids, sizes, li, metric, norms=None):
    block = data[li].astype(np.float32)  # (nq, g, cap, d)
    ip = np.einsum("qd,qgcd->qgc", q, block)
    if metric == "dot":
        s = ip
    else:
        qn = np.sum(q * q, axis=1)[:, None, None]
        bn = norms[li] if norms is not None else np.sum(block * block, axis=3)
        s = -(qn - 2.0 * ip + bn)
    cap = data.shape[1]
    valid = (np.arange(cap)[None, None, :] < sizes[li][:, :, None]) & (ids[li] >= 0)
    return np.where(valid, s, -np.inf)


def run_kernel(q, data, ids, sizes, li, metric, norms=None, codec="f16",
               vmin=None, span=None, scan_bf16=False, tile=64):
    import jax.numpy as jnp

    return np.asarray(flat_pallas.flat_list_scan_pallas(
        jnp.asarray(q), jnp.asarray(data), jnp.asarray(ids),
        jnp.asarray(li), jnp.asarray(sizes[li]),
        None if norms is None else jnp.asarray(norms),
        None if vmin is None else jnp.asarray(vmin),
        None if span is None else jnp.asarray(span),
        metric=metric, codec=codec, scan_bf16=scan_bf16, tile=tile,
        interpret=True))


@pytest.mark.parametrize("metric", ["l2", "dot"])
def test_kernel_golden_recompute(problem, metric):
    q, data, ids, sizes, li = problem
    got = run_kernel(q, data, ids, sizes, li, metric)
    want = np_reference(q, data, ids, sizes, li, metric)
    assert np.array_equal(np.isinf(got), np.isinf(want))
    f = np.isfinite(want)
    np.testing.assert_allclose(got[f], want[f], rtol=1e-4, atol=1e-4)


def test_kernel_golden_stored_norms(problem):
    q, data, ids, sizes, li = problem
    norms = np.sum(data.astype(np.float32) ** 2, axis=2)
    got = run_kernel(q, data, ids, sizes, li, "l2", norms=norms)
    want = np_reference(q, data, ids, sizes, li, "l2", norms=norms)
    f = np.isfinite(want)
    assert np.array_equal(np.isinf(got), np.isinf(want))
    np.testing.assert_allclose(got[f], want[f], rtol=1e-4, atol=1e-4)


def test_kernel_sq8_dequant(rng):
    nq, d, nlist, cap, g = 3, 16, 8, 64, 2
    q = rng.standard_normal((nq, d)).astype(np.float32)
    codes = rng.integers(0, 256, (nlist, cap, d)).astype(np.uint8)
    vmin = rng.standard_normal(d).astype(np.float32)
    span = np.abs(rng.standard_normal(d)).astype(np.float32) + 0.5
    ids = rng.integers(0, 60, (nlist, cap)).astype(np.int32)
    sizes = np.full(nlist, cap, np.int32)
    li = rng.integers(0, nlist, (nq, g)).astype(np.int32)
    deq = vmin + codes.astype(np.float32) * (span / 255.0)
    got = run_kernel(q, codes, ids, sizes, li, "l2", codec="sq8",
                     vmin=vmin, span=span)
    want = np_reference(q, deq, ids, sizes, li, "l2")
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_kernel_cap_not_tile_multiple_small_cap(rng):
    """cap smaller than the default tile: the tile clamps to cap."""
    nq, d, nlist, cap = 2, 8, 4, 16
    q = rng.standard_normal((nq, d)).astype(np.float32)
    data = rng.standard_normal((nlist, cap, d)).astype(np.float32)
    ids = rng.integers(0, 9, (nlist, cap)).astype(np.int32)
    sizes = np.full(nlist, cap, np.int32)
    li = rng.integers(0, nlist, (nq, 1)).astype(np.int32)
    got = run_kernel(q, data, ids, sizes, li, "dot", codec="f32", tile=1024)
    want = np_reference(q, data, ids, sizes, li, "dot")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_kernel_bf16_close(problem):
    """bf16 scan (the refine-gated fast mode): error bounded by bf16
    rounding of the operands, inf mask identical."""
    q, data, ids, sizes, li = problem
    norms = np.sum(data.astype(np.float32) ** 2, axis=2)
    got = run_kernel(q, data, ids, sizes, li, "l2", norms=norms, scan_bf16=True)
    want = np_reference(q, data, ids, sizes, li, "l2", norms=norms)
    assert np.array_equal(np.isinf(got), np.isinf(want))
    f = np.isfinite(want)
    np.testing.assert_allclose(got[f], want[f], rtol=5e-2, atol=5e-1)


def test_index_pallas_matches_xla(rng):
    """End-to-end IVFFlatIndex: pallas scan returns the XLA path's results
    (the first-use oracle check runs and passes)."""
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n, d = 2000, 24
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((15, d)).astype(np.float32)
    ref = IVFFlatIndex(d, 8, "l2", codec="f16", kmeans_iters=3)
    ref.train(x[:800]); ref.add(x); ref.set_nprobe(4)
    Dx, Ix = ref.search(q, 7)

    idx = IVFFlatIndex(d, 8, "l2", codec="f16", kmeans_iters=3, use_pallas=True)
    idx.centroids = ref.centroids
    idx.lists = idx._make_lists()
    assign = idx._assign_host(x)
    rows = idx._encode(x, assign)
    gids = np.arange(n, dtype=np.int64)
    pos = idx.lists.append(assign, rows, gids)
    idx._append_extra(x, assign, gids, rows)
    idx._host_assign = [assign.astype(np.int32)]
    idx._host_pos = [pos]
    idx._n = n
    idx.set_nprobe(4)
    Dp, Ip = idx.search(q, 7)
    assert idx._pallas_flat_validated and idx._pallas_runtime_ok
    np.testing.assert_array_equal(Ip, Ix)
    np.testing.assert_allclose(Dp, Dx, rtol=1e-4, atol=1e-4)


def test_flat_kernel_failure_demotes_to_xla(rng, monkeypatch):
    """An injected flat-kernel fault after validation falls back to the XLA
    path via pallas_guarded (m=ksub=0 rung: no nibble machinery involved)
    and serves the request from the oracle result."""
    from distributed_faiss_tpu.models import ivf as ivfmod
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n, d = 1200, 16
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    idx = IVFFlatIndex(d, 8, "l2", codec="f16", kmeans_iters=3, use_pallas=True)
    idx.train(x[:600]); idx.add(x); idx.set_nprobe(4)
    want_d, want_i = idx.search(q, 5)  # validates + serves via pallas
    assert idx._pallas_runtime_ok

    def boom(*a, **k):
        raise RuntimeError("flat kernel abort (injected)")

    ivfmod._ivf_flat_search.clear_cache()
    monkeypatch.setattr(flat_pallas, "flat_list_scan_auto", boom)
    got_d, got_i = idx.search(q, 5)
    assert idx._pallas_runtime_ok is False, "flat kernel fault not demoted"
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)
    # nibble state untouched by the flat rung
    from distributed_faiss_tpu.ops import adc_pallas
    assert adc_pallas.USE_NIBBLE in (True, False)  # no sweep crash


def test_first_use_oracle_mismatch_demotes(rng, monkeypatch):
    """A kernel that runs but returns wrong numbers is caught by the
    first-use oracle check — never served to a caller."""
    from distributed_faiss_tpu.models import ivf as ivfmod
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n, d = 1200, 16
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    idx = IVFFlatIndex(d, 8, "l2", codec="f16", kmeans_iters=3, use_pallas=True)
    idx.train(x[:600]); idx.add(x); idx.set_nprobe(4)
    ref = IVFFlatIndex(d, 8, "l2", codec="f16", kmeans_iters=3)
    ref.centroids, ref.lists, ref.norm_lists = idx.centroids, idx.lists, idx.norm_lists
    ref._host_assign, ref._host_pos, ref._n = idx._host_assign, idx._host_pos, idx._n
    ref.set_nprobe(4)
    want_d, want_i = ref.search(q, 5)

    orig = flat_pallas.flat_list_scan_auto

    def skewed(*a, **k):
        return orig(*a, **k) + 1.0  # uniformly wrong scores

    ivfmod._ivf_flat_search.clear_cache()
    monkeypatch.setattr(flat_pallas, "flat_list_scan_auto", skewed)
    got_d, got_i = idx.search(q, 5)
    assert idx._pallas_flat_validated
    assert idx._pallas_runtime_ok is False, "wrong-numbers kernel survived validation"
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)
