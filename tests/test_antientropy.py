"""Fast unit tier for server-side anti-entropy (ISSUE 10): replica
digests (order-independence, caching, delete sensitivity), the health
table / failure detector, compaction-lease leader math, suspect pre-skip
in the read plan, repair-queue overflow (drop warning + degraded flag +
sweep coverage), the opt-in periodic repair driver, and loopback
sweep-heal end-to-end (delta pull, full sync, delete reconciliation) —
plus the ChaosProxy drop-kind fault pinning that a suspect-marked peer
still serves direct reads. The live-cluster repair-queue-overflow
convergence gate is in tests/test_antientropy_chaos.py."""

import os
import random
import socket
import threading
import time
from collections import deque
from multiprocessing.dummy import Pool as ThreadPool

import numpy as np
import pytest

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.mutation.tombstones import TombstoneSet, id_match_key
from distributed_faiss_tpu.parallel import antientropy, replication, rpc
from distributed_faiss_tpu.parallel.antientropy import (
    AntiEntropySweeper,
    HealthTable,
    digests_match,
    read_peers,
)
from distributed_faiss_tpu.parallel.client import REROUTE_LOG_LEN, IndexClient
from distributed_faiss_tpu.parallel.replication import (
    MembershipTable,
    RepairQueue,
    assign_groups,
    plan_read_fanout,
)
from distributed_faiss_tpu.parallel.server import IndexServer
from distributed_faiss_tpu.testing.chaos import ChaosProxy, Fault
from distributed_faiss_tpu.utils.config import (
    AntiEntropyCfg,
    IndexCfg,
    ReplicationCfg,
)
from distributed_faiss_tpu.utils.state import IndexState
from distributed_faiss_tpu.utils import lockdep, racecheck
from distributed_faiss_tpu.utils.atomics import AtomicCounters

pytestmark = pytest.mark.antientropy

DIM = 8


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", DIM)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 10)
    return IndexCfg(**kw)


def wait_for(cond, timeout=30.0, msg="condition never held"):
    deadline = time.time() + timeout
    while not cond():
        assert time.time() < deadline, msg
        time.sleep(0.02)


def drained(engine):
    return engine.get_idx_data_num()[0] == 0


# ---------------------------------------------------------------- config


def test_antientropy_cfg_env_and_validation():
    cfg = AntiEntropyCfg.from_env({"DFT_ANTIENTROPY": "0",
                                   "DFT_ANTIENTROPY_INTERVAL": "7.5",
                                   "DFT_SUSPECT_AFTER": "5",
                                   "DFT_COMPACT_LEASE_TTL": "30",
                                   "DFT_ANTIENTROPY_DELTA_MAX": "99"})
    assert cfg.enabled is False and cfg.interval_s == 7.5
    assert cfg.suspect_after == 5 and cfg.lease_ttl_s == 30.0
    assert cfg.delta_max_rows == 99
    assert AntiEntropyCfg().enabled is True  # default on
    with pytest.raises(ValueError):
        AntiEntropyCfg(interval_s=0)
    with pytest.raises(ValueError):
        AntiEntropyCfg(suspect_after=0)
    with pytest.raises(ValueError):
        AntiEntropyCfg(lease_ttl_s=0)
    with pytest.raises(TypeError):
        AntiEntropyCfg(bogus=1)
    with pytest.raises(ValueError):
        ReplicationCfg(repair_interval_s=-1)


def test_read_peers_parses_and_dedupes(tmp_path):
    p = tmp_path / "disc.txt"
    assert read_peers(str(p)) == []  # missing file degrades to no peers
    p.write_text("3\nhosta,1000\n\nhostb,2000\nhosta,1000\ngarbage\n")
    assert read_peers(str(p)) == [("hosta", 1000), ("hostb", 2000)]


# ---------------------------------------------------------------- digests


def make_engine(tmp_path=None, name="e"):
    cfg = flat_cfg()
    if tmp_path is not None:
        cfg.index_storage_dir = str(tmp_path / name)
    return Index(cfg)


def test_replica_digest_is_insertion_order_independent():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((20, DIM)).astype(np.float32)
    a, b = make_engine(), make_engine()
    a.add_batch(x, [(i,) for i in range(20)], train_async_if_triggered=False)
    order = list(reversed(range(20)))
    b.add_batch(x[order], [(i,) for i in order], train_async_if_triggered=False)
    wait_for(lambda: drained(a) and drained(b))
    da, db = a.replica_digest(), b.replica_digest()
    assert digests_match(da, db) and da == db
    assert da["live_n"] == 20 and da["dead_n"] == 0


def test_replica_digest_caches_until_mutation():
    a = make_engine()
    a.add_batch(np.zeros((12, DIM), np.float32),
                [(i,) for i in range(12)], train_async_if_triggered=False)
    wait_for(lambda: drained(a))
    d1 = a.replica_digest()
    with a.buffer_lock, a.index_lock:
        assert a._digest_cache is not None  # cached
    assert a.replica_digest() == d1
    a.remove_ids([3])
    d2 = a.replica_digest()
    assert not digests_match(d1, d2)
    assert d2["live_n"] == 11 and d2["dead_n"] == 1
    # an add moves the digest too (buffered rows count immediately)
    a.add_batch(np.ones((1, DIM), np.float32), [(99,)],
                train_async_if_triggered=False)
    assert a.replica_digest()["live_n"] == 12


def test_digest_dead_side_is_informational_not_compared():
    # converged live sets with different ledgers must still MATCH —
    # ledgers legitimately differ (a delete for a never-held id records
    # nothing), so comparing them would mismatch forever
    a = {"live_n": 3, "live_hash": "aa", "dead_n": 0, "dead_hash": "00"}
    b = {"live_n": 3, "live_hash": "aa", "dead_n": 2, "dead_hash": "ff"}
    assert digests_match(a, b)
    assert not digests_match(a, {**a, "live_hash": "bb"})
    assert not digests_match(a, None)


def test_ledger_survives_compaction_and_readds_unledger(tmp_path):
    eng = make_engine(tmp_path, "led")
    rng = np.random.default_rng(1)
    x = rng.standard_normal((20, DIM)).astype(np.float32)
    eng.add_batch(x, [(i,) for i in range(20)], train_async_if_triggered=False)
    wait_for(lambda: drained(eng))
    eng.remove_ids([2, 3])
    with racecheck.peeking():  # white-box peek, reviewed
        assert eng.tombstones.ledger() == {2, 3}
    assert eng.compact()
    # rows reclaimed, ledger intact
    with racecheck.peeking():  # white-box peek, reviewed
        assert len(eng.tombstones) == 0
    with racecheck.peeking():  # white-box peek, reviewed
        assert eng.tombstones.ledger() == {2, 3}
    # a legal re-add (upsert) removes its ledger entry
    eng.add_batch(x[2:3], [(2,)], train_async_if_triggered=False)
    with racecheck.peeking():  # white-box peek, reviewed
        assert eng.tombstones.ledger() == {3}


def test_tombstone_payload_roundtrips_ledger():
    t = TombstoneSet()
    t.add([5], [(5,)])
    t.ledger_update([("x", 1)])
    p = t.to_payload()
    back = TombstoneSet.from_payload(p)
    assert back.ledger() == t.ledger()
    # pre-ledger payloads seed the ledger from dead_ids
    legacy = {"format": 1, "layout": 0, "dead_rows": [1], "dead_ids": [(7,)]}
    assert TombstoneSet.from_payload(legacy).ledger() == {id_match_key((7,))}


def test_reconcile_deletes_applies_and_records(tmp_path):
    eng = make_engine(tmp_path, "rec")
    x = np.random.default_rng(2).standard_normal((10, DIM)).astype(np.float32)
    eng.add_batch(x, [(i,) for i in range(10)], train_async_if_triggered=False)
    wait_for(lambda: drained(eng))
    removed = eng.reconcile_deletes([4, 77])
    assert removed == 1  # 77 never lived here
    sets = eng.id_sets()
    assert 4 not in set(sets["live"])
    # BOTH keys recorded (pull guard), durable in the sidecar
    assert set(sets["dead"]) >= {4, 77}
    side_path = os.path.join(eng.cfg.index_storage_dir, "tombstones.json")
    assert os.path.exists(side_path)


def test_export_rows_returns_live_rows_only():
    eng = make_engine()
    x = np.random.default_rng(3).standard_normal((10, DIM)).astype(np.float32)
    eng.add_batch(x, [(i,) for i in range(10)], train_async_if_triggered=False)
    wait_for(lambda: drained(eng))
    eng.remove_ids([1])
    emb, meta = eng.export_rows([0, 1, 5, 42])
    assert [m[0] for m in meta] == [0, 5]  # dead + absent ids skipped
    np.testing.assert_allclose(emb, x[[0, 5]], rtol=1e-6)
    # buffered rows export verbatim too
    eng.add_batch(x[:2] + 10.0, [(100,), (101,)],
                  train_async_if_triggered=False)
    emb2, meta2 = eng.export_rows([101])
    assert meta2 == [(101,)]
    np.testing.assert_allclose(emb2[0], x[1] + 10.0, rtol=1e-6)


# ------------------------------------------------------- health / suspects


def test_health_table_suspect_and_recovery():
    h = HealthTable()
    addr = ("hosta", 1234)
    boom = ConnectionRefusedError("down")
    assert h.note_fail(addr, 3, boom) is False
    assert h.note_fail(addr, 3, boom) is False
    assert h.note_fail(addr, 3, boom) is True  # crossed the threshold
    assert h.note_fail(addr, 3, boom) is False  # already suspect
    assert [s["host"] for s in h.suspects()] == ["hosta"]
    h.note_ok(addr, rank=1, group=0)  # one good round trip clears it
    assert h.suspects() == []
    assert h.known_group(*addr) == (True, 0)
    assert h.known_group("other", 1) == (False, None)


def test_health_alive_ranks_uses_both_directions_and_ttl():
    h = HealthTable()
    h.note_ok(("a", 1), rank=2, group=0)
    h.note_inbound(5, group=0)
    h.note_inbound(7, group=1)  # another group: not in this electorate
    assert h.alive_ranks(0, ttl_s=10.0) == {2, 5}
    assert h.alive_ranks(1, ttl_s=10.0) == {7}
    assert h.alive_ranks(0, ttl_s=0.0) == set()  # aged out


class _FakeServer:
    def __init__(self, rank, group):
        self.rank = rank
        self.shard_group = group
        self.socket = None
        self.indexes = {}
        self.indexes_lock = threading.Lock()


def test_compaction_lease_lowest_live_rank_leads(tmp_path):
    cfg = AntiEntropyCfg(interval_s=600, lease_ttl_s=10.0)
    sw = AntiEntropySweeper(_FakeServer(rank=2, group=0),
                            str(tmp_path / "d"), cfg)
    # alone in the group: self is the lowest live rank -> holds the token
    assert sw.may_compact() is True
    # a LOWER live rank appears -> token moves there
    sw.health.note_ok(("peer", 1), rank=0, group=0)
    assert sw.may_compact() is False
    # a lower rank in ANOTHER group is irrelevant
    sw2 = AntiEntropySweeper(_FakeServer(rank=2, group=1),
                             str(tmp_path / "d"), cfg)
    sw2.health.note_ok(("peer", 1), rank=0, group=0)
    assert sw2.may_compact() is True
    # unreplicated rank (no group): always holds its own token
    sw3 = AntiEntropySweeper(_FakeServer(rank=9, group=None),
                             str(tmp_path / "d"), cfg)
    assert sw3.may_compact() is True


def test_compaction_lease_expires_with_ttl(tmp_path):
    cfg = AntiEntropyCfg(interval_s=600, lease_ttl_s=0.2)
    sw = AntiEntropySweeper(_FakeServer(rank=3, group=0),
                            str(tmp_path / "d"), cfg)
    sw.health.note_ok(("peer", 1), rank=1, group=0)
    assert sw.may_compact() is False  # rank 1 leads while live
    time.sleep(0.3)
    assert sw.may_compact() is True  # leader silent past the TTL: take over


def test_plan_read_fanout_pre_skips_suspects_without_removing():
    t = MembershipTable([0, 1, 0, 1])
    plan = plan_read_fanout(t, {}, suspects={0})
    # group 0: suspect 0 rotated to the TAIL, still present
    assert plan[0] == (0, 2, [2, 0])
    assert plan[1] == (1, 1, [1, 3])
    # a suspect pinned replica is demoted too (re-pick a healthy lead)
    plan = plan_read_fanout(t, {0: 0}, suspects={0})
    assert plan[0] == (0, 2, [2, 0])
    # every replica suspect: ordering unchanged (suspicion never blacklists)
    plan = plan_read_fanout(t, {}, suspects={0, 2})
    assert plan[0] == (0, 0, [0, 2])


# -------------------------------------------- repair queue overflow (S1/S3)


def test_repair_queue_drop_warns_and_degrades(caplog):
    q = RepairQueue(maxlen=1)
    with caplog.at_level("WARNING"):
        q.record({"batch": 0})
        assert not any("repair queue full" in r.message for r in caplog.records)
        q.record({"batch": 1})  # first drop: WARNING fires
    assert q.stats()["dropped"] == 1
    warns = [r for r in caplog.records if "repair queue full" in r.message]
    assert len(warns) == 1
    # rate-limited: an immediate second drop stays quiet
    with caplog.at_level("WARNING"):
        caplog.clear()
        q.record({"batch": 2})
    assert not any("repair queue full" in r.message for r in caplog.records)
    assert q.stats()["dropped"] == 2


class FakeStub:
    """Quacks like rpc.Client for the fan-out paths under test."""

    def __init__(self, sid, score=0.0, always_fail=False, health=None):
        self.id = sid
        self.host = "fake"
        self.port = 9000 + sid
        self.score = float(score)
        self.always_fail = always_fail
        self.health = health
        self.acked = []

    def generic_fun(self, fname, args=(), kwargs=None, **_kw):
        if self.always_fail:
            raise ConnectionRefusedError(f"rank {self.id} down")
        self.acked.append((fname, args))
        if fname == "search":
            _iid, q, k, _emb = args
            d = self.score + np.arange(k, dtype=np.float32)
            return (np.tile(d, (q.shape[0], 1)),
                    [[(self.id, j) for j in range(k)] for _ in range(q.shape[0])],
                    None)
        if fname == "get_health":
            if self.health is None:
                raise rpc.ServerException("no health op")
            return self.health
        return f"ok-{self.id}"

    def close(self):
        pass


def make_client(stubs, rcfg=None, groups=None):
    c = object.__new__(IndexClient)
    c.sub_indexes = stubs
    c.num_indexes = len(stubs)
    c.pool = ThreadPool(max(len(stubs), 1))
    c.cur_server_ids = {}
    c._rng = random.Random(0)
    c.retry = rpc.RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0.0)
    c._stats_lock = lockdep.lock("IndexClient._stats_lock")
    c.reroutes = deque(maxlen=REROUTE_LOG_LEN)
    c.counters = AtomicCounters(
                  ("reroutes", "failovers", "under_replicated", "quorum_failures"))
    c.rcfg = rcfg or ReplicationCfg()
    eff = min(c.rcfg.replication, max(len(stubs), 1))
    c.quorum = replication.quorum_size(eff, min(c.rcfg.write_quorum, eff))
    c.repair_queue = replication.RepairQueue(c.rcfg.repair_queue_len)
    c._preferred = {}
    c._suspects = set()
    c.membership = MembershipTable(
        groups if groups is not None
        else assign_groups(len(stubs), c.rcfg.replication))
    c.cfg = IndexCfg(metric="l2", dim=DIM)
    return c


def test_repair_queue_overflow_survivors_still_repair_and_degraded_flag():
    """Records past maxlen bump ``dropped``; repair of the SURVIVORS still
    completes; get_replication_stats surfaces degraded=True. The dropped
    batches are exactly what the server-side sweep covers (loopback test
    below + the chaos gate)."""
    live = FakeStub(0)
    dead = FakeStub(1, always_fail=True)
    client = make_client(
        [live, dead],
        rcfg=ReplicationCfg(replication=2, write_quorum=1,
                            repair_queue_len=2))
    client.cur_server_ids["idx"] = 0
    for i in range(5):  # 5 under-replicated batches into a 2-slot queue
        client.add_index_data("idx", np.zeros((1, DIM), np.float32), [(i,)])
    stats = client.get_replication_stats()
    assert stats["repair"]["dropped"] == 3
    assert stats["degraded"] is True
    assert len(client.repair_queue) == 2
    dead.always_fail = False
    out = client.repair_under_replicated()
    assert out == {"repaired": 2, "still_pending": 0}
    # only the two surviving records could be replayed — the three
    # dropped batches are unreachable to client-driven repair by design
    assert len(dead.acked) == 2
    assert client.get_replication_stats()["degraded"] is True  # sticky


# ------------------------------------------------- periodic repair driver


def test_periodic_repair_driver_heals_without_explicit_calls():
    live = FakeStub(0)
    dead = FakeStub(1, always_fail=True,
                    health={"enabled": True, "suspects": []})
    client = make_client(
        [live, dead],
        rcfg=ReplicationCfg(replication=2, write_quorum=1,
                            repair_interval_s=0.05))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((2, DIM), np.float32),
                          [(0,), (1,)])
    assert len(client.repair_queue) == 1
    # start the driver the way __init__ does (fixture clients skip it)
    client._repair_stop = threading.Event()
    client._repair_thread = threading.Thread(
        target=client._repair_loop, name="repair-driver", daemon=True)
    client._repair_thread.start()
    dead.always_fail = False  # rank heals; the DRIVER must repair it
    wait_for(lambda: len(client.repair_queue) == 0, timeout=10,
             msg="driver never repaired the queued record")
    assert any(f == "add_index_data" for f, _ in dead.acked)
    assert client._repair_thread.name == "repair-driver"
    client._repair_stop.set()
    client._repair_thread.join(timeout=10)
    assert not client._repair_thread.is_alive()


def test_refresh_health_marks_suspects_and_search_pre_skips():
    """The server-side failure detector's suspect list reorders the read
    walk: the suspect replica is tried LAST (not removed)."""
    health = {"enabled": True, "suspects": [{"host": "fake", "port": 9000}]}
    a = FakeStub(0, score=1.0, health=health)
    b = FakeStub(1, score=1.0, health=health)
    client = make_client([a, b], rcfg=ReplicationCfg(replication=2))
    suspects = client.refresh_health()
    assert suspects == {0}
    assert client.get_replication_stats()["suspects"] == [0]
    client.search(np.zeros((1, DIM), np.float32), 3, "idx")
    # the suspect replica 0 served nothing; the healthy peer did
    assert not any(f == "search" for f, _ in a.acked)
    assert any(f == "search" for f, _ in b.acked)


def test_refresh_health_falls_past_sweeper_disabled_replica():
    """A replica whose sweeper is inert (no discovery file /
    DFT_ANTIENTROPY=0) answers get_health with the enabled=False stub:
    the client must ask the NEXT replica instead of settling for the
    stub's empty suspect view (regression: the walk used to break on the
    first replica that answered at all, so a disabled replica listed
    first permanently hid the group's real suspects)."""
    stub = {"enabled": False, "suspects": []}
    real = {"enabled": True, "suspects": [{"host": "fake", "port": 9000}]}
    a = FakeStub(0, health=stub)
    b = FakeStub(1, health=real)
    client = make_client([a, b], rcfg=ReplicationCfg(replication=2))
    assert client.refresh_health() == {0}
    assert any(f == "get_health" for f, _ in b.acked)


# ------------------------------------------------ loopback sweep end-to-end


def start_server(rank, port, storage, disc, group, cfg):
    os.environ["DFT_SHARD_GROUP"] = str(group)
    try:
        srv = IndexServer(rank, storage, discovery_path=disc,
                          antientropy_cfg=cfg)
    finally:
        del os.environ["DFT_SHARD_GROUP"]
    threading.Thread(target=srv.start_blocking, args=(port,),
                     daemon=True).start()
    deadline = time.time() + 30
    while srv.socket is None:
        assert time.time() < deadline
        time.sleep(0.02)
    return srv


def test_sweep_full_syncs_missing_index_then_delta_heals(tmp_path):
    """Loopback end-to-end: an empty replica's sweep streams the whole
    index from its peer (full-sync path, MANIFEST-committed), a diverged
    replica's sweep pulls the id-delta, deletes reconcile (never
    resurrect), and the lease lands on the lowest live rank."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)  # idle thread; tests drive sweeps
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        assert a._antientropy is not None and b._antientropy is not None
        a.create_index("t", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((40, DIM)).astype(np.float32)
        a.add_index_data("t", x, [(i,) for i in range(40)])
        wait_for(lambda: (a.get_state("t") == IndexState.TRAINED
                          and a.get_aggregated_ntotal("t") == 0))
        a.remove_ids("t", [5, 6])

        # --- B is EMPTY: its sweep must full-sync the index in
        out = b._antientropy.sweep_once()
        assert any(h.get("full_sync") for h in out["healed"])
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert digests_match(da, db) and da == db
        assert b._antientropy.stats()["full_syncs"] == 1

        # --- diverge again: rows + a delete land on A only
        a.add_index_data("t", x[:5] + 30.0, [(100 + i,) for i in range(5)])
        a.remove_ids("t", [7])
        out = b._antientropy.sweep_once()
        healed = [h for h in out["healed"] if h["index_id"] == "t"]
        assert healed and healed[0]["pulled"] == 5 and healed[0]["removed"] == 1
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert digests_match(da, db) and da == db
        # deleted ids never resurrected on either side
        for srv in (a, b):
            ids = srv.get_ids("t")
            assert (5,) not in ids and (6,) not in ids and (7,) not in ids
        # byte-identical serving
        sa, sb = a.search("t", x[:4], 3), b.search("t", x[:4], 3)
        np.testing.assert_array_equal(sa[0], sb[0])
        assert sa[1] == sb[1]

        # --- A's own sweep sees convergence, nothing to pull
        out = a._antientropy.sweep_once()
        assert out["healed"] == []
        stats = a._antientropy.stats()
        assert stats["digests_matched"] >= 1 and stats["suspect_peers"] == []

        # --- lease: exactly one holder per group (lowest live rank)
        assert a._antientropy.may_compact() is True
        assert b._antientropy.may_compact() is False
        assert a.get_health()["compaction"]["held"] is True
        assert b.get_health()["compaction"]["held"] is False
        # perf-stats surface
        perf = a.get_perf_stats()["antientropy"]
        assert perf["enabled"] and "rows_repaired" in perf
        # compaction gates installed on the engines
        assert a._get_index("t").compaction_gate is not None
    finally:
        a.stop()
        b.stop()


def test_sweep_never_resurrects_dropped_index(tmp_path):
    """drop_index leaves a drop tombstone: a sweep that sees a peer still
    serving the dropped index must NOT full-sync it back (regression: the
    marker existed but nothing ever wrote or consulted it, so on a
    sweeping cluster a dropped index came back within one interval from
    any in-group peer that missed the drop). An explicit resync clears
    the marker and the index heals back in."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)  # idle thread; tests drive sweeps
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        a.create_index("t", flat_cfg())
        rng = np.random.default_rng(5)
        x = rng.standard_normal((20, DIM)).astype(np.float32)
        a.add_index_data("t", x, [(i,) for i in range(20)])
        wait_for(lambda: (a.get_state("t") == IndexState.TRAINED
                          and a.get_aggregated_ntotal("t") == 0))
        b._antientropy.sweep_once()
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        assert "t" in b.indexes

        # drop on B; A still serves the index (missed-drop scenario)
        b.drop_index("t")
        out = b._antientropy.sweep_once()
        assert "t" not in b.indexes, "sweep resurrected a dropped index"
        assert not any(h["index_id"] == "t" for h in out["healed"])

        # an explicit resync clears the marker; healing resumes
        b.sync_shard_from("t", "localhost", pa)
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert digests_match(da, db)
        b._antientropy.sweep_once()
        assert "t" in b.indexes
    finally:
        a.stop()
        b.stop()


def test_sweep_learns_group_registered_after_first_exchange(tmp_path):
    """Group registration can postdate the first digest exchange
    (set_shard_group arrives with the first IndexClient): a peer whose
    group was cached as None while unregistered must keep being dialed —
    a stale cached None can never wedge a genuine group peer out of the
    sweep (regression: the skip branch used to stop dialing forever,
    silently disabling digests, healing, and the lease for the cluster's
    whole life whenever a client arrived after the first sweep)."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)  # idle thread; tests drive sweeps
    a = IndexServer(0, str(tmp_path / "a"), discovery_path=disc,
                    antientropy_cfg=cfg)
    b = IndexServer(1, str(tmp_path / "b"), discovery_path=disc,
                    antientropy_cfg=cfg)
    for srv, port in ((a, pa), (b, pb)):
        threading.Thread(target=srv.start_blocking, args=(port,),
                         daemon=True).start()
    wait_for(lambda: a.socket is not None and b.socket is not None)
    try:
        assert a.shard_group is None and b.shard_group is None
        # first exchanges happen UNREGISTERED: both sides cache the
        # peer's group as None (liveness-only contact)
        a._antientropy.sweep_once()
        b._antientropy.sweep_once()
        assert b._antientropy.health.known_group("localhost", pa) == (True,
                                                                      None)
        # groups register afterwards — what IndexClient._register_groups
        # does on its first construction
        a.set_shard_group(0)
        b.set_shard_group(0)
        # diverge A; B's next sweep must still dial A (a cached None is
        # not a concrete other group), learn group 0, and heal
        a.create_index("t", flat_cfg())
        rng = np.random.default_rng(3)
        x = rng.standard_normal((20, DIM)).astype(np.float32)
        a.add_index_data("t", x, [(i,) for i in range(20)])
        wait_for(lambda: (a.get_state("t") == IndexState.TRAINED
                          and a.get_aggregated_ntotal("t") == 0))
        out = b._antientropy.sweep_once()
        assert out["skipped"] == 0
        assert any(h.get("full_sync") for h in out["healed"])
        _k, g = b._antientropy.health.known_group("localhost", pa)
        assert g == 0
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert digests_match(da, db)
    finally:
        a.stop()
        b.stop()


def test_one_directional_divergence_stays_quiet(tmp_path, caplog):
    """The AHEAD side of a one-directional divergence (the peer is simply
    behind) has an empty pull delta but a non-empty local_only set — the
    normal transient the pull-only design expects (the peer's own sweep
    heals it), NOT invisible divergence: no empty_deltas bump, no
    operator warning (regression: the ahead replica warned 'divergence is
    invisible to id sets' once per rate-limit window during every
    ordinary heal)."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        rng = np.random.default_rng(6)
        x = rng.standard_normal((12, DIM)).astype(np.float32)
        for srv in (a, b):
            srv.create_index("t", flat_cfg())
            srv.add_index_data("t", x, [(i,) for i in range(12)])
            wait_for(lambda: (srv.get_state("t") == IndexState.TRAINED
                              and srv.get_aggregated_ntotal("t") == 0))
        # one NEW id on A only: A is ahead, B is behind
        y = rng.standard_normal((1, DIM)).astype(np.float32)
        a.add_index_data("t", y, [(100,)])
        wait_for(lambda: a.get_aggregated_ntotal("t") == 0)
        assert not digests_match(a._get_index("t").replica_digest(),
                                 b._get_index("t").replica_digest())
        with caplog.at_level("WARNING"):
            out = a._antientropy.sweep_once()
        healed = [h for h in out["healed"] if h["index_id"] == "t"]
        assert healed == [{"index_id": "t", "peer": ("localhost", pb),
                           "removed": 0, "pulled": 0, "refreshed": 0,
                           "full_sync": False}]
        assert a._antientropy.stats()["empty_deltas"] == 0
        assert not any("id-set delta is empty" in r.message
                       for r in caplog.records)
        # the behind side's own sweep heals the divergence
        b._antientropy.sweep_once()
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        wait_for(lambda: digests_match(a._get_index("t").replica_digest(),
                                       b._get_index("t").replica_digest()))
        assert b._antientropy.stats()["rows_repaired"] == 1
    finally:
        a.stop()
        b.stop()


def test_empty_delta_mismatch_counts_and_warns(tmp_path, caplog):
    """A digest mismatch whose id-set delta is empty (an id duplicated on
    one side by an at-least-once ingest retry) cannot be healed by the
    sweep — but it must be SURFACED: the empty_deltas counter moves and a
    rate-limited warning names the remedies (regression: the mismatch
    counter climbed silently forever with no heal and no log)."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        rng = np.random.default_rng(5)
        x = rng.standard_normal((12, DIM)).astype(np.float32)
        for srv in (a, b):
            srv.create_index("t", flat_cfg())
            srv.add_index_data("t", x, [(i,) for i in range(12)])
            wait_for(lambda: (srv.get_state("t") == IndexState.TRAINED
                              and srv.get_aggregated_ntotal("t") == 0))
        # duplicate ONE id on A only: live_n diverges, id SETS stay equal
        a.add_index_data("t", x[:1], [(0,)])
        wait_for(lambda: a.get_aggregated_ntotal("t") == 0)
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert not digests_match(da, db)
        with caplog.at_level("WARNING"):
            out = b._antientropy.sweep_once()
        healed = [h for h in out["healed"] if h["index_id"] == "t"]
        assert healed == [{"index_id": "t", "peer": ("localhost", pa),
                           "removed": 0, "pulled": 0, "refreshed": 0,
                           "full_sync": False}]
        assert b._antientropy.stats()["empty_deltas"] == 1
        assert any("id-set delta is empty" in r.message
                   for r in caplog.records)
        # rate limit: an immediate second sweep bumps the counter only
        caplog.clear()
        with caplog.at_level("WARNING"):
            b._antientropy.sweep_once()
        assert b._antientropy.stats()["empty_deltas"] == 2
        assert not any("id-set delta is empty" in r.message
                       for r in caplog.records)
    finally:
        a.stop()
        b.stop()


def test_sweep_detects_dead_peer_and_marks_suspect(tmp_path):
    pa = free_port()
    dead_port = free_port()  # nothing listens here
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{dead_port}\n")
    cfg = AntiEntropyCfg(interval_s=600, suspect_after=2,
                         exchange_timeout_s=0.5)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    try:
        a._antientropy.sweep_once()
        assert a.get_health()["suspects"] == []  # one failure: not yet
        a._antientropy.sweep_once()
        suspects = a.get_health()["suspects"]
        assert [s["port"] for s in suspects] == [dead_port]
        assert a.get_perf_stats()["antientropy"]["suspect_peers"]
    finally:
        a.stop()


def test_digest_frames_blackholed_marks_suspect_but_direct_reads_serve(
        tmp_path):
    """ChaosProxy drop-kind fault (S6): blackhole ONLY the KIND_DIGEST
    frames on the path A uses to reach B — A's failure detector marks B
    suspect, while B keeps serving reads both through the faulted proxy
    (query frames pass) and directly."""
    pa, pb = free_port(), free_port()
    proxy = ChaosProxy("localhost", pb).start()
    proxy.set_fault(Fault(Fault.DROP_KIND, direction="up",
                          drop_kinds={rpc.KIND_DIGEST}))
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        # A resolves B through the proxy; B runs sweeper-inert
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{proxy.port}\n")
    cfg = AntiEntropyCfg(interval_s=600, suspect_after=2,
                         exchange_timeout_s=0.5)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = IndexServer(1, str(tmp_path / "b"))
    b.set_shard_group(0)
    threading.Thread(target=b.start_blocking, args=(pb,), daemon=True).start()
    time.sleep(0.3)
    try:
        b.create_index("t", flat_cfg())
        x = np.random.default_rng(4).standard_normal((20, DIM)).astype(
            np.float32)
        b.add_index_data("t", x, [(i,) for i in range(20)])
        wait_for(lambda: (b.get_state("t") == IndexState.TRAINED
                          and b.get_aggregated_ntotal("t") == 0))
        # two sweeps, both digest exchanges blackholed -> suspect
        a._antientropy.sweep_once()
        a._antientropy.sweep_once()
        assert [s["port"] for s in a.get_health()["suspects"]] \
            == [proxy.port]
        # the SAME proxied link still serves query traffic (only digest
        # frames are dropped)...
        via_proxy = rpc.Client(7, "localhost", proxy.port, mux=False)
        scores, meta, _ = via_proxy.generic_fun(
            "search", ("t", x[:2], 3, False))
        assert scores.shape == (2, 3)
        via_proxy.close()
        # ...and the suspect-marked peer still serves DIRECT reads
        direct = rpc.Client(8, "localhost", pb, mux=False)
        scores, meta, _ = direct.generic_fun("search", ("t", x[:2], 3, False))
        assert scores.shape == (2, 3)
        direct.close()
    finally:
        proxy.stop()
        a.stop()
        b.stop()


def test_compaction_watcher_defers_without_lease(tmp_path):
    """The background watcher consults the lease gate; a rank that does
    not hold its group's token defers, and the explicit compact op still
    works (operator override)."""
    eng = make_engine(tmp_path, "gate")
    x = np.random.default_rng(5).standard_normal((20, DIM)).astype(np.float32)
    eng.add_batch(x, [(i,) for i in range(20)], train_async_if_triggered=False)
    wait_for(lambda: drained(eng))
    eng.remove_ids(list(range(10)))
    eng.compaction_gate = lambda: False
    from distributed_faiss_tpu.utils.config import MutationCfg
    from distributed_faiss_tpu.mutation import compaction

    # one watcher pass worth of logic: gate blocks the threshold trigger
    assert eng.tombstone_fraction() >= 0.25
    gate = eng.compaction_gate
    assert gate() is False  # the watcher's check (run_watcher consults it)
    assert eng._mutation_counters["compactions"] == 0
    # explicit operator compact bypasses the lease
    assert eng.compact()
    assert eng._mutation_counters["compactions"] == 1


# -------------------------------------------- content-hash verified pulls


def test_export_rows_versioned_hash_roundtrip(tmp_path):
    """with_hash=True appends a sha256 the receiver can recompute over
    the decoded payload; the bare call keeps the PR-12 3-tuple shape."""
    from distributed_faiss_tpu.utils import serialization

    eng = make_engine(tmp_path, "h")
    x = np.random.default_rng(0).standard_normal((12, DIM)).astype(np.float32)
    eng.add_batch(x, [(i,) for i in range(12)],
                  train_async_if_triggered=False)
    wait_for(lambda: drained(eng))
    bare = eng.export_rows_versioned(list(range(5)))
    assert len(bare) == 3 and len(bare[1]) == 5
    emb, meta, vers, digest = eng.export_rows_versioned(
        list(range(5)), with_hash=True)
    np.testing.assert_array_equal(emb, bare[0])
    assert serialization.row_payload_hash(emb, meta, vers) == digest
    # any payload change breaks the hash
    assert serialization.row_payload_hash(emb + 1.0, meta, vers) != digest
    assert serialization.row_payload_hash(emb, meta[:-1], vers) != digest
    # canonicalization: set-valued metadata hashes by CONTENT, not by
    # per-process repr order (str-hash randomization), and equal sets
    # built differently hash equal while different sets differ
    h1 = serialization.row_payload_hash(
        emb[:1], [({"a", "b", "c"},)], [None])
    h2 = serialization.row_payload_hash(
        emb[:1], [(set(["c", "b", "a"]),)], [None])
    h3 = serialization.row_payload_hash(
        emb[:1], [({"a", "b", "z"},)], [None])
    assert h1 == h2 and h1 != h3


def test_heal_rejects_corrupt_chunk_and_marks_peer(tmp_path):
    """A delta pull whose chunk fails content-hash verification is
    counted, retried once, NEVER applied, and surfaces as a transport
    failure feeding the failure detector; with the corruption gone the
    next sweep heals normally."""
    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        a.create_index("t", flat_cfg())
        rng = np.random.default_rng(1)
        x = rng.standard_normal((30, DIM)).astype(np.float32)
        a.add_index_data("t", x, [(i,) for i in range(30)])
        wait_for(lambda: (a.get_state("t") == IndexState.TRAINED
                          and a.get_aggregated_ntotal("t") == 0))
        b._antientropy.sweep_once()  # full-sync B in
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)

        # diverge: 6 fresh rows land on A only, and A's export corrupts
        # the payload while keeping its claimed hash (simulated transport
        # corruption past the TCP checksum)
        a.add_index_data("t", x[:6] + 9.0, [(100 + i,) for i in range(6)])
        wait_for(lambda: a.get_aggregated_ntotal("t") == 0)
        eng_a = a._get_index("t")
        orig = eng_a.export_rows_versioned

        def corrupting(ids, with_hash=False):
            out = orig(ids, with_hash=with_hash)
            if with_hash:
                emb, meta, vers, digest = out
                return emb + 1.0, meta, vers, digest  # payload != hash
            return out

        eng_a.export_rows_versioned = corrupting
        before = b.get_ntotal("t")
        out = b._antientropy.sweep_once()
        stats = b._antientropy.stats()
        assert stats["chunk_hash_mismatch"] == 2  # first try + one retry
        assert b.get_ntotal("t") == before, "corrupt rows were applied"
        assert out["failed"] >= 1
        peers = b._antientropy.health.snapshot()
        assert any(e.get("failures", 0) >= 1 for e in peers.values())

        # corruption clears -> the next sweep heals and verifies clean
        eng_a.export_rows_versioned = orig
        b._antientropy.sweep_once()
        wait_for(lambda: b.get_aggregated_ntotal("t") == 0)
        assert b._antientropy.stats()["chunk_hash_mismatch"] == 2
        da = a._get_index("t").replica_digest()
        db = b._get_index("t").replica_digest()
        assert digests_match(da, db)
    finally:
        a.stop()
        b.stop()


# ---------------------------------------------- deletion-ledger pruning


def test_tombstone_prune_ledger_unit():
    from distributed_faiss_tpu.mutation.versions import HLC

    clock = HLC(writer_id=1)
    ts = TombstoneSet()
    v1, v2, v3 = clock.tick(), clock.tick(), clock.tick()
    ts.ledger_update_versioned([("a", v1), ("b", v2)])
    ts.ledger_update(["legacy"])  # version None: never prunable
    assert ts.prune_ledger(None) == 0
    assert ts.prune_ledger(v1) == 0          # strictly below only
    assert ts.prune_ledger(v2) == 1          # drops ("a", v1)
    assert ts.ledger() == frozenset({"b", "legacy"})
    assert ts.prune_ledger(v3) == 1          # drops ("b", v2)
    assert ts.ledger() == frozenset({"legacy"})
    assert ts.prune_ledger(v3) == 0
    # the age bound: a below-floor entry YOUNGER than the cutoff
    # survives (a client's repair queue may still replay the pre-delete
    # add this pair gates — DFT_LEDGER_PRUNE_AGE_S)
    v4, v5 = clock.tick(), clock.tick()
    ts.ledger_update_versioned([("c", v4)])
    assert ts.prune_ledger(v5, max_wall_ms=v4[0] - 10_000) == 0
    assert "c" in ts.ledger()
    assert ts.prune_ledger(v5, max_wall_ms=v4[0]) == 1


def test_ledger_prunes_after_cluster_watermark_never_while_suspect(tmp_path):
    """The sweeper prunes deletion-ledger version pairs once every
    registered replica's watermark passed them — and NEVER while a group
    peer is unreachable/suspect this round (a replica we cannot hear
    from might be missing exactly the delete we would prune). A
    decommissioned address REMOVED from discovery stops blocking (its
    stale suspect entry is out of scope)."""
    from distributed_faiss_tpu.mutation.versions import HLC

    pa, pb, pdead = free_port(), free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600, suspect_after=1,
                         exchange_timeout_s=1.0, ledger_prune_age_s=0.0)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        clock = HLC(writer_id=7)
        rng = np.random.default_rng(2)
        x = rng.standard_normal((20, DIM)).astype(np.float32)
        for srv in (a, b):
            srv.create_index("t", flat_cfg())
            srv.add_index_data("t", x, [(i,) for i in range(20)],
                               version=clock.tick())
        wait_for(lambda: all(s.get_state("t") == IndexState.TRAINED
                             and s.get_aggregated_ntotal("t") == 0
                             for s in (a, b)))
        vdel = clock.tick()
        for srv in (a, b):
            srv.remove_ids("t", [0, 1, 2], version=vdel)
        for eng in (a._get_index("t"), b._get_index("t")):
            with racecheck.peeking():  # white-box peek, reviewed
                assert eng.tombstones.ledger_size() == 3
        # the delete IS the watermark: nothing is strictly below it yet
        a._antientropy.sweep_once()
        with racecheck.peeking():  # white-box peek, reviewed
            assert a._get_index("t").tombstones.ledger_size() == 3

        # a newer write on both replicas moves every watermark past vdel
        vnew = clock.tick()
        for srv in (a, b):
            srv.add_index_data("t", x[:1] + 50.0, [(200,)], version=vnew)
        wait_for(lambda: all(s.get_aggregated_ntotal("t") == 0
                             for s in (a, b)))

        # ... but with an UNREACHABLE registered peer in discovery, the
        # sweep must NOT prune (dial failure -> suspect after 1 miss)
        with open(disc, "w") as f:
            f.write(f"3\nlocalhost,{pa}\nlocalhost,{pb}\n"
                    f"localhost,{pdead}\n")
        a._antientropy.sweep_once()
        with racecheck.peeking():  # white-box peek, reviewed
            assert a._get_index("t").tombstones.ledger_size() == 3
        a._antientropy.sweep_once()  # now suspect-marked: still no prune
        with racecheck.peeking():  # white-box peek, reviewed
            assert a._get_index("t").tombstones.ledger_size() == 3
        # the dead address is decommissioned (removed from discovery) but
        # a LIVE unregistered peer (no shard_group yet — a fresh restart
        # no client has dialed) joins: it might be a member of OUR
        # group, so it must block pruning exactly like a failed dial
        pc = free_port()
        c = IndexServer(2, str(tmp_path / "c"), discovery_path=disc,
                        antientropy_cfg=cfg)
        threading.Thread(target=c.start_blocking, args=(pc,),
                         daemon=True).start()
        wait_for(lambda: c.socket is not None)
        with open(disc, "w") as f:
            f.write(f"3\nlocalhost,{pa}\nlocalhost,{pb}\nlocalhost,{pc}\n")
        try:
            a._antientropy.sweep_once()
            with racecheck.peeking():  # white-box peek, reviewed
                assert a._get_index("t").tombstones.ledger_size() == 3
            # ... until it registers into a DIFFERENT group: another
            # group's replica never blocks ours
            c.set_shard_group(1)
            a._antientropy.sweep_once()
        finally:
            c.stop()
            # decommission c before B's own sweep: a dead listed peer
            # would (correctly) block B's pruning
            with open(disc, "w") as f:
                f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
        eng_a = a._get_index("t")
        with racecheck.peeking():  # white-box peek, reviewed
            assert eng_a.tombstones.ledger_size() == 0
        assert eng_a.mutation_stats()["ledger_pruned"] == 3
        assert a._antientropy.stats()["ledger_pruned"] == 3
        # B prunes from its own sweep
        b._antientropy.sweep_once()
        with racecheck.peeking():  # white-box peek, reviewed
            assert b._get_index("t").tombstones.ledger_size() == 0
        # pruning persisted: the reloaded sidecar stays pruned
        sets = eng_a.id_sets()
        assert sets["dead"] == []
    finally:
        a.stop()
        b.stop()


def test_delete_churn_ledger_stays_bounded(tmp_path):
    """The ISSUE 14 regression: delete-heavy churn used to grow the
    sidecar's version-pair ledger without bound; with sweeper-driven
    pruning the ledger retains only entries at/after the cluster
    watermark floor."""
    from distributed_faiss_tpu.mutation.versions import HLC

    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    cfg = AntiEntropyCfg(interval_s=600, ledger_prune_age_s=0.0)
    a = start_server(0, pa, str(tmp_path / "a"), disc, 0, cfg)
    b = start_server(1, pb, str(tmp_path / "b"), disc, 0, cfg)
    try:
        clock = HLC(writer_id=9)
        rng = np.random.default_rng(3)
        x = rng.standard_normal((10, DIM)).astype(np.float32)
        for srv in (a, b):
            srv.create_index("t", flat_cfg())
            srv.add_index_data("t", x, [(i,) for i in range(10)],
                               version=clock.tick())
        wait_for(lambda: all(s.get_state("t") == IndexState.TRAINED
                             and s.get_aggregated_ntotal("t") == 0
                             for s in (a, b)))
        batch, rounds = 8, 5
        next_id = 1000
        for _r in range(rounds):
            ids = list(range(next_id, next_id + batch))
            next_id += batch
            vadd = clock.tick()
            for srv in (a, b):
                srv.add_index_data("t", rng.standard_normal(
                    (batch, DIM)).astype(np.float32),
                    [(i,) for i in ids], version=vadd)
            wait_for(lambda: all(s.get_aggregated_ntotal("t") == 0
                                 for s in (a, b)))
            vdel = clock.tick()
            for srv in (a, b):
                srv.remove_ids("t", ids, version=vdel)
            a._antientropy.sweep_once()
            b._antientropy.sweep_once()
        total_deleted = batch * rounds
        for srv in (a, b):
            with racecheck.peeking():  # white-box peek, reviewed
                size = srv._get_index("t").tombstones.ledger_size()
            # without pruning this is total_deleted (40); with it, only
            # the final round's pairs (nothing newer outranks them yet)
            # survive
            assert size <= batch, (size, total_deleted)
        assert a._antientropy.stats()["ledger_pruned"] > 0
    finally:
        a.stop()
        b.stop()
