"""Per-id mutation versions (ISSUE 12): HLC ordering, the LWW gates,
idempotent replays, sidecar round-trip + legacy payload upgrade, the
read-your-writes watermark, and generation-pinned point-in-time reads —
engine + client plumbing against fake stubs. Fast tests run in tier-1;
the live-cluster upsert-vs-delete SIGKILL gate is in
tests/test_versions_chaos.py."""

import json
import random
import threading
import time
from multiprocessing.dummy import Pool as ThreadPool

import numpy as np
import pytest

from distributed_faiss_tpu.engine import Index
from distributed_faiss_tpu.mutation import tombstones, versions
from distributed_faiss_tpu.mutation.tombstones import TombstoneSet
from distributed_faiss_tpu.mutation.versions import HLC
from distributed_faiss_tpu.parallel import replication, rpc
from distributed_faiss_tpu.parallel.client import IndexClient
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import (
    IndexCfg,
    ReplicationCfg,
    VersioningCfg,
)
from distributed_faiss_tpu.utils.state import (
    STALE_READ_REJECTION_FMT,
    STALE_READ_REJECTION_PREFIX,
    IndexState,
)
from distributed_faiss_tpu.utils import lockdep, racecheck
from distributed_faiss_tpu.utils.atomics import AtomicCounters

pytestmark = pytest.mark.versions

DIM = 16


@pytest.fixture
def rng():
    return np.random.default_rng(12)


@pytest.fixture(autouse=True)
def _no_background_compaction(monkeypatch):
    monkeypatch.setenv("DFT_COMPACT", "0")


def flat_cfg(tmp_path, **kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", DIM)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 10)
    kw.setdefault("index_storage_dir", str(tmp_path / "shard"))
    return IndexCfg(**kw)


def wait_drained(idx, n, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (idx.get_idx_data_num() == (0, n)
                and idx.get_state() == IndexState.TRAINED):
            return
        time.sleep(0.02)
    raise AssertionError(f"engine never drained to {n} rows: "
                         f"{idx.get_idx_data_num()} ({idx.get_state()})")


def build_engine(tmp_path, rng, n=60, version=None, **kw):
    idx = Index(flat_cfg(tmp_path, **kw))
    x = rng.standard_normal((n, DIM)).astype(np.float32)
    idx.add_batch(x, [(i,) for i in range(n)],
                  train_async_if_triggered=False, version=version)
    wait_drained(idx, n)
    return idx, x


# ------------------------------------------------------------------ HLC


def test_hlc_ticks_strictly_increase():
    clock = HLC(writer_id=1)
    stamps = [clock.tick() for _ in range(200)]
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == len(stamps)


def test_hlc_observe_advances_past_remote():
    clock = HLC(writer_id=1)
    future = (clock.tick()[0] + 60_000, 5, 9)
    clock.observe(future)
    nxt = clock.tick()
    assert versions.compare(nxt, future) > 0


def test_hlc_restart_with_backward_wall_clock_stamps_ahead():
    """The restart story (ISSUE 12 satellite): a client re-created on a
    machine whose wall clock runs BEHIND the cluster seeds its clock from
    the max observed version (get_id_sets watermark) and still stamps
    strictly ahead — wall clock alone would issue stale stamps every
    replica no-ops."""
    wall = {"ms": 1_000_000}
    old = HLC(writer_id=1, clock_ms=lambda: wall["ms"] + 50_000)
    pre_restart = [old.tick() for _ in range(3)]
    cluster_max = pre_restart[-1]
    # restarted client: wall clock 50 s behind its own earlier stamps
    fresh = HLC(writer_id=1, clock_ms=lambda: wall["ms"])
    stale = fresh.tick()
    assert versions.compare(stale, cluster_max) < 0  # the failure mode
    seeded = HLC(writer_id=1, clock_ms=lambda: wall["ms"])
    seeded.observe(cluster_max)
    assert versions.compare(seeded.tick(), cluster_max) > 0


def test_version_key_normalizes_json_lists():
    v = (1234, 5, 6)
    assert versions.version_key(list(v)) == v
    assert versions.version_key(None) is None
    assert versions.version_key(json.loads(json.dumps(list(v)))) == v
    with pytest.raises(ValueError):
        versions.version_key("nope")


def test_compare_total_order_with_none_minimal():
    a, b = (10, 0, 1), (10, 0, 2)
    assert versions.compare(None, a) < 0 < versions.compare(a, None)
    assert versions.compare(None, None) == 0
    assert versions.compare(a, b) < 0  # writer id breaks the tie
    assert versions.newest(a, b) == b
    assert versions.newest(None, a) == a


def test_lww_gates_tie_semantics():
    v = (10, 0, 1)
    newer, older = (11, 0, 1), (9, 0, 1)
    # add: loses to same-or-newer LIVE (replay) and strictly newer DEAD
    assert versions.add_loses(v, live=v, dead=None)
    assert versions.add_loses(v, live=newer, dead=None)
    assert versions.add_loses(v, live=None, dead=newer)
    assert not versions.add_loses(v, live=older, dead=None)
    assert not versions.add_loses(v, live=None, dead=v)  # upsert's own delete
    assert not versions.add_loses(v, live=None, dead=None)
    # delete: loses to same-or-newer LIVE (upsert won) and same-or-newer DEAD
    assert versions.delete_loses(v, live=v, dead=None)
    assert versions.delete_loses(v, live=None, dead=v)
    assert not versions.delete_loses(v, live=older, dead=older)
    assert not versions.delete_loses(v, live=None, dead=None)


def test_versioning_cfg_env_and_validation():
    cfg = VersioningCfg.from_env({"DFT_VERSIONING": "0",
                                  "DFT_RETAIN_GENERATIONS": "4"})
    assert cfg.enabled is False and cfg.retain_generations == 4
    assert VersioningCfg().enabled is True
    assert VersioningCfg().retain_generations == 2
    for bad in (0, 1):  # 1 would be silently floored to the engine's
        with pytest.raises(ValueError):  # crash-fallback pair — reject it
            VersioningCfg(retain_generations=bad)
    with pytest.raises(TypeError):
        VersioningCfg(bogus=1)


# ------------------------------------------------------ sidecar round-trip


def test_payload_round_trips_version_planes():
    t = TombstoneSet()
    t.add([3, 4], [("m", 3), ("m", 4)], version=(10, 0, 1))
    t.set_live_version(("m", 7), (11, 2, 1))
    payload = json.loads(tombstones.dump_payload(t.to_payload()))
    assert payload["format"] == tombstones.PAYLOAD_FORMAT == 2
    back = TombstoneSet.from_payload(payload)
    assert back.ledger_version(("m", 3)) == (10, 0, 1)
    assert back.live_version(("m", 7)) == (11, 2, 1)
    assert back.ledger() == t.ledger()
    assert back.max_version() == (11, 2, 1)


def test_legacy_payload_upgrades_to_version_none():
    """A format-1 payload (no version planes) loads with every version
    None — unversioned is minimal, so any later stamped write outranks
    the recovered legacy state (the documented upgrade semantics)."""
    legacy = {"format": 1, "layout": 0, "dead_rows": [2],
              "dead_ids": [("m", 2)], "dead_ledger": [["m", 2]]}
    t = TombstoneSet.from_payload(legacy)
    assert t.ledger_version(("m", 2)) is None
    assert t.live_version(("m", 9)) is None
    assert t.max_version() is None
    assert not versions.add_loses((1, 0, 1), t.live_version(("m", 2)),
                                  t.ledger_version(("m", 2)))


def test_merge_payload_max_merges_versions():
    a = TombstoneSet()
    a.add([1], [("m", 1)], version=(5, 0, 1))
    b = TombstoneSet()
    b.add([1], [("m", 1)], version=(9, 0, 1))
    b.set_live_version(("m", 2), (4, 0, 2))
    a.merge_payload(b.to_payload())
    assert a.ledger_version(("m", 1)) == (9, 0, 1)
    assert a.live_version(("m", 2)) == (4, 0, 2)
    a.merge_payload(TombstoneSet().to_payload())  # empty merge: no-op
    assert a.ledger_version(("m", 1)) == (9, 0, 1)


# ------------------------------------------------------------ engine gates


def test_versioned_add_replay_is_noop(tmp_path, rng):
    """The repair-queue idempotency fast path: a re-send of a batch the
    replica already holds (same version — anti-entropy healed it, or the
    ack was lost) must not double-apply."""
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=40, version=v1)
    try:
        idx.add_batch(x, [(i,) for i in range(40)],
                      train_async_if_triggered=False, version=v1)
        assert idx.get_idx_data_num() == (0, 40)
        assert idx.mutation_stats()["version_noop_adds"] == 40
        # digest unchanged by the replay
        assert idx.replica_digest() == idx.replica_digest()
    finally:
        idx.retire()


def test_upsert_vs_delete_converges_to_last_writer(tmp_path, rng):
    """The PR 9/10 documented loss, closed: a delete replayed AFTER a
    newer upsert no-ops instead of destroying the upsert; a delete newer
    than the live write still wins."""
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=40, version=v1)
    try:
        v_del = clock.tick()
        assert idx.remove_ids([7], version=v_del) == 1
        v_up = clock.tick()
        idx.upsert([7], rng.standard_normal((1, DIM)).astype(np.float32),
                   version=v_up)
        wait_drained(idx, 41)
        # stale delete replay (e.g. a repair re-send): the upsert wins
        assert idx.remove_ids([7], version=v_del) == 0
        assert 7 in idx.get_ids()
        assert idx.mutation_stats()["version_noop_deletes"] >= 1
        # upsert replay: both halves no-op
        before = idx.get_idx_data_num()
        idx.upsert([7], rng.standard_normal((1, DIM)).astype(np.float32),
                   version=v_up)
        time.sleep(0.2)
        assert idx.get_idx_data_num() == before
        # a NEWER delete still wins
        v_del2 = clock.tick()
        assert idx.remove_ids([7], version=v_del2) == 1
        assert 7 not in idx.get_ids()
    finally:
        idx.retire()


def test_versioned_add_replaces_older_live_row(tmp_path, rng):
    """The anti-entropy refresh path: a PER-ROW-versioned add (the
    delta-pull shape — export_rows_versioned output) of an id that is
    live at a strictly OLDER version replaces the old row in place (the
    in-place upsert a peer pulls during a heal). A plain single-stamp
    batch must NOT replace (shared-id corpora: see the companion test)."""
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=30, version=v1)
    try:
        v2 = clock.tick()
        new_vec = rng.standard_normal((1, DIM)).astype(np.float32)
        idx.add_batch(new_vec, [(5,)], train_async_if_triggered=False,
                      version=[v2])
        deadline = time.time() + 30
        # the drain worker flips ADD -> TRAINED only after the buffer
        # count is already observable as 0: wait for BOTH (like
        # wait_drained) or the search below races the state flip
        while (idx.get_idx_data_num()[0] > 0
               or idx.get_state() != IndexState.TRAINED):
            assert time.time() < deadline
            time.sleep(0.02)
        assert idx.mutation_stats()["version_replaced"] == 1
        scores, meta, _ = idx.search(new_vec, 1)
        assert meta[0][0] == (5,)
        # only ONE live row carries id 5 (the old one is tombstoned)
        sets = idx.id_sets()
        assert sets["live"].count(5) == 1
    finally:
        idx.retire()


def test_plain_versioned_ingest_never_replaces_shared_ids(tmp_path, rng):
    """Regression: metadata ids are NOT required to be unique (the
    integration goldens ingest every row under one shared id). A plain
    single-stamp ingest batch whose id is already live at an older
    version must APPEND like legacy ingest — treating it as an upsert
    would make shared-id corpora eat their own earlier batches."""
    clock = HLC(writer_id=1)
    idx = Index(flat_cfg(tmp_path))
    try:
        x = rng.standard_normal((40, DIM)).astype(np.float32)
        for s in range(0, 40, 10):
            idx.add_batch(x[s:s + 10], [("doc", s + i) for i in range(10)],
                          train_async_if_triggered=False,
                          version=clock.tick())
        wait_drained(idx, 40)
        with racecheck.peeking():  # white-box peek, reviewed
            assert len(idx.tombstones) == 0
        assert idx.mutation_stats()["version_replaced"] == 0
        sets = idx.id_sets()
        assert sets["live"].count("doc") == 40
    finally:
        idx.retire()


def test_refresh_pull_replaces_unversioned_live_row(tmp_path, rng):
    """Review regression (F1): a delta-pull row must displace an
    UNVERSIONED live occupant of its id too (legacy ingest, or the crash
    window that drops uncommitted live versions) — appending beside it
    would leave two live rows for the id and wedge digest convergence
    forever."""
    idx, x = build_engine(tmp_path, rng, n=20)  # unversioned ingest
    try:
        clock = HLC(writer_id=3)
        v = clock.tick()
        new_vec = rng.standard_normal((1, DIM)).astype(np.float32)
        idx.add_batch(new_vec, [(4,)], train_async_if_triggered=False,
                      version=[v])  # the delta-pull shape
        deadline = time.time() + 30
        # buffer-empty alone races the drain worker's ADD -> TRAINED
        # flip (see the companion test): wait for both
        while (idx.get_idx_data_num()[0] > 0
               or idx.get_state() != IndexState.TRAINED):
            assert time.time() < deadline
            time.sleep(0.02)
        sets = idx.id_sets()
        assert sets["live"].count(4) == 1  # replaced, not duplicated
        assert idx.mutation_stats()["version_replaced"] == 1
        _s, meta, _e = idx.search(new_vec, 1)
        assert meta[0][0] == (4,)
    finally:
        idx.retire()


def test_mixed_version_reconcile_records_per_key_versions(tmp_path, rng):
    """Review regression (F3): peer deletes carrying DIFFERENT versions
    apply through the versioned remove path — each key's ledger entry
    records its OWN delete version, and a local live write newer than
    its key's delete survives while older keys delete."""
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=20, version=v1)
    try:
        v_up = clock.tick()
        idx.upsert([8], rng.standard_normal((1, DIM)).astype(np.float32),
                   version=v_up)
        wait_drained(idx, 21)
        vd_old = (v1[0], v1[1] + 1, 9)   # beats v1, loses to v_up
        vd_new = clock.tick()            # beats everything so far
        removed = idx.reconcile_deletes(
            [7, 8], [[7, list(vd_new)], [8, list(vd_old)]])
        assert removed == 1              # 7 deleted; 8's upsert survives
        assert 8 in idx.get_ids() and 7 not in idx.get_ids()
        with racecheck.peeking():  # white-box peek, reviewed
            assert idx.tombstones.ledger_version(7) == vd_new
    finally:
        idx.retire()


def test_versioned_state_survives_restart(tmp_path, rng):
    """SIGKILL-equivalent: versions persist in the sidecar/generation
    payloads, so a stale delete arriving AFTER a restart still loses to
    the pre-restart upsert, and the watermark re-seeds."""
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=30, version=v1)
    v_up = clock.tick()
    idx.upsert([3], rng.standard_normal((1, DIM)).astype(np.float32),
               version=v_up)
    wait_drained(idx, 31)
    assert idx.save()
    idx.retire()
    back = Index.from_storage_dir(str(tmp_path / "shard"),
                                  ignore_buffer=False)
    try:
        stale = clock.tick()  # newer than v_up? no — craft older:
        assert back.remove_ids([3], version=v1) == 0  # older than v_up
        assert 3 in back.get_ids()
        back.assert_min_version(v_up)  # watermark recovered
        with pytest.raises(RuntimeError,
                           match=STALE_READ_REJECTION_PREFIX):
            back.assert_min_version(stale)  # not yet applied here
        assert back.replica_digest() == idx.replica_digest()
    finally:
        back.retire()


def test_reconcile_deletes_versioned_gates(tmp_path, rng):
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    idx, x = build_engine(tmp_path, rng, n=20, version=v1)
    try:
        # peer delete OLDER than the local live write: live wins
        older = (v1[0] - 1, 0, 9)
        assert idx.reconcile_deletes([4], [[4, list(older)]]) == 0
        assert 4 in idx.get_ids()
        # peer delete NEWER: applies, and is recorded at the peer version
        newer = clock.tick()
        assert idx.reconcile_deletes([4], [[4, list(newer)]]) == 1
        assert 4 not in idx.get_ids()
        with racecheck.peeking():  # white-box peek, reviewed
            assert idx.tombstones.ledger_version(4) == newer
        # unversioned peer delete vs a versioned live row: the versioned
        # write outranks the minimal legacy delete
        assert idx.reconcile_deletes([5]) == 0
        assert 5 in idx.get_ids()
    finally:
        idx.retire()


def test_digest_version_plane_sees_content_divergence(tmp_path, rng):
    """Two replicas with IDENTICAL id sets but different write versions
    (one missed an in-place upsert) mismatch on live_vhash while
    live_hash still matches — the divergence the id-only digest could
    never see; a version-aware vs pre-version comparison falls back to
    the id plane."""
    from distributed_faiss_tpu.parallel.antientropy import digests_match

    clock = HLC(writer_id=1)
    v1 = clock.tick()
    a, x = build_engine(tmp_path / "a", rng, n=20, version=v1)
    b, _ = build_engine(tmp_path / "b",
                        np.random.default_rng(12), n=20, version=v1)
    try:
        da, db = a.replica_digest(), b.replica_digest()
        assert digests_match(da, db) and da["live_vhash"] == db["live_vhash"]
        v2 = clock.tick()
        # per-row shape: the in-place refresh (replace) — the id SET
        # stays identical, only the write version moves
        a.add_batch(rng.standard_normal((1, DIM)).astype(np.float32),
                    [(9,)], train_async_if_triggered=False, version=[v2])
        deadline = time.time() + 30
        while a.get_idx_data_num()[0] > 0:
            assert time.time() < deadline
            time.sleep(0.02)
        da, db = a.replica_digest(), b.replica_digest()
        assert da["live_hash"] == db["live_hash"]  # same id set
        assert da["live_vhash"] != db["live_vhash"]
        assert not digests_match(da, db)
        # pre-version peer (no live_vhash): id plane decides
        legacy = {k: v for k, v in db.items() if k != "live_vhash"}
        assert digests_match(da, legacy)
    finally:
        a.retire()
        b.retire()


def test_versioned_export_rows_round_trip(tmp_path, rng):
    clock = HLC(writer_id=1)
    v1 = clock.tick()
    a, x = build_engine(tmp_path / "a", rng, n=20, version=v1)
    b, _ = build_engine(tmp_path / "b",
                        np.random.default_rng(12), n=10, version=v1)
    try:
        emb, meta, vers = a.export_rows_versioned([15, 16])
        assert len(meta) == 2 and all(v == v1 for v in vers)
        b.add_batch(emb, meta, version=vers)
        deadline = time.time() + 30
        while b.get_idx_data_num()[0] > 0:
            assert time.time() < deadline
            time.sleep(0.02)
        assert {15, 16} <= b.get_ids()
        # replaying the same pull is a no-op
        before = b.get_idx_data_num()
        b.add_batch(emb, meta, version=vers)
        assert b.get_idx_data_num() == before
    finally:
        a.retire()
        b.retire()


# --------------------------------------------- read-your-writes watermark


def test_assert_min_version_per_writer(tmp_path, rng):
    clock1, clock2 = HLC(writer_id=1), HLC(writer_id=2)
    v1 = clock1.tick()
    idx, x = build_engine(tmp_path, rng, n=20, version=v1)
    try:
        idx.assert_min_version(None)  # no demand: always fine
        idx.assert_min_version(v1)
        # ANOTHER writer's higher wall-clock version must not satisfy a
        # demand from writer 2 (per-writer watermarks)
        v2 = (v1[0] + 1, 0, 2)
        with pytest.raises(RuntimeError,
                           match=STALE_READ_REJECTION_PREFIX):
            idx.assert_min_version(v2)
        idx.remove_ids([1], version=v2)
        idx.assert_min_version(v2)
    finally:
        idx.retire()


def test_stale_read_matcher_matches_live_raise_site(tmp_path, rng):
    """Drift guard (the drain-failover precedent): the replicated read
    path classifies the stale-read rejection by the shared prefix
    constant — a reworded raise site must fail THIS test, not silently
    disable the failover."""
    clock = HLC(writer_id=1)
    idx, x = build_engine(tmp_path, rng, n=20, version=clock.tick())
    try:
        future = (clock.tick()[0] + 10_000, 0, 1)
        with pytest.raises(RuntimeError) as ei:
            idx.assert_min_version(future)
        wrapped = rpc.ServerException(
            f"remote traceback:\nRuntimeError: {ei.value}")
        assert replication.stale_read_failover_eligible(wrapped)
        assert not replication.stale_read_failover_eligible(
            rpc.ServerException("Server has no index with id=t"))
        assert not replication.stale_read_failover_eligible(
            RuntimeError(str(ei.value)))  # not a ServerException
        # the format constant really is what the raise site used
        assert STALE_READ_REJECTION_FMT.split("{")[0] in str(ei.value)
    finally:
        idx.retire()


# ------------------------------------------------ generation-pinned reads


def test_search_at_generation_serves_pinned_snapshot(tmp_path, rng):
    clock = HLC(writer_id=1)
    idx, x = build_engine(tmp_path, rng, n=40, version=clock.tick())
    try:
        assert idx.save()
        g1 = idx.current_generation()
        idx.remove_ids([5], version=clock.tick())
        assert idx.save()  # delete-only change commits a new generation
        g2 = idx.current_generation()
        assert g2 == g1 + 1
        # pinned read at g1: the deleted id still serves
        _s, meta, _e = idx.search_at_generation(x[5:6], 3, generation=g1)
        assert meta[0][0] == (5,)
        # pinned read at g2 (and the live path): it does not
        _s, meta2, _e = idx.search_at_generation(x[5:6], 3, generation=g2)
        assert (5,) not in [m for m in meta2[0] if m]
        _s, live_meta, _e = idx.search(x[5:6], 3)
        assert (5,) not in [m for m in live_meta[0] if m]
        # unknown / pruned generation: clear application error
        with pytest.raises(RuntimeError, match="not retained"):
            idx.search_at_generation(x[:1], 3, generation=g2 + 50)
    finally:
        idx.retire()


def test_retain_generations_knob_widens_the_window(tmp_path, rng,
                                                   monkeypatch):
    monkeypatch.setenv("DFT_RETAIN_GENERATIONS", "3")
    clock = HLC(writer_id=1)
    idx, x = build_engine(tmp_path, rng, n=30, version=clock.tick())
    try:
        assert idx.versioning.retain_generations == 3
        gens = []
        for i in range(3):
            idx.remove_ids([i], version=clock.tick())
            assert idx.save()
            gens.append(idx.current_generation())
        on_disk = [g for g, _m in serialization.list_generations(
            str(tmp_path / "shard"))]
        assert on_disk == sorted(gens, reverse=True)  # all 3 retained
        _s, meta, _e = idx.search_at_generation(x[1:2], 2,
                                                generation=gens[0])
        assert meta[0][0] == (1,)  # deleted in gens[1], alive in gens[0]
    finally:
        idx.retire()


# ----------------------------------------------------- client plumbing


class FakeStub:
    """Quacks like rpc.Client for the versioned write fan-out: records
    every call with kwargs, optionally rejects the ``version`` keyword
    like a pre-version server, and serves a watermark through
    get_id_sets."""

    def __init__(self, sid, legacy=False, watermark=None, fail=False):
        self.id = sid
        self.host, self.port = "fake", 9000 + sid
        self.legacy = legacy
        self.watermark = watermark
        self.fail = fail
        self.calls = []

    def generic_fun(self, fname, args=(), kwargs=None, **_kw):
        if self.fail:
            raise ConnectionRefusedError(f"rank {self.id} down")
        if self.legacy and kwargs and "version" in kwargs:
            raise rpc.ServerException(
                f"TypeError: {fname}() got an unexpected keyword "
                "argument 'version'")
        self.calls.append((fname, args, dict(kwargs or {})))
        if fname == "get_id_sets":
            return {"live": [], "dead": [], "watermark": self.watermark}
        if fname == "get_shard_group":
            return None
        if fname == "remove_ids":
            return 1
        return f"ok-{self.id}"


def make_client(stubs, rcfg=None, vcfg=None):
    c = object.__new__(IndexClient)
    c.sub_indexes = stubs
    c.num_indexes = len(stubs)
    c.pool = ThreadPool(max(len(stubs), 1))
    c.cur_server_ids = {}
    c._rng = random.Random(0)
    c.retry = rpc.RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0.0)
    c._stats_lock = lockdep.lock("IndexClient._stats_lock")
    from collections import deque

    c.reroutes = deque(maxlen=8)
    c.counters = AtomicCounters(
                  ("reroutes", "failovers", "under_replicated", "quorum_failures"))
    c.rcfg = rcfg or ReplicationCfg()
    eff = min(c.rcfg.replication, max(len(stubs), 1))
    c.quorum = replication.quorum_size(eff, min(c.rcfg.write_quorum, eff))
    c.repair_queue = replication.RepairQueue(c.rcfg.repair_queue_len)
    c._preferred = {}
    c.membership = replication.MembershipTable(
        replication.assign_groups(len(stubs), c.rcfg.replication))
    c.cfg = None
    c.vcfg = vcfg if vcfg is not None else VersioningCfg()
    c._hlc = HLC(writer_id=42) if c.vcfg.enabled else None
    c._seeded = set()
    c._last_write_version = {}
    c._unversioned_ranks = set()
    return c


def test_client_stamps_one_version_per_batch_across_replicas():
    a, b = FakeStub(0), FakeStub(1)
    client = make_client([a, b], rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((2, 8), np.float32), [1, 2])
    va = [kw["version"] for f, _a, kw in a.calls if f == "add_index_data"]
    vb = [kw["version"] for f, _a, kw in b.calls if f == "add_index_data"]
    assert va and va == vb  # the SAME stamp reached both replicas
    assert client.last_write_version("idx") == va[0]
    # a second batch gets a strictly newer stamp
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [3])
    va2 = [kw["version"] for f, _a, kw in a.calls if f == "add_index_data"]
    assert versions.compare(va2[-1], va[0]) > 0


def test_client_seeds_clock_from_cluster_watermark():
    remote = (int(time.time() * 1000) + 90_000, 3, 7)  # far-future peer
    a = FakeStub(0, watermark=list(remote))
    client = make_client([a])
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert any(f == "get_id_sets" for f, _a, _k in a.calls)  # seeded once
    v = client.last_write_version("idx")
    assert versions.compare(v, remote) > 0
    # second mutation does not re-seed
    n_seeds = sum(1 for f, _a, _k in a.calls if f == "get_id_sets")
    client.remove_ids("idx", [1])
    assert sum(1 for f, _a, _k in a.calls
               if f == "get_id_sets") == n_seeds


def test_repair_resend_carries_original_version():
    """ISSUE 12 satellite: the repair record holds the batch's ORIGINAL
    stamp, and the re-send presents it — so a replica that already
    healed via anti-entropy no-ops instead of double-applying."""
    live, dead = FakeStub(0), FakeStub(1, fail=True)
    client = make_client([live, dead],
                         rcfg=ReplicationCfg(replication=2, write_quorum=1))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((2, 8), np.float32), [1, 2])
    v = client.last_write_version("idx")
    assert len(client.repair_queue) == 1
    item = client.repair_queue.drain()[0]
    assert item["version"] == v
    client.repair_queue.record(item)
    dead.fail = False  # rank healed (e.g. by the sweep)
    out = client.repair_under_replicated()
    assert out == {"repaired": 1, "still_pending": 0}
    resent = [kw for f, _a, kw in dead.calls if f == "add_index_data"]
    assert resent and resent[0]["version"] == v


def test_versioned_delete_repair_record_carries_version():
    live, dead = FakeStub(0), FakeStub(1, fail=True)
    client = make_client([live, dead],
                         rcfg=ReplicationCfg(replication=2, write_quorum=1))
    client.remove_ids("idx", [1, 2])
    v = client.last_write_version("idx")
    item = client.repair_queue.drain()[0]
    assert item["op"] == "remove_ids" and item["version"] == v


def test_client_degrades_gracefully_against_pre_version_server():
    """Rolling-upgrade compat: a rank that rejects the ``version``
    keyword is retried without it and remembered — ingest never wedges,
    and the degrade is visible in get_replication_stats."""
    new, old = FakeStub(0), FakeStub(1, legacy=True)
    client = make_client([new, old], rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert [kw for f, _a, kw in old.calls
            if f == "add_index_data"] == [{}]  # un-versioned re-send
    assert "version" in [kw for f, _a, kw in new.calls
                         if f == "add_index_data"][0]
    stats = client.get_replication_stats()
    assert stats["versioning"]["enabled"] is True
    assert stats["versioning"]["unversioned_ranks"] == [1]
    # subsequent writes skip the doomed attempt entirely
    calls_before = len(old.calls)
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [2])
    extra = old.calls[calls_before:]
    assert [kw for f, _a, kw in extra if f == "add_index_data"] == [{}]


def test_failed_write_does_not_poison_read_your_writes():
    """Review regression (F2): a write that acks NOWHERE must not become
    the read-your-writes floor — no replica will ever incorporate its
    stamp, so RYW searches would reject everywhere until the next
    successful write."""
    stubs = [FakeStub(0, fail=True), FakeStub(1, fail=True)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0
    with pytest.raises(RuntimeError, match="every rank"):
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert client.last_write_version("idx") is None
    # ...and an acked write DOES move the floor
    for s in stubs:
        s.fail = False
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert client.last_write_version("idx") is not None


def test_seed_clock_observes_every_replica_not_first():
    """Review regression (F4): a quorum-minority write lives only on
    SOME replicas — seeding must max-merge every reachable watermark,
    not stop at the first responder (a laggard answering first would
    let a backward-clock restart stamp below the client's own writes)."""
    now_ms = int(time.time() * 1000)
    behind = [now_ms + 30_000, 0, 7]
    ahead = [now_ms + 90_000, 2, 7]
    stubs = [FakeStub(0, watermark=behind), FakeStub(1, watermark=ahead)]
    client = make_client(stubs, rcfg=ReplicationCfg(replication=2))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    assert versions.compare(client.last_write_version("idx"),
                            tuple(ahead)) > 0


def test_seed_clock_retries_after_total_outage():
    """Review regression: a transient total outage during the first
    mutation must not latch 'seeded' — the next mutation re-seeds, or a
    backward-clock restart would stamp below its own pre-restart writes
    forever."""
    remote = (int(time.time() * 1000) + 120_000, 0, 7)
    a = FakeStub(0, watermark=list(remote), fail=True)
    client = make_client([a])
    client.cur_server_ids["idx"] = 0
    with pytest.raises(RuntimeError, match="every rank"):
        client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    with client._stats_lock:
        assert "idx" not in client._seeded  # outage: seed NOT latched
    a.fail = False  # cluster back: the next mutation re-seeds
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    with client._stats_lock:
        assert "idx" in client._seeded
    assert versions.compare(client.last_write_version("idx"), remote) > 0


def test_full_sync_vetoed_by_gated_peer_delete(tmp_path):
    """Review regression: a local live write that OUTRANKED a peer's
    delete must veto the full-snapshot sync — the peer snapshot holds
    that id DELETED, so installing it would lose the winning upsert even
    though local_only/local_newer/extra_dead are all empty. The heal
    must fall back to the chunked delta instead."""
    import socket
    import threading

    from distributed_faiss_tpu.parallel.server import IndexServer
    from distributed_faiss_tpu.utils.config import AntiEntropyCfg

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    pa, pb = free_port(), free_port()
    disc = str(tmp_path / "disc.txt")
    with open(disc, "w") as f:
        f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
    # delta_max_rows=1 makes ANY multi-row divergence full-sync-eligible
    cfg = AntiEntropyCfg(interval_s=600, delta_max_rows=1)
    servers = []
    try:
        rng = np.random.default_rng(3)
        x = rng.standard_normal((30, DIM)).astype(np.float32)
        clock = HLC(writer_id=1)
        v1 = clock.tick()
        for rank, port, path in ((0, pa, "a"), (1, pb, "b")):
            srv = IndexServer(rank, str(tmp_path / path),
                              discovery_path=disc, antientropy_cfg=cfg)
            srv.set_shard_group(0)
            threading.Thread(target=srv.start_blocking, args=(port,),
                             daemon=True).start()
            servers.append(srv)
        time.sleep(0.3)
        for srv in servers:
            srv.create_index("t", IndexCfg(index_builder_type="flat",
                                           dim=DIM, metric="l2",
                                           train_num=10))
            srv.add_index_data("t", x, [(i,) for i in range(30)],
                               version=v1)
            deadline = time.time() + 60
            while not (srv.get_state("t") == IndexState.TRAINED
                       and srv.get_aggregated_ntotal("t") == 0):
                assert time.time() < deadline
                time.sleep(0.05)
        a, b = servers
        # peer B: delete id 5 @v2 and add MANY rows A lacks (> delta_max)
        v2 = clock.tick()
        b._get_index("t").remove_ids([5], version=v2)
        extra = rng.standard_normal((8, DIM)).astype(np.float32)
        b._get_index("t").add_batch(extra, [(100 + i,) for i in range(8)],
                                    train_async_if_triggered=False,
                                    version=clock.tick())
        # local A: upsert id 5 at a NEWER version — it must survive
        v3 = clock.tick()
        a._get_index("t").upsert([5], x[5:6] + 1.0, version=v3)
        deadline = time.time() + 60
        while (a.get_aggregated_ntotal("t") > 0
               or b.get_aggregated_ntotal("t") > 0):
            assert time.time() < deadline
            time.sleep(0.05)
        out = a._antientropy.sweep_once()
        healed = [h for h in out["healed"] if h["index_id"] == "t"]
        assert healed and healed[0]["full_sync"] is False, healed
        assert 5 in a._get_index("t").get_ids(), "full sync ate the upsert"
        with racecheck.peeking():  # white-box peek, reviewed
            assert a._get_index("t").tombstones.live_version(5) == v3
        assert {100 + i for i in range(8)} <= a._get_index("t").get_ids()
    finally:
        for srv in servers:
            srv.stop()


def test_versioning_off_sends_no_version():
    a = FakeStub(0)
    client = make_client([a], vcfg=VersioningCfg(enabled=False))
    client.cur_server_ids["idx"] = 0
    client.add_index_data("idx", np.zeros((1, 8), np.float32), [1])
    adds = [kw for f, _a, kw in a.calls if f == "add_index_data"]
    assert adds == [{}]
    assert not any(f == "get_id_sets" for f, _a, _k in a.calls)
    assert client.last_write_version("idx") is None
