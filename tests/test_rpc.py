"""RPC transport tests (model: reference tests/test_rpc.py).

Covers frame round-trips (tensors + nested containers), client/server over
loopback, remote-exception propagation, multi-threaded clients, and the
selector serving mode (which the reference ships broken and skips;
ours must pass)."""

import socket
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel import rpc


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def loopback_pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_round_trip_tensors():
    a, b = loopback_pair()
    obj = {
        "x": np.arange(12, dtype=np.float32).reshape(3, 4),
        "y": [np.array([1, 2], dtype=np.int64), "text", 3.5],
        "z": (None, {"w": np.zeros((0, 5), np.float16)}),
    }
    t = threading.Thread(target=lambda: rpc.send_frame(a, rpc.KIND_RESULT, obj))
    t.start()
    kind, got = rpc.recv_frame(b)
    t.join()
    assert kind == rpc.KIND_RESULT
    np.testing.assert_array_equal(got["x"], obj["x"])
    np.testing.assert_array_equal(got["y"][0], obj["y"][0])
    assert got["y"][1:] == ["text", 3.5]
    assert got["z"][0] is None
    assert got["z"][1]["w"].shape == (0, 5)
    a.close(); b.close()


def test_frame_large_tensor():
    a, b = loopback_pair()
    big = np.random.default_rng(0).standard_normal((512, 256)).astype(np.float32)
    t = threading.Thread(target=lambda: rpc.send_frame(a, rpc.KIND_CALL, ("add", (big,), {})))
    t.start()
    kind, (fname, args, kwargs) = rpc.recv_frame(b)
    t.join()
    assert fname == "add"
    np.testing.assert_array_equal(args[0], big)
    assert args[0].dtype == np.float32
    a.close(); b.close()


class EchoServer:
    """Minimal dispatch target for transport tests."""

    def __init__(self):
        self.calls = 0

    def echo(self, x):
        self.calls += 1
        return x

    def boom(self):
        raise ValueError("intentional failure")

    def double(self, arr, scale=2.0):
        return arr * scale


def _serve(server_obj, sock):
    """Legacy (pre-mux) serve loop: one frame at a time, untagged in-order
    responses, meta element (deadline_s/req_id) ignored — the interop
    shape a mux client must degrade against (FIFO demux attribution)."""
    try:
        while True:
            kind, payload = rpc.recv_frame(sock)
            if kind == rpc.KIND_CLOSE:
                break
            fname, args, kwargs = payload[:3]
            try:
                ret = getattr(server_obj, fname)(*args, **kwargs)
                rpc.send_frame(sock, rpc.KIND_RESULT, ret)
            except Exception:
                import traceback

                rpc.send_frame(sock, rpc.KIND_ERROR, traceback.format_exc())
    except (EOFError, OSError):
        pass
    finally:
        sock.close()


@pytest.fixture
def echo_endpoint():
    port = free_port()
    srv = EchoServer()
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", port))
    lsock.listen(5)

    def accept_loop():
        try:
            while True:
                conn, _ = lsock.accept()
                threading.Thread(target=_serve, args=(srv, conn), daemon=True).start()
        except OSError:
            pass

    threading.Thread(target=accept_loop, daemon=True).start()
    yield "localhost", port, srv
    lsock.close()


def test_client_dynamic_dispatch(echo_endpoint):
    host, port, srv = echo_endpoint
    c = rpc.Client(0, host, port)
    assert c.echo(42) == 42
    arr = np.ones((4, 4), np.float32)
    np.testing.assert_array_equal(c.double(arr, scale=3.0), arr * 3.0)
    c.close()


def test_remote_exception(echo_endpoint):
    host, port, _ = echo_endpoint
    c = rpc.Client(0, host, port)
    with pytest.raises(rpc.ServerException) as ei:
        c.boom()
    assert "intentional failure" in str(ei.value)
    # connection still usable after a remote error
    assert c.echo("ok") == "ok"
    c.close()


@pytest.mark.parametrize("mode", ["blocking", "selector"])
def test_malformed_frame_kills_only_that_connection(tmp_path, mode):
    """Garbage bytes on the wire must drop that connection, not the server —
    in BOTH serving modes (the selector loop used to die on the first
    malformed frame: RuntimeError('bad frame magic') escaped its
    per-connection except clause and killed the whole serving loop)."""
    from distributed_faiss_tpu.parallel.server import IndexServer

    port = free_port()
    srv = IndexServer(0, str(tmp_path))
    target = srv.start_blocking if mode == "blocking" else srv.start
    threading.Thread(target=target, args=(port,), daemon=True).start()
    deadline = time.time() + 10
    probe = None
    while time.time() < deadline:
        try:
            probe = socket.create_connection(("localhost", port), timeout=1)
            break
        except OSError:
            time.sleep(0.05)
    assert probe is not None, "server never started listening"
    # send garbage on a raw socket
    probe.sendall(b"\x00" * 64)
    probe.close()
    bad = socket.create_connection(("localhost", port))
    bad.sendall(b"NOPE" + b"\xff" * 32)
    time.sleep(0.2)
    bad.close()
    # server still serves well-formed clients
    c = rpc.Client(0, "localhost", port)
    assert c.get_rank() == 0
    c.close()
    srv.stop()


@pytest.mark.slow
def test_multiprocess_clients(tmp_path):
    """10 client *processes* against one server (reference tests/test_rpc.py
    runs a multiprocessing.Pool of 10; we use subprocesses for isolation)."""
    import os
    import subprocess
    import sys

    from distributed_faiss_tpu.parallel.server import IndexServer

    port = free_port()
    srv = IndexServer(0, str(tmp_path))
    threading.Thread(target=srv.start_blocking, args=(port,), daemon=True).start()

    client_code = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from distributed_faiss_tpu.parallel.rpc import Client
from distributed_faiss_tpu.utils.config import IndexCfg
port, wid = int(sys.argv[1]), int(sys.argv[2])
c = Client(wid, "localhost", port)
c.create_index("mp", IndexCfg(index_builder_type="flat", dim=8, metric="l2", train_num=1))
x = np.full((5, 8), float(wid), np.float32)
c.add_index_data("mp", x, [(wid, j) for j in range(5)])
assert c.get_rank() == 0
c.close()
print("ok", wid)
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen([sys.executable, "-c", client_code, str(port), str(i)],
                         env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo},
                         cwd=repo, stdout=subprocess.PIPE, text=True)
        for i in range(10)
    ]
    for p in procs:
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0 and out.startswith("ok")
    # all 50 rows landed
    deadline = time.time() + 60
    while time.time() < deadline:
        if srv.get_ntotal("mp") == 50:
            break
        time.sleep(0.1)
    assert srv.get_ntotal("mp") == 50
    srv.stop()


def test_many_threaded_clients(echo_endpoint):
    host, port, srv = echo_endpoint
    errors = []

    def worker(i):
        try:
            c = rpc.Client(i, host, port)
            for j in range(20):
                assert c.echo((i, j)) == (i, j)
            c.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert srv.calls == 200


def test_client_auto_reconnect_and_cooldown():
    """Transport failures drop the connection; the next call redials within
    the bounded budget, repeated failures hit the fail-fast cooldown, and a
    server restarted on the same port is reachable through the SAME stub.
    close() is terminal — no redial after a user-initiated shutdown."""
    port = free_port()
    srv = EchoServer()

    def run_listener(p):
        lsock = socket.socket()
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind(("", p))
        lsock.listen(5)
        conns = []

        def loop():
            while True:
                try:
                    c, _ = lsock.accept()
                except OSError:
                    break
                conns.append(c)
                threading.Thread(target=_serve, args=(srv, c), daemon=True).start()

        threading.Thread(target=loop, daemon=True).start()
        return lsock, conns

    lsock, conns = run_listener(port)
    cli = rpc.Client(0, "localhost", port, connect_timeout=10)
    assert cli.echo(1) == 1

    lsock.close()
    for c in conns:
        c.close()
    with pytest.raises((OSError, EOFError)):
        cli.echo(2)  # in-flight socket died -> marked closed
    # outage phase dials a closed privileged port: instant RST, and immune
    # to the loopback TCP self-connect artifact that can make a redial to
    # an unheld EPHEMERAL port spuriously succeed
    cli.port = 1
    t0 = time.time()
    with pytest.raises(OSError):
        cli.echo(3)  # bounded redial against the dead port
    assert time.time() - t0 < rpc.Client.RECONNECT_TIMEOUT + 2.0
    t0 = time.time()
    with pytest.raises(OSError):
        cli.echo(4)  # inside the cooldown window: fails fast
    assert time.time() - t0 < 0.25

    # restart the server and repoint the stub (a fresh port sidesteps
    # lingering-socket EADDRINUSE in-process; same-PORT restart is proven
    # end-to-end by test_launcher.py::test_degraded_mode_search_with_dead_rank)
    port2 = free_port()
    cli.port = port2
    lsock, conns = run_listener(port2)
    cli._next_redial = 0.0  # skip the wall-clock cooldown wait
    assert cli.echo(5) == 5  # same stub, back to serving after restart

    cli.close()
    with pytest.raises(RuntimeError):
        cli.echo(6)
    lsock.close()


# ------------------------------------------------------- restricted pickle


def test_restricted_loads_roundtrips_rpc_payload_types():
    """Everything the RPC surface legitimately pickles must survive the
    allowlisted Unpickler: skeleton containers, numpy object arrays and
    scalars, package types (IndexCfg, IndexState, _TensorRef)."""
    import pickle

    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    def roundtrip(obj):
        return rpc.restricted_loads(pickle.dumps(obj, protocol=4))

    # exact content equality for containers and scalars
    skel = [("meta", 0), None, {1, 2}, frozenset({3}), b"bytes", 1.5, range(3)]
    assert roundtrip(skel) == skel
    call = ("search", ("idx", 10), {"return_embeddings": False})
    assert roundtrip(call) == call
    assert roundtrip(IndexState.TRAINED) is IndexState.TRAINED
    assert roundtrip(np.float32(1.25)) == np.float32(1.25)
    assert roundtrip(np.int64(7)) == np.int64(7)

    ref = roundtrip(rpc._TensorRef(3))
    assert isinstance(ref, rpc._TensorRef) and ref.idx == 3
    nested = roundtrip(("call", (rpc._TensorRef(1), rpc._TensorRef(2))))
    assert [r.idx for r in nested[1]] == [1, 2]

    cfg = roundtrip(IndexCfg(dim=16, metric="dot"))
    assert cfg.dim == 16 and cfg.metric == "dot"

    obj_arr = np.empty(2, dtype=object)
    obj_arr[0] = ("doc", 1)
    obj_arr[1] = None
    out = roundtrip(obj_arr)
    assert out.dtype == object and out[0] == ("doc", 1) and out[1] is None


def test_restricted_loads_rejects_dangerous_globals(monkeypatch):
    """A crafted frame referencing an arbitrary callable must raise
    UnpicklingError instead of resolving it (remote-code-execution vector
    of bare pickle.loads); DFT_RPC_UNSAFE_PICKLE=1 is the explicit
    operator opt-out."""
    import os
    import pickle
    import pickletools

    class Evil:
        def __reduce__(self):
            return (os.getenv, ("HOME",))

    blob = pickle.dumps(Evil(), protocol=4)
    assert b"getenv" in pickletools.optimize(blob)
    monkeypatch.delenv("DFT_RPC_UNSAFE_PICKLE", raising=False)
    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        rpc.restricted_loads(blob)
    # builtins outside the safe subset are rejected too
    evil_builtin = pickle.dumps(eval, protocol=4)
    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        rpc.restricted_loads(evil_builtin)
    # proto-4 STACK_GLOBAL with a DOTTED name: CPython's find_class
    # getattr-walks "os.getenv" through the rpc module's own imports, so a
    # package-module reference must not bypass the allowlist (the exploit
    # a namespace-prefix allowlist permits)

    def _short_unicode(s):
        b = s.encode()
        return b"\x8c" + bytes([len(b)]) + b

    dotted = (b"\x80\x04"
              + _short_unicode("distributed_faiss_tpu.parallel.rpc")
              + _short_unicode("os.getenv")
              + b"\x93.")  # STACK_GLOBAL, STOP
    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        rpc.restricted_loads(dotted)
    # arbitrary package callables (even dot-free) are rejected: only the
    # three RPC-surface types resolve
    evil_pkg = (b"\x80\x04"
                + _short_unicode("distributed_faiss_tpu.parallel.rpc")
                + _short_unicode("Client")
                + b"\x93.")
    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        rpc.restricted_loads(evil_pkg)
    # the opt-out is strictly '1': truthy-but-wrong spellings must stay
    # on the restricted path (a security knob never widens by coercion)
    for spelling in ("true", "yes", "2", "on"):
        monkeypatch.setenv("DFT_RPC_UNSAFE_PICKLE", spelling)
        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            rpc.restricted_loads(blob)
    # explicit opt-out restores reference behavior for custom metadata
    monkeypatch.setenv("DFT_RPC_UNSAFE_PICKLE", "1")
    assert rpc.restricted_loads(blob) == os.getenv("HOME")


def test_wire_frames_decode_through_restricted_unpickler():
    """recv_frame's skeleton path uses restricted_loads end to end."""
    import io

    parts = rpc.pack_frame(
        rpc.KIND_CALL,
        ("add_index_data", (np.arange(6, dtype=np.float32).reshape(2, 3),
                            [("m", 0), ("m", 1)]), {}),
    )

    class FakeSock:
        def __init__(self, data):
            self.buf = io.BytesIO(data)

        def recv_into(self, view, n):
            chunk = self.buf.read(n)
            view[: len(chunk)] = chunk
            return len(chunk)

        def recv(self, n):
            return self.buf.read(n)

    kind, payload = rpc.recv_frame(FakeSock(b"".join(bytes(p) for p in parts)))
    assert kind == rpc.KIND_CALL
    fname, args, kwargs = payload
    assert fname == "add_index_data"
    np.testing.assert_array_equal(
        args[0], np.arange(6, dtype=np.float32).reshape(2, 3))
    assert args[1] == [("m", 0), ("m", 1)]


# ---------------------------------------------- deadline + BUSY (scheduler)


class _RecordingServer:
    """One-connection raw server scripting KIND_BUSY / result responses and
    recording the decoded call frames (to assert deadline stamping)."""

    def __init__(self, responses):
        self.responses = list(responses)
        self.frames = []
        self.port = free_port()
        self._lsock = socket.socket()
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("", self.port))
        self._lsock.listen(5)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        try:
            conn, _ = self._lsock.accept()
            while self.responses:
                kind, payload = rpc.recv_frame(conn)
                if kind == rpc.KIND_CLOSE:
                    return
                self.frames.append(payload)
                rkind, rpayload = self.responses.pop(0)
                rpc.send_frame(conn, rkind, rpayload)
        except (EOFError, OSError):
            pass

    def close(self):
        self._lsock.close()


def test_busy_frame_raises_busy_error_and_is_retryable():
    srv = _RecordingServer([
        (rpc.KIND_BUSY, {"reason": "queue_full", "queue_depth": 9,
                         "max_queue": 9}),
        (rpc.KIND_RESULT, "served"),
    ])
    c = rpc.Client(0, "localhost", srv.port)
    with pytest.raises(rpc.BusyError) as ei:
        c.generic_fun("search", ("idx",))
    assert ei.value.info["queue_depth"] == 9
    # the connection survives a BUSY rejection (it is a structured
    # response, not a transport fault) — and RetryPolicy retries it
    assert rpc.RetryPolicy().is_retryable(ei.value)
    assert c.generic_fun("search", ("idx",)) == "served"
    c.close()
    srv.close()


def test_busy_frame_with_deadline_reason_raises_deadline_exceeded():
    srv = _RecordingServer([(rpc.KIND_BUSY, {"reason": "deadline"})])
    c = rpc.Client(0, "localhost", srv.port)
    with pytest.raises(rpc.DeadlineExceeded):
        c.generic_fun("search", ("idx",))
    # NOT retryable: the budget is already spent
    assert not rpc.RetryPolicy().is_retryable(rpc.DeadlineExceeded("x"))
    c.close()
    srv.close()


def test_deadline_stamped_as_relative_budget_in_frame():
    srv = _RecordingServer([
        (rpc.KIND_RESULT, "ok"),
        (rpc.KIND_RESULT, "ok"),
    ])
    c = rpc.Client(0, "localhost", srv.port)
    # mux frames always carry the meta element (req_id, plus the binary-
    # wire capability advert since ISSUE 14); deadline_s joins it only
    # when a deadline is set. A SERIAL (DFT_RPC_MUX=0) client still
    # sends legacy 3-tuple frames without a deadline — checked below.
    assert c.generic_fun("ping", ()) == "ok"
    assert c.generic_fun("ping", (), deadline=time.time() + 5.0) == "ok"
    deadline = time.time() + 5
    while len(srv.frames) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert len(srv.frames[0]) == 4
    assert srv.frames[0][3].keys() == {"req_id", "wire"}
    assert srv.frames[0][3]["wire"] == 1
    assert len(srv.frames[1]) == 4
    assert srv.frames[1][3]["req_id"] != srv.frames[0][3]["req_id"]
    budget = srv.frames[1][3]["deadline_s"]
    assert 0.0 < budget <= 5.0  # RELATIVE seconds, clock-skew-safe
    c.close()

    srv2 = _RecordingServer([(rpc.KIND_RESULT, "ok")])
    serial = rpc.Client(0, "localhost", srv2.port, mux=False)
    assert serial.generic_fun("ping", ()) == "ok"
    deadline = time.time() + 5
    while not srv2.frames and time.time() < deadline:
        time.sleep(0.01)
    assert len(srv2.frames[0]) == 3  # no-meta legacy frame
    serial.close()
    srv2.close()
    srv.close()


def test_expired_deadline_fails_before_touching_the_wire():
    srv = _RecordingServer([(rpc.KIND_RESULT, "never")])
    c = rpc.Client(0, "localhost", srv.port)
    with pytest.raises(rpc.DeadlineExceeded):
        c.generic_fun("search", ("idx",), deadline=time.time() - 0.1)
    time.sleep(0.1)
    assert srv.frames == []  # zero bytes hit the wire
    # connection is still healthy for the next (in-budget) call
    assert c.generic_fun("search", ("idx",)) == "never"
    c.close()
    srv.close()


def test_retry_policy_run_filtered_respects_deadline():
    calls = []

    def always_busy():
        calls.append(time.time())
        raise rpc.BusyError("busy")

    p = rpc.RetryPolicy(max_attempts=10, base_delay=0.2, jitter=0.0)
    t0 = time.time()
    with pytest.raises(rpc.BusyError):
        p.run_filtered((rpc.BusyError,), t0 + 0.3, always_busy)
    # backoff abandoned once the next sleep would land past the deadline:
    # far fewer than max_attempts, and no sleep beyond the budget
    assert len(calls) < 10
    assert time.time() - t0 < 1.0


def test_retry_policy_run_retries_busy_then_succeeds():
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise rpc.BusyError("busy")
        return "done"

    p = rpc.RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    assert p.run(flaky) == "done"
    assert state["n"] == 3
