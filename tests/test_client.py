"""Client unit tests: discovery parsing + result aggregation golden
(model: reference tests/test_client.py:19-39 and
tests/test_integration.py:181-203), plus discovery-file robustness and
global-RNG hygiene."""

import random
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.parallel.client import IndexClient, merge_result_blocks


def write_list(tmp_path, count, entries, name="servers.txt"):
    p = tmp_path / name
    lines = [str(count)] + [f"{h},{p_}" for h, p_ in entries]
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_read_server_list_ok(tmp_path):
    path = write_list(tmp_path, 3, [("a", 1), ("b", 2), ("c", 3)])
    assert IndexClient.read_server_list(path) == [("a", 1), ("b", 2), ("c", 3)]


def test_read_server_list_dedupes_restarted_rank(tmp_path):
    """Regression (ISSUE 8 satellite): a RESTARTED rank re-appends its
    ``host,port`` discovery line, pushing the raw entry count past the
    advertised header — the old exact-count check then looped until the
    7200 s timeout. Duplicates must dedupe (keeping registration order)
    and the wait must accept len >= advertised."""
    p = tmp_path / "servers.txt"
    p.write_text("3\na,1\nb,2\nc,3\nb,2\n")  # rank b restarted and re-registered
    assert IndexClient.read_server_list(str(p), total_max_timeout=1) == [
        ("a", 1), ("b", 2), ("c", 3)]


def test_read_server_list_accepts_extra_distinct_entries(tmp_path):
    """A rank that moved ports mid-life leaves an extra DISTINCT entry:
    connect to everything rather than hang (the dead entry degrades
    through the normal transport-error paths)."""
    p = tmp_path / "servers.txt"
    p.write_text("2\na,1\nb,2\nb,3\n")
    assert IndexClient.read_server_list(str(p), total_max_timeout=1) == [
        ("a", 1), ("b", 2), ("b", 3)]


def test_read_server_list_timeout(tmp_path):
    path = write_list(tmp_path, 4, [("a", 1), ("b", 2), ("c", 3)])
    with pytest.raises(RuntimeError) as ei:
        IndexClient.read_server_list(path, total_max_timeout=0)
    assert "4 != 3" in str(ei.value)


def test_read_server_list_waits_for_missing_file(tmp_path):
    """The launcher creates the discovery file AFTER a client may have
    started: a missing file must enter the registration backoff loop (as
    '0 of N registered'), not raise FileNotFoundError immediately."""
    path = str(tmp_path / "late.txt")

    def create_late():
        time.sleep(0.4)
        write_list(tmp_path, 2, [("a", 1), ("b", 2)], name="late.txt")

    t = threading.Thread(target=create_late)
    t.start()
    got = IndexClient.read_server_list(path, total_max_timeout=30)
    t.join()
    assert got == [("a", 1), ("b", 2)]


def test_read_server_list_missing_file_times_out(tmp_path):
    path = str(tmp_path / "never.txt")
    with pytest.raises(RuntimeError, match="not created"):
        IndexClient.read_server_list(path, total_max_timeout=0)


def test_read_server_list_empty_file_waits_then_times_out(tmp_path):
    # an empty-but-existing file is a header mid-write, not a fatal state
    path = tmp_path / "empty.txt"
    path.write_text("")
    with pytest.raises(RuntimeError, match="empty"):
        IndexClient.read_server_list(str(path), total_max_timeout=0)


def test_client_ctor_does_not_stomp_global_rng(tmp_path):
    """The reference's random.seed(time.time()) in IndexClient.__init__
    resets the GLOBAL RNG of the host process, breaking reproducibility
    for any suite constructing a client; placement must use a private
    random.Random instance."""
    import socket

    from distributed_faiss_tpu.parallel.server import IndexServer

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    srv = IndexServer(0, str(tmp_path))
    threading.Thread(target=srv.start_blocking, args=(port,), daemon=True).start()
    path = write_list(tmp_path, 1, [("localhost", port)])

    random.seed(1234)
    state_before = random.getstate()
    client = IndexClient(path)
    assert random.getstate() == state_before, (
        "IndexClient.__init__ mutated the global random state"
    )
    # placement still works off the private generator
    assert 0 <= client._rng.randint(0, client.num_indexes - 1) < 1
    client.close()
    srv.stop()


def test_merge_result_blocks():
    a = np.array([[1.0, 3.0], [0.5, 2.0]], np.float32)
    b = np.array([[2.0, 0.1], [4.0, 5.0]], np.float32)
    D, I = merge_result_blocks([a, b], 2)
    np.testing.assert_allclose(D, [[0.1, 1.0], [0.5, 2.0]])
    np.testing.assert_array_equal(I, [[3, 0], [0, 1]])


def mock_server_results(metric_max):
    # two servers, 2 queries, k=3; metadata = ("s{server}", j)
    d0 = np.array([[0.9, 0.5, 0.1], [0.8, 0.6, 0.3]], np.float32)
    d1 = np.array([[0.7, 0.4, 0.2], [1.0, 0.95, 0.05]], np.float32)
    if not metric_max:  # l2-style: ascending best-first within each server
        d0 = np.sort(d0, axis=1)
        d1 = np.sort(d1, axis=1)
    m0 = [[("s0", j) for j in range(3)] for _ in range(2)]
    m1 = [[("s1", j) for j in range(3)] for _ in range(2)]
    return [(d0, m0, None), (d1, m1, None)]


def test_aggregate_results_minimize():
    results = mock_server_results(metric_max=False)
    D, meta = IndexClient._aggregate_results(results, 3, 2, False, False)
    # ascending merge of the two sorted rows
    assert D.shape == (2, 3)
    assert np.all(np.diff(D, axis=1) >= 0)
    # query 0: server0 row [0.1,0.5,0.9], server1 [0.2,0.4,0.7] -> 0.1,0.2,0.4
    np.testing.assert_allclose(D[0], [0.1, 0.2, 0.4])
    assert meta[0][0] == ("s0", 0) and meta[0][1] == ("s1", 0) and meta[0][2] == ("s1", 1)


def test_aggregate_results_maximize():
    results = mock_server_results(metric_max=True)
    D, meta = IndexClient._aggregate_results(results, 3, 2, True, False)
    # dot semantics: D holds NEGATED similarities, ascending
    # (reference client.py:282-294)
    np.testing.assert_allclose(D[1], [-1.0, -0.95, -0.8])
    assert meta[1][0] == ("s1", 0) and meta[1][1] == ("s1", 1) and meta[1][2] == ("s0", 0)
