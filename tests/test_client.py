"""Client unit tests: discovery parsing + result aggregation golden
(model: reference tests/test_client.py:19-39 and
tests/test_integration.py:181-203)."""

import numpy as np
import pytest

from distributed_faiss_tpu.parallel.client import IndexClient, merge_result_blocks


def write_list(tmp_path, count, entries, name="servers.txt"):
    p = tmp_path / name
    lines = [str(count)] + [f"{h},{p_}" for h, p_ in entries]
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_read_server_list_ok(tmp_path):
    path = write_list(tmp_path, 3, [("a", 1), ("b", 2), ("c", 3)])
    assert IndexClient.read_server_list(path) == [("a", 1), ("b", 2), ("c", 3)]


def test_read_server_list_timeout(tmp_path):
    path = write_list(tmp_path, 4, [("a", 1), ("b", 2), ("c", 3)])
    with pytest.raises(RuntimeError) as ei:
        IndexClient.read_server_list(path, total_max_timeout=0)
    assert "4 != 3" in str(ei.value)


def test_merge_result_blocks():
    a = np.array([[1.0, 3.0], [0.5, 2.0]], np.float32)
    b = np.array([[2.0, 0.1], [4.0, 5.0]], np.float32)
    D, I = merge_result_blocks([a, b], 2)
    np.testing.assert_allclose(D, [[0.1, 1.0], [0.5, 2.0]])
    np.testing.assert_array_equal(I, [[3, 0], [0, 1]])


def mock_server_results(metric_max):
    # two servers, 2 queries, k=3; metadata = ("s{server}", j)
    d0 = np.array([[0.9, 0.5, 0.1], [0.8, 0.6, 0.3]], np.float32)
    d1 = np.array([[0.7, 0.4, 0.2], [1.0, 0.95, 0.05]], np.float32)
    if not metric_max:  # l2-style: ascending best-first within each server
        d0 = np.sort(d0, axis=1)
        d1 = np.sort(d1, axis=1)
    m0 = [[("s0", j) for j in range(3)] for _ in range(2)]
    m1 = [[("s1", j) for j in range(3)] for _ in range(2)]
    return [(d0, m0, None), (d1, m1, None)]


def test_aggregate_results_minimize():
    results = mock_server_results(metric_max=False)
    D, meta = IndexClient._aggregate_results(results, 3, 2, False, False)
    # ascending merge of the two sorted rows
    assert D.shape == (2, 3)
    assert np.all(np.diff(D, axis=1) >= 0)
    # query 0: server0 row [0.1,0.5,0.9], server1 [0.2,0.4,0.7] -> 0.1,0.2,0.4
    np.testing.assert_allclose(D[0], [0.1, 0.2, 0.4])
    assert meta[0][0] == ("s0", 0) and meta[0][1] == ("s1", 0) and meta[0][2] == ("s1", 1)


def test_aggregate_results_maximize():
    results = mock_server_results(metric_max=True)
    D, meta = IndexClient._aggregate_results(results, 3, 2, True, False)
    # dot semantics: D holds NEGATED similarities, ascending
    # (reference client.py:282-294)
    np.testing.assert_allclose(D[1], [-1.0, -0.95, -0.8])
    assert meta[1][0] == ("s1", 0) and meta[1][1] == ("s1", 1) and meta[1][2] == ("s0", 0)
