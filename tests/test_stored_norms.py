"""Stored-row-norms parity: the add-time ``||x||^2`` sidecar must be
bit-identical to the in-scan recompute (same decode + same minor-axis fp32
reduction — base.row_norms_f32), across codecs, metrics, capacity growth,
save/load, pre-norms-snapshot backfill, and the sharded masked path."""

import numpy as np
import pytest

from distributed_faiss_tpu.models.ivf import IVFFlatIndex


def build(rng, codec, metric, d=24, n=3000, nlist=16, chunks=3, **kw):
    x = rng.standard_normal((n, d)).astype(np.float32) * 2.0
    idx = IVFFlatIndex(d, nlist, metric, codec=codec, kmeans_iters=3, **kw)
    idx.train(x[: n // 2])
    # multi-batch adds so the norm sidecar rides capacity growth in
    # lockstep with the payload lists
    for c in np.array_split(x, chunks):
        idx.add(c)
    idx.set_nprobe(max(2, nlist // 4))
    return idx, x


@pytest.mark.parametrize("codec,metric", [
    ("f16", "l2"), ("sq8", "l2"), ("f16", "dot"), ("sq8", "dot"),
])
def test_stored_norm_scan_golden_equality(rng, codec, metric):
    """Acceptance: stored-norm scan == recompute scan, bit-exact (fp16 and
    sq8, l2 and dot — dot never touches norms, included as the no-op
    control)."""
    idx, x = build(rng, codec, metric)
    if metric == "l2":
        assert idx.norm_lists.cap == idx.lists.cap
    else:
        # dot never reads norms: no sidecar is built, stored/recompute is a
        # trivially identical no-op pair (kept as the control arm)
        assert idx.norm_lists is None
    q = rng.standard_normal((25, x.shape[1])).astype(np.float32)
    D_stored, I_stored = idx.search(q, 10)
    idx.use_stored_norms = False
    D_rec, I_rec = idx.search(q, 10)
    np.testing.assert_array_equal(I_stored, I_rec)
    np.testing.assert_array_equal(D_stored, D_rec)  # bit-exact, not allclose


def test_stored_norms_match_decoded_rows(rng):
    """The sidecar values themselves equal a direct norm of the decoded
    stored rows (sq8: dequantized codes, not the fp32 input)."""
    from distributed_faiss_tpu.ops import sq

    idx, x = build(rng, "sq8", "l2")
    rows = idx._rows_in_insertion_order()
    deq = np.asarray(sq.sq8_decode(
        np.asarray(rows), idx.sq_params["vmin"], idx.sq_params["span"]))
    want = np.sum(deq.astype(np.float32) ** 2, axis=1)
    got = idx._rows_in_insertion_order(lists=idx.norm_lists)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("codec", ["f16", "sq8"])
def test_save_load_roundtrip_and_prenorm_backfill(rng, codec, tmp_path):
    """Acceptance: a snapshot round-trips bit-exactly, and a PRE-NORMS
    snapshot (no 'list_norms' key — what every pre-this-PR save file looks
    like) backfills norms on load with identical search results."""
    from distributed_faiss_tpu.utils.serialization import load_state, save_state

    idx, x = build(rng, codec, "l2")
    q = rng.standard_normal((12, x.shape[1])).astype(np.float32)
    D, I = idx.search(q, 8)

    path = str(tmp_path / "snap.npz")
    save_state(path, idx.state_dict())
    state = load_state(path)
    assert "list_norms" in state
    re1 = IVFFlatIndex.from_state_dict(state)
    D1, I1 = re1.search(q, 8)
    np.testing.assert_array_equal(I, I1)
    np.testing.assert_array_equal(D, D1)

    # simulate the old on-disk format: drop the norms payload entirely
    state = {k: v for k, v in load_state(path).items() if k != "list_norms"}
    re2 = IVFFlatIndex.from_state_dict(state)
    assert re2.norm_lists is not None and re2.norm_lists.ntotal == idx.ntotal
    D2, I2 = re2.search(q, 8)
    np.testing.assert_array_equal(I, I2)
    np.testing.assert_array_equal(D, D2)


def test_sharded_masked_stored_norms_golden(rng):
    """The sharded masked scan (parallel/mesh.py) uses the same stored-norm
    gather — stored vs recompute must be bit-exact there too, so the two
    scan implementations can't drift."""
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex

    n, d = 2500, 16
    x = rng.standard_normal((n, d)).astype(np.float32)
    idx = ShardedIVFFlatIndex(d, 8, "l2")
    idx.train(x[:1000])
    for c in np.array_split(x, 2):
        idx.add(c)
    idx.set_nprobe(4)
    q = rng.standard_normal((10, d)).astype(np.float32)
    D1, I1 = idx.search(q, 6)
    idx.use_stored_norms = False
    D0, I0 = idx.search(q, 6)
    np.testing.assert_array_equal(I1, I0)
    np.testing.assert_array_equal(D1, D0)


def test_scan_bf16_requires_refine():
    with pytest.raises(ValueError, match="refine_k_factor"):
        IVFFlatIndex(16, 4, "l2", codec="f16", scan_bf16=True)


def test_scan_bf16_with_refine_recall(rng):
    """bf16 scan + exact refine (the lut_bf16 precedent): final results
    match the fp32 pipeline on virtually every query — the shortlist is
    rescored exactly, so only genuine shortlist churn can differ."""
    idx, x = build(rng, "f16", "l2", refine_k_factor=4, scan_bf16=True)
    assert idx.refine_k_factor == 4 and idx.scan_bf16
    ref, _ = build(rng, "f16", "l2")
    ref.centroids = idx.centroids  # same coarse space for comparability
    q = rng.standard_normal((32, x.shape[1])).astype(np.float32)
    _, I_bf = idx.search(q, 10)
    ref.lists, ref.norm_lists = idx.lists, idx.norm_lists
    ref._host_assign, ref._host_pos, ref._n = idx._host_assign, idx._host_pos, idx._n
    ref.set_nprobe(idx.nprobe)
    _, I_f32 = ref.search(q, 10)
    overlap = np.mean([len(set(I_bf[i]) & set(I_f32[i])) / 10
                       for i in range(len(q))])
    assert overlap >= 0.9, overlap


def test_scan_bf16_state_roundtrip(rng):
    idx, x = build(rng, "sq8", "l2", refine_k_factor=4, scan_bf16=True)
    q = rng.standard_normal((8, x.shape[1])).astype(np.float32)
    D, I = idx.search(q, 5)
    re1 = IVFFlatIndex.from_state_dict(idx.state_dict())
    assert re1.scan_bf16 and re1.refine_k_factor == 4
    D1, I1 = re1.search(q, 5)
    np.testing.assert_array_equal(I, I1)
    np.testing.assert_allclose(D, D1, rtol=1e-5, atol=1e-5)


def test_factory_and_engine_knob_plumbing(rng):
    """cfg.extra -> builder -> index attribute plumbing for the new knobs,
    and the engine's runtime stored_norms A/B toggle."""
    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.models.factory import build_index
    from distributed_faiss_tpu.utils.config import IndexCfg

    cfg = IndexCfg(index_builder_type="ivfsq", dim=16, metric="l2",
                   centroids=4, pallas_flat=True, scan_bf16=True,
                   refine_k_factor=4)
    idx = build_index(cfg)
    assert idx.use_pallas and idx.scan_bf16 and idx.refine_k_factor == 4

    # factory grammar channel
    cfg2 = IndexCfg(faiss_factory="IVF4,SQfp16,RFlat", dim=16, metric="l2",
                    centroids=4, scan_bf16=True)
    idx2 = build_index(cfg2)
    assert idx2.scan_bf16 and idx2.refine_k_factor == 8  # RFlat default

    # engine runtime toggle: applied at train time and on upd_cfg
    eng = Index(IndexCfg(index_builder_type="ivfsq", dim=16, metric="l2",
                         train_num=64, buffer_bsz=64, centroids=4,
                         stored_norms=False))
    eng.add_batch(rng.standard_normal((80, 16)).astype(np.float32), None,
                  train_async_if_triggered=False)
    assert eng.tpu_index.use_stored_norms is False
    cfg3 = eng.cfg
    cfg3.extra = dict(cfg3.extra, stored_norms=True)
    eng.upd_cfg(cfg3)
    assert eng.tpu_index.use_stored_norms is True
