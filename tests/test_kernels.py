"""Golden tests: every kernel against numpy brute force (SURVEY §7 step 1)."""

import numpy as np
import pytest

from distributed_faiss_tpu.ops import distance, kmeans, pq, sq


def np_scores(q, x, metric):
    if metric == "dot":
        return q @ x.T
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    return -d


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_pairwise_scores_golden(rng, metric):
    q = rng.standard_normal((7, 32)).astype(np.float32)
    x = rng.standard_normal((50, 32)).astype(np.float32)
    got = np.asarray(distance.pairwise_scores(q, x, metric))
    want = np_scores(q, x, metric)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("metric", ["dot", "l2"])
@pytest.mark.parametrize("chunk", [16, 64, 1024])
def test_knn_golden(rng, metric, chunk):
    q = rng.standard_normal((5, 24)).astype(np.float32)
    x = rng.standard_normal((200, 24)).astype(np.float32)
    k = 10
    vals, ids = distance.knn(q, x, k, metric=metric, chunk=chunk)
    vals, ids = np.asarray(vals), np.asarray(ids)
    want = np_scores(q, x, metric)
    want_ids = np.argsort(-want, axis=1)[:, :k]
    np.testing.assert_array_equal(ids, want_ids)
    np.testing.assert_allclose(vals, np.take_along_axis(want, want_ids, 1), rtol=1e-4, atol=1e-4)


def test_knn_ntotal_masks_padding(rng):
    x = rng.standard_normal((64, 8)).astype(np.float32)
    x[40:] = 0.0  # capacity padding
    q = rng.standard_normal((3, 8)).astype(np.float32)
    vals, ids = distance.knn(q, x, 5, metric="l2", ntotal=40, chunk=16)
    assert np.asarray(ids).max() < 40


def test_merge_topk(rng):
    a = rng.standard_normal((4, 6)).astype(np.float32)
    b = rng.standard_normal((4, 9)).astype(np.float32)
    ia = rng.integers(0, 100, (4, 6)).astype(np.int32)
    ib = rng.integers(100, 200, (4, 9)).astype(np.int32)
    v, i = distance.merge_topk(a, ia, b, ib, 5)
    allv = np.concatenate([a, b], axis=1)
    alli = np.concatenate([ia, ib], axis=1)
    order = np.argsort(-allv, axis=1)[:, :5]
    np.testing.assert_allclose(np.asarray(v), np.take_along_axis(allv, order, 1))
    np.testing.assert_array_equal(np.asarray(i), np.take_along_axis(alli, order, 1))


def test_kmeans_decreases_inertia(rng):
    # Three well-separated blobs: k-means must recover them.
    centers = np.array([[0, 0], [10, 10], [-10, 10]], dtype=np.float32)
    x = np.concatenate(
        [c + rng.standard_normal((100, 2)).astype(np.float32) * 0.5 for c in centers]
    )
    cent = np.asarray(kmeans.kmeans(x, 3, iters=15, chunk=64))
    assert cent.shape == (3, 2)
    # each true center has a learned centroid within 0.5
    d = np.linalg.norm(centers[:, None, :] - cent[None, :, :], axis=-1)
    assert d.min(axis=1).max() < 0.5


def test_kmeans_batched_shapes(rng):
    xs = rng.standard_normal((4, 300, 8)).astype(np.float32)
    cent = np.asarray(kmeans.kmeans_batched(xs, 16, iters=5, chunk=128))
    assert cent.shape == (4, 16, 8)
    # subspaces are independent: different data -> different codebooks
    assert not np.allclose(cent[0], cent[1])


def test_sq8_round_trip(rng):
    x = rng.standard_normal((100, 16)).astype(np.float32) * 3
    params = sq.sq8_train(x)
    codes = sq.sq8_encode(x, params["vmin"], params["span"])
    assert np.asarray(codes).dtype == np.uint8
    rec = np.asarray(sq.sq8_decode(codes, params["vmin"], params["span"]))
    span = np.asarray(params["span"])
    # quantization error bounded by half a grid step per dim
    assert np.max(np.abs(rec - x) / span[None, :]) <= (1.0 / 255.0) * 0.51


def test_pq_round_trip_quality(rng):
    # PQ reconstruction should be far better than random guessing.
    d, m = 32, 8
    x = rng.standard_normal((2000, d)).astype(np.float32)
    cb = pq.pq_train(x, m, iters=10)
    assert np.asarray(cb).shape == (m, 256, d // m)
    codes = pq.pq_encode(x, cb)
    assert np.asarray(codes).shape == (2000, m)
    rec = np.asarray(pq.pq_decode(codes, cb))
    err = np.mean((rec - x) ** 2)
    base = np.mean(x**2)
    assert err < 0.5 * base


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_adc_matches_decoded_distance(rng, metric):
    """ADC(lut, codes) must equal exact score against the decoded vectors."""
    d, m = 16, 4
    x = rng.standard_normal((500, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    cb = pq.pq_train(x, m, iters=8)
    codes = pq.pq_encode(x, cb)
    rec = np.asarray(pq.pq_decode(codes, cb))
    lut = pq.adc_lut(q, cb, metric=metric)
    got = np.asarray(pq.adc_scan_shared(lut, codes))
    want = np_scores(q, rec, metric)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_adc_scan_per_query_lists(rng):
    d, m = 16, 4
    x = rng.standard_normal((300, d)).astype(np.float32)
    q = rng.standard_normal((3, d)).astype(np.float32)
    cb = pq.pq_train(x, m, iters=5)
    codes = np.asarray(pq.pq_encode(x, cb))
    lists = np.stack([codes[0:10], codes[10:20], codes[20:30]])  # (3, 10, m)
    lut = pq.adc_lut(q, cb, metric="l2")
    got = np.asarray(pq.adc_scan(lut, lists))
    rec = np.asarray(pq.pq_decode(codes, cb))
    for qi in range(3):
        want = np_scores(q[qi : qi + 1], rec[qi * 10 : (qi + 1) * 10], "l2")[0]
        np.testing.assert_allclose(got[qi], want, rtol=1e-3, atol=1e-3)


def test_bucket_and_pad():
    assert distance.bucket_size(1) == 8
    assert distance.bucket_size(8) == 8
    assert distance.bucket_size(9) == 16
    x = np.ones((3, 4), np.float32)
    p = distance.pad_rows(x, 8)
    assert p.shape == (8, 4)
    np.testing.assert_array_equal(p[:3], x)
    assert p[3:].sum() == 0


def test_segmented_topk_matches_plain(rng):
    import jax.numpy as jnp
    from distributed_faiss_tpu.ops import distance

    nq, w, k = 4, 8192, 10  # w a multiple of the segment width
    s = jnp.asarray(rng.standard_normal((nq, w)).astype(np.float32))
    gids = jnp.arange(w, dtype=jnp.int32) + 100
    sv, si = distance.segmented_topk(s, k, gids)
    import jax
    pv, pp = jax.lax.top_k(s, k)
    np.testing.assert_allclose(np.asarray(sv), np.asarray(pv))
    np.testing.assert_array_equal(np.asarray(si), np.asarray(pp) + 100)


def test_segmented_topk_fallback_narrow(rng):
    import jax.numpy as jnp
    from distributed_faiss_tpu.ops import distance

    s = jnp.asarray(rng.standard_normal((3, 500)).astype(np.float32))
    gids = jnp.arange(500, dtype=jnp.int32)
    sv, si = distance.segmented_topk(s, 7, gids)
    assert sv.shape == (3, 7) and si.shape == (3, 7)
    assert np.all(np.diff(np.asarray(sv), axis=1) <= 0)


def test_segmented_topk_nonaligned_width_padded(rng):
    """Non-segment-multiple widths take the padded fast path exactly."""
    import jax
    import jax.numpy as jnp
    from distributed_faiss_tpu.ops import distance

    nq, w, k = 3, 5000, 10  # > 2*seg, not a multiple of 2048
    s = jnp.asarray(rng.standard_normal((nq, w)).astype(np.float32))
    gids = jnp.arange(w, dtype=jnp.int32)
    sv, si = distance.segmented_topk(s, k, gids)
    pv, pp = jax.lax.top_k(s, k)
    np.testing.assert_allclose(np.asarray(sv), np.asarray(pv))
    np.testing.assert_array_equal(np.asarray(si), np.asarray(pp))


def test_segmented_topk_pad_columns_yield_minus_one(rng):
    """Regression (round-2 review): when a row has fewer than k finite
    entries and the width is non-aligned, NEG_INF pad slots must carry
    id -1 — not a clamped real column id (which the sharded refine path
    would rescore into a phantom duplicate result)."""
    import jax.numpy as jnp
    from distributed_faiss_tpu.ops import distance

    nq, w, k = 2, 5000, 16  # non-multiple of 2048 -> padded fast path
    s = np.full((nq, w), -np.inf, np.float32)
    s[:, :5] = rng.standard_normal((nq, 5)).astype(np.float32)  # 5 finite
    ids = jnp.asarray(np.arange(w, dtype=np.int32) + 7)
    sv, si = distance.segmented_topk(jnp.asarray(s), k, ids)
    si = np.asarray(si)
    sv = np.asarray(sv)
    assert np.isfinite(sv[:, :5]).all()
    assert (si[:, :5] >= 7).all()
    # every -inf slot: either a real masked column's id or -1, NEVER an id
    # fabricated from the pad region; in this fully--inf tail the only
    # guarantee callers rely on is: ids of -inf slots are allowed to be
    # anything already present in ids[w] OR -1 — pin that pads are -1 by
    # checking no id exceeds the last real column's id
    assert (si <= 7 + w - 1).all()
    rows_ids = jnp.asarray(np.tile(np.arange(w, dtype=np.int32)[None, :], (nq, 1)))
    _, si2 = distance.segmented_topk_rows(jnp.asarray(s), k, rows_ids)
    assert (np.asarray(si2) <= w - 1).all()
