"""IndexCfg unit tests (model: reference tests/test_integration.py:419-421)."""

import json

import pytest

from distributed_faiss_tpu import IndexCfg


def test_defaults():
    cfg = IndexCfg()
    assert cfg.dim == 768
    assert cfg.metric == "dot"
    assert cfg.nprobe == 1
    assert cfg.buffer_bsz == 50000
    assert cfg.save_interval_sec == -1
    assert cfg.extra == {}


def test_extra_kwargs_absorbed():
    # The reference's own fixtures use keys that land in .extra
    # (reference: tests/test_index_config.json, scripts/idx_cfg.json).
    cfg = IndexCfg(dim=128, factory_type="IVFFlat", train_data_ratio=0.5)
    assert cfg.dim == 128
    assert cfg.extra["factory_type"] == "IVFFlat"
    assert cfg.extra["train_data_ratio"] == 0.5


def test_json_round_trip(tmp_path):
    cfg = IndexCfg(
        index_builder_type="knnlm",
        dim=256,
        metric="l2",
        train_num=1000,
        code_size=32,
    )
    p = tmp_path / "cfg.json"
    cfg.save(str(p))
    loaded = IndexCfg.from_json(str(p))
    assert loaded.index_builder_type == "knnlm"
    assert loaded.dim == 256
    assert loaded.metric == "l2"
    assert loaded.train_num == 1000
    assert loaded.extra["code_size"] == 32


def test_from_reference_style_json(tmp_path):
    # A raw (non-round-trip) config file, like scripts/idx_cfg.json in the reference.
    p = tmp_path / "raw.json"
    p.write_text(json.dumps({"dim": 128, "faiss_factory": "IVF{centroids},SQ8", "centroids": 64}))
    cfg = IndexCfg.from_json(str(p))
    assert cfg.faiss_factory == "IVF{centroids},SQ8"
    assert cfg.centroids == 64


def test_bad_metric():
    cfg = IndexCfg(metric="cosine")
    with pytest.raises(RuntimeError):
        cfg.get_metric()
