"""Mesh-scale centroid-tier coverage (the 65,536 tier).

engine.infer_n_centroids mirrors the reference's tiers (index.py:497-508):
corpora past 1e6 rows get 65,536+ centroids. Round 1 never exercised any
k >= 65,536 path; these slow tests run the >16,384-centroid random-seeding
branch of sharded_kmeans, the auto_chunk memory bounding, and a sharded
IVF search at the tier's k on the virtual 8-device mesh.

Geometry is shrunk (small d, n barely above k) to keep the 1-core CPU
suite tractable — the point is exercising the k=65,536 code paths, not
clustering quality at scale (that needs the real-TPU bench).
"""

import numpy as np
import pytest

from distributed_faiss_tpu.engine import infer_n_centroids

K_TIER = 65_536


def test_tier_thresholds_match_reference():
    assert infer_n_centroids(999_999) == int(2 * 999_999 ** 0.5)
    assert infer_n_centroids(1_000_000) == 65_536
    assert infer_n_centroids(9_999_999) == 65_536
    assert infer_n_centroids(10_000_000) == 262_144
    assert infer_n_centroids(100_000_000) == 1_048_576


@pytest.mark.slow
def test_sharded_kmeans_65536_tier(rng):
    from distributed_faiss_tpu.ops.kmeans import auto_chunk
    from distributed_faiss_tpu.parallel.mesh import make_mesh, sharded_kmeans

    mesh = make_mesh()
    n, d = K_TIER + 8_192, 8
    x = rng.standard_normal((n, d)).astype(np.float32)

    chunk = auto_chunk(K_TIER, None)
    # memory bound auto_chunk enforces: chunk x k distance block stays
    # well under HBM scale even at the megacentroid tiers
    assert chunk * K_TIER * 4 <= 2 ** 31

    cent = np.asarray(sharded_kmeans(mesh, x, K_TIER, iters=1))
    assert cent.shape == (K_TIER, d)
    assert np.isfinite(cent).all()

    # seeding quality of the >16,384 random-init branch: seeds are drawn
    # from the data, so after one Lloyd step no centroid may escape the
    # data's bounding box, and the centroid set must not collapse
    lo, hi = x.min(0) - 1e-3, x.max(0) + 1e-3
    assert (cent >= lo).all() and (cent <= hi).all()
    sample = cent[rng.permutation(K_TIER)[:4096]]
    dists = np.linalg.norm(sample[:-1] - sample[1:], axis=1)
    assert np.median(dists) > 1e-3  # not collapsed onto one point


@pytest.mark.slow
def test_sharded_ivf_search_at_65536_lists(rng):
    """End-to-end sharded IVF-Flat with nlist = the 65,536 tier: train
    (random-seed branch), add (chunked coarse assignment over the mesh),
    search (probe gather + ICI merge) — golden-checked against exact."""
    from distributed_faiss_tpu.models.flat import FlatIndex
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex, make_mesh

    n, d, k = K_TIER + 8_192, 8, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = x[:8] + 0.01 * rng.standard_normal((8, d)).astype(np.float32)

    idx = ShardedIVFFlatIndex(d, K_TIER, "l2", mesh=make_mesh(), kmeans_iters=1)
    idx.train(x)
    idx.add(x)
    assert idx.ntotal == n

    exact = FlatIndex(d, "l2")
    exact.add(x)
    _, gt = exact.search(q, k)

    idx.set_nprobe(64)
    _, ids = idx.search(q, k)
    # near-duplicate queries: the true nearest neighbor's list is probed
    # with near-certainty; most of the top-10 should agree with exact
    overlap = np.mean([len(set(ids[i]) & set(gt[i])) / k for i in range(len(q))])
    assert overlap >= 0.5, overlap
    # the self-neighbor must be found (its own centroid is always probed)
    assert all(gt[i][0] in ids[i] for i in range(len(q)))


@pytest.mark.slow
@pytest.mark.scale
def test_sharded_kmeans_262144_tier(rng):
    """The 262,144-centroid tier (corpora past 1e7 rows, reference
    index.py:497-508) — never exercised before r4. Same invariants as the
    65,536-tier test one tier up: the random-seed branch, the auto_chunk
    byte bound, bounding-box containment, and no centroid collapse."""
    from distributed_faiss_tpu.ops.kmeans import auto_chunk
    from distributed_faiss_tpu.parallel.mesh import make_mesh, sharded_kmeans

    k = 262_144
    mesh = make_mesh()
    # d=2 keeps the n x k x d assignment FLOPs tractable on the 1-core CPU
    # suite; the tier's code paths (random seeding, chunk bound, psum
    # accumulation shapes) do not depend on d
    n, d = k + 4_096, 2
    x = rng.standard_normal((n, d)).astype(np.float32)

    chunk = auto_chunk(k, None)
    assert chunk * k * 4 <= 2 ** 31

    cent = np.asarray(sharded_kmeans(mesh, x, k, iters=1))
    assert cent.shape == (k, d)
    assert np.isfinite(cent).all()
    lo, hi = x.min(0) - 1e-3, x.max(0) + 1e-3
    assert (cent >= lo).all() and (cent <= hi).all()
    sample = cent[rng.permutation(k)[:4096]]
    dists = np.linalg.norm(sample[:-1] - sample[1:], axis=1)
    assert np.median(dists) > 1e-4  # not collapsed onto one point


@pytest.mark.slow
@pytest.mark.scale
def test_sharded_kmeans_and_routed_search_1048576_tier(rng):
    """The 1,048,576-centroid tier (corpora past 1e8 rows, reference
    index.py:505-508) — the last unexercised tier (VERDICT r4 #4): the
    random-seed branch at k=1M, one sharded Lloyd psum step against the
    1M-centroid table, the int32 cell-space guard, and a routed sharded
    IVF search over the million-list layout.

    A full Lloyd pass at this tier is n*k ~ 1e12 pair-FLOPs — an hour on
    the 1-core CPU suite — so seeding runs iters=0 (the full pass at 1M is
    real-TPU bench territory) and the psum step is exercised explicitly on
    a small row batch against all 1M centroids: the (k, d) sum / (k,)
    count accumulation shapes and the chunk loop are what this tier
    changes, and they do not depend on the batch size."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_faiss_tpu.ops.kmeans import auto_chunk
    from distributed_faiss_tpu.parallel.mesh import (
        AXIS, ShardedIVFFlatIndex, ShardedPaddedLists, _kmeans_step_jit,
        make_mesh, sharded_kmeans)

    k = 1_048_576
    mesh = make_mesh()
    n, d = k + 4_096, 2
    x = rng.standard_normal((n, d)).astype(np.float32)

    chunk = auto_chunk(k, None)
    assert chunk * k * 4 <= 2 ** 31

    # int32 flat-cell-address guard: 1M padded lists at cap 4096 overflows
    # int32 addressing and must be refused, not silently wrapped
    with pytest.raises(ValueError, match="int32"):
        ShardedPaddedLists(k, (d,), np.float32, mesh, min_cap=4096)

    # seeds-from-data branch at k=1M
    cent = sharded_kmeans(mesh, x, k, iters=0)
    cent_np = np.asarray(cent)
    assert cent_np.shape == (k, d)
    assert np.isfinite(cent_np).all()
    lo, hi = x.min(0) - 1e-3, x.max(0) + 1e-3
    assert (cent_np >= lo).all() and (cent_np <= hi).all()
    sample = cent_np[rng.permutation(k)[:4096]]
    dists = np.linalg.norm(sample[:-1] - sample[1:], axis=1)
    assert np.median(dists) > 1e-4  # not collapsed onto one point

    # one sharded Lloyd psum step against the full 1M-centroid table
    S = mesh.shape[AXIS]
    nb = chunk * S  # minimal batch that divides per-shard rows by chunk
    xb = x[:nb]
    wb = np.ones(nb, np.float32)
    xs = jax.device_put(jnp.asarray(xb), NamedSharding(mesh, P(AXIS, None)))
    ws = jax.device_put(jnp.asarray(wb), NamedSharding(mesh, P(AXIS)))
    stepped = np.asarray(_kmeans_step_jit(xs, ws, cent, mesh, k, chunk))
    assert stepped.shape == (k, d)
    assert np.isfinite(stepped).all()
    # empty centroids keep their seed; touched ones move inside the bbox
    moved = np.abs(stepped - cent_np).max(1) > 0
    assert 0 < moved.sum() <= nb
    assert (stepped >= lo).all() and (stepped <= hi).all()

    # routed search over the million-list layout
    idx = ShardedIVFFlatIndex(d, k, "l2", mesh=mesh, probe_routing=True)
    idx.centroids = cent
    idx.lists = idx._make_lists()
    idx.add(x[:4096])
    assert idx.ntotal == 4096
    idx.set_nprobe(16)
    q = x[:8] + 1e-3 * rng.standard_normal((8, d)).astype(np.float32)
    D, I = idx.search(q, 5)
    assert I.shape == (8, 5)
    assert (I[:, 0] >= 0).all()
    # near-duplicate queries: row i's own list is probed with
    # near-certainty even among a million lists (centroids ARE data rows)
    hits = sum(i in I[i] for i in range(8))
    assert hits >= 6, (hits, I)
