"""Mesh-scale centroid-tier coverage (the 65,536 tier).

engine.infer_n_centroids mirrors the reference's tiers (index.py:497-508):
corpora past 1e6 rows get 65,536+ centroids. Round 1 never exercised any
k >= 65,536 path; these slow tests run the >16,384-centroid random-seeding
branch of sharded_kmeans, the auto_chunk memory bounding, and a sharded
IVF search at the tier's k on the virtual 8-device mesh.

Geometry is shrunk (small d, n barely above k) to keep the 1-core CPU
suite tractable — the point is exercising the k=65,536 code paths, not
clustering quality at scale (that needs the real-TPU bench).
"""

import numpy as np
import pytest

from distributed_faiss_tpu.engine import infer_n_centroids

K_TIER = 65_536


def test_tier_thresholds_match_reference():
    assert infer_n_centroids(999_999) == int(2 * 999_999 ** 0.5)
    assert infer_n_centroids(1_000_000) == 65_536
    assert infer_n_centroids(9_999_999) == 65_536
    assert infer_n_centroids(10_000_000) == 262_144
    assert infer_n_centroids(100_000_000) == 1_048_576


@pytest.mark.slow
def test_sharded_kmeans_65536_tier(rng):
    from distributed_faiss_tpu.ops.kmeans import auto_chunk
    from distributed_faiss_tpu.parallel.mesh import make_mesh, sharded_kmeans

    mesh = make_mesh()
    n, d = K_TIER + 8_192, 8
    x = rng.standard_normal((n, d)).astype(np.float32)

    chunk = auto_chunk(K_TIER, None)
    # memory bound auto_chunk enforces: chunk x k distance block stays
    # well under HBM scale even at the megacentroid tiers
    assert chunk * K_TIER * 4 <= 2 ** 31

    cent = np.asarray(sharded_kmeans(mesh, x, K_TIER, iters=1))
    assert cent.shape == (K_TIER, d)
    assert np.isfinite(cent).all()

    # seeding quality of the >16,384 random-init branch: seeds are drawn
    # from the data, so after one Lloyd step no centroid may escape the
    # data's bounding box, and the centroid set must not collapse
    lo, hi = x.min(0) - 1e-3, x.max(0) + 1e-3
    assert (cent >= lo).all() and (cent <= hi).all()
    sample = cent[rng.permutation(K_TIER)[:4096]]
    dists = np.linalg.norm(sample[:-1] - sample[1:], axis=1)
    assert np.median(dists) > 1e-3  # not collapsed onto one point


@pytest.mark.slow
def test_sharded_ivf_search_at_65536_lists(rng):
    """End-to-end sharded IVF-Flat with nlist = the 65,536 tier: train
    (random-seed branch), add (chunked coarse assignment over the mesh),
    search (probe gather + ICI merge) — golden-checked against exact."""
    from distributed_faiss_tpu.models.flat import FlatIndex
    from distributed_faiss_tpu.parallel.mesh import ShardedIVFFlatIndex, make_mesh

    n, d, k = K_TIER + 8_192, 8, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = x[:8] + 0.01 * rng.standard_normal((8, d)).astype(np.float32)

    idx = ShardedIVFFlatIndex(d, K_TIER, "l2", mesh=make_mesh(), kmeans_iters=1)
    idx.train(x)
    idx.add(x)
    assert idx.ntotal == n

    exact = FlatIndex(d, "l2")
    exact.add(x)
    _, gt = exact.search(q, k)

    idx.set_nprobe(64)
    _, ids = idx.search(q, k)
    # near-duplicate queries: the true nearest neighbor's list is probed
    # with near-certainty; most of the top-10 should agree with exact
    overlap = np.mean([len(set(ids[i]) & set(gt[i])) / k for i in range(len(q))])
    assert overlap >= 0.5, overlap
    # the self-neighbor must be found (its own centroid is always probed)
    assert all(gt[i][0] in ids[i] for i in range(len(q)))


@pytest.mark.slow
@pytest.mark.scale
def test_sharded_kmeans_262144_tier(rng):
    """The 262,144-centroid tier (corpora past 1e7 rows, reference
    index.py:497-508) — never exercised before r4. Same invariants as the
    65,536-tier test one tier up: the random-seed branch, the auto_chunk
    byte bound, bounding-box containment, and no centroid collapse."""
    from distributed_faiss_tpu.ops.kmeans import auto_chunk
    from distributed_faiss_tpu.parallel.mesh import make_mesh, sharded_kmeans

    k = 262_144
    mesh = make_mesh()
    # d=2 keeps the n x k x d assignment FLOPs tractable on the 1-core CPU
    # suite; the tier's code paths (random seeding, chunk bound, psum
    # accumulation shapes) do not depend on d
    n, d = k + 4_096, 2
    x = rng.standard_normal((n, d)).astype(np.float32)

    chunk = auto_chunk(k, None)
    assert chunk * k * 4 <= 2 ** 31

    cent = np.asarray(sharded_kmeans(mesh, x, k, iters=1))
    assert cent.shape == (k, d)
    assert np.isfinite(cent).all()
    lo, hi = x.min(0) - 1e-3, x.max(0) + 1e-3
    assert (cent >= lo).all() and (cent <= hi).all()
    sample = cent[rng.permutation(k)[:4096]]
    dists = np.linalg.norm(sample[:-1] - sample[1:], axis=1)
    assert np.median(dists) > 1e-4  # not collapsed onto one point
