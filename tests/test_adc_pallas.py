"""Pallas ADC kernel golden tests (interpreter mode on CPU — same kernel
code path the TPU runs compiled)."""

import numpy as np
import pytest

from distributed_faiss_tpu.ops import adc_pallas, pq


@pytest.fixture
def problem(rng):
    nq, m, ksub, L = 8, 4, 256, 700  # L deliberately not a tile multiple
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (L, m)).astype(np.uint8)
    return lut, codes


def np_adc(lut, codes):
    nq = lut.shape[0]
    L = codes.shape[0]
    out = np.zeros((nq, L), np.float32)
    for mi in range(codes.shape[1]):
        out += lut[:, mi, codes[:, mi].astype(np.int64)]
    return out


def test_shared_kernel_golden(problem):
    lut, codes = problem
    got = np.asarray(adc_pallas.adc_scan_shared_pallas(lut, codes, tile=128, interpret=True))
    np.testing.assert_allclose(got, np_adc(lut, codes), rtol=1e-5, atol=1e-5)


def test_shared_kernel_matches_xla_path(problem):
    lut, codes = problem
    got = np.asarray(adc_pallas.adc_scan_shared_auto(lut, codes, tile=256))
    want = np.asarray(pq.adc_scan_shared(lut, codes))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_per_query_kernel_golden(rng):
    nq, m, ksub, L = 5, 8, 256, 300
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (nq, L, m)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_pallas(lut, codes, tile=128, interpret=True))
    want = np.asarray(pq.adc_scan(lut, codes))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_bf16_lut_close_to_f32(problem):
    """bf16 LUT (the fast serving mode, 1.5x on TPU v5e): one-hot side is
    exact, so error is bounded by bf16 rounding of the LUT entries."""
    import jax.numpy as jnp

    lut, codes = problem
    got = np.asarray(adc_pallas.adc_scan_shared_pallas(
        jnp.asarray(lut).astype(jnp.bfloat16), codes, tile=128, interpret=True))
    want = np_adc(lut, codes)
    # m=4 sums of bf16-rounded values (~0.4% rel each)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_bf16_lut_ivfpq_with_refine_recall(rng):
    """End-to-end: adc_lut_bf16 + refine matches the f32 pipeline's recall
    (the refine stage rescores the shortlist exactly either way)."""
    from distributed_faiss_tpu.models.ivf import IVFPQIndex

    n, d = 3000, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((16, d)).astype(np.float32)

    def build(**kw):
        idx = IVFPQIndex(d, 16, m=8, metric="l2", kmeans_iters=4, pq_iters=4,
                         refine_k_factor=4, **kw)
        idx.train(x[:2000])
        idx.add(x)
        idx.set_nprobe(8)
        return idx

    _, ids_f32 = build(use_pallas=True).search(q, 10)
    _, ids_bf16 = build(use_pallas=True, adc_lut_bf16=True).search(q, 10)
    overlap = np.mean([
        len(set(ids_f32[i]) & set(ids_bf16[i])) / 10 for i in range(len(q))
    ])
    assert overlap >= 0.9, overlap


def test_tiny_list(rng):
    lut = rng.standard_normal((2, 4, 256)).astype(np.float32)
    codes = rng.integers(0, 256, (3, 4)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_shared_pallas(lut, codes, interpret=True))
    np.testing.assert_allclose(got, np_adc(lut, codes), rtol=1e-5, atol=1e-5)


def test_nibble_kernel_golden(rng):
    nq, m, ksub, L = 5, 8, 256, 300  # L not a tile multiple
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (nq, L, m)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_pallas_nibble(lut, codes, tile=128, interpret=True))
    want = np.zeros((nq, L), np.float32)
    for qi in range(nq):
        for mi in range(m):
            want[qi] += lut[qi, mi, codes[qi, :, mi].astype(np.int64)]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_nibble_matches_onehot_kernel(rng):
    """Nibble decomposition must reproduce the one-hot kernel (same rounding
    class: f32 accumulation of exact LUT values)."""
    nq, m, ksub, L = 4, 64, 256, 520  # flagship m
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (nq, L, m)).astype(np.uint8)
    a = np.asarray(adc_pallas.adc_scan_pallas_nibble(lut, codes, tile=256, interpret=True))
    b = np.asarray(adc_pallas.adc_scan_pallas(lut, codes, tile=256, interpret=True))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_nibble_bf16_lut(rng):
    nq, m, ksub, L = 3, 16, 256, 200
    import jax.numpy as jnp

    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (nq, L, m)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_pallas_nibble(
        jnp.asarray(lut).astype(jnp.bfloat16), codes, tile=128, interpret=True))
    want = np.asarray(pq.adc_scan(lut, codes))
    # bf16 LUT rounding only (~0.4% rel)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_nibble_auto_dispatch(rng, monkeypatch):
    """adc_scan_auto picks nibble when geometry allows, one-hot otherwise."""
    calls = []
    orig_nib = adc_pallas.adc_scan_pallas_nibble
    orig_old = adc_pallas.adc_scan_pallas

    def spy_nib(*a, **k):
        calls.append("nibble")
        return orig_nib(*a, **k)

    def spy_old(*a, **k):
        calls.append("onehot")
        return orig_old(*a, **k)

    monkeypatch.setattr(adc_pallas, "adc_scan_pallas_nibble", spy_nib)
    monkeypatch.setattr(adc_pallas, "adc_scan_pallas", spy_old)
    lut8 = rng.standard_normal((2, 8, 256)).astype(np.float32)
    codes8 = rng.integers(0, 256, (2, 64, 8)).astype(np.uint8)
    adc_pallas.adc_scan_auto(lut8, codes8)
    lut4 = rng.standard_normal((2, 4, 256)).astype(np.float32)
    codes4 = rng.integers(0, 256, (2, 64, 4)).astype(np.uint8)
    adc_pallas.adc_scan_auto(lut4, codes4)  # m=4 -> one-hot fallback
    assert calls == ["nibble", "onehot"]


def test_auto_forwards_explicit_tile(rng, monkeypatch):
    """An explicit tile reaches whichever kernel dispatches; tile=None lets
    each kernel use its own tuned default (ADVICE r3)."""
    seen = {}
    orig_nib = adc_pallas.adc_scan_pallas_nibble

    def spy_nib(lut, codes, **k):
        seen.update(k)
        return orig_nib(lut, codes, **k)

    monkeypatch.setattr(adc_pallas, "adc_scan_pallas_nibble", spy_nib)
    lut = rng.standard_normal((1, 8, 256)).astype(np.float32)
    codes = rng.integers(0, 256, (1, 64, 8)).astype(np.uint8)
    adc_pallas.adc_scan_auto(lut, codes)
    assert "tile" not in seen
    adc_pallas.adc_scan_auto(lut, codes, tile=256)
    assert seen["tile"] == 256


def test_pallas_degrade_ladder(rng, monkeypatch):
    """A nibble-kernel failure falls back to the one-hot pallas kernel, not
    straight to XLA; a one-hot failure then falls to XLA (ADVICE r3)."""
    from distributed_faiss_tpu.models import ivf as ivfmod
    from distributed_faiss_tpu.models.ivf import IVFPQIndex

    n, d, m = 1500, 32, 8
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((6, d)).astype(np.float32)
    idx = IVFPQIndex(d, 8, m=m, metric="dot", kmeans_iters=3, pq_iters=3,
                     use_pallas=True)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    ref = IVFPQIndex(d, 8, m=m, metric="dot", kmeans_iters=3, pq_iters=3,
                     use_pallas=False)
    ref.centroids, ref.codebooks = idx.centroids, idx.codebooks
    ref.lists = idx.lists
    ref._n = idx._n
    ref.set_nprobe(4)
    want_d, want_i = ref.search(q, 5)

    def boom(*a, **k):
        raise RuntimeError("kernel abort (injected)")

    # drop compiled variants so the injected failure is actually reached
    ivfmod._ivf_pq_search.clear_cache()
    monkeypatch.setattr(adc_pallas, "USE_NIBBLE", True)
    monkeypatch.setattr(adc_pallas, "NIBBLE_SWEPT", False)
    monkeypatch.setattr(adc_pallas, "NIBBLE_EXCUSES_LEFT", 8)
    monkeypatch.setattr(ivfmod, "_BOTH_FAILED_SIGS", set())
    monkeypatch.setattr(adc_pallas, "adc_scan_pallas_nibble", boom)

    # a user error (bad dim) re-raises from the XLA oracle with every
    # kernel flag untouched — no demotion, no cache wipe
    with pytest.raises(Exception):
        idx.search(rng.standard_normal((2, d + 1)).astype(np.float32), 5)
    assert adc_pallas.USE_NIBBLE is True
    assert idx._pallas_runtime_ok

    got_d, got_i = idx.search(q, 5)
    assert adc_pallas.USE_NIBBLE is False, "nibble not demoted"
    assert idx._pallas_runtime_ok, "one-hot pallas abandoned with the nibble"
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)

    # now the one-hot kernel breaks too. The first failure is excused as a
    # possible stale pre-demotion executable (ADVICE r4: caches swept, the
    # request served from the XLA result in hand, NO synchronous re-trace);
    # the second failure — necessarily a fresh trace — demotes pallas.
    ivfmod._ivf_pq_search.clear_cache()
    monkeypatch.setattr(adc_pallas, "adc_scan_pallas", boom)
    got_d, got_i = idx.search(q, 5)
    assert idx._pallas_runtime_ok, "demoted on the excusable first failure"
    assert adc_pallas.NIBBLE_SWEPT is True
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)
    got_d, got_i = idx.search(q, 5)
    assert not idx._pallas_runtime_ok
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_allclose(got_d, want_d, rtol=1e-4, atol=1e-4)


def test_nibble_consumer_registry_complete():
    """Every jitted program that bakes the adc_scan_auto dispatch in at
    trace time must be registered, or disable_nibble leaves a stale
    nibble executable behind and the ladder misattributes the next fault."""
    from distributed_faiss_tpu.models import ivf as ivfmod
    from distributed_faiss_tpu.parallel import mesh as meshmod

    registered = {id(f) for f in adc_pallas.NIBBLE_JIT_CONSUMERS}
    expected = [
        ivfmod._ivf_pq_search, ivfmod._ivf_pq_search_fused,
        meshmod._sharded_ivf_pq_search, meshmod._sharded_ivf_pq_search_fused,
        meshmod._sharded_ivf_pq_search_routed,
    ]
    assert all(id(f) in registered for f in expected)
    assert len(adc_pallas.NIBBLE_JIT_CONSUMERS) == len(expected)

    # tripwire against silent drift: a NEW adc_scan_auto call site means a
    # new (possibly unregistered) consumer — this count forces whoever adds
    # one to register its enclosing jitted program(s) and update both lists
    import inspect

    sites = sum(inspect.getsource(mod).count("adc_scan_auto(")
                for mod in (ivfmod, meshmod))
    assert sites == 3, (
        "adc_scan_auto call-site count changed: register the new consumer "
        "in NIBBLE_JIT_CONSUMERS and update this test")


def test_both_failed_repeat_demotes_nibble(monkeypatch):
    """When kernel AND oracle fail with messages that normalize equal (e.g.
    OOMs differing only in byte counts), the first request is read as 'bad
    request' (no demotion, no cache wipe), but a repeat of the SAME failure
    signature demotes the nibble kernel — never-demoting would re-fault
    every search forever. Distinct bad requests never accumulate."""
    from distributed_faiss_tpu.models import ivf as ivfmod

    class FakeIdx:
        use_pallas = True
        _pallas_runtime_ok = True

    def oom_call(use_pallas):
        if use_pallas:
            raise RuntimeError("RESOURCE_EXHAUSTED allocating 8589934592 bytes")
        raise RuntimeError("RESOURCE_EXHAUSTED allocating 17179869184 bytes")

    def other_bad_call(use_pallas):
        raise RuntimeError("dim mismatch: got 33, want 32")

    monkeypatch.setattr(adc_pallas, "USE_NIBBLE", True)
    monkeypatch.setattr(ivfmod, "_BOTH_FAILED_SIGS", set())
    assert adc_pallas.nibble_supported(8, 256)

    with pytest.raises(RuntimeError):
        ivfmod.pallas_guarded(FakeIdx(), oom_call, 8, 256)
    assert adc_pallas.USE_NIBBLE is True, "one bad request must not demote"

    # a DIFFERENT bad request in between must not count toward the repeat
    with pytest.raises(RuntimeError):
        ivfmod.pallas_guarded(FakeIdx(), other_bad_call, 8, 256)
    assert adc_pallas.USE_NIBBLE is True, "distinct signatures accumulated"

    # the OOM signature repeating demotes — the interleaved unrelated bad
    # request must NOT have displaced it (signature set, not single slot)
    with pytest.raises(RuntimeError):
        ivfmod.pallas_guarded(FakeIdx(), oom_call, 8, 256)
    assert adc_pallas.USE_NIBBLE is False, "repeated signature must demote"

    # genuinely distinct failures demote immediately (reset state first)
    monkeypatch.setattr(adc_pallas, "USE_NIBBLE", True)
    monkeypatch.setattr(ivfmod, "_BOTH_FAILED_SIGS", set())

    def distinct_call(use_pallas):
        if use_pallas:
            raise RuntimeError("kernel abort")
        raise ValueError("one-hot materialization OOM")

    with pytest.raises(ValueError):
        ivfmod.pallas_guarded(FakeIdx(), distinct_call, 8, 256)
    assert adc_pallas.USE_NIBBLE is False


def test_stale_executable_excuse_covers_concurrent_inflight(monkeypatch):
    """Two in-flight searches whose traces predate a concurrent nibble
    demotion must BOTH be excused (served via XLA, pallas kept) — the sweep
    epoch moves under the first excuse, covering the second (r5 review)."""
    from distributed_faiss_tpu.models import ivf as ivfmod

    class FakeIdx:
        use_pallas = True
        _pallas_runtime_ok = True

    monkeypatch.setattr(adc_pallas, "USE_NIBBLE", False)  # demotion landed
    monkeypatch.setattr(adc_pallas, "NIBBLE_SWEPT", True)  # excuse spent
    monkeypatch.setattr(adc_pallas, "NIBBLE_EXCUSES_LEFT", 2)
    epoch0 = adc_pallas.NIBBLE_SWEEP_EPOCH
    monkeypatch.setattr(adc_pallas, "NIBBLE_SWEEP_EPOCH", epoch0)

    # pallas_guarded captures the epoch at entry; emulate "this call's trace
    # started before the concurrent demotion's sweep" by rewinding the epoch
    # before each entry and bumping it from inside the failing pallas call
    # (the moment the demotion sweep would land)
    def stale_exec(use_pallas):
        if use_pallas:
            adc_pallas.NIBBLE_SWEEP_EPOCH = epoch0 + 1
            raise RuntimeError("stale nibble executable abort")
        return "xla-result"

    idx_a, idx_b = FakeIdx(), FakeIdx()
    adc_pallas.NIBBLE_SWEEP_EPOCH = epoch0
    assert ivfmod.pallas_guarded(idx_a, stale_exec, 8, 256) == "xla-result"
    assert idx_a._pallas_runtime_ok, "in-flight stale executable demoted pallas"
    adc_pallas.NIBBLE_SWEEP_EPOCH = epoch0
    assert ivfmod.pallas_guarded(idx_b, stale_exec, 8, 256) == "xla-result"
    assert idx_b._pallas_runtime_ok, "second in-flight victim demoted pallas"

    # budget exhausted: a further "stale-looking" failure is no longer
    # excused — a genuinely broken one-hot kernel under constant concurrency
    # must converge to the XLA path, not excuse itself forever (r5 review)
    assert adc_pallas.NIBBLE_EXCUSES_LEFT == 0
    idx_c = FakeIdx()
    adc_pallas.NIBBLE_SWEEP_EPOCH = epoch0
    assert ivfmod.pallas_guarded(idx_c, stale_exec, 8, 256) == "xla-result"
    assert idx_c._pallas_runtime_ok is False, "budget spent yet still excused"
