"""Pallas ADC kernel golden tests (interpreter mode on CPU — same kernel
code path the TPU runs compiled)."""

import numpy as np
import pytest

from distributed_faiss_tpu.ops import adc_pallas, pq


@pytest.fixture
def problem(rng):
    nq, m, ksub, L = 8, 4, 256, 700  # L deliberately not a tile multiple
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (L, m)).astype(np.uint8)
    return lut, codes


def np_adc(lut, codes):
    nq = lut.shape[0]
    L = codes.shape[0]
    out = np.zeros((nq, L), np.float32)
    for mi in range(codes.shape[1]):
        out += lut[:, mi, codes[:, mi].astype(np.int64)]
    return out


def test_shared_kernel_golden(problem):
    lut, codes = problem
    got = np.asarray(adc_pallas.adc_scan_shared_pallas(lut, codes, tile=128, interpret=True))
    np.testing.assert_allclose(got, np_adc(lut, codes), rtol=1e-5, atol=1e-5)


def test_shared_kernel_matches_xla_path(problem):
    lut, codes = problem
    got = np.asarray(adc_pallas.adc_scan_shared_auto(lut, codes, tile=256))
    want = np.asarray(pq.adc_scan_shared(lut, codes))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_per_query_kernel_golden(rng):
    nq, m, ksub, L = 5, 8, 256, 300
    lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
    codes = rng.integers(0, 256, (nq, L, m)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_pallas(lut, codes, tile=128, interpret=True))
    want = np.asarray(pq.adc_scan(lut, codes))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_tiny_list(rng):
    lut = rng.standard_normal((2, 4, 256)).astype(np.float32)
    codes = rng.integers(0, 256, (3, 4)).astype(np.uint8)
    got = np.asarray(adc_pallas.adc_scan_shared_pallas(lut, codes, interpret=True))
    np.testing.assert_allclose(got, np_adc(lut, codes), rtol=1e-5, atol=1e-5)
