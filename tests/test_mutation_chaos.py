"""Mutation acceptance gate (ISSUE 9): on an R=2 replicated cluster under
a live mux query storm, delete 30% of one group's ids, trigger
compaction, SIGKILL the compacting rank mid-pass — no deleted id may ever
appear in any storm result, the rank must restart on the pre-compaction
generation with tombstones intact, and post-restart results must be
byte-identical to a freshly built index over the surviving rows."""

import os
import socket
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu.models.flat import FlatIndex
from distributed_faiss_tpu.parallel.client import IndexClient
from distributed_faiss_tpu.testing.chaos import QueryStorm, ServerHarness
from distributed_faiss_tpu.utils import serialization
from distributed_faiss_tpu.utils.config import IndexCfg, ReplicationCfg
from distributed_faiss_tpu.utils.state import IndexState

pytestmark = [pytest.mark.mutation, pytest.mark.chaos, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# DFT_COMPACT=0: the gate triggers compaction explicitly (compact_index)
# so the SIGKILL lands deterministically inside the widened mid-pass
# window (DFT_COMPACT_TEST_DELAY_S)
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT,
       "DFT_COMPACT": "0", "DFT_COMPACT_TEST_DELAY_S": "4.0"}

DIM = 16


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def flat_cfg():
    return IndexCfg(index_builder_type="flat", dim=DIM, metric="l2",
                    train_num=50)


def wait_drained(client, index_id, n, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if (client.get_state(index_id) == IndexState.TRAINED
                and client.get_buffer_depth(index_id) == 0
                and client.get_ntotal(index_id) >= n):
            return
        time.sleep(0.2)
    raise AssertionError(f"cluster never drained to {n} indexed rows")


def test_sigkill_mid_compaction_under_storm_gate(tmp_path):
    """The gate, end to end:

    1. healthy R=2 cluster (4 ranks, 2 groups), 300 rows ingested + saved;
    2. delete 30% of group 0's ids cluster-wide (quorum delete);
    3. golden = post-delete search; verified byte-identical against a
       freshly built local index over the surviving rows;
    4. 4-thread mux query storm; trigger compaction on one group-0 replica
       and SIGKILL it inside the pass (before its commit point);
    5. zero storm errors, every storm result byte-identical to golden, no
       deleted id in any result (failover + the peer's tombstones);
    6. restart the victim from storage: it comes back on the
       PRE-compaction generation with tombstones intact (sidecar), pinned
       reads serve golden again on the same client.
    """
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    with ServerHarness(4, disc, storage, base_port=free_port(), env=ENV) as h:
        client = IndexClient(
            disc, replication_cfg=ReplicationCfg(replication=2,
                                                 write_quorum=1))
        client.create_index("gidx", flat_cfg())
        rng = np.random.default_rng(0)
        x = rng.standard_normal((300, DIM)).astype(np.float32)
        for s in range(0, 300, 50):
            client.add_index_data("gidx", x[s:s + 50],
                                  [(i,) for i in range(s, s + 50)])
        wait_drained(client, "gidx", 300)
        client.save_index("gidx")

        # ---- delete 30% of ONE group's ids (cluster-wide quorum delete)
        group = 0
        g0_pos = client.membership.replicas(group)[0]
        g0_ids = sorted(client.sub_indexes[g0_pos].generic_fun(
            "get_ids", ("gidx",)))
        doomed = g0_ids[: max(1, int(0.3 * len(g0_ids)))]
        removed = client.remove_ids("gidx", doomed)
        assert removed == len(doomed)
        dead_meta = {(i,) for i in doomed}

        # ---- golden AND the freshly-built-over-survivors oracle
        q = np.ascontiguousarray(x[:8])
        g_scores, g_meta = client.search(q, 5, "gidx")
        survivors = [i for i in range(300) if i not in set(doomed)]
        fresh = FlatIndex(DIM, "l2")
        fresh.train(x)
        fresh.add(x[survivors])
        f_scores, f_ids = fresh.search(q, 5)
        np.testing.assert_array_equal(g_scores, f_scores)
        assert g_meta == [[(survivors[j],) for j in row]
                          for row in f_ids.tolist()]

        # ---- storm + compaction + SIGKILL mid-pass
        victim_pos = g0_pos
        victim_rank = client.sub_indexes[victim_pos].port - h.base_port
        victim_dir = os.path.join(storage, "gidx", str(victim_rank))
        gens_before = serialization.list_generations(victim_dir)
        assert gens_before, "victim never committed its save"

        compact_err = []

        def trigger_compaction():
            try:
                client.sub_indexes[victim_pos].generic_fun(
                    "compact_index", ("gidx",), timeout=30.0)
            except Exception as e:  # the kill lands mid-call: expected
                compact_err.append(e)

        with QueryStorm(client, "gidx", q, 5, threads=4) as storm:
            time.sleep(0.7)  # storm baseline against the healthy cluster
            t = threading.Thread(target=trigger_compaction, daemon=True)
            t.start()
            time.sleep(1.5)  # compaction is inside its (4s) mid-pass window
            h.kill(victim_rank)
            time.sleep(1.5)  # storm keeps running against the outage
        results, errors = storm.stop()

        assert errors == [], f"storm saw search errors: {errors[:3]}"
        assert len(results) >= 10, "storm produced too few samples"
        for scores, meta in results:
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta
            assert not any(m in dead_meta for row in meta for m in row)

        # ---- the killed compaction never committed a generation
        assert (serialization.list_generations(victim_dir)[0][0]
                == gens_before[0][0])

        # ---- restart from storage: pre-compaction generation + sidecar
        h.restart(victim_rank, load_index=False,
                  extra_env={"DFT_SHARD_GROUP": str(group)})
        h.wait_port(victim_rank)
        deadline = time.time() + 60
        while True:
            try:
                assert client.sub_indexes[victim_pos].generic_fun(
                    "load_index", ("gidx", None), timeout=30.0)
                stats = client.sub_indexes[victim_pos].generic_fun(
                    "get_perf_stats", timeout=10.0)
                if stats["mutation"]["gidx"]["tombstoned_rows"] \
                        == len(doomed):
                    break
            except AssertionError:
                raise
            except Exception:
                pass
            assert time.time() < deadline, "victim never restored tombstones"
            time.sleep(0.3)
        mu = stats["mutation"]["gidx"]
        assert mu["compactions"] == 0  # it restarted PRE-compaction
        assert mu["live_fraction"] == pytest.approx(
            1.0 - len(doomed) / len(g0_ids))

        # pinned reads on the restarted rank: byte-identical to golden ==
        # byte-identical to the freshly built index over survivors
        with client._stats_lock:
            client._preferred[group] = victim_pos
        scores2, meta2 = client.search(q, 5, "gidx")
        np.testing.assert_array_equal(scores2, g_scores)
        assert meta2 == g_meta
        served = client.sub_indexes[victim_pos].generic_fun("get_perf_stats")
        assert served.get("search", {}).get("count", 0) >= 1, (
            "pinned search was not served by the restarted rank")
        client.close()


def test_compaction_commits_and_serves_identically_under_storm(tmp_path):
    """The non-crash half: a compaction that RUNS TO COMMIT under a live
    storm changes no result byte and reclaims the tombstones."""
    disc = str(tmp_path / "disc.txt")
    storage = str(tmp_path / "storage")
    env = dict(ENV, DFT_COMPACT_TEST_DELAY_S="0.5")
    with ServerHarness(2, disc, storage, base_port=free_port(), env=env):
        client = IndexClient(disc, replication_cfg=ReplicationCfg())
        client.create_index("cidx", flat_cfg())
        rng = np.random.default_rng(1)
        x = rng.standard_normal((200, DIM)).astype(np.float32)
        for s in range(0, 200, 50):
            client.add_index_data("cidx", x[s:s + 50],
                                  [(i,) for i in range(s, s + 50)])
        wait_drained(client, "cidx", 200)
        client.save_index("cidx")
        client.remove_ids("cidx", list(range(0, 60)))
        q = np.ascontiguousarray(x[100:108])
        g_scores, g_meta = client.search(q, 5, "cidx")

        with QueryStorm(client, "cidx", q, 5, threads=4) as storm:
            time.sleep(0.3)
            outcomes = client.compact_index("cidx")
            time.sleep(0.5)
        results, errors = storm.stop()
        assert errors == []
        assert any(outcomes)  # ranks holding tombstones compacted
        for scores, meta in results:
            np.testing.assert_array_equal(scores, g_scores)
            assert meta == g_meta
        # post-compaction: same bytes, tombstones reclaimed
        scores2, meta2 = client.search(q, 5, "cidx")
        np.testing.assert_array_equal(scores2, g_scores)
        assert meta2 == g_meta
        for entry in client.get_perf_stats():
            mu = entry["mutation"]["cidx"]
            assert mu["tombstoned_rows"] == 0 or mu["compactions"] >= 1
        client.close()
