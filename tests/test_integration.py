"""Distributed integration tests without a cluster: real servers as
in-process threads on loopback ports + temp discovery files.

Mirrors the reference's strategy (tests/test_integration.py:51-115) and its
key assertions: threshold training honored end-to-end, golden equality of a
4-server cluster vs a single flat server, exact round-robin balance, config
persistence, centroid export.
"""

import socket
import threading
import time

import numpy as np
import pytest

from distributed_faiss_tpu import IndexClient, IndexCfg, IndexServer, IndexState
from distributed_faiss_tpu.parallel import rpc


def free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_listening(port, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            s = socket.create_connection(("localhost", port), timeout=1)
            s.close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def start_cluster(n, storage_dir, selector=False):
    servers, ports = [], []
    for rank in range(n):
        port = free_port()
        srv = IndexServer(rank, str(storage_dir))
        target = srv.start if selector else srv.start_blocking
        threading.Thread(target=target, args=(port,), daemon=True).start()
        servers.append(srv)
        ports.append(port)
    for port in ports:
        assert wait_listening(port)
    return servers, ports


def write_discovery(tmp_path, ports, name):
    p = tmp_path / name
    p.write_text("\n".join([str(len(ports))] + [f"localhost,{port}" for port in ports]) + "\n")
    return str(p)


def wait_trained(client, index_id, timeout=60.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if client.get_state(index_id) == IndexState.TRAINED:
            return True
        time.sleep(0.1)
    return False


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """4-server cluster + 1 single server, shared across tests in this module
    (per-test isolation via index ids, like the reference's setUpClass)."""
    base = tmp_path_factory.mktemp("cluster")
    multi_servers, multi_ports = start_cluster(4, base / "multi")
    single_servers, single_ports = start_cluster(1, base / "single")
    disc_dir = tmp_path_factory.mktemp("disc")
    multi_list = write_discovery(disc_dir, multi_ports, "multi.txt")
    single_list = write_discovery(disc_dir, single_ports, "single.txt")
    yield {"multi": multi_list, "single": single_list}


def flat_cfg(**kw):
    kw.setdefault("index_builder_type", "flat")
    kw.setdefault("dim", 16)
    kw.setdefault("metric", "l2")
    kw.setdefault("train_num", 64)
    return IndexCfg(**kw)


def fill(client, index_id, x, meta, bs=100):
    for s in range(0, x.shape[0], bs):
        client.add_index_data(index_id, x[s : s + bs], meta[s : s + bs])


def test_train_num_honored_cluster(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    cfg = flat_cfg(train_num=100)
    client.create_index(index_id, cfg)
    x = rng.standard_normal((396, 16)).astype(np.float32)
    meta = [("d", i) for i in range(396)]
    fill(client, index_id, x[:320], meta[:320], bs=80)  # 80/server < 100
    assert client.get_state(index_id) == IndexState.NOT_TRAINED
    fill(client, index_id, x[320:], meta[320:], bs=19)  # pushes each past 100
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    assert client.get_ntotal(index_id) == 396
    client.close()


def test_golden_single_vs_multi(cluster, rng, request):
    """Same corpus into 4-shard cluster and 1 flat server: merged results
    must match exactly (reference test_search_quality..., :205-265)."""
    index_id = request.node.name
    x = rng.standard_normal((800, 16)).astype(np.float32)
    meta = [("doc", i) for i in range(800)]
    q = rng.standard_normal((12, 16)).astype(np.float32)

    results = {}
    for name in ("multi", "single"):
        client = IndexClient(cluster[name])
        cfg = flat_cfg(train_num=10)
        client.create_index(index_id, cfg)
        fill(client, index_id, x, meta, bs=50)
        client.sync_train(index_id)
        assert wait_trained(client, index_id)
        assert client.get_ntotal(index_id) == 800
        results[name] = client.search(q, 10, index_id)
        client.close()

    d_multi, m_multi = results["multi"]
    d_single, m_single = results["single"]
    np.testing.assert_allclose(d_multi, d_single, rtol=1e-4, atol=1e-5)
    assert m_multi == m_single


def test_golden_single_vs_multi_dot(cluster, rng, request):
    index_id = request.node.name
    x = rng.standard_normal((600, 16)).astype(np.float32)
    meta = [i for i in range(600)]
    q = rng.standard_normal((8, 16)).astype(np.float32)
    results = {}
    for name in ("multi", "single"):
        client = IndexClient(cluster[name])
        client.create_index(index_id, flat_cfg(metric="dot", train_num=10))
        fill(client, index_id, x, meta, bs=50)
        client.sync_train(index_id)
        assert wait_trained(client, index_id)
        results[name] = client.search(q, 7, index_id)
        client.close()
    np.testing.assert_allclose(results["multi"][0], results["single"][0], rtol=1e-4, atol=1e-5)
    assert results["multi"][1] == results["single"][1]
    # dot D is negated similarity, ascending (reference heap semantics)
    assert np.all(np.diff(results["multi"][0], axis=1) >= 0)


def test_round_robin_balance(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=25))
    x = rng.standard_normal((400, 16)).astype(np.float32)
    meta = list(range(400))
    fill(client, index_id, x, meta, bs=25)  # 16 batches over 4 servers
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    # every server holds exactly total/num_servers vectors
    # (reference test_integration.py:308-313)
    per_server = [c.get_ntotal(index_id) for c in client.sub_indexes]
    assert per_server == [100, 100, 100, 100]
    assert client.get_ntotal("missing-index-id") == 0
    d, m = client.search(x[:3], 5, index_id)
    assert d.shape == (3, 5) and len(m) == 3 and len(m[0]) == 5
    client.close()


def test_save_drop_load(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=20))
    x = rng.standard_normal((200, 16)).astype(np.float32)
    meta = [("m", i) for i in range(200)]
    fill(client, index_id, x, meta, bs=50)  # one batch per server
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    q = x[:4]
    d0, m0 = client.search(q, 5, index_id)
    client.save_index(index_id)
    client.drop_index(index_id)
    with pytest.raises(rpc.ServerException):
        client.search(q, 5, index_id)
    assert client.load_index(index_id, flat_cfg(train_num=20)) is True
    assert wait_trained(client, index_id)
    d1, m1 = client.search(q, 5, index_id)
    np.testing.assert_allclose(d0, d1, rtol=1e-5)
    assert m0 == m1
    client.close()


def test_config_persisted_and_recovered(cluster, rng, request):
    """cfg.json lands at {save_dir}/{index_id}/{rank}/ and reload without an
    explicit cfg restores it (reference test_config_to_file :332-385)."""
    import os

    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    cfg = flat_cfg(train_num=30, metric="dot")
    client.create_index(index_id, cfg)
    x = rng.standard_normal((160, 16)).astype(np.float32)
    fill(client, index_id, x, list(range(160)), bs=40)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    client.save_index(index_id)
    cfg_path = client.sub_indexes[0].get_config_path(index_id)
    assert os.path.isfile(cfg_path)
    assert f"{index_id}/0/cfg.json" in cfg_path.replace("\\", "/")
    client.drop_index(index_id)
    assert client.load_index(index_id, cfg=None) is True
    assert client.cfg.metric == "dot"
    assert client.cfg.train_num == 30
    client.close()


def test_get_centroids_and_nprobe(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    cfg = IndexCfg(index_builder_type="ivf_simple", dim=16, metric="l2",
                   train_num=100, centroids=4, nprobe=4)
    client.create_index(index_id, cfg)
    x = rng.standard_normal((480, 16)).astype(np.float32)
    fill(client, index_id, x, list(range(480)), bs=60)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    cents = client.get_centroids(index_id)
    assert len(cents) == 4
    for c in cents:
        assert c.shape == (4, 16)
    client.set_nprobe(index_id, 2)
    d, m = client.search(x[:2], 3, index_id)
    assert d.shape == (2, 3)
    client.close()


def test_search_with_filter(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=20))
    x = rng.standard_normal((200, 16)).astype(np.float32)
    meta = [("even" if i % 2 == 0 else "odd", i) for i in range(200)]
    fill(client, index_id, x, meta)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    scores, m = client.search_with_filter(x[:4], 5, index_id, filter_pos=0,
                                          filter_value="even")
    for row in m:
        assert len(row) <= 5
        for entry in row:
            assert entry[0] == "odd"
    client.close()


def test_search_with_filter_requery(cluster, rng, request):
    """Heavily-filtered corpus: the re-query loop (our fix of the
    reference's TODO, client.py:254-257) must fill rows the first
    over-fetch couldn't."""
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=20))
    x = rng.standard_normal((400, 16)).astype(np.float32)
    # 95% of entries filtered out: 3x over-fetch of k=5 won't find 5 rares
    meta = [("rare" if i % 20 == 0 else "common", i) for i in range(400)]
    fill(client, index_id, x, meta)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    _, no_requery = client.search_with_filter(
        x[:4], 5, index_id, filter_pos=0, filter_value="common", max_requery=0)
    _, with_requery = client.search_with_filter(
        x[:4], 5, index_id, filter_pos=0, filter_value="common", max_requery=3)
    assert all(len(row) == 5 for row in with_requery)
    assert all(e[0] == "rare" for row in with_requery for e in row)
    # reference behavior returns short rows here
    assert any(len(row) < 5 for row in no_requery)
    client.close()


def test_get_ids_and_embeddings(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=20, custom_meta_id_idx=1))
    x = rng.standard_normal((120, 16)).astype(np.float32)
    meta = [("m", 1000 + i) for i in range(120)]
    fill(client, index_id, x, meta, bs=30)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    assert client.get_ids(index_id) == set(range(1000, 1120))
    d, m, embs = client.search(x[:2], 3, index_id, return_embeddings=True)
    assert len(embs) == 2 and len(embs[0]) == 3
    # top-1 for x[i] is itself; returned embedding must reconstruct it
    np.testing.assert_allclose(np.asarray(embs[0][0]), x[0], rtol=1e-4, atol=1e-5)
    client.close()


def test_selector_server_mode(tmp_path, rng):
    """The reference's selector loop is broken (test skipped); ours serves."""
    servers, ports = start_cluster(1, tmp_path / "sel", selector=True)
    lst = write_discovery(tmp_path, ports, "sel.txt")
    client = IndexClient(lst)
    client.create_index("sel-idx", flat_cfg(train_num=10))
    x = rng.standard_normal((50, 16)).astype(np.float32)
    client.add_index_data("sel-idx", x, list(range(50)))
    client.sync_train("sel-idx")
    assert wait_trained(client, "sel-idx")
    d, m = client.search(x[:2], 3, "sel-idx")
    assert m[0][0] == 0 and m[1][0] == 1
    client.close()


def test_buffer_depth(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=10_000))  # never auto-trains
    x = rng.standard_normal((120, 16)).astype(np.float32)
    fill(client, index_id, x, list(range(120)), bs=30)
    assert client.get_buffer_depth(index_id) == 120  # all buffered, none indexed
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    assert client.get_buffer_depth(index_id) == 0
    assert client.get_ntotal(index_id) == 120
    client.close()


def test_ping_health(cluster, rng, request):
    index_id = request.node.name
    client = IndexClient(cluster["multi"])
    client.create_index(index_id, flat_cfg(train_num=10))
    client.add_index_data(index_id, rng.standard_normal((20, 16)).astype(np.float32), None)
    client.sync_train(index_id)
    assert wait_trained(client, index_id)
    health = client.ping()
    assert len(health) == 4
    assert sorted(h["rank"] for h in health) == [0, 1, 2, 3]
    # every server must report the index (add only hit one, create hit all)
    assert all(h["indexes"].get(index_id) == "TRAINED" for h in health)
    # ADC kernel observability: no demotions on a healthy interpreter run
    assert all(h["kernels"]["pallas_degraded"] == [] for h in health)
    assert all(isinstance(h["kernels"]["use_nibble"], bool) for h in health)
    client.close()


def test_missing_index_raises_server_exception(cluster):
    client = IndexClient(cluster["multi"])
    # no cfg yet: the client itself refuses to merge-search
    with pytest.raises(RuntimeError, match="no cfg"):
        client.search(np.zeros((1, 16), np.float32), 3, "never-created")
    client.create_index("exists-but-not-the-target", flat_cfg())
    with pytest.raises(rpc.ServerException) as ei:
        client.search(np.zeros((1, 16), np.float32), 3, "never-created")
    assert "no index with id" in str(ei.value)
    client.close()
