"""Index model zoo tests: golden vs numpy brute force, persistence round-trips."""

import numpy as np
import pytest

from distributed_faiss_tpu.models import FlatIndex, IVFFlatIndex, IVFPQIndex
from distributed_faiss_tpu.models import base
from distributed_faiss_tpu.models.factory import (
    INDEX_BUILDERS,
    build_index,
    index_from_state_dict,
    parse_factory,
)
from distributed_faiss_tpu.utils.config import IndexCfg
from distributed_faiss_tpu.utils.serialization import load_state, save_state


def brute(q, x, k, metric):
    if metric == "dot":
        s = q @ x.T
        ids = np.argsort(-s, axis=1)[:, :k]
        return np.take_along_axis(s, ids, 1), ids
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    ids = np.argsort(d, axis=1)[:, :k]
    return np.take_along_axis(d, ids, 1), ids


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_flat_exact(rng, metric):
    x = rng.standard_normal((500, 16)).astype(np.float32)
    q = rng.standard_normal((9, 16)).astype(np.float32)
    idx = FlatIndex(16, metric)
    assert idx.is_trained
    idx.add(x)
    assert idx.ntotal == 500
    D, I = idx.search(q, 7)
    wd, wi = brute(q, x, 7, metric)
    np.testing.assert_array_equal(I, wi)
    np.testing.assert_allclose(D, wd, rtol=1e-4, atol=1e-4)


def test_flat_growth_across_capacity(rng):
    idx = FlatIndex(8, "l2")
    chunks = [rng.standard_normal((3000, 8)).astype(np.float32) for _ in range(3)]
    for c in chunks:
        idx.add(c)
    x = np.concatenate(chunks)
    q = rng.standard_normal((4, 8)).astype(np.float32)
    D, I = idx.search(q, 5)
    _, wi = brute(q, x, 5, "l2")
    np.testing.assert_array_equal(I, wi)


def test_flat_empty_search(rng):
    idx = FlatIndex(8, "l2")
    D, I = idx.search(rng.standard_normal((3, 8)).astype(np.float32), 4)
    assert (I == -1).all()
    assert np.isinf(D).all()


def test_flat_sq8(rng):
    x = (rng.standard_normal((800, 12)) * 2).astype(np.float32)
    q = rng.standard_normal((5, 12)).astype(np.float32)
    idx = FlatIndex(12, "l2", codec="sq8")
    assert not idx.is_trained
    with pytest.raises(RuntimeError):
        idx.add(x)
    idx.train(x)
    idx.add(x)
    D, I = idx.search(q, 10)
    _, wi = brute(q, x, 10, "l2")
    # quantized search: near-exact, check recall
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 10 for i in range(5)])
    assert recall > 0.8


def test_flat_reconstruct(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    idx = FlatIndex(8, "l2")
    idx.add(x)
    rec = idx.reconstruct_batch(np.array([3, 50, 99]))
    np.testing.assert_allclose(rec, x[[3, 50, 99]], rtol=1e-6)


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_ivf_flat_full_probe_equals_exact(rng, metric):
    """nprobe == nlist makes IVF-Flat an exact search: golden vs brute force."""
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((6, 16)).astype(np.float32)
    idx = IVFFlatIndex(16, 8, metric)
    idx.train(x[:1000])
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    wd, wi = brute(q, x, 10, metric)
    np.testing.assert_array_equal(I, wi)
    np.testing.assert_allclose(D, wd, rtol=1e-3, atol=1e-3)


def test_ivf_flat_partial_probe_recall(rng):
    x = rng.standard_normal((4000, 16)).astype(np.float32)
    q = rng.standard_normal((16, 16)).astype(np.float32)
    idx = IVFFlatIndex(16, 16, "l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 10)
    _, wi = brute(q, x, 10, "l2")
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 10 for i in range(16)])
    assert recall > 0.6  # half the lists probed


@pytest.mark.parametrize("codec", ["f16", "sq8"])
def test_ivf_flat_codecs(rng, codec):
    x = rng.standard_normal((1500, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    idx = IVFFlatIndex(16, 4, "l2", codec=codec)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    D, I = idx.search(q, 10)
    _, wi = brute(q, x, 10, "l2")
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 10 for i in range(8)])
    assert recall > 0.9  # full probe, only quantization noise


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_ivf_pq_recall(rng, metric):
    d, m = 32, 8
    x = rng.standard_normal((3000, d)).astype(np.float32)
    q = rng.standard_normal((8, d)).astype(np.float32)
    idx = IVFPQIndex(d, 8, m=m, metric=metric)
    idx.train(x[:2000])
    idx.add(x)
    idx.set_nprobe(8)
    D, I = idx.search(q, 20)
    _, wi = brute(q, x, 20, metric)
    recall = np.mean([len(set(I[i]) & set(wi[i])) / 20 for i in range(8)])
    assert recall > 0.35  # ADC on random gaussian data, full probe
    assert (I >= 0).all()


@pytest.mark.parametrize("metric", ["dot", "l2"])
def test_ivf_pq_pallas_path_matches_xla(rng, metric):
    """use_pallas=True (interpreter on CPU — same kernel body as TPU) must
    produce identical rankings to the XLA one-hot path."""
    d, m = 32, 8
    x = rng.standard_normal((1200, d)).astype(np.float32)
    q = rng.standard_normal((5, d)).astype(np.float32)
    a = IVFPQIndex(d, 4, m=m, metric=metric)
    a.train(x[:600]); a.add(x); a.set_nprobe(4)
    b = IVFPQIndex(d, 4, m=m, metric=metric, use_pallas=True)
    b.centroids, b.codebooks = a.centroids, a.codebooks
    b.lists = a.lists
    b._host_pos, b._host_assign, b._n = a._host_pos, a._host_assign, a._n
    b.set_nprobe(4)
    Da, Ia = a.search(q, 8)
    Db, Ib = b.search(q, 8)
    np.testing.assert_array_equal(Ia, Ib)
    np.testing.assert_allclose(Da, Db, rtol=1e-4, atol=1e-4)


def test_ivf_pq_refine_lifts_recall(rng, tmp_path):
    """refine_k_factor reranks the ADC shortlist with exact fp16 distances:
    recall must beat plain ADC on the same nprobe, and the results must
    match the exact ranking over the candidate superset."""
    d, m = 32, 8
    x = rng.standard_normal((4000, d)).astype(np.float32)
    q = rng.standard_normal((10, d)).astype(np.float32)
    plain = IVFPQIndex(d, 8, m=m, metric="l2")
    plain.train(x[:2000]); plain.add(x); plain.set_nprobe(8)
    refined = IVFPQIndex(d, 8, m=m, metric="l2", refine_k_factor=8)
    refined.centroids, refined.codebooks = plain.centroids, plain.codebooks
    refined.lists = plain.lists
    refined._host_pos, refined._host_assign = plain._host_pos, plain._host_assign
    refined._n = plain._n
    refined.refine_store.add(x.astype(np.float16))
    refined.set_nprobe(8)

    gt = brute(q, x, 10, "l2")[1]
    _, Ip = plain.search(q, 10)
    Dr, Ir = refined.search(q, 10)
    rec_plain = np.mean([len(set(Ip[i]) & set(gt[i])) / 10 for i in range(10)])
    rec_ref = np.mean([len(set(Ir[i]) & set(gt[i])) / 10 for i in range(10)])
    assert rec_ref > rec_plain + 0.15, (rec_plain, rec_ref)
    assert np.all(np.diff(Dr, axis=1) >= 0)  # exact l2, ascending

    # persistence round trip keeps the refine store
    from distributed_faiss_tpu.models.factory import index_from_state_dict
    from distributed_faiss_tpu.utils.serialization import load_state, save_state
    p = str(tmp_path / "refine.npz")
    save_state(p, refined.state_dict())
    again = index_from_state_dict(load_state(p))
    D2, I2 = again.search(q, 10)
    np.testing.assert_array_equal(Ir, I2)


def test_ivf_pq_reconstruct_matches_adc(rng):
    """Search scores must equal exact distance to the reconstructed vectors."""
    d, m = 16, 4
    x = rng.standard_normal((600, d)).astype(np.float32)
    q = rng.standard_normal((3, d)).astype(np.float32)
    idx = IVFPQIndex(d, 4, m=m, metric="l2")
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    D, I = idx.search(q, 5)
    rec = idx.reconstruct_batch(I.reshape(-1)).reshape(3, 5, d)
    want = ((q[:, None, :] - rec) ** 2).sum(-1)
    np.testing.assert_allclose(D, want, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("maker", [
    lambda: FlatIndex(16, "l2"),
    lambda: FlatIndex(16, "dot"),
    lambda: FlatIndex(16, "l2", codec="sq8"),
    lambda: IVFFlatIndex(16, 4, "l2"),
    lambda: IVFFlatIndex(16, 4, "dot", codec="f16"),
    lambda: IVFPQIndex(16, 4, m=4, metric="l2"),
])
def test_state_dict_round_trip(rng, maker, tmp_path):
    x = rng.standard_normal((700, 16)).astype(np.float32)
    q = rng.standard_normal((4, 16)).astype(np.float32)
    idx = maker()
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(4)
    D0, I0 = idx.search(q, 6)

    path = str(tmp_path / "index.npz")
    save_state(path, idx.state_dict())
    idx2 = index_from_state_dict(load_state(path))
    D1, I1 = idx2.search(q, 6)
    np.testing.assert_array_equal(I0, I1)
    np.testing.assert_allclose(D0, D1, rtol=1e-5, atol=1e-5)
    assert idx2.ntotal == idx.ntotal


def test_builder_registry(rng):
    assert set(INDEX_BUILDERS) == {"flat", "ivf_simple", "knnlm", "ivfsq", "hnswsq", "ivf_tpu"}
    cfg = IndexCfg(index_builder_type="knnlm", dim=32, centroids=4, code_size=8, metric="l2")
    idx = build_index(cfg)
    assert isinstance(idx, IVFPQIndex)
    assert idx.m == 8
    cfg = IndexCfg(index_builder_type="flat", dim=16, metric="l2")
    idx = build_index(cfg)
    assert isinstance(idx, FlatIndex)
    assert idx.metric == "l2"  # conscious fix: reference flat ignores metric


def test_factory_strings():
    cfg = IndexCfg(faiss_factory="IVF{centroids},SQ8", dim=16, centroids=32, metric="l2")
    idx = parse_factory(cfg)
    assert isinstance(idx, IVFFlatIndex) and idx.codec == "sq8" and idx.nlist == 32
    cfg = IndexCfg(faiss_factory="IVF8,PQ4x8", dim=16, metric="l2")
    idx = parse_factory(cfg)
    assert isinstance(idx, IVFPQIndex) and idx.m == 4
    cfg = IndexCfg(faiss_factory="Flat", dim=16, metric="dot")
    assert isinstance(parse_factory(cfg), FlatIndex)
    cfg = IndexCfg(faiss_factory="PQ4", dim=16, metric="l2")
    idx = parse_factory(cfg)
    assert isinstance(idx, IVFPQIndex) and idx.nlist == 1
    with pytest.raises(RuntimeError):
        parse_factory(IndexCfg(faiss_factory="LSH", dim=16))
    with pytest.raises(RuntimeError):
        build_index(IndexCfg(index_builder_type="nope", dim=16))
    with pytest.raises(RuntimeError):
        build_index(IndexCfg(dim=16))


def test_pick_query_block_budget():
    # tiny payload -> max block; the headline config (cap=512, d=128 fp32
    # gather) must allow the full 1024 block the relay-latency fix relies on
    assert base.pick_query_block(512 * 128 * 4) == base.MAX_QUERY_BLOCK
    # 4 MB/query (ivf_simple's huge-cap lists) -> pinned at the 256 floor
    assert base.pick_query_block(8192 * 128 * 4) == 256
    # block * payload always fits the budget (or is the floor)
    for b in (1, 10_000, 1 << 20, 1 << 24):
        blk = base.pick_query_block(b)
        assert blk == 256 or blk * b <= base._QUERY_PAYLOAD_BUDGET


def test_search_results_independent_of_block(rng):
    # a >256-query batch crosses block boundaries; results must equal the
    # per-row searches regardless of how the batch is blocked
    x = rng.standard_normal((2000, 16)).astype(np.float32)
    q = rng.standard_normal((300, 16)).astype(np.float32)
    idx = IVFFlatIndex(16, 8, "l2", kmeans_iters=4)
    idx.train(x)
    idx.add(x)
    idx.set_nprobe(8)  # exhaustive -> exact, order-deterministic
    d_all, i_all = idx.search(q, 5)
    d_one, i_one = idx.search(q[:1], 5)
    np.testing.assert_array_equal(i_all[:1], i_one)
    np.testing.assert_allclose(d_all[:1], d_one, rtol=1e-5)


def test_ivf_host_state_is_position_map_only(rng):
    """IVF/PQ keep NO host copy of the payload: per-row host state is the
    8-byte (assign, pos) map, and reconstruct/persistence stream the rows
    back from the device lists (VERDICT r4 weak #2)."""
    n, d = 5000, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((8, d)).astype(np.float32)
    for make in (
        lambda: IVFFlatIndex(d, 16, "l2", codec="f16", kmeans_iters=2),
        lambda: IVFPQIndex(d, 16, m=8, metric="l2", kmeans_iters=2, pq_iters=2),
    ):
        idx = make()
        idx.train(x[:2000])
        idx.add(x[:3000])
        idx.add(x[3000:])  # multi-batch: positions must chain across appends
        assert not hasattr(idx, "_host_rows")
        host_bytes = sum(c.nbytes for c in idx._host_assign) \
            + sum(c.nbytes for c in idx._host_pos)
        assert host_bytes == n * 8, host_bytes

        ids = rng.integers(0, n, 64)
        rec = idx.reconstruct_batch(ids)
        assert rec.shape == (64, d)
        if isinstance(idx, IVFFlatIndex):
            # f16 codec: device rows are the stored payload, exactly
            np.testing.assert_allclose(rec, x[ids], rtol=2e-3, atol=2e-3)

        # round-trip through state_dict preserves search results exactly
        idx2 = type(idx).from_state_dict(idx.state_dict())
        idx.set_nprobe(16)
        idx2.set_nprobe(16)
        d1, i1 = idx.search(q, 5)
        d2, i2 = idx2.search(q, 5)
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(d1, d2, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(idx2.reconstruct_batch(ids), rec,
                                   rtol=1e-6, atol=1e-6)
