#!/usr/bin/env python3
"""Bulk-ingest throughput at reference scale (VERDICT r3 item 6).

The reference's headline workflow is a memmap load across a server fleet
(README.md:147-176, scripts/load_data.py with periodic saves). This runs
that exact pipeline — scripts/load_data.py against a launch_local
subprocess cluster — at 1e7 x 128-d rows / 4 ranks by default and reports
end-to-end ingest rows/s (memmap read + fp32 convert + binary RPC +
server buffering + async index add), excluding the final save.

CPU measures the protocol path (the driver's relay makes per-launch
dispatch the TPU bottleneck anyway — RESULTS.md "launch-bound serving");
run on the real chip via benchmarks/hw_sweep.sh when the relay lives.

    python benchmarks/ingest_scale.py [--rows 10000000] [--dim 128]
        [--ranks 4] [--bs 20000] [--keep]

Prints one JSON line: {"metric": "bulk ingest rows/s ...", ...}.
"""

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rss_kb(pids):
    """Summed VmRSS of the given pids (0 for ones already gone)."""
    total = 0
    for pid in pids:
        try:
            with open(f"/proc/{pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        total += int(line.split()[1])
                        break
        except OSError:
            pass
    return total


class RssSampler(threading.Thread):
    """Samples the server fleet's summed RSS during ingest (VERDICT r4: the
    IVF/PQ family must not mirror the corpus in host RAM — growth per row
    should be codes+ids+position-map+metadata, not a second payload copy)."""

    def __init__(self, pids, period=1.0):
        super().__init__(daemon=True)
        self.pids = pids
        self.period = period
        self.samples = []  # (t, rss_kb)
        # NB: must not be named _stop — Thread.join() calls self._stop()
        self._halt = threading.Event()

    def run(self):
        while not self._halt.is_set():
            self.samples.append((time.time(), _rss_kb(self.pids)))
            self._halt.wait(self.period)

    def stop(self):
        self._halt.set()
        self.join()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--bs", type=int, default=20_000)
    ap.add_argument("--base-port", type=int, default=13741)
    ap.add_argument("--builder", choices=("flat", "ivfpq"), default="flat",
                    help="flat = reference default; ivfpq = the knnlm "
                         "IVF-PQ family (exercises encode+list append and "
                         "the no-host-mirror memory story)")
    ap.add_argument("--centroids", type=int, default=1024,
                    help="nlist for --builder ivfpq")
    ap.add_argument("--keep", action="store_true",
                    help="keep the temp dir (memmap + index storage)")
    ap.add_argument("--verify-reload", action="store_true",
                    help="after ingest+save: kill the fleet, relaunch with "
                         "load_index=True, and golden-check a search batch "
                         "against pre-kill results (the reference's "
                         "save/restore workflow, README.md:147-176)")
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
           # persistent executable cache: without it every server rank pays
           # the cold IVF-PQ add/scatter compiles (~10 min measured on this
           # 1-core box) on every run
           "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO, ".jax_cache_cpu"),
           "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1"}
    tmp = tempfile.mkdtemp(prefix="ingest_scale_")
    mmap_path = os.path.join(tmp, "data.mmap")
    disc = os.path.join(tmp, "disc.txt")
    storage = os.path.join(tmp, "storage")

    from distributed_faiss_tpu.parallel import launcher
    from distributed_faiss_tpu.utils.config import IndexCfg

    t_mk = time.time()
    subprocess.run(
        [sys.executable, "scripts/load_data.py", "--data", mmap_path,
         "--dtype", "fp16", "--dim", str(args.dim), "--discovery", disc,
         "--make-random", str(args.rows)],
        cwd=REPO, env=env, check=True, capture_output=True,
    )
    print(f"memmap ready ({args.rows}x{args.dim} fp16, "
          f"{os.path.getsize(mmap_path) / 2 ** 30:.2f} GiB, "
          f"{time.time() - t_mk:.0f}s)", file=sys.stderr)

    if args.builder == "ivfpq":
        cfg = IndexCfg(index_builder_type="knnlm", dim=args.dim, metric="l2",
                       train_num=100_000, centroids=args.centroids)
    else:
        cfg = IndexCfg(index_builder_type="flat", dim=args.dim, metric="l2",
                       train_num=100_000)
    cfg_path = os.path.join(tmp, "cfg.json")
    cfg.save(cfg_path)

    procs = launcher.launch_local(args.ranks, disc, storage,
                                  base_port=args.base_port, env=env)
    rc = 1
    sampler = RssSampler([p.pid for p in procs])
    sampler.start()
    try:
        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "scripts/load_data.py", "--data", mmap_path,
             "--dtype", "fp16", "--dim", str(args.dim), "--bs", str(args.bs),
             "--discovery", disc, "--index-id", "ingest", "--cfg", cfg_path],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=4 * 3600,
        )
        wall = time.time() - t0
        log = out.stdout + out.stderr
        if out.returncode != 0:
            print(log[-4000:], file=sys.stderr)
            raise SystemExit(f"loader failed rc={out.returncode}")
        # "load complete: N rows in Xs; ntotal=N" — ingest only, save excluded
        m = re.search(r"load complete: (\d+) rows in ([\d.]+)s; ntotal=(\d+)",
                      log)
        assert m, log[-2000:]
        rows, secs, ntotal = int(m.group(1)), float(m.group(2)), int(m.group(3))
        assert ntotal == rows, (ntotal, rows)
        rate = rows / secs
        sampler.stop()
        # RSS growth per ingested row over the steady second half of the
        # INGEST interval only — the final save deliberately materializes a
        # full per-rank host array (the bytes the save file needs) and must
        # not contaminate the steady-state number (r5 review). NOTE: on the
        # CPU jax backend "device" arrays live in process RSS too, so the
        # floor is one payload copy (codes/vectors + ids); the
        # no-host-mirror claim is growth ~= that single copy, not 2x.
        rss_per_row = None
        # anchor the window to the loader's own ingest-start timestamp
        # (ADVICE r5): t0 is the subprocess spawn time, which includes
        # python/jax startup and client connect, so a t0-anchored window
        # shifts earlier than the true ingest interval and absorbs
        # pre-ingest compile/allocation RSS growth into the per-row number
        m_ts = re.search(r"ingest start ts=([\d.]+)", log)
        ingest_t0 = float(m_ts.group(1)) if m_ts else t0
        ingest_t1 = ingest_t0 + secs
        window = [s for s in sampler.samples
                  if ingest_t0 + 0.5 * secs <= s[0] <= ingest_t1]
        if len(window) >= 2:
            dt = window[-1][0] - window[0][0]
            if dt > 1:
                rows_in_window = rate * dt
                rss_per_row = (window[-1][1] - window[0][1]) * 1024.0 / rows_in_window
        result = {
            "metric": (f"bulk ingest rows/s (backend=cpu protocol path, "
                       f"{args.ranks} subprocess ranks, {args.builder}, "
                       f"{rows}x{args.dim} fp16 memmap, bs={args.bs}; "
                       f"wall incl. save {wall:.0f}s)"),
            "value": round(rate, 1),
            "unit": "rows/s",
            "rows": rows,
            "ingest_seconds": round(secs, 1),
            "rss_peak_mb": round(max(s[1] for s in sampler.samples) / 1024.0, 1)
            if sampler.samples else None,
        }
        if rss_per_row is not None:
            result["rss_bytes_per_row_steady"] = round(rss_per_row, 1)
        if args.verify_reload:
            import numpy as np

            from distributed_faiss_tpu.parallel.client import IndexClient

            rng = np.random.default_rng(7)
            q = rng.standard_normal((16, args.dim)).astype(np.float32)
            client = IndexClient(disc, cfg_path=cfg_path)
            ref_scores, ref_meta = client.search(q, 5, "ingest")
            client.close()
            for p in procs:
                p.kill()
            for p in procs:
                p.wait()
            t_re = time.time()
            disc2 = os.path.join(tmp, "disc_reload.txt")
            procs = launcher.launch_local(
                args.ranks, disc2, storage, base_port=args.base_port + 100,
                env=env)
            client = IndexClient(disc2)
            assert client.load_index("ingest", cfg), "reload failed"
            deadline = time.time() + 1800
            while time.time() < deadline:
                if client.get_ntotal("ingest") == rows:
                    break
                time.sleep(2)
            got_scores, got_meta = client.search(q, 5, "ingest")
            client.close()
            np.testing.assert_allclose(got_scores, ref_scores, rtol=1e-4,
                                       atol=1e-4)
            assert got_meta == ref_meta, "metadata changed across reload"
            result["reload_seconds"] = round(time.time() - t_re, 1)
            result["reload_golden_equal"] = True
        print(json.dumps(result))
        rc = 0
    finally:
        if sampler.is_alive():
            sampler.stop()
        for p in procs:
            p.kill()
        if not args.keep:
            shutil.rmtree(tmp, ignore_errors=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
