#!/usr/bin/env python3
"""Bulk-ingest throughput at reference scale (VERDICT r3 item 6).

The reference's headline workflow is a memmap load across a server fleet
(README.md:147-176, scripts/load_data.py with periodic saves). This runs
that exact pipeline — scripts/load_data.py against a launch_local
subprocess cluster — at 1e7 x 128-d rows / 4 ranks by default and reports
end-to-end ingest rows/s (memmap read + fp32 convert + binary RPC +
server buffering + async index add), excluding the final save.

CPU measures the protocol path (the driver's relay makes per-launch
dispatch the TPU bottleneck anyway — RESULTS.md "launch-bound serving");
run on the real chip via benchmarks/hw_sweep.sh when the relay lives.

    python benchmarks/ingest_scale.py [--rows 10000000] [--dim 128]
        [--ranks 4] [--bs 20000] [--keep]

Prints one JSON line: {"metric": "bulk ingest rows/s ...", ...}.
"""

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--bs", type=int, default=20_000)
    ap.add_argument("--base-port", type=int, default=13741)
    ap.add_argument("--keep", action="store_true",
                    help="keep the temp dir (memmap + index storage)")
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    tmp = tempfile.mkdtemp(prefix="ingest_scale_")
    mmap_path = os.path.join(tmp, "data.mmap")
    disc = os.path.join(tmp, "disc.txt")
    storage = os.path.join(tmp, "storage")

    from distributed_faiss_tpu.parallel import launcher
    from distributed_faiss_tpu.utils.config import IndexCfg

    t_mk = time.time()
    subprocess.run(
        [sys.executable, "scripts/load_data.py", "--data", mmap_path,
         "--dtype", "fp16", "--dim", str(args.dim), "--discovery", disc,
         "--make-random", str(args.rows)],
        cwd=REPO, env=env, check=True, capture_output=True,
    )
    print(f"memmap ready ({args.rows}x{args.dim} fp16, "
          f"{os.path.getsize(mmap_path) / 2 ** 30:.2f} GiB, "
          f"{time.time() - t_mk:.0f}s)", file=sys.stderr)

    cfg = IndexCfg(index_builder_type="flat", dim=args.dim, metric="l2",
                   train_num=100_000)
    cfg_path = os.path.join(tmp, "cfg.json")
    cfg.save(cfg_path)

    procs = launcher.launch_local(args.ranks, disc, storage,
                                  base_port=args.base_port, env=env)
    rc = 1
    try:
        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "scripts/load_data.py", "--data", mmap_path,
             "--dtype", "fp16", "--dim", str(args.dim), "--bs", str(args.bs),
             "--discovery", disc, "--index-id", "ingest", "--cfg", cfg_path],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=4 * 3600,
        )
        wall = time.time() - t0
        log = out.stdout + out.stderr
        if out.returncode != 0:
            print(log[-4000:], file=sys.stderr)
            raise SystemExit(f"loader failed rc={out.returncode}")
        # "load complete: N rows in Xs; ntotal=N" — ingest only, save excluded
        m = re.search(r"load complete: (\d+) rows in ([\d.]+)s; ntotal=(\d+)",
                      log)
        assert m, log[-2000:]
        rows, secs, ntotal = int(m.group(1)), float(m.group(2)), int(m.group(3))
        assert ntotal == rows, (ntotal, rows)
        rate = rows / secs
        print(json.dumps({
            "metric": (f"bulk ingest rows/s (backend=cpu protocol path, "
                       f"{args.ranks} subprocess ranks, flat-f32, "
                       f"{rows}x{args.dim} fp16 memmap, bs={args.bs}; "
                       f"wall incl. save {wall:.0f}s)"),
            "value": round(rate, 1),
            "unit": "rows/s",
            "rows": rows,
            "ingest_seconds": round(secs, 1),
        }))
        rc = 0
    finally:
        for p in procs:
            p.kill()
        if not args.keep:
            shutil.rmtree(tmp, ignore_errors=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
