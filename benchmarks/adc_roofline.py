"""ADC kernel roofline: measured throughput vs v5e peaks, per variant.

VERDICT r2 missing #3: nothing quantified device utilization for the kernel
SURVEY §7 says "decides IVF-PQ QPS". This script times the three ADC
implementations (XLA one-hot einsum, Pallas one-hot, Pallas nibble) at the
flagship geometry and prints, per variant:

  - codes/s (candidate rows x m scored per second)
  - achieved HBM bytes/s for the true input traffic (codes + lut + out)
  - the VPU-side one-hot store traffic the kernel generates (the measured
    bottleneck of the one-hot variant; the nibble variant cuts it 16x)
  - % of v5e HBM peak (819 GB/s) for the true traffic

Run on the real chip (no env overrides). One JSON line per row.
"""

import json
import time

import numpy as np

V5E_HBM_GBS = 819.0  # v5e HBM bandwidth peak
V5E_BF16_TFLOPS = 197.0


def bench(fn, *args, warmup=2, iters=8):
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    import jax
    import jax.numpy as jnp

    from distributed_faiss_tpu.ops import adc_pallas, pq

    backend = jax.default_backend()
    rng = np.random.default_rng(0)
    # flagship knnlm-like geometry: per-(query,probe) lists, m=64
    nq, m, ksub, L = 256, 64, 256, 4096
    lut = jnp.asarray(rng.standard_normal((nq, m, ksub)).astype(np.float32))
    lut_bf16 = lut.astype(jnp.bfloat16)
    codes = jnp.asarray(rng.integers(0, 256, (nq, L, m)).astype(np.uint8))

    rows = nq * L
    code_bytes = rows * m  # true codes traffic
    lut_bytes_f32 = nq * m * ksub * 4
    out_bytes = rows * 4

    variants = [
        ("xla-onehot", lambda: pq.adc_scan(lut, codes)),
        ("pallas-onehot-f32",
         lambda: adc_pallas.adc_scan_pallas(lut, codes, interpret=backend == "cpu")),
        ("pallas-onehot-bf16",
         lambda: adc_pallas.adc_scan_pallas(lut_bf16, codes, interpret=backend == "cpu")),
        ("pallas-nibble-f32",
         lambda: adc_pallas.adc_scan_pallas_nibble(lut, codes, interpret=backend == "cpu")),
        ("pallas-nibble-bf16",
         lambda: adc_pallas.adc_scan_pallas_nibble(lut_bf16, codes, interpret=backend == "cpu")),
    ]

    for name, fn in variants:
        try:
            dt = bench(fn)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(json.dumps({"variant": name, "backend": backend,
                              "error": repr(e)[:200]}), flush=True)
            continue
        lut_bytes = lut_bytes_f32 // (2 if "bf16" in name else 1)
        true_bytes = code_bytes + lut_bytes + out_bytes
        onehot_factor = 16 if "nibble" in name else ksub
        row = {
            "variant": name,
            "backend": backend,
            "nq": nq, "m": m, "L": L,
            "ms": round(dt * 1e3, 3),
            "codes_per_s": round(rows * m / dt / 1e6, 1),  # M codes/s
            "rows_per_s": round(rows / dt / 1e6, 2),  # M rows/s
            "true_gbs": round(true_bytes / dt / 1e9, 2),
            "hbm_pct": round(100 * true_bytes / dt / 1e9 / V5E_HBM_GBS, 2),
            "onehot_store_gbs": round(
                rows * m * onehot_factor * (2 if "bf16" in name else 4) / dt / 1e9, 1),
        }
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
