#!/usr/bin/env python3
"""Compiled-on-TPU validation of the Pallas ADC kernels.

VERDICT (round 1) flagged that ops/adc_pallas.py had only ever executed via
the Pallas interpreter (interpret=True); compiled Mosaic behavior (tiling
constraints, dtype rules) was unproven. This script runs both kernels
compiled (interpret=False) on the real chip and asserts parity against a
numpy golden, across the shapes the IVF-PQ path actually emits:

  - shared-list scan at the default TILE=512 with a non-multiple L
  - per-query scan (the probed-lists path) at m=64 / ksub=256 (the knnlm
    flagship geometry) and the small smoke geometry
  - tiny-L edge case (tile clamp path)

Prints one JSON line per case; exits nonzero on any mismatch. Run from the
repo root (the axon PJRT plugin only registers there).

Results are recorded in benchmarks/RESULTS.md; tests/test_adc_pallas.py
keeps the interpreter-mode coverage for CPU CI.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def np_adc_shared(lut, codes):
    nq, L = lut.shape[0], codes.shape[0]
    out = np.zeros((nq, L), np.float32)
    for mi in range(codes.shape[1]):
        out += lut[:, mi, codes[:, mi].astype(np.int64)]
    return out


def np_adc_per_query(lut, codes):
    nq, L = codes.shape[0], codes.shape[1]
    out = np.zeros((nq, L), np.float32)
    for qi in range(nq):
        out[qi] = np_adc_shared(lut[qi:qi + 1], codes[qi])[0]
    return out


def main():
    import jax

    from distributed_faiss_tpu.ops import adc_pallas

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon"):
        print(json.dumps({"error": f"not on TPU (platform={platform})"}))
        return 1

    rng = np.random.default_rng(0)
    failures = 0

    cases = [
        # (name, nq, m, ksub, L, tile, shared)
        ("shared_default_tile", 64, 16, 256, 5000, 512, True),
        ("shared_knnlm_geometry", 32, 64, 256, 4096, 512, True),
        ("shared_tiny_L", 4, 8, 256, 13, 512, True),
        ("per_query_smoke", 8, 16, 256, 700, 256, False),
        ("per_query_knnlm", 16, 64, 256, 2048, 512, False),
    ]
    for name, nq, m, ksub, L, tile, shared in cases:
        lut = rng.standard_normal((nq, m, ksub)).astype(np.float32)
        if shared:
            codes = rng.integers(0, ksub, (L, m)).astype(np.uint8)
            t0 = time.time()
            got = np.asarray(adc_pallas.adc_scan_shared_pallas(
                lut, codes, tile=tile, interpret=False))
            dt = time.time() - t0
            want = np_adc_shared(lut, codes)
        else:
            codes = rng.integers(0, ksub, (nq, L, m)).astype(np.uint8)
            t0 = time.time()
            got = np.asarray(adc_pallas.adc_scan_pallas(
                lut, codes, tile=tile, interpret=False))
            dt = time.time() - t0
            want = np_adc_per_query(lut, codes)
        err = float(np.max(np.abs(got - want)))
        ok = bool(np.allclose(got, want, rtol=1e-4, atol=1e-4))
        print(json.dumps({
            "case": name, "nq": nq, "m": m, "L": L, "tile": tile,
            "compiled": True, "max_abs_err": round(err, 7), "ok": ok,
            "first_call_s": round(dt, 2),
        }), flush=True)
        failures += 0 if ok else 1

    # steady-state throughput of the compiled shared scan at flagship
    # geometry — device-resident inputs (the serving pattern; numpy args
    # would re-ride the host relay every call and measure the tunnel).
    from distributed_faiss_tpu.ops import pq as pq_ops

    nq, m, ksub, L = 32, 64, 256, 65536
    lut = jax.device_put(rng.standard_normal((nq, m, ksub)).astype(np.float32))
    codes = jax.device_put(rng.integers(0, ksub, (L, m)).astype(np.uint8))
    reps = 20
    import jax.numpy as jnp

    lut_bf16 = jax.device_put(np.asarray(lut)).astype(jnp.bfloat16)
    for name, fn in (
        ("pallas_shared_throughput",
         lambda: adc_pallas.adc_scan_shared_pallas(lut, codes, interpret=False)),
        ("pallas_shared_bf16_lut_throughput",
         lambda: adc_pallas.adc_scan_shared_pallas(lut_bf16, codes, interpret=False)),
        ("xla_onehot_shared_throughput",
         lambda: pq_ops.adc_scan_shared(lut, codes)),
    ):
        fn().block_until_ready()
        t0 = time.time()
        for _ in range(reps):
            out = fn()
        out.block_until_ready()
        dt = (time.time() - t0) / reps
        print(json.dumps({
            "case": name, "nq": nq, "m": m, "L": L,
            "ms_per_scan": round(dt * 1e3, 3),
            "codes_scored_per_s": round(nq * L / dt / 1e6, 1),
            "unit": "M code-scores/s",
        }), flush=True)
    return failures


if __name__ == "__main__":
    os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
