"""Operating curves for the flagship configs (VERDICT r2 next #6/#8).

- knnlm: nprobe x refine_k_factor recall/QPS grid at the full-size config
  (the refine store is built once; refine_k_factor is a search-time knob).
- ivfsq: nprobe recall/QPS curve post top-k/block fixes.

One JSON line per grid point; the chosen operating point is the cheapest
point clearing recall@10 >= 0.95 (BASELINE.md protocol). The single-core
numpy IVF floor (cpu_ivf_qps) is printed for the chosen points so every
headline row carries its honest baseline.

Run on the real chip: `python benchmarks/operating_curves.py [--small]`.
"""

import argparse
import json
import sys
import time

import numpy as np

from benchmarks.baseline_configs import (
    cpu_ivf_qps, make_lowrank_corpus, measure_qps, recall_at_k)


def note(msg):
    print(f"[curves] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


def grid_rows(name, index, x, q, gt, k, nprobes, refine_factors=(None,),
              recall_only=False):
    rows = []
    for np_ in nprobes:
        index.set_nprobe(np_)
        for rf in refine_factors:
            if rf is not None:
                index.refine_k_factor = rf
            _, ids = index.search(q[:128], k)
            rec = recall_at_k(ids, gt, k)
            row = {"config": name, "nprobe": np_, "recall@10": round(rec, 4)}
            if not recall_only:
                qps = measure_qps(lambda qq, kk: index.search(qq, kk), q, k)
                row["qps"] = round(qps, 1)
            if rf is not None:
                row["refine_k_factor"] = rf
            rows.append(row)
            print(json.dumps(row), flush=True)
    return rows


def pick_operating_point(rows, bar=0.95):
    ok = [r for r in rows if r["recall@10"] >= bar]
    if not ok:
        return None
    if "qps" in ok[0]:
        return max(ok, key=lambda r: r["qps"])
    # recall-only mode (QPS unmeasurable on this backend at full size):
    # cheapest point clearing the bar — lowest nprobe, then lowest refine
    return min(ok, key=lambda r: (r["nprobe"], r.get("refine_k_factor", 0)))


def knnlm_curve(rng, size, recall_only=False):
    from distributed_faiss_tpu.models.flat import FlatIndex
    from distributed_faiss_tpu.models.ivf import IVFPQIndex
    from distributed_faiss_tpu.ops.adc_pallas import on_tpu

    n = {"full": 500_000, "small": 20_000, "tiny": 3_000}[size]
    nlist = {"full": 4096, "small": 128, "tiny": 32}[size]
    m = {"full": 64, "small": 16, "tiny": 8}[size]
    d = {"full": 768, "small": 256, "tiny": 64}[size]
    small = size != "full"
    k = 10
    on_chip = on_tpu()
    gen = make_lowrank_corpus(rng, d, r=max(d // 12, 8), n_latent_clusters=2 * nlist)
    x, q = gen(n), gen(128 if small else 512)
    # refine store built at the largest factor we sweep; the factor itself
    # is a search-time knob (adc_k = k * factor)
    idx = IVFPQIndex(d, nlist, m=m, metric="l2", kmeans_iters=8, pq_iters=10,
                     refine_k_factor=32, use_pallas=on_chip, adc_lut_bf16=on_chip)
    t0 = time.time()
    idx.train(x[:min(n, 100_000)])
    idx.add(x)
    note(f"knnlm built in {time.time() - t0:.1f}s")
    exact = FlatIndex(d, "l2")
    exact.add(x)
    _, gt = exact.search(q[:128], k)
    note("ground truth ready")

    nprobes = {"full": [32, 64, 128, 256], "small": [8, 16, 32],
               "tiny": [4, 32]}[size]
    factors = [0, 8, 16, 32] if size != "tiny" else [0, 16]
    rows = grid_rows("knnlm-curve", idx, x, q, gt, k, nprobes, factors,
                     recall_only=recall_only)
    best = pick_operating_point(rows)
    if best is not None:
        idx.set_nprobe(best["nprobe"])
        floor = cpu_ivf_qps(x, np.asarray(idx.get_centroids()),
                            idx.get_assignments(), q[:32], k, best["nprobe"], "l2")
        best = dict(best, config="knnlm-operating-point",
                    cpu_ivf_qps=round(floor, 1))
        if "qps" in best:
            best["vs_cpu_ivf"] = round(best["qps"] / floor, 2)
        print(json.dumps(best), flush=True)


def ivfsq_curve(rng, size, recall_only=False):
    from distributed_faiss_tpu.models.flat import FlatIndex
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n = {"full": 500_000, "small": 50_000, "tiny": 4_000}[size]
    nlist = {"full": 1024, "small": 128, "tiny": 32}[size]
    d = 512 if size != "tiny" else 64
    k = 10
    centers = rng.standard_normal((nlist, d)).astype(np.float32) * 4.0
    from benchmarks.baseline_configs import clustered
    x = clustered(rng, n, d, centers)
    q = clustered(rng, 512, d, centers)
    idx = IVFFlatIndex(d, nlist, "l2", codec="f16", kmeans_iters=8)
    t0 = time.time()
    idx.train(x[:min(n, 100_000)])
    idx.add(x)
    note(f"ivfsq built in {time.time() - t0:.1f}s")
    exact = FlatIndex(d, "l2")
    exact.add(x)
    _, gt = exact.search(q[:128], k)

    nprobes = {"full": [8, 16, 32, 64, 128], "small": [4, 8, 16, 32],
               "tiny": [2, 16]}[size]
    rows = grid_rows("ivfsq-curve", idx, x, q, gt, k, nprobes,
                     recall_only=recall_only)
    best = pick_operating_point(rows)
    if best is not None:
        floor = cpu_ivf_qps(x, np.asarray(idx.get_centroids()),
                            idx.get_assignments(), q[:32], k, best["nprobe"], "l2")
        best = dict(best, config="ivfsq-operating-point",
                    cpu_ivf_qps=round(floor, 1))
        if "qps" in best:
            best["vs_cpu_ivf"] = round(best["qps"] / floor, 2)
        print(json.dumps(best), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU-sized corpora")
    ap.add_argument("--tiny", action="store_true", help="smoke-test sizes")
    ap.add_argument("--only", choices=["knnlm", "ivfsq"], default=None)
    ap.add_argument("--recall-only", action="store_true",
                    help="skip QPS timing (recall is backend-independent: "
                         "lets a CPU box validate the full-size recall bar "
                         "while the chip is unavailable)")
    args = ap.parse_args()
    size = "tiny" if args.tiny else ("small" if args.small else "full")
    rng = np.random.default_rng(7)
    if args.only in (None, "knnlm"):
        knnlm_curve(rng, size, recall_only=args.recall_only)
    if args.only in (None, "ivfsq"):
        ivfsq_curve(rng, size, recall_only=args.recall_only)


if __name__ == "__main__":
    main()
