#!/usr/bin/env python3
"""Multi-client serving throughput: dynamic batching vs per-call launches.

Measures aggregate QPS of T concurrent client threads, each issuing
B-query searches against one engine Index, three ways:

  percall  — each caller drives its own device launch (the reference's
             serving model: one launch per RPC under index_lock)
  natural  — the SearchBatcher with window 0 (callers arriving while a
             launch is in flight coalesce into the next one)
  window   — SearchBatcher with a small wait window (leader waits
             window_ms for followers before launching)

On a launch-bound backend (the TPU relay: ~66 ms/dispatch —
benchmarks/profile_ivf.py) natural/window batching multiplies multi-
client QPS; on CPU the dispatch floor is tiny so the three converge.

Prints one JSON line per mode.
"""

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_mode(idx, mode, queries, n_threads, reps, k=10):
    """Aggregate QPS of n_threads concurrent callers."""
    from distributed_faiss_tpu.utils.batching import SearchBatcher

    if mode == "percall":
        search = idx._device_search
    elif mode == "natural":
        search = SearchBatcher(idx._device_search, window_ms=0).search
    else:
        search = SearchBatcher(idx._device_search, window_ms=3).search

    barrier = threading.Barrier(n_threads + 1)
    errs = []

    def client(tid):
        q = queries[tid]
        barrier.wait()
        try:
            for _ in range(reps):
                search(q, k)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.time()
    for t in ts:
        t.join()
    dt = time.time() - t0
    assert not errs, errs[:1]
    total = n_threads * reps * queries[0].shape[0]
    return total / dt


def main():
    import jax

    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 50_000 if small else 500_000
    d, k = 128, 10
    n_threads, batch, reps = 8, 32, 4 if small else 8

    rng = np.random.default_rng(0)
    centers = rng.standard_normal((256, d)).astype(np.float32) * 4.0
    a = rng.integers(0, 256, n)
    x = (centers[a] + rng.standard_normal((n, d))).astype(np.float32)

    cfg = IndexCfg(index_builder_type="ivfsq", dim=d, metric="l2",
                   train_num=min(n, 100_000), centroids=256, nprobe=4)
    idx = Index(cfg)
    idx.add_batch(x, list(range(n)), train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 1800
    while idx.get_state() != IndexState.TRAINED:
        assert time.time() < deadline, "train timed out"
        time.sleep(0.5)

    queries = [
        (centers[rng.integers(0, 256, batch)]
         + rng.standard_normal((batch, d))).astype(np.float32)
        for _ in range(n_threads)
    ]
    idx.search(queries[0], k)  # warm the jit cache

    backend = jax.devices()[0].platform
    for mode in ("percall", "natural", "window"):
        qps = run_mode(idx, mode, queries, n_threads, reps, k)
        print(json.dumps({
            "case": f"concurrency_{mode}", "backend": backend,
            "threads": n_threads, "batch": batch, "qps": round(qps, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
