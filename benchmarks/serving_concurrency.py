#!/usr/bin/env python3
"""Multi-client serving throughput: dynamic batching vs per-call launches.

Measures aggregate QPS (and per-request p99 latency) of T concurrent
client threads, each issuing B-query searches against one engine Index:

  percall  — each caller drives its own device launch (the reference's
             serving model: one launch per RPC under index_lock)
  natural  — the SearchBatcher with window 0 (callers arriving while a
             launch is in flight coalesce into the next one)
  window   — SearchBatcher with a small wait window (leader waits
             window_ms for followers before launching)

plus the serving-scheduler A/B (``--scheduler``, default both arms):

  scheduler_off — the per-call reference serving shape (same path as
                  percall: one padded device batch per request)
  scheduler_on  — requests flow through serving.SearchScheduler (bounded
                  queue + batcher thread, 2 ms flush window), the path
                  the RPC serving loops use

plus the RPC-multiplexing A/B (``--mux``, default both arms): a real
IndexServer + ONE IndexClient driven by ``--inflight`` threads over
loopback.

  rpc_mux_off — the serial stub (DFT_RPC_MUX=0): the stub lock holds the
                connection for the whole round trip, so one call is in
                flight per rank no matter how many caller threads
  rpc_mux_on  — pipelined stub: the whole in-flight window rides one
                connection and reaches the server scheduler TOGETHER, so
                a single client's W concurrent searches become merged
                device batches (the row reports the max merged
                batch_requests the scheduler observed — >1 is impossible
                in the off arm)

plus the tracing-overhead A/B (``--trace-sample``, off by default): the
mux serving path driven with DFT_TRACE_SAMPLE=0 vs 1 on the same
engine — one JSON row with both arms' qps/p99 and the deltas, so the
observability subsystem's "near-zero when off, bounded when sampled"
claim is a measured number (RESULTS.md),

plus the mesh-sharded serving A/B (``--mesh``, off by default): a
mesh-backed engine (flat corpus sharded over a virtual 8-device CPU
mesh, forced via XLA_FLAGS before jax imports) served per-request vs
through the scheduler:

  mesh_scheduler_off — one device launch per request on the mesh
  mesh_scheduler_on  — merged windows through serving.SearchScheduler;
                       the row reports the engine's new launch counters
                       (launches_per_window_max MUST be exactly 1.0:
                       one pjit launch per merged batch, results leave
                       the device once per window)

The scheduler AND mux arms cross-check RESULT IDENTITY: every client's
results must be byte-identical to direct/sequential serving (the batch
or connection a row rides must not change its answer).

On a launch-bound backend (the TPU relay: ~66 ms/dispatch —
benchmarks/profile_ivf.py) batching multiplies multi-client QPS; on CPU
the dispatch floor is tiny so the gap narrows.

Prints one JSON line per mode/arm (qps, p99_ms) for the trajectory file.
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_clients(search, queries, n_threads, reps, k=10):
    """Drive n_threads concurrent callers of ``search(q, k)``; returns
    (aggregate qps, p99 per-request latency in ms)."""
    barrier = threading.Barrier(n_threads + 1)
    errs = []
    lats = [[] for _ in range(n_threads)]

    def client(tid):
        q = queries[tid]
        barrier.wait()
        try:
            for _ in range(reps):
                t0 = time.perf_counter()
                search(q, k)
                lats[tid].append(time.perf_counter() - t0)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.time()
    for t in ts:
        t.join()
    dt = time.time() - t0
    assert not errs, errs[:1]
    total = n_threads * reps * queries[0].shape[0]
    all_lats = np.array([x for row in lats for x in row])
    return total / dt, float(np.percentile(all_lats, 99) * 1000.0)


def make_search(idx, mode):
    from distributed_faiss_tpu.utils.batching import SearchBatcher

    if mode == "percall":
        return idx._device_search
    if mode == "natural":
        return SearchBatcher(idx._device_search, window_ms=0).search
    if mode == "window":
        return SearchBatcher(idx._device_search, window_ms=3).search
    raise ValueError(mode)


def scheduler_arms(idx, arm):
    """(name, search(q, k)) pairs for the requested --scheduler arm(s)."""
    from distributed_faiss_tpu.serving import SearchScheduler
    from distributed_faiss_tpu.utils.config import SchedulerCfg

    arms = []
    if arm in ("off", "both"):
        # the reference serving shape: one padded launch per request
        arms.append(("scheduler_off", idx._device_search))
    if arm in ("on", "both"):
        sched = SearchScheduler(
            lambda _iid, q, k, _re: idx._device_search(q, k),
            SchedulerCfg(max_wait_ms=2.0, max_batch_rows=1024, max_queue=512),
            name="bench-batcher",
        )
        arms.append(("scheduler_on",
                     lambda q, k: sched.submit("bench", q, k)))
    return arms


def check_identity(idx, arms, queries, k, reps=3):
    """Every client's results must match the direct per-call launch exactly
    — with the arm driven CONCURRENTLY, so the scheduler arm's rows really
    ride merged batches (a sequential probe would submit one request per
    flush and never reach the concat/split path this check exists for)."""
    golden = [idx._device_search(q, k) for q in queries]
    identical = {}
    for name, search in arms:
        res = [[] for _ in queries]
        errs = []
        barrier = threading.Barrier(len(queries))

        def client(t, search=search, res=res, barrier=barrier, errs=errs):
            barrier.wait()
            try:
                for _ in range(reps):
                    res[t].append(search(queries[t], k))
            except Exception as e:  # a silent dead thread would leave
                errs.append(e)      # res[t] empty and the check vacuous

        ts = [threading.Thread(target=client, args=(t,))
              for t in range(len(queries))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, (name, errs[:1])
        arm_ok = True
        for t, (g_scores, g_ids) in enumerate(golden):
            assert len(res[t]) == reps, (name, t, len(res[t]))
            for scores, ids in res[t]:
                if not (np.array_equal(scores, g_scores)
                        and np.array_equal(ids, g_ids)):
                    arm_ok = False
        identical[name] = arm_ok  # per arm: a scheduler divergence must
    return identical              # not stamp the direct-launch row false


def _loopback_server(idx):
    """One IndexServer (blocking loop, scheduler on) serving the trained
    engine over loopback: returns (srv, discovery path, teardown). Light
    teardown only — no srv.stop(), which would save the whole bench
    corpus; the process exits right after the arms."""
    import socket as socketlib
    import tempfile

    from distributed_faiss_tpu.parallel.server import IndexServer
    from distributed_faiss_tpu.utils.config import SchedulerCfg

    tmp = tempfile.mkdtemp(prefix="mux_bench_")
    s = socketlib.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    srv = IndexServer(0, tmp, scheduler_cfg=SchedulerCfg(max_wait_ms=2.0))
    srv.indexes["bench"] = idx  # serve the trained engine directly
    srv._wire_engine(idx)
    threading.Thread(target=srv.start_blocking, args=(port,),
                     daemon=True).start()
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            socketlib.create_connection(("localhost", port), timeout=1).close()
            break
        except OSError:
            time.sleep(0.05)
    disc = os.path.join(tmp, "disc.txt")
    with open(disc, "w") as f:
        f.write(f"1\nlocalhost,{port}\n")

    def teardown():
        srv._stopping.set()
        if srv.socket is not None:
            try:
                srv.socket.close()
            except OSError:
                pass
        if srv.scheduler is not None:
            srv.scheduler.stop()

    return srv, disc, teardown


def _warmed_request_list(idx, queries, k, inflight, mux_batch):
    """Per-caller request batches for a loopback-client arm, with every
    merged-batch jit bucket the scheduler can produce (2..W coalesced
    requests) pre-warmed: without this, first-use compiles of the larger
    row counts land inside the measured window and dominate the
    pipelined arm's p99 (a serial arm only ever launches the native
    size). Shared by the mux and trace-overhead A/Bs so both measure
    identical compile behavior."""
    qlist = [queries[t % len(queries)][:mux_batch] for t in range(inflight)]
    warm = np.concatenate(qlist, axis=0)
    for rows in range(mux_batch, mux_batch * inflight + 1, mux_batch):
        idx.search_batched(warm[:rows], k)
    return qlist


def run_mux_arms(idx, queries, k, arm, inflight, reps, backend,
                 mux_batch=4):
    """RPC-level A/B: one IndexServer (blocking loop, scheduler on) serving
    the already-trained engine, ONE IndexClient per arm, ``inflight``
    caller threads. Returns one JSON-ready row per arm.

    Requests are ``mux_batch`` rows each (default 4): individual user
    queries are small, and small launches sit on the per-dispatch floor —
    the regime multiplexing exists for. The serial arm pays one floor per
    request, serialized; the mux arm's in-flight window coalesces into one
    launch per flush (every backend has a dispatch floor; the TPU relay's
    ~66 ms just makes the same crossover much larger)."""
    from distributed_faiss_tpu.parallel.client import IndexClient

    srv, disc, teardown = _loopback_server(idx)
    qlist = _warmed_request_list(idx, queries, k, inflight, mux_batch)
    arms = [("rpc_mux_off", "0")] if arm in ("off", "both") else []
    if arm in ("on", "both"):
        arms.append(("rpc_mux_on", "1"))

    rows = []
    saved = os.environ.get("DFT_RPC_MUX")
    try:
        # golden: sequential serving through a serial client
        os.environ["DFT_RPC_MUX"] = "0"
        ref = IndexClient(disc)
        ref.cfg = idx.cfg
        golden = [ref.search(q, k, "bench") for q in qlist]
        ref.close()
        for name, env in arms:
            os.environ["DFT_RPC_MUX"] = env
            client = IndexClient(disc)
            client.cfg = idx.cfg
            srv.scheduler.stats.reset()  # per-arm merged-batch observation

            res = [[] for _ in qlist]
            errs = []
            barrier = threading.Barrier(inflight)

            def caller(t, client=client, res=res, errs=errs,
                       barrier=barrier):
                barrier.wait()
                try:
                    for _ in range(reps):
                        res[t].append(client.search(qlist[t], k, "bench"))
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            ts = [threading.Thread(target=caller, args=(t,))
                  for t in range(inflight)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, (name, errs[:1])
            identical = all(
                len(res[t]) == reps
                and all(np.array_equal(sc, golden[t][0]) and m == golden[t][1]
                        for sc, m in res[t])
                for t in range(len(qlist)))

            qps, p99 = run_clients(
                lambda q, kk, client=client: client.search(q, kk, "bench"),
                qlist, inflight, reps, k)
            merged = srv.scheduler.stats.summary().get(
                "batch_requests", {}).get("max_s", 0.0)
            rows.append({
                "case": name, "backend": backend, "threads": inflight,
                "batch": qlist[0].shape[0], "qps": round(qps, 1),
                "p99_ms": round(p99, 2), "identical": identical,
                "merged_batch_max": merged,
            })
            client.close()
    finally:
        if saved is None:
            os.environ.pop("DFT_RPC_MUX", None)
        else:
            os.environ["DFT_RPC_MUX"] = saved
        teardown()
    return rows


def _serialize_microbench(idx, queries, k, encoding, mux_batch, iters=200):
    """Median per-frame-pair (search CALL + tagged RESULT) encode+decode
    cost under one skeleton encoding, measured over a socketpair
    in-process. The deterministic half of the --wire A/B: loopback QPS
    on a compute-bound CPU backend is noisy, the serialization cost per
    frame is not. Returns microseconds per CALL+RESULT round."""
    import socket as socketlib

    from distributed_faiss_tpu.parallel import rpc

    q = queries[0][:mux_batch]
    result = idx.search_batched(q, k)
    meta = {"req_id": 1, "wire": 1}
    a, b = socketlib.socketpair()
    try:
        def one_round(i):
            if encoding == "binary":
                call = rpc.pack_binary_call("search", ("bench", q, k, False),
                                            {}, meta)
                resp = rpc.pack_binary_response(rpc.KIND_RESULT, result, i)
                assert call is not None and resp is not None
            else:
                call = rpc.pack_frame(
                    rpc.KIND_CALL, ("search", ("bench", q, k, False), {},
                                    meta))
                resp = rpc.pack_tagged_response(rpc.KIND_RESULT, result, i)
            rpc._send_parts(a, call)
            rpc.recv_frame(b)
            rpc._send_parts(b, resp)
            rpc.recv_frame(a)

        one_round(0)  # warm
        t0 = time.perf_counter()
        for i in range(iters):
            one_round(i)
        return (time.perf_counter() - t0) / iters * 1e6
    finally:
        a.close()
        b.close()


def run_wire_arms(idx, queries, k, arm, inflight, reps, backend,
                  mux_batch=4):
    """Binary-wire A/B (ISSUE 14): the same loopback server + ONE mux
    IndexClient per arm, with DFT_RPC_WIRE flipped client-side —
    ``pickle`` never advertises, so the whole path stays on pickle
    skeletons; ``binary`` negotiates per connection and the hot search
    frames ride the compact binary encoding. Each row reports QPS/p99,
    the identity check vs sequential pickle serving, whether the stub
    actually negotiated, and the in-process per-frame serialization
    microbench (encode+decode of one CALL+RESULT pair)."""
    from distributed_faiss_tpu.parallel.client import IndexClient

    srv, disc, teardown = _loopback_server(idx)
    qlist = _warmed_request_list(idx, queries, k, inflight, mux_batch)
    arms = [("wire_pickle", "pickle")] if arm in ("pickle", "both") else []
    if arm in ("binary", "both"):
        arms.append(("wire_binary", "binary"))

    rows = []
    saved = os.environ.get("DFT_RPC_WIRE")
    try:
        os.environ["DFT_RPC_WIRE"] = "pickle"
        ref = IndexClient(disc)
        ref.cfg = idx.cfg
        golden = [ref.search(q, k, "bench") for q in qlist]
        ref.close()
        for name, env in arms:
            os.environ["DFT_RPC_WIRE"] = env
            client = IndexClient(disc)
            client.cfg = idx.cfg
            client.search(qlist[0], k, "bench")  # dial + negotiate

            res = [[] for _ in qlist]
            errs = []
            barrier = threading.Barrier(inflight)

            def caller(t, client=client, res=res, errs=errs,
                       barrier=barrier):
                barrier.wait()
                try:
                    for _ in range(reps):
                        res[t].append(client.search(qlist[t], k, "bench"))
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            ts = [threading.Thread(target=caller, args=(t,))
                  for t in range(inflight)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, (name, errs[:1])
            identical = all(
                len(res[t]) == reps
                and all(np.array_equal(sc, golden[t][0]) and m == golden[t][1]
                        for sc, m in res[t])
                for t in range(len(qlist)))

            qps, p99 = run_clients(
                lambda q, kk, client=client: client.search(q, kk, "bench"),
                qlist, inflight, reps, k)
            negotiated = client.sub_indexes[0].rpc_stats()["peer_wire"]
            rows.append({
                "case": name, "backend": backend, "threads": inflight,
                "batch": qlist[0].shape[0], "qps": round(qps, 1),
                "p99_ms": round(p99, 2), "identical": identical,
                "negotiated": negotiated,
                "serialize_us_per_call_result": round(
                    _serialize_microbench(idx, queries, k, env, mux_batch),
                    2),
            })
            client.close()
    finally:
        if saved is None:
            os.environ.pop("DFT_RPC_WIRE", None)
        else:
            os.environ["DFT_RPC_WIRE"] = saved
        teardown()
    return rows


def run_trace_arms(idx, queries, k, inflight, reps, backend, mux_batch=4):
    """Tracing-overhead A/B (the ISSUE 13 acceptance number): the same
    loopback server + ONE mux IndexClient serving ``inflight`` caller
    threads, once with DFT_TRACE_SAMPLE=0 (tracing off — the claim is
    byte-identical frames and near-zero cost) and once with =1 (every
    request traced end to end — the worst case; production samples a
    fraction). Returns one JSON row carrying both arms AND the deltas,
    so "near-zero when off, bounded when sampled" is a measured number
    in RESULTS.md, not an assertion."""
    from distributed_faiss_tpu.parallel.client import IndexClient

    srv, disc, teardown = _loopback_server(idx)
    qlist = _warmed_request_list(idx, queries, k, inflight, mux_batch)
    results = {}
    saved = os.environ.get("DFT_TRACE_SAMPLE")
    try:
        for name, env in (("off", "0"), ("on", "1")):
            os.environ["DFT_TRACE_SAMPLE"] = env
            client = IndexClient(disc)
            client.cfg = idx.cfg
            spans0 = srv.spans.stats()["recorded"]
            qps, p99 = run_clients(
                lambda q, kk, client=client: client.search(q, kk, "bench"),
                qlist, inflight, reps, k)
            results[name] = {
                "qps": qps, "p99_ms": p99,
                "spans": srv.spans.stats()["recorded"] - spans0,
            }
            client.close()
    finally:
        if saved is None:
            os.environ.pop("DFT_TRACE_SAMPLE", None)
        else:
            os.environ["DFT_TRACE_SAMPLE"] = saved
        teardown()
    off, on = results["off"], results["on"]
    return [{
        "case": "trace_overhead", "backend": backend, "threads": inflight,
        "batch": mux_batch,
        "qps_off": round(off["qps"], 1), "qps_on": round(on["qps"], 1),
        "p99_off_ms": round(off["p99_ms"], 2),
        "p99_on_ms": round(on["p99_ms"], 2),
        "qps_delta_pct": round(
            100.0 * (off["qps"] - on["qps"]) / max(off["qps"], 1e-9), 2),
        "p99_delta_pct": round(
            100.0 * (on["p99_ms"] - off["p99_ms"])
            / max(off["p99_ms"], 1e-9), 2),
        "spans_off": off["spans"], "spans_on": on["spans"],
    }]


def run_mesh_arms(arm, n_threads=8, batch=32, reps=4, k=10):
    """Mesh-sharded serving A/B: per-request launches vs scheduler-merged
    windows against ONE mesh-backed engine rank. Returns JSON-ready rows
    carrying the launch counters (ISSUE 6 acceptance: exactly one device
    launch per merged window, identical results across arms)."""
    import jax

    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.parallel.mesh import ShardedFlatIndex
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    small = os.environ.get("BENCH_SMALL") == "1"
    n, d = (50_000 if small else 200_000), 64
    rng = np.random.default_rng(1)
    x = rng.standard_normal((n, d)).astype(np.float32)
    cfg = IndexCfg(index_builder_type="flat", dim=d, metric="l2",
                   train_num=1024, mesh_shards=True)
    idx = Index(cfg)
    idx.add_batch(x, list(range(n)), train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 1800
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "mesh train/drain timed out"
        time.sleep(0.2)
    assert isinstance(idx.tpu_index, ShardedFlatIndex)
    ndev = idx.tpu_index.nshards

    queries = [rng.standard_normal((batch, d)).astype(np.float32)
               for _ in range(n_threads)]
    idx.search(queries[0], k)  # warm the jit cache
    # warm the merged-window row buckets the scheduler can produce
    warm = np.concatenate(queries, axis=0)
    for rows in range(batch, batch * n_threads + 1, batch):
        idx.search_batched(warm[:rows], k)

    arms = scheduler_arms(idx, arm)
    identical = check_identity(idx, arms, queries, k)
    backend = jax.devices()[0].platform
    out = []
    for name, search in arms:
        idx.perf.reset()
        qps, p99 = run_clients(search, queries, n_threads, reps, k)
        s = idx.perf.summary()
        launches = s.get("device_launches", {})
        out.append({
            "case": f"mesh_{name}", "backend": backend,
            "mesh_devices": ndev, "threads": n_threads, "batch": batch,
            "qps": round(qps, 1), "p99_ms": round(p99, 2),
            "identical": identical[name],
            "launches_per_window_max": launches.get("max_s", 0.0),
            "windows": launches.get("count", 0),
            "rows_per_launch_max":
                s.get("rows_per_launch", {}).get("max_s", 0.0),
        })
    return out


def run_churn_arm(n_threads=8, batch=32, reps=8, k=10):
    """Mutable-corpora churn arm (mutation subsystem): interleaved
    delete/upsert under a live query storm, with and without an active
    compaction pass.

      churn_idle       — baseline: storm only, no mutations
      churn_mutating   — storm + a mutator thread upserting/deleting ids
                         between launches (tombstones accumulate)
      churn_compacting — same storm while a compaction pass rewrites 30%
                         tombstoned rows into a fresh generation mid-run
                         (phase 2 overlaps serving; the commit+swap holds
                         the engine locks briefly)

    Identity is asserted the strong way: after every arm, no deleted id
    may appear in a verification search.
    """
    import tempfile

    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState
    import jax

    backend = jax.devices()[0].platform
    small = os.environ.get("BENCH_SMALL") == "1"
    n = 20_000 if small else 200_000
    d = 128
    rng = np.random.default_rng(3)
    x = rng.standard_normal((n, d)).astype(np.float32)
    tmp = tempfile.mkdtemp(prefix="dft-churn-")
    os.environ["DFT_COMPACT"] = "0"  # the arm drives compaction explicitly
    cfg = IndexCfg(index_builder_type="ivfsq", dim=d, metric="l2",
                   train_num=min(n, 50_000), centroids=128, nprobe=4,
                   index_storage_dir=os.path.join(tmp, "shard"))
    idx = Index(cfg)
    idx.add_batch(x, [(i,) for i in range(n)],
                  train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 1800
    while (idx.get_state() != IndexState.TRAINED
           or idx.get_idx_data_num()[0] > 0):
        assert time.time() < deadline, "churn arm train/drain timed out"
        time.sleep(0.5)
    queries = [
        x[rng.integers(0, n, batch)] + 0.01 for _ in range(n_threads)]
    idx.search(queries[0], k)  # warm the jit cache

    def storm(extra=None):
        stop = threading.Event()
        state = {"mutations": 0}
        side = None
        if extra is not None:
            side = threading.Thread(target=extra, args=(stop, state),
                                    daemon=True)
            side.start()
        def churn_search(q, kk):
            # ride through the engine's transient mid-ADD rejection (the
            # drain window an R>=2 client fails over across — the retry
            # wait is honest single-replica serving cost here)
            while True:
                try:
                    return idx.search(q, kk)
                except RuntimeError as e:
                    if "IndexState.ADD" not in str(e):
                        raise
                    time.sleep(0.0005)

        qps, p99 = run_clients(churn_search, queries, n_threads, reps, k)
        stop.set()
        if side is not None:
            side.join(timeout=60)
        return qps, p99, state

    def mutator(stop, state):
        mrng = np.random.default_rng(11)
        next_id = n
        while not stop.is_set():
            victims = mrng.integers(0, n, 8).tolist()
            idx.remove_ids(victims)
            # upsert: re-add half of them with fresh vectors
            up = victims[:4]
            idx.upsert(up, mrng.standard_normal((4, d)).astype(np.float32),
                       [(i,) for i in up])
            state["mutations"] += 12
            next_id += 4
            time.sleep(0.002)

    rows = []
    qps, p99, _ = storm()
    rows.append({"case": "churn_idle", "backend": backend,
                 "threads": n_threads, "batch": batch,
                 "qps": round(qps, 1), "p99_ms": round(p99, 2)})

    qps, p99, st = storm(mutator)
    rows.append({"case": "churn_mutating", "backend": backend,
                 "threads": n_threads, "batch": batch,
                 "qps": round(qps, 1), "p99_ms": round(p99, 2),
                 "mutations": st["mutations"]})

    # cross the compaction threshold, then run the storm with the pass
    # live. The previous arm's upserts may still be draining when this
    # starts; compact() aborts (returns False) if an ADD lands
    # mid-rebuild, so retry until a pass commits — an uncaught assert in
    # a daemon thread would otherwise surface only minutes later as an
    # undiagnosable compactions==0 failure.
    idx.remove_ids(list(range(0, int(0.3 * n), 1)))

    def compactor(stop, state):
        deadline = time.time() + 120
        while not idx.compact():
            assert time.time() < deadline, "compaction never committed"
            time.sleep(0.2)

    qps, p99, st = storm(compactor)
    mu = idx.mutation_stats()
    rows.append({"case": "churn_compacting", "backend": backend,
                 "threads": n_threads, "batch": batch,
                 "qps": round(qps, 1), "p99_ms": round(p99, 2),
                 "compactions": mu["compactions"],
                 "compaction_s": round(
                     mu.get("compaction_s", {}).get("max_s", 0.0), 3)})
    assert mu["compactions"] >= 1, mu
    # the strong check: no tombstoned id in a verification search
    d_, m_, _ = idx.search(x[:64], k)
    dead = {(i,) for i in range(0, int(0.3 * n))}
    assert not any(mm in dead for row in m_ for mm in row)
    return rows


def run_convergence_arm(reps=1):
    """Versioning A/B (ISSUE 12): time an R=2 group's server-side
    anti-entropy convergence to IDENTICAL wire digests after a one-sided
    mutation burst (deletes + upserts applied to one replica only — the
    outage shape), with per-id versions on vs off.

    Each arm reports ``convergence_s`` (burst -> byte-identical digests
    over the wire) and ``upserts_replicated``: whether the peer replica
    ends up serving the upserted VECTORS. With versioning on the sweep
    refresh-pulls them (rows_refreshed); with versioning off the id-only
    digest cannot see an in-place upsert, so the digests converge while
    the content silently doesn't — the exact blind spot the versioned
    plane exists to close (the row records it honestly)."""
    import socket as socketlib
    import tempfile
    import threading

    from distributed_faiss_tpu.mutation.versions import HLC
    from distributed_faiss_tpu.parallel import antientropy, rpc
    from distributed_faiss_tpu.parallel.client import IndexClient
    from distributed_faiss_tpu.parallel.server import IndexServer
    from distributed_faiss_tpu.utils.config import (
        AntiEntropyCfg,
        IndexCfg,
        ReplicationCfg,
        VersioningCfg,
    )
    from distributed_faiss_tpu.utils.state import IndexState
    import jax

    backend = jax.devices()[0].platform
    small = os.environ.get("BENCH_SMALL") == "1"
    n, d, burst = (4_000 if small else 20_000), 64, 64

    def free_port():
        s = socketlib.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def wire_digest(port, index_id):
        resp = rpc.digest_exchange(
            "localhost", port, {"rank": None, "group": None, "want": None},
            timeout=5.0)
        return resp["digests"].get(index_id)

    rows = []
    for versioned in (True, False):
        tmp = tempfile.mkdtemp(prefix="dft-vconv-")
        pa, pb = free_port(), free_port()
        disc = os.path.join(tmp, "disc.txt")
        with open(disc, "w") as f:
            f.write(f"2\nlocalhost,{pa}\nlocalhost,{pb}\n")
        ae = AntiEntropyCfg(interval_s=0.25)
        a = IndexServer(0, os.path.join(tmp, "a"), discovery_path=disc,
                        antientropy_cfg=ae)
        b = IndexServer(1, os.path.join(tmp, "b"), discovery_path=disc,
                        antientropy_cfg=ae)
        threading.Thread(target=a.start_blocking, args=(pa,),
                         daemon=True).start()
        threading.Thread(target=b.start_blocking, args=(pb,),
                         daemon=True).start()
        time.sleep(0.5)
        client = IndexClient(
            disc,
            replication_cfg=ReplicationCfg(replication=2, write_quorum=1),
            versioning_cfg=VersioningCfg(enabled=versioned))
        try:
            cfg = IndexCfg(index_builder_type="flat", dim=d, metric="l2",
                           train_num=min(n, 2048))
            client.create_index("conv", cfg)
            rng = np.random.default_rng(7)
            x = rng.standard_normal((n, d)).astype(np.float32)
            step = max(n // 4, 1)
            for s in range(0, n, step):
                client.add_index_data(
                    "conv", x[s:s + step],
                    [(i,) for i in range(s, min(s + step, n))])
            deadline = time.time() + 600
            while not (client.get_state("conv") == IndexState.TRAINED
                       and client.get_buffer_depth("conv") == 0):
                assert time.time() < deadline, "ingest never drained"
                time.sleep(0.1)
            deadline = time.time() + 120
            while wire_digest(pa, "conv") != wire_digest(pb, "conv"):
                assert time.time() < deadline, "never converged pre-burst"
                time.sleep(0.2)

            # one-sided burst on rank A only (the outage shape): deletes
            # + upserts the peer never saw
            clock = HLC(writer_id=99)
            eng = a._get_index("conv")
            dead_ids = list(range(0, burst))
            up_ids = list(range(burst, 2 * burst))
            new_vecs = (x[up_ids] + 0.5).astype(np.float32)
            eng.remove_ids(dead_ids,
                           version=clock.tick() if versioned else None)
            eng.upsert(up_ids, new_vecs, [(i,) for i in up_ids],
                       version=clock.tick() if versioned else None)
            while a.get_aggregated_ntotal("conv") > 0:
                time.sleep(0.05)
            t0 = time.perf_counter()
            deadline = time.time() + 300
            while True:
                da, db = wire_digest(pa, "conv"), wire_digest(pb, "conv")
                if da is not None and da == db:
                    break
                assert time.time() < deadline, "burst never converged"
                time.sleep(0.1)
            dt = time.perf_counter() - t0
            while b.get_aggregated_ntotal("conv") > 0:
                time.sleep(0.05)
            # did the upserted CONTENT replicate to the peer? exact-match
            # distance, not nearest-id: the stale row is still the
            # nearest ID to its own upsert, so only a ~zero l2 distance
            # proves the peer serves the new VECTORS
            sc, meta, _e = b._get_index("conv").search(new_vecs[:8], 1)
            replicated = (
                [m[0] for m in meta] == [(i,) for i in up_ids[:8]]
                and float(np.abs(sc).max()) < 1e-3)
            ae_stats = b.get_perf_stats()["antientropy"]
            rows.append({
                "case": "churn_convergence", "backend": backend,
                "versioning": "on" if versioned else "off",
                "rows": n, "burst_deletes": burst, "burst_upserts": burst,
                "convergence_s": round(dt, 2),
                "rows_repaired": ae_stats["rows_repaired"],
                "rows_refreshed": ae_stats.get("rows_refreshed", 0),
                "upserts_replicated": replicated,
            })
        finally:
            client.close()
            for srv in (a, b):
                # light teardown (run_mux_arms precedent): no full stop()
                # saves — the process exits right after the arms
                srv._stopping.set()
                if srv._antientropy is not None:
                    srv._antientropy.stop()
                if srv.socket is not None:
                    try:
                        srv.socket.close()
                    except OSError:
                        pass
                if srv.scheduler is not None:
                    srv.scheduler.stop()
    # the headline contract: versions make the sweep converge CONTENT,
    # not just id sets
    by_arm = {r["versioning"]: r for r in rows}
    assert by_arm["on"]["upserts_replicated"] is True, by_arm
    return rows


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--scheduler", choices=("on", "off", "both", "none"), default="both",
        help="serving-scheduler A/B arm(s) to run (default: both, with a "
             "result-identity cross-check)")
    parser.add_argument(
        "--mux", choices=("on", "off", "both", "none"), default="both",
        help="RPC-multiplexing A/B arm(s): real server + ONE IndexClient "
             "over loopback (default: both, with identity cross-check and "
             "the merged-batch observation)")
    parser.add_argument(
        "--inflight", type=int, default=8, metavar="W",
        help="concurrent caller threads on the single mux-arm client "
             "(the per-connection in-flight window; default 8)")
    parser.add_argument(
        "--mux-batch", type=int, default=4,
        help="rows per request in the mux arms (default 4: user-sized "
             "requests riding the per-launch dispatch floor)")
    parser.add_argument(
        "--wire", choices=("binary", "pickle", "both", "none"),
        default="none",
        help="binary-wire A/B arm(s): the mux serving path with "
             "DFT_RPC_WIRE=pickle vs binary on the same engine — per-arm "
             "qps/p99, cross-arm identity, negotiation check, and an "
             "in-process per-frame serialization microbench (default: "
             "none)")
    parser.add_argument(
        "--trace-sample", action="store_true",
        help="tracing-overhead A/B arm: the mux serving path with "
             "DFT_TRACE_SAMPLE=0 vs 1 on the same engine — one JSON row "
             "with both arms' qps/p99 and the deltas (off by default)")
    parser.add_argument(
        "--mesh", choices=("on", "off", "both", "none"), default="none",
        help="mesh-sharded serving A/B arm(s) on a virtual 8-device CPU "
             "mesh (forces XLA_FLAGS before jax imports; default: none — "
             "run with --mesh both for the one-launch-per-window check)")
    parser.add_argument(
        "--churn", choices=("on", "convergence", "both", "none"),
        default="none",
        help="mutable-corpora churn arms: 'on' = interleaved delete/upsert "
             "under a live query storm with/without an active compaction "
             "pass; 'convergence' = R=2 anti-entropy "
             "convergence-to-identical-digests after a one-sided mutation "
             "burst, per-id versioning on vs off (default: none)")
    parser.add_argument(
        "--modes", default="percall,natural,window",
        help="comma list of legacy batcher modes to run ('' = skip)")
    args = parser.parse_args()

    if args.mesh != "none":
        # must land before the first jax import anywhere in this process
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        if (any(args.modes.split(",")) or args.scheduler != "none"
                or args.mux != "none"):
            # the flag is process-wide: every arm in this invocation runs
            # on the forced topology, so its rows are not comparable to
            # single-device baseline rows (RESULTS.md r6-r8)
            print("WARNING: --mesh forces an 8-virtual-device host platform "
                  "for the whole process; run the scheduler/mux/legacy arms "
                  "in a separate invocation for baseline-comparable rows",
                  file=sys.stderr, flush=True)

    import jax

    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 50_000 if small else 500_000
    d, k = 128, 10
    n_threads, batch, reps = 8, 32, 4 if small else 8
    backend = jax.devices()[0].platform

    modes = [m for m in args.modes.split(",") if m]
    need_single = (bool(modes) or args.scheduler != "none"
                   or args.mux != "none" or args.trace_sample
                   or args.wire != "none")
    if need_single:
        rng = np.random.default_rng(0)
        centers = rng.standard_normal((256, d)).astype(np.float32) * 4.0
        a = rng.integers(0, 256, n)
        x = (centers[a] + rng.standard_normal((n, d))).astype(np.float32)

        cfg = IndexCfg(index_builder_type="ivfsq", dim=d, metric="l2",
                       train_num=min(n, 100_000), centroids=256, nprobe=4)
        idx = Index(cfg)
        idx.add_batch(x, list(range(n)), train_async_if_triggered=False)
        idx.train()
        deadline = time.time() + 1800
        while idx.get_state() != IndexState.TRAINED:
            assert time.time() < deadline, "train timed out"
            time.sleep(0.5)

        queries = [
            (centers[rng.integers(0, 256, batch)]
             + rng.standard_normal((batch, d))).astype(np.float32)
            for _ in range(n_threads)
        ]
        idx.search(queries[0], k)  # warm the jit cache

    for mode in modes:
        qps, p99 = run_clients(make_search(idx, mode), queries,
                               n_threads, reps, k)
        print(json.dumps({
            "case": f"concurrency_{mode}", "backend": backend,
            "threads": n_threads, "batch": batch, "qps": round(qps, 1),
            "p99_ms": round(p99, 2),
        }), flush=True)

    if args.scheduler != "none":
        arms = scheduler_arms(idx, args.scheduler)
        identical = check_identity(idx, arms, queries, k)
        for name, search in arms:
            qps, p99 = run_clients(search, queries, n_threads, reps, k)
            print(json.dumps({
                "case": name, "backend": backend, "threads": n_threads,
                "batch": batch, "qps": round(qps, 1),
                "p99_ms": round(p99, 2), "identical": identical[name],
            }), flush=True)
        assert all(identical.values()), \
            f"results diverged from direct launches: {identical}"

    if args.mux != "none":
        rows = run_mux_arms(idx, queries, k, args.mux, args.inflight,
                            reps, backend, mux_batch=args.mux_batch)
        for row in rows:
            print(json.dumps(row), flush=True)
        assert all(r["identical"] for r in rows), \
            f"mux results diverged from sequential serving: {rows}"
        by_case = {r["case"]: r for r in rows}
        if "rpc_mux_on" in by_case:
            # the tentpole observation: a single client's in-flight window
            # reached the scheduler as one merged batch (impossible with
            # the serial stub)
            assert by_case["rpc_mux_on"]["merged_batch_max"] > 1, by_case

    if args.wire != "none":
        rows = run_wire_arms(idx, queries, k, args.wire, args.inflight,
                             reps, backend, mux_batch=args.mux_batch)
        for row in rows:
            print(json.dumps(row), flush=True)
        assert all(r["identical"] for r in rows), \
            f"wire results diverged from sequential pickle serving: {rows}"
        by_case = {r["case"]: r for r in rows}
        if "wire_binary" in by_case:
            assert by_case["wire_binary"]["negotiated"] is True, by_case
        if len(by_case) == 2:
            # the tentpole number: the binary skeleton encodes+decodes a
            # CALL+RESULT pair measurably cheaper than pickle
            assert (by_case["wire_binary"]["serialize_us_per_call_result"]
                    < by_case["wire_pickle"]["serialize_us_per_call_result"]), \
                by_case

    if args.trace_sample:
        rows = run_trace_arms(idx, queries, k, args.inflight, reps,
                              backend, mux_batch=args.mux_batch)
        for row in rows:
            print(json.dumps(row), flush=True)
        # the off arm must stay within noise of untraced serving; the on
        # arm is the 100%-sampled worst case and merely needs to be
        # bounded (spans actually recorded proves the arm traced)
        assert rows[0]["spans_on"] > 0 and rows[0]["spans_off"] == 0, rows

    if args.mesh != "none":
        rows = run_mesh_arms(args.mesh, n_threads=n_threads, batch=batch,
                             reps=reps, k=k)
        for row in rows:
            print(json.dumps(row), flush=True)
        assert all(r["identical"] for r in rows), \
            f"mesh results diverged from direct launches: {rows}"
        for r in rows:
            # the ISSUE 6 acceptance: every merged window crossed to the
            # mesh as exactly ONE pjit launch
            assert r["launches_per_window_max"] == 1.0, r

    if args.churn in ("on", "both"):
        for row in run_churn_arm(n_threads=n_threads, batch=batch,
                                 reps=reps, k=k):
            print(json.dumps(row), flush=True)

    if args.churn in ("convergence", "both"):
        for row in run_convergence_arm():
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
