#!/usr/bin/env python3
"""Multi-client serving throughput: dynamic batching vs per-call launches.

Measures aggregate QPS (and per-request p99 latency) of T concurrent
client threads, each issuing B-query searches against one engine Index:

  percall  — each caller drives its own device launch (the reference's
             serving model: one launch per RPC under index_lock)
  natural  — the SearchBatcher with window 0 (callers arriving while a
             launch is in flight coalesce into the next one)
  window   — SearchBatcher with a small wait window (leader waits
             window_ms for followers before launching)

plus the serving-scheduler A/B (``--scheduler``, default both arms):

  scheduler_off — the per-call reference serving shape (same path as
                  percall: one padded device batch per request)
  scheduler_on  — requests flow through serving.SearchScheduler (bounded
                  queue + batcher thread, 2 ms flush window), the path
                  the RPC serving loops use

The scheduler arms also cross-check RESULT IDENTITY: every client's
scheduler-on (scores, ids) must be byte-identical to its scheduler-off
results (the batch a row rides in must not change its answer).

On a launch-bound backend (the TPU relay: ~66 ms/dispatch —
benchmarks/profile_ivf.py) batching multiplies multi-client QPS; on CPU
the dispatch floor is tiny so the gap narrows.

Prints one JSON line per mode/arm (qps, p99_ms) for the trajectory file.
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_clients(search, queries, n_threads, reps, k=10):
    """Drive n_threads concurrent callers of ``search(q, k)``; returns
    (aggregate qps, p99 per-request latency in ms)."""
    barrier = threading.Barrier(n_threads + 1)
    errs = []
    lats = [[] for _ in range(n_threads)]

    def client(tid):
        q = queries[tid]
        barrier.wait()
        try:
            for _ in range(reps):
                t0 = time.perf_counter()
                search(q, k)
                lats[tid].append(time.perf_counter() - t0)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.time()
    for t in ts:
        t.join()
    dt = time.time() - t0
    assert not errs, errs[:1]
    total = n_threads * reps * queries[0].shape[0]
    all_lats = np.array([x for row in lats for x in row])
    return total / dt, float(np.percentile(all_lats, 99) * 1000.0)


def make_search(idx, mode):
    from distributed_faiss_tpu.utils.batching import SearchBatcher

    if mode == "percall":
        return idx._device_search
    if mode == "natural":
        return SearchBatcher(idx._device_search, window_ms=0).search
    if mode == "window":
        return SearchBatcher(idx._device_search, window_ms=3).search
    raise ValueError(mode)


def scheduler_arms(idx, arm):
    """(name, search(q, k)) pairs for the requested --scheduler arm(s)."""
    from distributed_faiss_tpu.serving import SearchScheduler
    from distributed_faiss_tpu.utils.config import SchedulerCfg

    arms = []
    if arm in ("off", "both"):
        # the reference serving shape: one padded launch per request
        arms.append(("scheduler_off", idx._device_search))
    if arm in ("on", "both"):
        sched = SearchScheduler(
            lambda _iid, q, k, _re: idx._device_search(q, k),
            SchedulerCfg(max_wait_ms=2.0, max_batch_rows=1024, max_queue=512),
            name="bench-batcher",
        )
        arms.append(("scheduler_on",
                     lambda q, k: sched.submit("bench", q, k)))
    return arms


def check_identity(idx, arms, queries, k, reps=3):
    """Every client's results must match the direct per-call launch exactly
    — with the arm driven CONCURRENTLY, so the scheduler arm's rows really
    ride merged batches (a sequential probe would submit one request per
    flush and never reach the concat/split path this check exists for)."""
    golden = [idx._device_search(q, k) for q in queries]
    identical = {}
    for name, search in arms:
        res = [[] for _ in queries]
        errs = []
        barrier = threading.Barrier(len(queries))

        def client(t, search=search, res=res, barrier=barrier, errs=errs):
            barrier.wait()
            try:
                for _ in range(reps):
                    res[t].append(search(queries[t], k))
            except Exception as e:  # a silent dead thread would leave
                errs.append(e)      # res[t] empty and the check vacuous

        ts = [threading.Thread(target=client, args=(t,))
              for t in range(len(queries))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, (name, errs[:1])
        arm_ok = True
        for t, (g_scores, g_ids) in enumerate(golden):
            assert len(res[t]) == reps, (name, t, len(res[t]))
            for scores, ids in res[t]:
                if not (np.array_equal(scores, g_scores)
                        and np.array_equal(ids, g_ids)):
                    arm_ok = False
        identical[name] = arm_ok  # per arm: a scheduler divergence must
    return identical              # not stamp the direct-launch row false


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--scheduler", choices=("on", "off", "both", "none"), default="both",
        help="serving-scheduler A/B arm(s) to run (default: both, with a "
             "result-identity cross-check)")
    parser.add_argument(
        "--modes", default="percall,natural,window",
        help="comma list of legacy batcher modes to run ('' = skip)")
    args = parser.parse_args()

    import jax

    from distributed_faiss_tpu.engine import Index
    from distributed_faiss_tpu.utils.config import IndexCfg
    from distributed_faiss_tpu.utils.state import IndexState

    small = os.environ.get("BENCH_SMALL") == "1"
    n = 50_000 if small else 500_000
    d, k = 128, 10
    n_threads, batch, reps = 8, 32, 4 if small else 8

    rng = np.random.default_rng(0)
    centers = rng.standard_normal((256, d)).astype(np.float32) * 4.0
    a = rng.integers(0, 256, n)
    x = (centers[a] + rng.standard_normal((n, d))).astype(np.float32)

    cfg = IndexCfg(index_builder_type="ivfsq", dim=d, metric="l2",
                   train_num=min(n, 100_000), centroids=256, nprobe=4)
    idx = Index(cfg)
    idx.add_batch(x, list(range(n)), train_async_if_triggered=False)
    idx.train()
    deadline = time.time() + 1800
    while idx.get_state() != IndexState.TRAINED:
        assert time.time() < deadline, "train timed out"
        time.sleep(0.5)

    queries = [
        (centers[rng.integers(0, 256, batch)]
         + rng.standard_normal((batch, d))).astype(np.float32)
        for _ in range(n_threads)
    ]
    idx.search(queries[0], k)  # warm the jit cache

    backend = jax.devices()[0].platform
    modes = [m for m in args.modes.split(",") if m]
    for mode in modes:
        qps, p99 = run_clients(make_search(idx, mode), queries,
                               n_threads, reps, k)
        print(json.dumps({
            "case": f"concurrency_{mode}", "backend": backend,
            "threads": n_threads, "batch": batch, "qps": round(qps, 1),
            "p99_ms": round(p99, 2),
        }), flush=True)

    if args.scheduler != "none":
        arms = scheduler_arms(idx, args.scheduler)
        identical = check_identity(idx, arms, queries, k)
        for name, search in arms:
            qps, p99 = run_clients(search, queries, n_threads, reps, k)
            print(json.dumps({
                "case": name, "backend": backend, "threads": n_threads,
                "batch": batch, "qps": round(qps, 1),
                "p99_ms": round(p99, 2), "identical": identical[name],
            }), flush=True)
        assert all(identical.values()), \
            f"results diverged from direct launches: {identical}"


if __name__ == "__main__":
    main()
