#!/usr/bin/env python3
"""Benchmark runner for the five BASELINE.md configs.

    python benchmarks/baseline_configs.py [--small] [--config NAME]

Measures train+add wall-clock and search QPS (with recall@10 against an
exact fp32 ground truth) for each config BASELINE.md lists:

  flat       — brute-force L2, SIFT1M-like (dim=128), single shard
  ivf_simple — dot, dim=128, centroids=64, nprobe=12
  knnlm      — IVF-PQ, dim=768, 4096 centroids, PQ m=64x8 (scaled in --small)
  ivfsq      — fp16 IVF, dim=512, 1024 centroids
  sharded    — 8-way cluster (in-process loopback servers), client-side
               merge, nprobe sweep

Prints one JSON line per config (bench.py stays the driver's single-line
entry point; this is the full matrix).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def clustered(rng, n, d, centers):
    assign = rng.integers(0, centers.shape[0], n)
    return (centers[assign] + rng.standard_normal((n, d)).astype(np.float32)).astype(np.float32)


def make_lowrank_corpus(rng, d, r, n_latent_clusters, ambient_sigma=0.05):
    """Sampler for a low-intrinsic-dimension clustered corpus.

    The knnlm config models a kNN-LM datastore: transformer hidden states,
    which concentrate on a low-dimensional manifold of the 768-d ambient
    space. An *isotropic* 768-d gaussian mixture is the known-degenerate
    case for every quantization-based ANN method (distance concentration:
    same-cluster pairwise distances all converge to sqrt(2d)·sigma, so PQ
    distortion swamps the true-neighbor margins — measured here: FAISS-style
    IVF-PQ saturates at recall@10 = 0.93 even at nprobe == nlist). Low-rank
    structure is what makes PQ-based ANN meaningful at d=768, for the
    reference's FAISS backend exactly as for ours.

    Latents: mixture of ``n_latent_clusters`` gaussians in r dims, embedded
    by a fixed random orthonormal (r, d) map, plus small isotropic ambient
    noise. Returns gen(nn) -> (nn, d) fp32.
    """
    W = np.linalg.qr(rng.standard_normal((d, r)))[0].T.astype(np.float32)
    centers_z = rng.standard_normal((n_latent_clusters, r)).astype(np.float32) * 4.0

    def gen(nn):
        a = rng.integers(0, centers_z.shape[0], nn)
        z = centers_z[a] + rng.standard_normal((nn, r)).astype(np.float32)
        x = z @ W + ambient_sigma * rng.standard_normal((nn, d)).astype(np.float32)
        return x.astype(np.float32)

    return gen


def recall_at_k(ids, gt, k):
    return float(np.mean([len(set(ids[i][:k]) & set(gt[i][:k])) / k for i in range(len(gt))]))


def measure_qps(search_fn, q, k, reps=3):
    search_fn(q[:64], k)  # warm
    t0 = time.time()
    for _ in range(reps):
        search_fn(q, k)
    return reps * q.shape[0] / (time.time() - t0)


def cpu_exact_qps(x, q, k, metric, repeats=2):
    """numpy/BLAS brute-force top-k — the measurable CPU floor in this image.

    faiss-cpu (the reference's substrate, its setup.py:31) is NOT installable
    here (no package in the image, installs forbidden); a BLAS exact scan is
    the same arithmetic its IndexFlat runs. IVF baselines would beat this
    floor by ~nlist/nprobe, so treat vs_cpu_exact as an upper bound on the
    vs-FAISS-exact ratio, not a vs-FAISS-IVF number.
    """
    t0 = time.time()
    for _ in range(repeats):
        if metric == "l2":
            d2 = (x * x).sum(1)[None, :] - 2.0 * (q @ x.T)
        else:
            d2 = -(q @ x.T)
        part = np.argpartition(d2, k - 1, axis=1)[:, :k]
        pd = np.take_along_axis(d2, part, axis=1)
        order = np.argsort(pd, axis=1)
        np.take_along_axis(part, order, axis=1)
    return repeats * q.shape[0] / (time.time() - t0)


def cpu_ivf_qps(x, centroids, assign, q, k, nprobe, metric, repeats=2):
    """numpy IVF-Flat at the same nprobe — the honest CPU-IVF floor.

    What FAISS IndexIVFFlat computes per query (coarse scan -> gather the
    nprobe probed lists -> exact scan of candidates -> top-k), expressed in
    numpy/BLAS using the index's own centroids and list assignments. Lacks
    FAISS's SIMD/prefetch engineering, so treat it as a floor on the
    CPU-IVF baseline rather than a FAISS measurement — but unlike
    cpu_exact_qps it does the same *algorithmic* work per query, making
    vs_cpu_ivf the closest available analog of BASELINE.md's vs-FAISS-IVF
    target ratio.
    """
    order = np.argsort(assign, kind="stable")
    sorted_assign = assign[order]
    starts = np.searchsorted(sorted_assign, np.arange(centroids.shape[0]))
    ends = np.searchsorted(sorted_assign, np.arange(centroids.shape[0]), side="right")
    xs = x[order]
    t0 = time.time()
    for _ in range(repeats):
        # the coarse scan is part of every IVF query's work — timed
        if metric == "l2":
            cent_scores = ((q * q).sum(1)[:, None]
                           - 2.0 * (q @ centroids.T)
                           + (centroids * centroids).sum(1)[None, :])
        else:
            cent_scores = -(q @ centroids.T)
        probes = np.argpartition(cent_scores, nprobe - 1, axis=1)[:, :nprobe]
        for i in range(q.shape[0]):
            cand = np.concatenate([xs[starts[l]:ends[l]] for l in probes[i]])
            if cand.shape[0] == 0:
                continue
            if metric == "l2":
                d2 = ((cand - q[i]) ** 2).sum(1)
            else:
                d2 = -(cand @ q[i])
            kk = min(k, d2.shape[0])
            part = np.argpartition(d2, kk - 1)[:kk]
            part[np.argsort(d2[part])]
    return repeats * q.shape[0] / (time.time() - t0)


def run_model_config(name, index, metric, n, d, n_clusters, train_n, nprobe, rng,
                     k=10, nq=512, sweep_to_recall=None, corpus=None):
    """sweep_to_recall: instead of the fixed nprobe, double nprobe from 1
    until recall@10 clears the bar (capped at nlist) — the BASELINE.md
    protocol ('QPS @ recall@10 >= 0.95'). corpus: optional gen(nn) sampler
    overriding the default isotropic clustered draw (see
    make_lowrank_corpus)."""
    from distributed_faiss_tpu.models.flat import FlatIndex

    def note(msg):
        # phase progress on stderr: an unattended hardware run must not be
        # a black box for an hour (relay launches can degrade to seconds)
        print(f"[{name}] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)

    if corpus is None:
        centers = rng.standard_normal((n_clusters, d)).astype(np.float32) * 4.0
        corpus = lambda nn: clustered(rng, nn, d, centers)
    x = corpus(n)
    q = corpus(nq)
    note(f"corpus ready: n={n} d={d}")

    t0 = time.time()
    index.train(x[:train_n])
    note(f"train done in {time.time() - t0:.1f}s")
    t_add = time.time()
    index.add(x)
    build_s = time.time() - t0
    note(f"add done in {time.time() - t_add:.1f}s")

    exact = FlatIndex(d, metric)
    exact.add(x)
    _, gt = exact.search(q[:128], k)
    note("ground truth ready")

    def recall_at(np_):
        index.set_nprobe(np_)
        _, ids = index.search(q[:128], k)
        return recall_at_k(ids, gt, k)

    if sweep_to_recall is not None:
        nprobe, rec, measured_at = 1, 0.0, None
        while nprobe <= n_clusters:
            rec = recall_at(nprobe)
            measured_at = nprobe
            note(f"sweep nprobe={nprobe}: recall@{k}={rec:.4f}")
            if rec >= sweep_to_recall:
                break
            nprobe *= 2
        nprobe = min(nprobe, n_clusters)
        if measured_at != nprobe:  # clamp landed between sweep points
            rec = recall_at(nprobe)
        index.set_nprobe(nprobe)
    else:
        rec = recall_at(nprobe)
    note(f"measuring qps at nprobe={nprobe}")
    qps = measure_qps(lambda qq, kk: index.search(qq, kk), q, k)
    cpu_qps = cpu_exact_qps(x, q[:32], k, metric)
    row = {
        "config": name,
        "n": n, "dim": d, "nprobe": nprobe,
        "train_add_s": round(build_s, 2),
        "recall@10": round(rec, 4),
        "qps": round(qps, 1),
        "cpu_exact_qps": round(cpu_qps, 1),
        "vs_cpu_exact": round(qps / cpu_qps, 2),
    }
    cents = index.get_centroids() if hasattr(index, "get_centroids") else None
    if cents is not None and hasattr(index, "get_assignments"):
        ivf_qps = cpu_ivf_qps(x, np.asarray(cents), index.get_assignments(),
                              q[:32], k, nprobe, metric)
        row["cpu_ivf_qps"] = round(ivf_qps, 1)
        row["vs_cpu_ivf"] = round(qps / ivf_qps, 2)
    note("done")
    return row


def run_flat(rng, small):
    from distributed_faiss_tpu.models.flat import FlatIndex

    n = 100_000 if small else 1_000_000
    d = 128
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((512, d)).astype(np.float32)
    idx = FlatIndex(d, "l2")
    t0 = time.time()
    idx.add(x)
    build_s = time.time() - t0
    qps = measure_qps(lambda qq, kk: idx.search(qq, kk), q, 10)
    cpu_qps = cpu_exact_qps(x, q[:32], 10, "l2")
    return {"config": "flat", "n": n, "dim": d, "train_add_s": round(build_s, 2),
            "recall@10": 1.0, "qps": round(qps, 1),
            "cpu_exact_qps": round(cpu_qps, 1),
            "vs_cpu_exact": round(qps / cpu_qps, 2)}


def run_ivf_simple(rng, small):
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n = 50_000 if small else 500_000
    idx = IVFFlatIndex(128, 64, "dot", codec="f32")
    return run_model_config("ivf_simple", idx, "dot", n, 128, 64,
                            min(n, 10_000), 12, rng)


def run_knnlm(rng, small, opq=False):
    from distributed_faiss_tpu.models.ivf import IVFPQIndex
    from distributed_faiss_tpu.ops.adc_pallas import on_tpu

    # --small keeps the CPU smoke tractable (the ADC one-hot path is
    # MXU-shaped; on CPU it is orders of magnitude slower)
    n = 20_000 if small else 500_000
    nlist = 128 if small else 4096
    m = 16 if small else 64
    d = 256 if small else 768
    on_chip = on_tpu()
    # refine: exact fp16 rerank of the ADC shortlist — the config that takes
    # PQ past the recall@10 >= 0.95 bar BASELINE.md measures at. On TPU the
    # serving mode is the compiled pallas kernel with the bf16 LUT (1.5x);
    # refine keeps final scores exact.
    idx = IVFPQIndex(d, nlist, m=m, metric="l2", kmeans_iters=8, pq_iters=10,
                     refine_k_factor=16, use_pallas=on_chip, adc_lut_bf16=on_chip)
    name = "knnlm"
    if opq:
        # OPQ balances per-subspace energy before PQ, which matters exactly
        # in the low-intrinsic-dim regime the corpus models — the rotation
        # spreads the r informative directions across all m subspaces
        from distributed_faiss_tpu.models.pretransform import PreTransformIndex

        idx = PreTransformIndex(idx, d, opq_m=m, opq_iters=8)
        name = "knnlm-opq"
    # kNN-LM keys are low-intrinsic-dim (see make_lowrank_corpus); 2x latent
    # clusters vs index cells so data clusters != index cells
    gen = make_lowrank_corpus(rng, d, r=max(d // 12, 8), n_latent_clusters=2 * nlist)
    return run_model_config(name, idx, "l2", n, d, nlist,
                            min(n, 100_000), max(nlist // 16, 8), rng,
                            nq=128 if small else 512, sweep_to_recall=0.95,
                            corpus=gen)


def run_knnlm_opq(rng, small):
    return run_knnlm(rng, small, opq=True)


def run_ivfsq(rng, small):
    from distributed_faiss_tpu.models.ivf import IVFFlatIndex

    n = 50_000 if small else 500_000
    nlist = 128 if small else 1024
    idx = IVFFlatIndex(512, nlist, "l2", codec="f16", kmeans_iters=8)
    return run_model_config("ivfsq", idx, "l2", n, 512, nlist,
                            min(n, 100_000), max(nlist // 16, 8), rng)


def run_sharded(rng, small):
    """8-shard cluster with client-side merge + nprobe sweep."""
    import socket
    import threading

    from distributed_faiss_tpu import IndexClient, IndexCfg, IndexServer, IndexState
    import tempfile

    n = 40_000 if small else 400_000
    d = 128
    nlist = 64 if small else 512
    centers = rng.standard_normal((nlist, d)).astype(np.float32) * 4.0
    x = clustered(rng, n, d, centers)
    q = clustered(rng, 512, d, centers)

    tmp = tempfile.mkdtemp()
    servers, ports = [], []
    for rank in range(8):
        s = socket.socket(); s.bind(("", 0)); port = s.getsockname()[1]; s.close()
        srv = IndexServer(rank, tmp)
        threading.Thread(target=srv.start_blocking, args=(port,), daemon=True).start()
        servers.append(srv); ports.append(port)
    disc = os.path.join(tmp, "disc.txt")
    with open(disc, "w") as f:
        f.write("8\n" + "".join(f"localhost,{p}\n" for p in ports))
    client = IndexClient(disc)
    cfg = IndexCfg(index_builder_type="ivf_simple", dim=d, metric="l2",
                   train_num=max(2000, n // 80), centroids=max(nlist // 8, 8), nprobe=8)
    client.create_index("bench", cfg)

    t0 = time.time()
    bs = 5000
    for s0 in range(0, n, bs):
        client.add_index_data("bench", x[s0:s0 + bs], list(range(s0, min(s0 + bs, n))))
    client.sync_train("bench")
    while client.get_state("bench") != IndexState.TRAINED:
        time.sleep(0.2)
    build_s = time.time() - t0

    from distributed_faiss_tpu.models.flat import FlatIndex

    exact = FlatIndex(d, "l2")
    exact.add(x)
    _, gt = exact.search(q[:128], 10)

    best = None
    for nprobe in (1, 2, 4, 8, 16, 32):
        client.set_nprobe("bench", nprobe)
        _, meta = client.search(q[:128], 10, "bench")
        ids = np.array([[m if m is not None else -1 for m in row] for row in meta])
        rec = recall_at_k(ids, gt, 10)
        t0 = time.time()
        client.search(q, 10, "bench")
        qps = q.shape[0] / (time.time() - t0)
        row = {"nprobe": nprobe, "recall@10": round(rec, 4), "qps": round(qps, 1)}
        if best is None or rec >= 0.95:
            best = row
        if rec >= 0.95:
            break
    client.close()
    return {"config": "sharded-8", "n": n, "dim": d, "train_add_s": round(build_s, 2),
            **best}


CONFIGS = {
    "flat": run_flat,
    "ivf_simple": run_ivf_simple,
    "knnlm": run_knnlm,
    "knnlm-opq": run_knnlm_opq,
    "ivfsq": run_ivfsq,
    "sharded": run_sharded,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true", help="CPU-sized corpora")
    ap.add_argument("--config", choices=sorted(CONFIGS), default=None)
    args = ap.parse_args()
    # persistent executable cache: a re-run of the same config pays zero
    # compiles (the relay's remote-compile latency dominates sweep cost;
    # harmless no-op if the active backend ignores the cache)
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_cache"),
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    rng = np.random.default_rng(0)
    names = [args.config] if args.config else list(CONFIGS)
    for name in names:
        result = CONFIGS[name](rng, args.small)
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
