#!/bin/bash
# Watch for the TPU relay to come alive, then immediately run the
# prioritized hardware sweep (benchmarks/hw_sweep.sh). The relay has
# been dead at round start and alive for a ~1h window mid-round in
# every round so far; this loop makes sure no alive-minute is wasted.
#
#   bash benchmarks/relay_watch.sh [max_wait_seconds]
#
# Exits 0 after a completed sweep, 2 if the wait budget expires.

set -u
cd "$(dirname "$0")/.."
MAX_WAIT="${1:-28800}"   # default: keep watching for 8h
LOG=/tmp/relay_watch.log
START=$(date +%s)

echo "watch start $(date +%H:%M:%S)" | tee -a "$LOG"
while :; do
  now=$(date +%s)
  if (( now - START > MAX_WAIT )); then
    echo "watch budget expired $(date +%H:%M:%S)" | tee -a "$LOG"
    exit 2
  fi
  if timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
      >> "$LOG" 2>&1; then
    echo "RELAY ALIVE $(date +%H:%M:%S) — launching sweep" | tee -a "$LOG"
    bash benchmarks/hw_sweep.sh /tmp/hw_sweep.log >> "$LOG" 2>&1
    rc=$?
    echo "SWEEP EXITED rc=$rc $(date +%H:%M:%S)" | tee -a "$LOG"
    # a non-zero sweep (relay died between our probe and the sweep's, or
    # mid-run) must NOT burn the remaining wait budget: the alive window
    # may recur — keep watching
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
  fi
  echo "relay dead $(date +%H:%M:%S), retry in 180s" >> "$LOG"
  sleep 180
done
