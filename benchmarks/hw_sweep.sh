#!/bin/bash
# Prioritized hardware sweep: run EVERYTHING outstanding against the TPU,
# most important first, so a mid-run relay death loses the least.
#
#   bash benchmarks/hw_sweep.sh [logfile]
#
# Priority rationale (see benchmarks/RESULTS.md):
#   1. knnlm        — the flagship recall@0.95 row, still unmeasured at full size
#   2. sharded      — the only BASELINE config with no hardware row at all
#   3. bench.py     — the driver headline (re-check after the query-block fix)
#   4. flat/ivfsq/ivf_simple — refresh (rows predate segmented top-k + blocks)
#   5. serving_concurrency   — the dynamic-batching win is launch-bound-only
#   6. knnlm-opq    — OPQ ablation of the flagship
#   7. tpu_validate — pallas parity re-check (already passed once on v5e)
#
# The relay dies unannounced (three rounds running); every step is
# timeout-bounded and the log records per-phase progress.

set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/hw_sweep.log}"

note() { echo "=== $1 $(date +%H:%M:%S) ===" | tee -a "$LOG"; }

note "probe"
if ! timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
    >> "$LOG" 2>&1; then
  note "NO TPU (probe failed) — aborting sweep"
  exit 1
fi

note "1/7 knnlm"
timeout 5400 python benchmarks/baseline_configs.py --config knnlm >> "$LOG" 2>&1
note "2/7 sharded"
timeout 3600 python benchmarks/baseline_configs.py --config sharded >> "$LOG" 2>&1
note "3/7 bench.py headline"
timeout 3600 python bench.py >> "$LOG" 2>&1
note "4a/7 flat"
timeout 3600 python benchmarks/baseline_configs.py --config flat >> "$LOG" 2>&1
note "4b/7 ivfsq"
timeout 3600 python benchmarks/baseline_configs.py --config ivfsq >> "$LOG" 2>&1
note "4c/7 ivf_simple"
timeout 3600 python benchmarks/baseline_configs.py --config ivf_simple >> "$LOG" 2>&1
note "5/7 serving concurrency"
timeout 3600 python benchmarks/serving_concurrency.py >> "$LOG" 2>&1
note "6/7 knnlm-opq"
timeout 5400 python benchmarks/baseline_configs.py --config knnlm-opq >> "$LOG" 2>&1
note "7/7 pallas validate"
timeout 3600 python benchmarks/tpu_validate.py >> "$LOG" 2>&1
note "SWEEP DONE"
