#!/bin/bash
# Prioritized hardware sweep: run EVERYTHING outstanding against the TPU,
# most important first, so a mid-run relay death loses the least.
#
#   bash benchmarks/hw_sweep.sh [logfile]
#
# Priority rationale (see benchmarks/RESULTS.md):
#   1. knnlm        — the flagship recall@0.95 row, still unmeasured at full size
#   2. sharded      — the only BASELINE config with no hardware row at all
#   3. bench.py     — the driver headline (re-check after the query-block fix)
#   4. flat/ivfsq/ivf_simple — refresh (rows predate segmented top-k + blocks)
#   5. serving_concurrency   — the dynamic-batching win is launch-bound-only
#   6. knnlm-opq    — OPQ ablation of the flagship
#   7. tpu_validate — pallas parity re-check (already passed once on v5e)
#
# The relay dies unannounced (three rounds running); every step is
# timeout-bounded and the log records per-phase progress.

set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/hw_sweep.log}"

note() { echo "=== $1 $(date +%H:%M:%S) ===" | tee -a "$LOG"; }

alive() {
  timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
    >> "$LOG" 2>&1
}

# Completed steps leave a marker so a sweep revived after a mid-run relay
# death resumes at the first unmeasured step instead of re-burning the next
# alive window on steps already measured. rm -rf "$DONE" to force a full
# re-run (e.g. after a code change that invalidates earlier rows).
DONE=/tmp/hw_sweep.done
mkdir -p "$DONE"

# step <name> <timeout_s> <cmd...>: skip if already completed; re-probe
# liveness before each step so a mid-sweep relay death costs at most one
# step's timeout, not the sum of every remaining step's; exit 1 tells
# relay_watch to resume watching.
step() {
  local name=$1 to=$2
  shift 2
  local marker="$DONE/$(echo "$name" | tr ' /' '__')"
  if [ -e "$marker" ]; then
    note "$name SKIPPED (done marker)"
    return 0
  fi
  if ! alive; then
    note "RELAY DIED before $name — aborting sweep (rc=1)"
    exit 1
  fi
  note "$name"
  if timeout "$to" "$@" >> "$LOG" 2>&1; then
    touch "$marker"
  else
    note "$name FAILED rc=$?"
  fi
}

step "1/7 knnlm"              5400 python benchmarks/baseline_configs.py --config knnlm
step "2/7 sharded"            3600 python benchmarks/baseline_configs.py --config sharded
step "3/7 bench.py headline"  3600 python bench.py
step "4a/7 flat"              3600 python benchmarks/baseline_configs.py --config flat
step "4b/7 ivfsq"             3600 python benchmarks/baseline_configs.py --config ivfsq
step "4c/7 ivf_simple"        3600 python benchmarks/baseline_configs.py --config ivf_simple
step "5/7 serving concurrency" 3600 python benchmarks/serving_concurrency.py
step "6/7 knnlm-opq"          5400 python benchmarks/baseline_configs.py --config knnlm-opq
step "7/9 pallas validate"    3600 python benchmarks/tpu_validate.py
step "8/9 adc roofline"       3600 python benchmarks/adc_roofline.py
step "9/9 operating curves"   7200 python benchmarks/operating_curves.py
note "SWEEP DONE"
