"""Micro-benchmark: search-time metadata join, list-loop vs vectorized take.

The join runs on the serving thread for every search block (VERDICT r2
weak #5: nq*k interpreted ops under buffer_lock). Measures the old
per-element list comprehension against _MetaStore.snapshot()+take at the
serving geometry nq=1024, k=100, ntotal=1M. CPU-only; no device involved.
"""

import time

import numpy as np

# the engine import pulls in jax (already interpreter-preloaded by
# sitecustomize); steer any lazy backend init away from the TPU relay so
# this pure-numpy bench can never hang on a dead relay
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

from distributed_faiss_tpu.engine import _MetaStore


def main():
    ntotal, nq, k, iters = 1_000_000, 1024, 100, 20
    meta = [("passage", i) for i in range(ntotal)]
    store = _MetaStore(meta)
    rng = np.random.default_rng(0)
    indexes = rng.integers(0, ntotal, size=(nq, k))
    indexes[rng.random((nq, k)) < 0.01] = -1  # sprinkle of empty slots

    # old path: per-element list indexing
    t0 = time.perf_counter()
    for _ in range(iters):
        out_list = [
            [meta[indexes[i, j]] if indexes[i, j] != -1 else None for j in range(k)]
            for i in range(nq)
        ]
    t_loop = (time.perf_counter() - t0) / iters

    # new path: snapshot + vectorized take
    t0 = time.perf_counter()
    for _ in range(iters):
        arr, _ = store.snapshot()
        valid = indexes != -1
        safe = np.where(valid, indexes, 0)
        joined = arr.take(safe.ravel(), mode="clip").reshape(indexes.shape)
        joined[~valid] = None
        out_vec = joined.tolist()
    t_vec = (time.perf_counter() - t0) / iters

    assert out_vec == out_list
    print(
        f"meta join nq={nq} k={k} ntotal={ntotal}: "
        f"loop {t_loop * 1e3:.2f} ms, take {t_vec * 1e3:.2f} ms, "
        f"speedup {t_loop / t_vec:.1f}x"
    )


if __name__ == "__main__":
    main()
