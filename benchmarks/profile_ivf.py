#!/usr/bin/env python3
"""Dissect the headline IVF-fp16 serving latency on the live backend.

Answers one question: where does the ~0.35 s / 512-query batch go at the
bench.py operating point (n=500k, d=128, nlist=1024, nprobe=1)?
Suspects, measured independently:

  dispatch   — a trivial jitted add on a (8,) array, round-tripped to host.
               Under the axon relay every executable launch crosses a network
               tunnel, so this floor can be tens of ms and would dominate.
  transfer   — device_put of one query block + fetch of a (block, k) result.
  search     — the fused _ivf_flat_search call itself at block sizes
               256 / 512 / 1024 (lower bound per-call; if per-call time is
               flat in block size, dispatch dominates and bigger blocks are
               near-free QPS).

A/B flags for the stored-norms + pallas flat-scan work (this PR):

  --norms {stored,recompute}   gather the add-time (nlist, cap) fp32 norm
               sidecar vs recomputing ||x||^2 from the gathered block per
               query (the pre-change behavior). Bit-exact either way.
  --kernel {xla,pallas}        the XLA gather+einsum scan vs the fused
               VMEM pallas kernel (ops/flat_pallas.py). On a non-TPU
               backend 'pallas' runs the interpreter — correct but slow;
               use it for numerics, not timing, off-chip.

Run both arms of either flag on the same machine for the A/B line in
benchmarks/RESULTS.md (BENCH_SMALL=1 for the CPU-sized corpus).

Prints one JSON line per measurement. Safe to run CPU-only (numbers are then
about the CPU path, labeled by backend).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, reps=20, warm=3):
    for _ in range(warm):
        fn()
    t0 = time.time()
    for _ in range(reps):
        fn()
    return (time.time() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--norms", choices=("stored", "recompute"), default="stored")
    ap.add_argument("--kernel", choices=("xla", "pallas"), default="xla")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_faiss_tpu.models.ivf import IVFFlatIndex, _ivf_flat_search

    backend = jax.devices()[0].platform
    arm = f"{args.norms}/{args.kernel}"
    rng = np.random.default_rng(0)
    small = os.environ.get("BENCH_SMALL") == "1"
    n = 50_000 if small else 500_000
    d, nlist, k, nprobe = 128, 256 if small else 1024, 10, 1

    centers = rng.standard_normal((nlist, d)).astype(np.float32) * 4.0
    assign = rng.integers(0, nlist, n)
    x = (centers[assign] + rng.standard_normal((n, d))).astype(np.float32)

    idx = IVFFlatIndex(d, nlist, "l2", codec="f16", kmeans_iters=4,
                       use_pallas=args.kernel == "pallas")
    idx.train(x[: min(n, 100_000)])
    idx.add(x)
    idx.set_nprobe(nprobe)
    idx.use_stored_norms = args.norms == "stored"

    # 1. dispatch floor
    tiny = jnp.zeros((8,), jnp.float32)
    f_tiny = jax.jit(lambda a: a + 1.0)
    t = timeit(lambda: np.asarray(f_tiny(tiny)))
    print(json.dumps({"case": "dispatch_floor", "backend": backend,
                      "ms": round(t * 1e3, 2)}))

    # 2. transfer: host->device 256x128 fp32 + device->host (256,k)
    qb = rng.standard_normal((256, d)).astype(np.float32)
    dev_res = jnp.zeros((256, k), jnp.float32)
    t = timeit(lambda: (jax.device_put(qb).block_until_ready(),
                        np.asarray(dev_res)))
    print(json.dumps({"case": "transfer_256q", "backend": backend,
                      "ms": round(t * 1e3, 2)}))

    # 3. fused search call at growing block sizes, on the selected A/B arm
    norms = idx._scan_norms()
    for block in (256, 512, 1024):
        q = (centers[rng.integers(0, nlist, block)]
             + rng.standard_normal((block, d))).astype(np.float32)
        qj = jnp.asarray(q)

        def call():
            v, i = _ivf_flat_search(
                idx.centroids, idx.lists.data, idx.lists.ids, idx.lists.sizes,
                qj, k, nprobe, 1, "l2", "f16", list_norms=norms,
                use_pallas=idx.use_pallas)
            np.asarray(v); np.asarray(i)

        t = timeit(call, reps=10)
        print(json.dumps({"case": f"search_block{block}", "backend": backend,
                          "arm": arm, "ms": round(t * 1e3, 2),
                          "qps_equiv": round(block / t, 1)}))

    # 4. end-to-end idx.search at the bench batch size
    q = (centers[rng.integers(0, nlist, 512)]
         + rng.standard_normal((512, d))).astype(np.float32)
    t = timeit(lambda: idx.search(q, k), reps=10)
    print(json.dumps({"case": "e2e_512q", "backend": backend, "arm": arm,
                      "ms": round(t * 1e3, 2), "qps": round(512 / t, 1)}))


if __name__ == "__main__":
    main()
