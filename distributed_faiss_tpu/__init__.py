"""distributed_faiss_tpu — a TPU-native distributed ANN search framework.

A from-scratch rebuild of the *capabilities* of facebookresearch/distributed-faiss
(reference layout: distributed_faiss/{client,server,index,rpc,index_cfg,index_state}.py)
on a JAX/XLA/Pallas compute substrate:

- N isolated index-server processes, each owning a corpus shard resident in TPU HBM
  (reference: CPU-FAISS shards, distributed_faiss/server.py:38-45).
- All coordination is client-side: round-robin placement, fan-out search, top-k merge,
  state aggregation (reference: distributed_faiss/client.py:57-345).
- All distance / k-means / PQ / SQ math is jitted XLA (MXU matmuls) with a Pallas
  kernel for the PQ asymmetric-distance (ADC) scan, replacing the FAISS C++ surface
  (reference: faiss.* usage in distributed_faiss/index.py:25-100).
- Within a server, the corpus can be sharded over a multi-chip ``jax.sharding.Mesh``
  with XLA collectives over ICI (the reference has no intra-server parallelism beyond
  FAISS OpenMP threads).

Public API mirrors the reference's external surface:
``IndexClient``, ``IndexServer``, ``IndexCfg``, ``IndexState``.

Imports are lazy (PEP 562) so kernel-only use doesn't pull in the server/RPC stack.
"""

__version__ = "0.1.0"

_LAZY = {
    "IndexCfg": ("distributed_faiss_tpu.utils.config", "IndexCfg"),
    "IndexState": ("distributed_faiss_tpu.utils.state", "IndexState"),
    "Index": ("distributed_faiss_tpu.engine", "Index"),
    "IndexServer": ("distributed_faiss_tpu.parallel.server", "IndexServer"),
    "IndexClient": ("distributed_faiss_tpu.parallel.client", "IndexClient"),
    "MultiRankError": ("distributed_faiss_tpu.parallel.client", "MultiRankError"),
    "RetryPolicy": ("distributed_faiss_tpu.parallel.rpc", "RetryPolicy"),
    "BusyError": ("distributed_faiss_tpu.parallel.rpc", "BusyError"),
    "DeadlineExceeded": ("distributed_faiss_tpu.parallel.rpc", "DeadlineExceeded"),
    "SchedulerCfg": ("distributed_faiss_tpu.utils.config", "SchedulerCfg"),
    "MeshCfg": ("distributed_faiss_tpu.utils.config", "MeshCfg"),
    "ReplicationCfg": ("distributed_faiss_tpu.utils.config", "ReplicationCfg"),
    "AntiEntropyCfg": ("distributed_faiss_tpu.utils.config", "AntiEntropyCfg"),
    "VersioningCfg": ("distributed_faiss_tpu.utils.config", "VersioningCfg"),
    "TracingCfg": ("distributed_faiss_tpu.utils.config", "TracingCfg"),
    "WireCfg": ("distributed_faiss_tpu.utils.config", "WireCfg"),
    "HLC": ("distributed_faiss_tpu.mutation.versions", "HLC"),
    "QuorumError": ("distributed_faiss_tpu.parallel.client", "QuorumError"),
    "MembershipTable": ("distributed_faiss_tpu.parallel.replication",
                        "MembershipTable"),
    "SearchScheduler": ("distributed_faiss_tpu.serving.scheduler", "SearchScheduler"),
}

__all__ = list(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
