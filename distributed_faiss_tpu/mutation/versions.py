"""Per-id mutation versions: hybrid logical clocks and the LWW gates.

Through PR 10 the mutation/replication stack had one standing correctness
residual (ROADMAP item 6): reconciliation was **delete-wins**. An upsert
racing anti-entropy against a replica that only saw the delete converged
to *deleted* until re-ingested, and a replayed repair-queue record (or a
duplicated quorum fan-out) double-applied. This module is the fix's
foundation: every mutation is stamped with a **version** — a hybrid
logical clock (HLC) reading — and every engine-side apply site compares
versions instead of assuming arrival order.

A version is a 3-tuple ``(wall_ms, counter, writer_id)``:

- ``wall_ms`` — the stamping client's wall clock in integer milliseconds,
  advanced to at least one past the largest version ever *observed*
  (HLC merge), so a client whose wall clock is behind the cluster still
  stamps ahead of everything it has seen;
- ``counter`` — the logical component: increments when several stamps
  land in one millisecond;
- ``writer_id`` — a per-client tie-break so versions form a TOTAL order
  (two clients stamping in the same millisecond never compare equal).

Versions are plain tuples on the wire and in JSON sidecars (lists after
a JSON round trip — ``version_key`` re-normalizes), and ``None`` means
*unversioned*: a legacy writer or a pre-version payload. ``None``
compares below every real version, which makes the legacy semantics
(delete always wins, re-ingest always restores) the correct degenerate
case of the LWW gates below.

The LWW gates (one place, so the engine's apply sites cannot drift):

- ``add_loses(v, live, dead)`` — a versioned add/upsert-re-add is a
  **no-op** when the id is already live at ``>= v`` (idempotent replay:
  repair re-sends, duplicated quorum fan-outs) or was deleted at
  ``> v`` (the delete is the last writer). Ties go to the ADD so an
  upsert that reuses its delete's version still lands its re-add.
- ``delete_loses(v, live, dead)`` — a versioned delete is a **no-op**
  when the id is live at ``>= v`` (an upsert outran it — the race that
  used to converge to deleted) or already deleted at ``>= v`` (replay).
"""

import os
import threading
import time
from typing import Optional, Tuple

from distributed_faiss_tpu.utils import lockdep

Version = Tuple[int, int, int]


def version_key(v) -> Optional[Version]:
    """Normalize a version from any carrier (wire tuple, JSON list,
    already-normalized tuple) to the canonical comparable 3-tuple;
    ``None`` (unversioned) passes through."""
    if v is None:
        return None
    if isinstance(v, (list, tuple)) and len(v) == 3:
        return (int(v[0]), int(v[1]), int(v[2]))
    raise ValueError(f"not a version: {v!r}")


def compare(a, b) -> int:
    """Total order over versions with ``None`` (unversioned) minimal:
    -1 when a < b, 0 when equal, 1 when a > b."""
    ka, kb = version_key(a), version_key(b)
    if ka is None and kb is None:
        return 0
    if ka is None:
        return -1
    if kb is None:
        return 1
    return (ka > kb) - (ka < kb)


def newest(a, b):
    """The larger of two versions (None minimal)."""
    return a if compare(a, b) >= 0 else b


def add_loses(v, live, dead) -> bool:
    """True when a versioned add of an id must NO-OP: the id is already
    live at the same-or-newer version (idempotent replay) or a strictly
    newer delete won. ``v`` must be a real version; ``live``/``dead``
    are the id's current live/deletion-ledger versions (None = absent
    or unversioned)."""
    return compare(live, v) >= 0 or compare(dead, v) > 0


def delete_loses(v, live, dead) -> bool:
    """True when a versioned delete must NO-OP: a same-or-newer live
    write (upsert) won, or the delete is a replay of one already
    applied."""
    return compare(live, v) >= 0 or compare(dead, v) >= 0


class HLC:
    """Hybrid logical clock: one per writing client (``IndexClient``).

    ``tick()`` returns a fresh version strictly greater than every
    version this clock has ticked or observed. ``observe(v)`` merges a
    remote version in — the restart story: a client seeds its clock from
    the max version visible in the cluster (``get_id_sets`` watermarks),
    so a machine whose wall clock went BACKWARD across the restart still
    stamps ahead of its own pre-restart writes instead of issuing stale
    stamps that every replica would no-op. Thread-safe."""

    def __init__(self, writer_id: Optional[int] = None,
                 clock_ms=None):
        # writer ids only need to distinguish concurrent writers; pid
        # xor a time-derived salt is enough without coordination
        if writer_id is None:
            writer_id = (os.getpid() << 16) ^ (time.time_ns() & 0xFFFF)
        self.writer_id = int(writer_id) & 0x7FFFFFFF
        self._clock_ms = clock_ms or (lambda: time.time_ns() // 1_000_000)
        self._lock = lockdep.lock("HLC._lock")
        self._last_ms = 0
        self._counter = 0

    def tick(self) -> Version:
        with self._lock:
            now = int(self._clock_ms())
            if now > self._last_ms:
                self._last_ms = now
                self._counter = 0
            else:
                self._counter += 1
            return (self._last_ms, self._counter, self.writer_id)

    def observe(self, v) -> None:
        """Merge a remote version: subsequent ticks compare above it."""
        k = version_key(v)
        if k is None:
            return
        with self._lock:
            if k[0] > self._last_ms:
                self._last_ms = k[0]
                self._counter = k[1]
            elif k[0] == self._last_ms and k[1] > self._counter:
                self._counter = k[1]

    def last(self) -> Optional[Version]:
        """The newest instant this clock has ticked or observed (None
        before the first tick/observe) — NOT a fresh stamp."""
        with self._lock:
            if self._last_ms == 0:
                return None
            return (self._last_ms, self._counter, self.writer_id)
