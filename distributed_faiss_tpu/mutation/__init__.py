"""Mutable corpora: id-keyed tombstone deletes, upsert, and background
compaction (ROADMAP item 3 — the subsystem the reference never had; its
only mutation primitive is whole-index ``drop_index``).

Layering:

- device side: every index model exposes ``remove_rows`` (models/base.py).
  Flat corpora materialize the tombstone set as a device-resident boolean
  ``live`` mask AND-ed with the ntotal padding mask inside the scan
  (ops/distance.py ``_knn_scan``); IVF families materialize it into the
  device ids plane — a tombstoned cell's id becomes -1, which the
  ``ids >= 0`` AND every scan entry (XLA, fused pallas, mesh-masked,
  probe-routed) already applies treats exactly like padding. Either way
  the device-side cost of mutability is one more mask AND, and the
  delete-nothing case traces the exact pre-mutation program (byte
  identity).
- engine side (engine.py): ``Index.remove_ids`` / ``Index.upsert`` map
  user metadata ids onto positional rows (buffer-aware — an id still in
  the add buffer is masked when its rows drain), record them in a
  :class:`tombstones.TombstoneSet`, and persist the set crash-safely —
  both as a sidecar file inside every MANIFEST generation and as a
  standalone ``tombstones.json`` rewritten atomically on every mutation,
  stamped with a layout epoch so a crash-fallback to an older generation
  can never resurrect a deleted row (see tombstones.py).
- background compaction (compaction.py): a named watcher thread per
  engine rewrites tombstoned rows out of the index into a fresh
  generation (committed via the shared ``_commit_generation`` protocol)
  once the tombstone fraction crosses ``DFT_COMPACT_THRESHOLD``, swapped
  in atomically under the index lock; SIGKILL at any point falls back to
  the previous complete generation with tombstones intact.
- distributed layer (parallel/client.py, parallel/server.py):
  ``remove_ids``/``upsert`` fan out per replica group under the quorum
  machinery; below-quorum deletes land in the repair queue (never
  rerouted cross-group), and ``get_perf_stats`` grows a ``mutation`` key.
- per-id versions (versions.py, ISSUE 12): every mutation carries a
  hybrid-logical-clock stamp, and the engine's apply sites run the LWW
  gates — replays no-op, upsert-vs-delete races converge to the true
  last writer, replica digests/deltas compare versioned state, and
  per-writer watermarks back read-your-writes plus generation-pinned
  point-in-time reads
  (docs/OPERATIONS.md#versioned-mutations--consistent-reads).
"""

from distributed_faiss_tpu.mutation.tombstones import (  # noqa: F401
    SIDECAR_NAME,
    TombstoneSet,
    load_sidecar,
    write_sidecar,
)
from distributed_faiss_tpu.mutation.compaction import (  # noqa: F401
    CompactionUnsupported,
    compact_state,
    run_watcher,
)
from distributed_faiss_tpu.mutation.versions import (  # noqa: F401
    HLC,
    add_loses,
    delete_loses,
    version_key,
)
