"""Background compaction: rewrite tombstoned rows out of an index.

Masked rows cost capacity (dead list cells, dead corpus rows) and scan
FLOPs until something reclaims them. Compaction is that something: it
filters the index's own ``state_dict`` down to the surviving rows and
rebuilds through ``index_from_state_dict`` — so centroids, codebooks and
quantizer params are preserved bit-for-bit, encoded payloads are copied
verbatim (no decode/re-encode drift), and the rebuilt inverted lists
come back TIGHT: tombstoned cells are gone and list capacity re-sizes to
the surviving fill (the rebuild's power-of-two growth is what also
splits any list that had outgrown its padded capacity). The engine's
three-phase ``Index.compact`` drives it: snapshot under the locks,
rebuild with serving live, then catch-up + MANIFEST commit + atomic swap
back under the locks (see engine.py for the crash-window analysis).

``run_watcher`` is the per-engine background driver — a named daemon
thread (like the save watcher) that wakes every ``DFT_COMPACT_INTERVAL``
seconds and triggers ``Index.compact`` once the indexed tombstone
fraction crosses ``DFT_COMPACT_THRESHOLD``. It rides the engine's
``_retired`` event, so retiring an engine (drop, shard-transfer
replacement) wakes and exits the watcher immediately.
"""

import logging
import os
import time

import numpy as np

logger = logging.getLogger()

# state-dict kinds compact_state knows how to filter, and the per-row
# arrays (insertion order) each carries. Graph/pretransform kinds are not
# maskable/filterable yet — the engine surfaces that as a no-op with a log.
_ROW_KEYS = ("rows", "assign", "list_norms", "refine_rows")
SUPPORTED_KINDS = frozenset({
    "flat", "sharded_flat", "ivf_flat", "sharded_ivf_flat",
    "ivf_pq", "sharded_ivf_pq",
})


class CompactionUnsupported(RuntimeError):
    """This index kind has no row-filterable state dict."""


def compact_state(state: dict, keep: np.ndarray) -> dict:
    """Filter a model ``state_dict`` down to the rows where ``keep`` is
    True (insertion order). Returns a NEW state dict of the same kind;
    structural fields (centroids, codebooks, sq params, knobs) are
    shared, per-row arrays are filtered."""
    kind = str(state["kind"])
    if kind not in SUPPORTED_KINDS:
        raise CompactionUnsupported(
            f"index kind {kind!r} has no row-filterable state")
    keep = np.asarray(keep, bool)
    out = dict(state)
    if kind == "flat":
        data = np.asarray(state["data"])
        if keep.shape[0] != data.shape[0]:
            raise ValueError(
                f"keep mask covers {keep.shape[0]} rows, state has "
                f"{data.shape[0]}")
        out["data"] = data[keep]
        out["ntotal"] = int(keep.sum())
        return out
    for key in _ROW_KEYS:
        if key in state:
            arr = np.asarray(state[key])
            if arr.shape[0] != keep.shape[0]:
                raise ValueError(
                    f"keep mask covers {keep.shape[0]} rows, state[{key!r}] "
                    f"has {arr.shape[0]}")
            out[key] = arr[keep]
    return out


def run_watcher(engine, cfg) -> None:
    """Body of the per-engine compaction watcher thread.

    ``engine`` is an ``engine.Index``; ``cfg`` a ``MutationCfg``. The
    retired event doubles as the sleep (save-watcher precedent): retire()
    wakes the watcher immediately instead of leaking it one interval."""
    name = os.path.basename(engine.cfg.index_storage_dir or "?")
    while not engine._retired.wait(cfg.interval_s):
        try:
            frac = engine.tombstone_fraction()
            if frac < cfg.threshold:
                continue
            # cross-replica compaction lease (parallel/antientropy.py):
            # when the server's sweeper installed a gate, only the rank
            # holding its group's compaction token passes — so the R
            # replicas of a group never pay the double-compaction p99
            # window by passing at once. The explicit compact_index op
            # bypasses this (operator override); standalone engines have
            # no gate and compact freely.
            gate = engine.compaction_gate
            if gate is not None and not gate():
                logger.debug(
                    "compaction watcher (%s): tombstone fraction %.3f but "
                    "another replica holds the group's compaction lease — "
                    "deferring", name, frac)
                continue
            logger.info(
                "compaction watcher (%s): tombstone fraction %.3f >= %.3f, "
                "compacting", name, frac, cfg.threshold)
            engine.compact()
        except Exception:
            # the watcher must survive any single failed pass — the next
            # interval retries against fresh state
            logger.exception("compaction pass failed (%s)", name)
            time.sleep(min(1.0, cfg.interval_s))
