"""Tombstone bookkeeping + the crash-safe sidecar protocol.

A :class:`TombstoneSet` is the engine-side record of deleted rows for ONE
shard: a ``row -> user id`` map (rows are the positional ids the
metadata join is keyed on — stable under the append-only contract) plus
a **layout epoch**. The set itself is plain data; the OWNING engine
guards it under ``index_lock`` (graftlint lock-discipline PIN), which is
also what makes a scheduler-coalesced device window see one consistent
tombstone snapshot — the mask scatter and the device launch serialize on
the same lock, so a merged batch is entirely pre-delete or entirely
post-delete, never torn.

Durability (the "a crash never resurrects deleted rows" contract):

- every committed MANIFEST generation carries a ``tombstones-gNNN.json``
  sidecar entry (sha256-verified like every other generation file) with
  the set AND the layout epoch the positions are valid for;
- additionally, every mutation rewrites the standalone, unversioned
  ``tombstones.json`` via tmp+fsync+rename — the delete is durable the
  moment ``remove_ids`` returns, without paying a full snapshot commit.

The layout epoch resolves the one hazard of positional tombstones:
compaction renumbers rows. A load applies the generation's own sidecar
unconditionally (positions and payload were committed together), and
merges the standalone sidecar ONLY when its layout matches — a stale
sidecar from a rolled-back (or newer, crashed-before-swap) layout is
ignored rather than misapplied. Because compaction commits its catch-up
tombstones inside the new generation's own sidecar *before* rewriting
the standalone file (all under the engine locks), every crash point
lands on a consistent (generation, sidecar) pair.
"""

import json
import os
from typing import Dict, Iterable, Optional

from distributed_faiss_tpu.utils import serialization

SIDECAR_NAME = "tombstones.json"

PAYLOAD_FORMAT = 1


class TombstoneSet:
    """Positional dead-row set with the id-keyed record riding along.

    Plain data — thread-safety is the owning engine's ``index_lock``
    (copy what you need under the lock before iterating outside it).
    """

    __slots__ = ("_rows", "layout")

    def __init__(self, rows: Optional[Dict[int, object]] = None,
                 layout: int = 0):
        self._rows: Dict[int, object] = (
            {int(r): v for r, v in rows.items()} if rows else {})
        self.layout = int(layout)

    def __len__(self) -> int:
        return len(self._rows)

    def __contains__(self, row: int) -> bool:
        return int(row) in self._rows

    def rows(self) -> list:
        """Dead row positions (copy — safe to use outside the lock)."""
        return list(self._rows)

    def ids(self) -> list:
        """User ids of the dead rows (copy; informational — positions are
        the authoritative recovery key)."""
        return list(self._rows.values())

    def items(self) -> list:
        """(row, user id) pairs (copy — safe outside the lock)."""
        return list(self._rows.items())

    def add(self, rows: Iterable[int], ids: Optional[Iterable] = None) -> None:
        if ids is None:
            for r in rows:
                self._rows.setdefault(int(r), None)
            return
        for r, i in zip(rows, ids):
            self._rows[int(r)] = i

    def count_below(self, n: int) -> int:
        """Dead rows with position < n (i.e. already indexed rows)."""
        return sum(1 for r in self._rows if r < n)

    def rows_in_range(self, lo: int, hi: int) -> list:
        """Dead positions in [lo, hi) — the buffer-drain mask window."""
        return [r for r in self._rows if lo <= r < hi]

    def to_payload(self) -> dict:
        rows = sorted(self._rows)
        return {
            "format": PAYLOAD_FORMAT,
            "layout": self.layout,
            "dead_rows": rows,
            "dead_ids": [self._rows[r] for r in rows],
        }

    @classmethod
    def from_payload(cls, payload: Optional[dict]) -> "TombstoneSet":
        if not payload:
            return cls()
        rows = [int(r) for r in payload.get("dead_rows", ())]
        ids = list(payload.get("dead_ids", ()))
        mapping = dict.fromkeys(rows)
        for r, i in zip(rows, ids):
            mapping[r] = i
        return cls(mapping, layout=int(payload.get("layout", 0)))

    def merge_payload(self, payload: Optional[dict]) -> None:
        """Union another payload's rows in (same-layout sidecar merge)."""
        if not payload:
            return
        other = TombstoneSet.from_payload(payload)
        for r, i in other._rows.items():
            self._rows.setdefault(r, i)

    def __repr__(self) -> str:
        return f"<TombstoneSet {len(self._rows)} dead, layout {self.layout}>"


def dump_payload(payload: dict) -> str:
    """JSON text for a tombstone payload. ``default=str`` keeps arbitrary
    metadata id objects from failing the dump — the stringified form is
    informational; the integer positions are the recovery key."""
    return json.dumps(payload, default=str, sort_keys=True)


def write_sidecar(storage_dir: str, payload: dict) -> None:
    """Atomically (tmp+fsync+rename) rewrite the standalone sidecar — the
    per-mutation durability point."""
    serialization.atomic_write(
        os.path.join(storage_dir, SIDECAR_NAME),
        lambda f: f.write(dump_payload(payload) + "\n"), "w",
    )


def load_sidecar(storage_dir: str) -> Optional[dict]:
    path = os.path.join(storage_dir, SIDECAR_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        # a torn sidecar is impossible via atomic_write; treat garbage as
        # absent but loudly (the generation sidecar still covers recovery
        # up to the last commit)
        import logging

        logging.getLogger().warning(
            "unreadable tombstone sidecar at %s: %s", path, e)
        return None


def load_generation_payload(storage_dir: str, manifest: dict) -> Optional[dict]:
    """The committed generation's own tombstone entry (None for
    pre-mutation generations)."""
    entry = manifest.get("files", {}).get("tombstones")
    if not entry:
        return None
    try:
        with open(os.path.join(storage_dir, entry["name"])) as f:
            return json.load(f)
    except (OSError, ValueError):
        # verify_manifest already sha256-checked the file; reaching here
        # means filesystem-level corruption after the check — degrade to
        # the standalone sidecar
        return None
