"""Tombstone bookkeeping + the crash-safe sidecar protocol.

A :class:`TombstoneSet` is the engine-side record of deleted rows for ONE
shard: a ``row -> user id`` map (rows are the positional ids the
metadata join is keyed on — stable under the append-only contract) plus
a **layout epoch**. The set itself is plain data; the OWNING engine
guards it under ``index_lock`` (graftlint lock-discipline PIN), which is
also what makes a scheduler-coalesced device window see one consistent
tombstone snapshot — the mask scatter and the device launch serialize on
the same lock, so a merged batch is entirely pre-delete or entirely
post-delete, never torn.

Durability (the "a crash never resurrects deleted rows" contract):

- every committed MANIFEST generation carries a ``tombstones-gNNN.json``
  sidecar entry (sha256-verified like every other generation file) with
  the set AND the layout epoch the positions are valid for;
- additionally, every mutation rewrites the standalone, unversioned
  ``tombstones.json`` via tmp+fsync+rename — the delete is durable the
  moment ``remove_ids`` returns, without paying a full snapshot commit.

The layout epoch resolves the one hazard of positional tombstones:
compaction renumbers rows. A load applies the generation's own sidecar
unconditionally (positions and payload were committed together), and
merges the standalone sidecar ONLY when its layout matches — a stale
sidecar from a rolled-back (or newer, crashed-before-swap) layout is
ignored rather than misapplied. Because compaction commits its catch-up
tombstones inside the new generation's own sidecar *before* rewriting
the standalone file (all under the engine locks), every crash point
lands on a consistent (generation, sidecar) pair.
"""

import json
import os
from typing import Dict, Iterable, Optional

from distributed_faiss_tpu.mutation import versions as _versions
from distributed_faiss_tpu.utils import serialization

SIDECAR_NAME = "tombstones.json"

# format 2 (ISSUE 12): the deletion ledger carries per-id delete versions
# (``dead_versions``) and the payload gains the per-id LIVE write
# versions (``live_versions``) — the state the LWW gates compare. Format
# 1 payloads load with every version None (legacy seeding: unversioned
# compares below any real version, so legacy delete-wins semantics are
# the degenerate case); a format-1 READER of a format-2 payload sees the
# same ``dead_rows``/``dead_ids``/``dead_ledger`` keys it always did.
PAYLOAD_FORMAT = 2


def id_match_key(v):
    """Normalize a metadata id for cross-layout / cross-replica matching:
    JSON round-trips tuples as lists and stringifies everything it can't
    serialize, so both sides reduce to (recursively) tuple-ized values or
    their str() as the last resort. Shared by the sidecar BY-ID recovery
    (engine._apply_sidecar_by_id) and the anti-entropy digest/delta
    machinery (parallel/antientropy.py), which compare id SETS across
    replicas whose persistence histories differ."""
    if isinstance(v, (list, tuple)):
        return tuple(id_match_key(e) for e in v)
    if isinstance(v, (int, float, str, bool)):
        return v
    return str(v)


class TombstoneSet:
    """Positional dead-row set with the id-keyed record riding along.

    Plain data — thread-safety is the owning engine's ``index_lock``
    (copy what you need under the lock before iterating outside it).

    Besides the positional ``row -> id`` map (which compaction clears as
    it reclaims the rows), the set carries a position-free **deletion
    ledger**: the normalized ``id_match_key`` of every id ever deleted on
    this shard, surviving compaction and persisted in every sidecar
    payload. The ledger is what lets server-side anti-entropy
    (parallel/antientropy.py) distinguish "the peer is missing this row"
    from "this row was deleted here" — without it, a sweep against a
    compacted replica would resurrect deleted ids. A legal re-add of a
    deleted id (upsert) removes its ledger entry (engine.add_batch), so
    delete-then-readd converges to live everywhere.
    """

    __slots__ = ("_rows", "layout", "_ledger", "_live_versions")

    def __init__(self, rows: Optional[Dict[int, object]] = None,
                 layout: int = 0, ledger=None,
                 seed_ledger_from_rows: bool = True):
        self._rows: Dict[int, object] = (
            {int(r): v for r, v in rows.items()} if rows else {})
        self.layout = int(layout)
        # deletion ledger: normalized id key -> version of the delete
        # (None for legacy/unversioned deletes). ``ledger`` accepts BARE
        # keys only (version None) — versioned pairs go through
        # ``ledger_update_versioned`` (a pair passed here would be
        # normalized as a tuple id and never match its key again).
        self._ledger: Dict[object, object] = {}
        if ledger:
            self.ledger_update(ledger)
        # per-id LIVE write versions (the other half of the LWW state):
        # normalized id key -> version of the last versioned add/upsert
        # that made the id live here. Position-free like the ledger —
        # survives compaction, persists in every payload. Unversioned
        # adds leave no entry (None = legacy).
        self._live_versions: Dict[object, object] = {}
        # seed the ledger from the positional dead ids: right for direct
        # construction (a dead row's id was deleted) and for PRE-ledger
        # payloads — but a payload that CARRIES a dead_ledger is
        # authoritative and must not be re-seeded (from_payload): a
        # re-added (upserted) id is unledgered while its old positional
        # row stays dead until compaction, and re-seeding from that row
        # would resurrect the ledger entry on every reload, letting a
        # peer's delete-wins sweep destroy the live upsert cluster-wide
        if seed_ledger_from_rows:
            for v in self._rows.values():
                if v is not None:
                    self._ledger.setdefault(id_match_key(v), None)

    def __len__(self) -> int:
        return len(self._rows)

    def __contains__(self, row: int) -> bool:
        return int(row) in self._rows

    def rows(self) -> list:
        """Dead row positions (copy — safe to use outside the lock)."""
        return list(self._rows)

    def ids(self) -> list:
        """User ids of the dead rows (copy; informational — positions are
        the authoritative recovery key)."""
        return list(self._rows.values())

    def items(self) -> list:
        """(row, user id) pairs (copy — safe outside the lock)."""
        return list(self._rows.items())

    def add(self, rows: Iterable[int], ids: Optional[Iterable] = None,
            version=None) -> None:
        """Record dead rows. ``version`` (optional) stamps the ledger
        entries of the ids — the delete's LWW version; None keeps the
        legacy unversioned entry (which never outranks a real version)."""
        if ids is None:
            for r in rows:
                self._rows.setdefault(int(r), None)
            return
        version = _versions.version_key(version)
        for r, i in zip(rows, ids):
            self._rows[int(r)] = i
            if i is not None:
                k = id_match_key(i)
                self._ledger[k] = _versions.newest(self._ledger.get(k),
                                                   version)

    # ------------------------------------------------------ deletion ledger

    def ledger(self) -> frozenset:
        """Normalized keys of every id ever deleted on this shard (copy —
        safe outside the lock). Survives compaction; the anti-entropy
        pull guard."""
        return frozenset(self._ledger)

    def ledger_size(self) -> int:
        return len(self._ledger)

    def ledger_version(self, key):
        """The recorded delete version for one (raw or normalized) id —
        None when unledgered OR ledgered unversioned (the LWW gates
        treat both as minimal)."""
        return self._ledger.get(id_match_key(key))

    def ledger_items(self) -> list:
        """(normalized key, delete version) pairs (copy — safe outside
        the lock)."""
        return list(self._ledger.items())

    def ledger_update(self, keys: Iterable) -> int:
        """Record peer-observed deletions (already-normalized keys or
        raw ids; version None — see ``ledger_update_versioned`` for the
        stamped variant). Returns how many keys were new."""
        before = len(self._ledger)
        for k in keys:
            self._ledger.setdefault(id_match_key(k), None)
        return len(self._ledger) - before

    def ledger_update_versioned(self, pairs: Iterable) -> int:
        """Record ``(key, version)`` deletion pairs — versions max-merge,
        so a replayed older delete can never roll a newer one back.
        Returns how many keys were new."""
        before = len(self._ledger)
        for k, v in pairs:
            kk = id_match_key(k)
            self._ledger[kk] = _versions.newest(
                self._ledger.get(kk), _versions.version_key(v))
        return len(self._ledger) - before

    def prune_ledger(self, min_version, max_wall_ms=None) -> int:
        """Drop ledger entries whose delete version is STRICTLY below
        ``min_version`` (the cluster-wide minimum replica watermark —
        every registered replica has provably incorporated the delete,
        so the never-resurrect guard is no longer needed for it) AND, when
        ``max_wall_ms`` is given, whose wall-clock component is at most
        that old — the age bound covers the one writer the watermark
        floor cannot see: a CLIENT whose bounded repair queue still holds
        a pre-delete add for a replica that was down (the replayed stamp
        would sail through the LWW gates once its ledger pair is gone).
        Entries with version None (legacy/unversioned deletes) are NEVER
        pruned: nothing proves a peer saw them. Returns entries dropped.
        This is what keeps the sidecar from growing without bound under
        delete-heavy churn (engine.prune_ledger owns the persistence and
        the counter)."""
        mk = _versions.version_key(min_version)
        if mk is None:
            return 0
        victims = [k for k, v in self._ledger.items()
                   if v is not None and _versions.compare(v, mk) < 0
                   and (max_wall_ms is None or v[0] <= max_wall_ms)]
        for k in victims:
            del self._ledger[k]
        return len(victims)

    def unledger(self, keys: Iterable) -> int:
        """Drop ledger entries for ids that were legally re-added (upsert
        visibility: a re-ingested id must become pullable again)."""
        hit = 0
        for k in keys:
            kk = id_match_key(k)
            if kk in self._ledger:
                del self._ledger[kk]
                hit += 1
        return hit

    # ---------------------------------------------------- live versions

    def live_version(self, key):
        """The last versioned write that made this id live here (None =
        never versioned-written, or deleted since)."""
        return self._live_versions.get(id_match_key(key))

    def set_live_version(self, key, version) -> None:
        self._live_versions[id_match_key(key)] = _versions.version_key(
            version)

    def drop_live_version(self, key) -> None:
        self._live_versions.pop(id_match_key(key), None)

    def live_versions(self) -> list:
        """(normalized key, version) pairs (copy — safe outside the
        lock)."""
        return list(self._live_versions.items())

    def live_versions_update(self, pairs: Iterable) -> None:
        """Max-merge (key, version) pairs in (compaction carry-over,
        payload merge)."""
        for k, v in pairs:
            kk = id_match_key(k)
            self._live_versions[kk] = _versions.newest(
                self._live_versions.get(kk), _versions.version_key(v))

    def max_version(self):
        """The newest version recorded anywhere in this set (live or
        ledger) — the shard's restart watermark seed. None when nothing
        versioned was ever applied."""
        out = None
        for v in self._ledger.values():
            out = _versions.newest(out, v)
        for v in self._live_versions.values():
            out = _versions.newest(out, v)
        return out

    def count_below(self, n: int) -> int:
        """Dead rows with position < n (i.e. already indexed rows)."""
        return sum(1 for r in self._rows if r < n)

    def rows_in_range(self, lo: int, hi: int) -> list:
        """Dead positions in [lo, hi) — the buffer-drain mask window."""
        return [r for r in self._rows if lo <= r < hi]

    def to_payload(self) -> dict:
        rows = sorted(self._rows)
        return {
            "format": PAYLOAD_FORMAT,
            "layout": self.layout,
            "dead_rows": rows,
            "dead_ids": [self._rows[r] for r in rows],
            # position-free: survives compaction and layout swaps; JSON
            # round-trips tuples as lists, re-normalized at load.
            # dead_ledger keeps its format-1 shape (bare keys) so a
            # format-1 reader of this payload still recovers the ledger;
            # the versions ride in the format-2 pair lists beside it
            "dead_ledger": sorted(self._ledger, key=repr),
            "dead_versions": sorted(
                ([k, v] for k, v in self._ledger.items() if v is not None),
                key=repr),
            "live_versions": sorted(
                ([k, v] for k, v in self._live_versions.items()
                 if v is not None), key=repr),
        }

    @classmethod
    def from_payload(cls, payload: Optional[dict]) -> "TombstoneSet":
        if not payload:
            return cls()
        rows = [int(r) for r in payload.get("dead_rows", ())]
        ids = list(payload.get("dead_ids", ()))
        mapping = dict.fromkeys(rows)
        for r, i in zip(rows, ids):
            mapping[r] = i
        out = cls(mapping, layout=int(payload.get("layout", 0)),
                  ledger=payload.get("dead_ledger", ()),
                  # a payload that carries the ledger key is
                  # authoritative (even when empty) — only pre-ledger
                  # payloads seed from dead_ids
                  seed_ledger_from_rows="dead_ledger" not in payload)
        # format-2 version planes: absent on legacy payloads, in which
        # case everything stays version-None (unversioned is minimal, so
        # legacy state correctly loses to any later versioned write)
        out.ledger_update_versioned(payload.get("dead_versions", ()))
        out.live_versions_update(payload.get("live_versions", ()))
        return out

    def merge_payload(self, payload: Optional[dict]) -> None:
        """Union another payload's rows in (same-layout sidecar merge)."""
        if not payload:
            return
        other = TombstoneSet.from_payload(payload)
        for r, i in other._rows.items():
            self._rows.setdefault(r, i)
        for k, v in other._ledger.items():
            self._ledger[k] = _versions.newest(self._ledger.get(k), v)
        self.live_versions_update(other._live_versions.items())

    def __repr__(self) -> str:
        return f"<TombstoneSet {len(self._rows)} dead, layout {self.layout}>"


def dump_payload(payload: dict) -> str:
    """JSON text for a tombstone payload. ``default=str`` keeps arbitrary
    metadata id objects from failing the dump — the stringified form is
    informational; the integer positions are the recovery key."""
    return json.dumps(payload, default=str, sort_keys=True)


def write_sidecar(storage_dir: str, payload: dict) -> None:
    """Atomically (tmp+fsync+rename) rewrite the standalone sidecar — the
    per-mutation durability point."""
    serialization.atomic_write(
        os.path.join(storage_dir, SIDECAR_NAME),
        lambda f: f.write(dump_payload(payload) + "\n"), "w",
    )


def load_sidecar(storage_dir: str) -> Optional[dict]:
    path = os.path.join(storage_dir, SIDECAR_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        # a torn sidecar is impossible via atomic_write; treat garbage as
        # absent but loudly (the generation sidecar still covers recovery
        # up to the last commit)
        import logging

        logging.getLogger().warning(
            "unreadable tombstone sidecar at %s: %s", path, e)
        return None


def load_generation_payload(storage_dir: str, manifest: dict) -> Optional[dict]:
    """The committed generation's own tombstone entry (None for
    pre-mutation generations)."""
    entry = manifest.get("files", {}).get("tombstones")
    if not entry:
        return None
    try:
        with open(os.path.join(storage_dir, entry["name"])) as f:
            return json.load(f)
    except (OSError, ValueError):
        # verify_manifest already sha256-checked the file; reaching here
        # means filesystem-level corruption after the check — degrade to
        # the standalone sidecar
        return None
