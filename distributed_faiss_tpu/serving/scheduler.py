"""Deadline-aware micro-batching scheduler for the serving path.

One ``SearchScheduler`` per server rank. Connection threads call
``submit`` (blocking) or — for multiplexed RPC, where the connection
reader must keep pulling frames — ``submit_async`` with a completion
callback; a single named batcher thread drains the queue, coalesces
compatible requests — same ``(index_id, top_k, return_embeddings,
dim)`` — into one concatenated device batch, runs the engine's batched
search entry once, and hands every caller its row slice. Two flush triggers: the pending compatible rows reach
``max_batch_rows``, or the oldest queued request has waited
``max_wait_ms``.

Admission control (the backpressure contract, docs/OPERATIONS.md):

- a request whose deadline has already passed is rejected with
  ``DeadlineExpired`` before it can occupy queue space — and a request
  whose deadline expires while queued is shed at flush time, in both
  cases without touching the device;
- a request arriving while ``max_queue`` requests are pending is
  rejected with ``SchedulerBusy`` — the RPC layer turns this into a
  structured BUSY response that clients retry under their RetryPolicy
  backoff, so overload degrades into client-side pacing instead of an
  unbounded server-side queue.

Identity invariant (tested in tests/test_scheduler_identity.py): query
rows are independent in every index's search, so a caller's slice of the
merged launch is bit-identical to the result of serving its request
alone. The splitter routes rows purely positionally from the extraction
order — a caller can get *no* result or an error, never another
caller's rows.

One flush = one engine call = (on a mesh-backed index) ONE pjit launch:
``search_fn`` is ``engine.Index.search_batched``, whose locked device
step routes through ``TpuIndex.search_batched`` — for a rank that owns a
device mesh the whole merged window crosses to the chips as a single
device program with the top-k reduce on-mesh, and results leave the
device once per window (parallel/mesh.py; the engine's
``device_launches`` perf rows pin the contract). The group key already
isolates ``(index_id, top_k, return_embeddings, dim)``, so every row of
a flushed batch is legal in the same launch by construction.

Observability rides the shared ``LatencyStats`` histogram surface
(utils/tracing.py): queue-wait and end-to-end latency with streaming
percentiles, batch occupancy (requests and rows per launch), queue depth
at flush, and monotonic shed/busy counters — all exported through the
rank's ``get_perf_stats`` RPC under the ``"scheduler"`` key. Sampled
requests (a non-None ``trace_id``) additionally record ``server.queue``
(wait + which merge window they landed in and its occupancy) and
``server.device`` (the window's launch) spans into the owning server's
SpanBuffer, and stamp the latency histograms' exemplars
(observability/spans.py).
"""

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from distributed_faiss_tpu.observability import spans as obs_spans
from distributed_faiss_tpu.utils import lockdep, xfercheck
from distributed_faiss_tpu.utils.atomics import AtomicCounters
from distributed_faiss_tpu.utils.config import SchedulerCfg
from distributed_faiss_tpu.utils.tracing import LatencyStats

logger = logging.getLogger()


class SchedulerBusy(RuntimeError):
    """The request queue is full: the rank is overloaded. Retryable —
    clients back off and retry (rpc.BusyError client-side)."""

    def __init__(self, queue_depth: int, max_queue: int):
        self.queue_depth = queue_depth
        self.max_queue = max_queue
        super().__init__(
            f"scheduler queue full ({queue_depth}/{max_queue} requests)"
        )


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before it reached the device.
    Not retryable — the client's budget is already gone."""


class SchedulerStopped(RuntimeError):
    """The scheduler was stopped while this request was queued."""


class _Request:
    __slots__ = ("index_id", "q", "k", "return_embeddings", "deadline",
                 "eager", "enqueue_t", "event", "result", "error",
                 "callback", "trace_id")

    def __init__(self, index_id: str, q: np.ndarray, k: int,
                 return_embeddings: bool, deadline: Optional[float],
                 eager: bool = False, callback: Optional[Callable] = None,
                 trace_id: Optional[str] = None):
        self.index_id = index_id
        self.q = q
        self.k = k
        self.return_embeddings = return_embeddings
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.eager = eager  # head of queue flushes without the wait window
        self.enqueue_t = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        # async completion (the mux serving path): fired exactly once with
        # (result, error) when the request completes, instead of a thread
        # blocking on ``event``
        self.callback = callback
        # sampled distributed trace this request belongs to (None for the
        # unsampled default): queue-wait / coalesce / device spans are
        # recorded against it, and it rides the latency histograms as
        # their exemplar (observability/spans.py)
        self.trace_id = trace_id

    @property
    def key(self) -> Tuple:
        return (self.index_id, self.k, self.return_embeddings, self.q.shape[1])

    @property
    def rows(self) -> int:
        return self.q.shape[0]


def _concat_rows(live: List["_Request"], n_rows: int) -> np.ndarray:
    """Buffer-aware concat for a merged window: allocate the exact array
    the device launch consumes and copy each request's rows into their
    slice ONCE. Requests arriving over the binary wire already hold
    contiguous float32 query planes (the schema pins the dtype, and
    ``rpc.recv_frame_ex`` decoded them straight off the socket), so this
    is the only copy between socket and device — there is no per-request
    intermediate materialize, and a non-f32 straggler (legacy pickle
    frame from an old peer) converts during its one slice copy instead
    of in a separate pass."""
    out = np.empty((n_rows, live[0].q.shape[1]), np.float32)
    ofs = 0
    for r in live:
        out[ofs:ofs + r.rows] = r.q
        ofs += r.rows
    return out


def _split_rows(value, offsets: List[Tuple[int, int]]):
    """Split one element of a batched search result back per caller.

    ndarrays and lists split along the leading (row) axis; None (e.g. the
    embeddings slot when not requested) and scalars broadcast unchanged.
    """
    if value is None:
        return [None] * len(offsets)
    if isinstance(value, np.ndarray):
        return [value[lo:hi] for lo, hi in offsets]
    if isinstance(value, list):
        return [value[lo:hi] for lo, hi in offsets]
    return [value] * len(offsets)


class SearchScheduler:
    """Bounded queue + batcher thread coalescing concurrent searches.

    ``search_fn(index_id, query_batch, top_k, return_embeddings)`` is the
    engine's already-batched entry (engine.Index.search_batched on a
    server); it must return a tuple whose ndarray/list elements have one
    leading row per query row.
    """

    def __init__(self, search_fn: Callable, cfg: Optional[SchedulerCfg] = None,
                 name: str = "search-batcher", tag: Optional[dict] = None,
                 span_buffer=None):
        self._search_fn = search_fn
        self.cfg = cfg if cfg is not None else SchedulerCfg()
        # span ring for sampled requests (the owning server's SpanBuffer):
        # None (standalone schedulers, tracing off) records nothing
        self.spans = span_buffer
        # replica identity riding the stats surface (replication layer):
        # admission behavior is unchanged per replica, but operators need
        # queue/shed numbers attributable to (rank, shard_group). Owned by
        # the server, which updates shard_group on (re-)registration.
        self.tag = dict(tag or {})
        self._cond = lockdep.condition("SearchScheduler._cond")
        self._queue: List[_Request] = []
        self._stopping = False
        self.stats = LatencyStats()
        # admission/flush counters ride the shared atomic-counter helper
        # (utils/atomics.py): the fast paths bump them without contending
        # the flush condition, and stats readers get a torn-free snapshot
        self._counters = AtomicCounters(
            ("submitted", "batches", "shed_deadline", "rejected_busy"))
        self._thread = threading.Thread(
            target=self._batcher_loop, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ client side

    def submit(self, index_id: str, query_batch: np.ndarray, top_k: int,
               return_embeddings: bool = False,
               deadline: Optional[float] = None, eager: bool = False,
               trace_id: Optional[str] = None):
        """Enqueue one search and block until its slice of a merged launch
        is ready. ``deadline`` is an absolute ``time.monotonic()`` instant;
        expired requests never reach the device. ``eager`` skips the
        max-wait window when this request heads the queue — for callers
        that cannot overlap (a legacy one-in-flight peer on the
        single-threaded selector loop, where waiting for followers that
        structurally cannot arrive would add max_wait_ms of pure latency);
        admission control and coalescing with already-queued requests
        still apply."""
        req = self.submit_async(index_id, query_batch, top_k,
                                return_embeddings, deadline=deadline,
                                eager=eager, trace_id=trace_id)
        # timeout-with-retry rather than one untimed wait: every admitted
        # request is eventually finished by the batcher (its loop survives
        # flush failures and stop() drains the queue) — the escape hatch
        # covers the one way that contract can break, the batcher thread
        # itself dying (interpreter teardown, untrappable error), which
        # would otherwise strand this caller forever
        while not req.event.wait(timeout=5.0):
            if not self._thread.is_alive() and not req.event.is_set():
                raise SchedulerStopped(
                    "scheduler batcher thread died with this request "
                    "in flight")
        if req.error is not None:
            raise req.error
        self.stats.record("e2e_s", time.monotonic() - req.enqueue_t,
                          exemplar=req.trace_id)
        return req.result

    def submit_async(self, index_id: str, query_batch: np.ndarray,
                     top_k: int, return_embeddings: bool = False,
                     deadline: Optional[float] = None, eager: bool = False,
                     callback: Optional[Callable] = None,
                     trace_id: Optional[str] = None) -> _Request:
        """Admission-checked enqueue that returns immediately (the mux
        serving loops' entry: the connection reader must keep pulling
        frames). ``callback(result, error)`` fires exactly once — on the
        batcher thread — when the request completes; exactly one of the
        two is non-None. Admission failures (SchedulerBusy /
        DeadlineExpired / SchedulerStopped) raise synchronously in the
        caller: the request was never queued and the callback will not
        fire."""
        q = np.asarray(query_batch, np.float32)
        if q.ndim != 2:
            raise ValueError(f"query batch must be 2-D, got shape {q.shape}")
        req = _Request(index_id, q, int(top_k), bool(return_embeddings),
                       deadline, eager=eager, callback=callback,
                       trace_id=trace_id)
        with self._cond:
            if self._stopping:
                raise SchedulerStopped("scheduler is stopped")
            if deadline is not None and time.monotonic() >= deadline:
                self._counters.inc("shed_deadline")
                raise DeadlineExpired(
                    "deadline expired before the request was admitted")
            if len(self._queue) >= self.cfg.max_queue:
                self._counters.inc("rejected_busy")
                raise SchedulerBusy(len(self._queue), self.cfg.max_queue)
            self._counters.inc("submitted")
            self._queue.append(req)
            self._cond.notify_all()
        return req

    def _finish(self, req: _Request) -> None:
        """Publish a request's outcome exactly once: wake a blocked
        ``submit`` and fire the async completion callback (if any). Every
        completion path funnels here, so a request can never complete
        twice (the event doubles as the fired-flag) or complete with
        neither result nor error."""
        if req.event.is_set():
            return
        if req.error is None and req.result is None:
            req.error = RuntimeError("scheduled search aborted")
        req.event.set()
        if req.callback is not None:
            if req.error is None:
                # successes only — parity with the blocking submit(), so
                # e2e_s stays comparable between mux and legacy serving
                # (shed/busy failures would otherwise pollute the p99
                # with their queue-wait ceilings)
                self.stats.record("e2e_s", time.monotonic() - req.enqueue_t,
                                  exemplar=req.trace_id)
            try:
                req.callback(req.result, req.error)
            except Exception:
                logger.exception("scheduler completion callback failed")

    # ----------------------------------------------------------- batcher side

    def _batcher_loop(self) -> None:
        while True:
            try:
                batch = self._next_batch()
            except BaseException:
                # the flush-wait itself failed (allocation under memory
                # pressure, a bug in the trigger logic): the thread MUST
                # survive — callers blocked in submit's untimed event.wait
                # would otherwise hang forever. Fail whatever is queued and
                # keep serving.
                logger.exception("scheduler flush-wait failed")
                with self._cond:
                    stranded, self._queue = self._queue, []
                for r in stranded:
                    r.error = RuntimeError("scheduler internal error")
                    self._finish(r)
                time.sleep(0.05)  # never spin hot on a persistent failure
                continue
            if batch is None:
                return  # stopped; stop() already drained the queue
            try:
                self._serve(batch)
            except BaseException:  # the loop must survive any launch failure
                logger.exception("scheduler batch failed")
                for r in batch:
                    self._finish(r)

    def _next_batch(self) -> Optional[List[_Request]]:
        """Block until a flush trigger fires; pop and return one batch of
        compatible requests (FIFO from the head's group)."""
        max_wait_s = self.cfg.max_wait_ms / 1000.0
        with self._cond:
            while True:
                if self._stopping:
                    return None
                if not self._queue:
                    # timed idle wait (blocking-under-lock): submit()
                    # notifies on every enqueue, so the timeout only
                    # bounds the window in which a lost/raced notify (or
                    # an interpreter bug) could strand the batcher — the
                    # loop re-checks the queue and stop flag each lap
                    self._cond.wait(timeout=1.0)
                    continue
                head = self._queue[0]
                rows = sum(r.rows for r in self._queue if r.key == head.key)
                flush_at = head.enqueue_t + max_wait_s
                now = time.monotonic()
                if (not head.eager and rows < self.cfg.max_batch_rows
                        and now < flush_at):
                    self._cond.wait(flush_at - now)
                    continue
                # pop whole compatible requests until the row budget is
                # reached; a single over-budget request still goes alone
                # (requests are never split)
                taken, taken_rows, rest = [], 0, []
                for r in self._queue:
                    if (r.key == head.key
                            and (taken_rows < self.cfg.max_batch_rows)):
                        taken.append(r)
                        taken_rows += r.rows
                    else:
                        rest.append(r)
                self._queue = rest
                self.stats.record("queue_depth", float(len(rest)))
                return taken

    def _serve(self, batch: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for r in batch:
            if r.deadline is not None and now >= r.deadline:
                # shed without touching the device; the device batch only
                # carries rows someone is still waiting for
                self._counters.inc("shed_deadline")
                r.error = DeadlineExpired(
                    "deadline expired while queued "
                    f"(waited {now - r.enqueue_t:.3f}s)")
                self._finish(r)
                continue
            self.stats.record("queue_wait_s", now - r.enqueue_t,
                              exemplar=r.trace_id)
            live.append(r)
        if not live:
            return
        window = self._counters.inc("batches")
        n_rows = sum(r.rows for r in live)
        self.stats.record("batch_requests", float(len(live)))
        self.stats.record("batch_rows", float(n_rows))
        traced = ([r for r in live if r.trace_id is not None]
                  if self.spans is not None else [])
        if traced:
            # one queue span per sampled request: which merge window it
            # landed in and that window's occupancy — the "why did my
            # request wait / what did it share a launch with" answer
            now_w = time.time()
            for r in traced:
                waited = now - r.enqueue_t
                self.spans.record(
                    r.trace_id, "server.queue", now_w - waited, waited,
                    window=window, occupancy_requests=len(live),
                    occupancy_rows=n_rows)
        head = live[0]
        try:
            qcat = head.q if len(live) == 1 else _concat_rows(live, n_rows)
            if traced:
                # hand the engine a representative trace for the launch
                # (the whole window IS one device program, so one span
                # per sampled request below shares its timing)
                obs_spans.set_current_trace(traced[0].trace_id)
                launch_w0, launch_p0 = time.time(), time.perf_counter()
            try:
                # DFT_XFERCHECK=1 arms jax's transfer guard for the whole
                # merged-window launch: any implicit host<->device copy in
                # the flush fails the provoking request with provenance
                with xfercheck.guarded("scheduler merge-window flush"):
                    result = self._search_fn(
                        head.index_id, qcat, head.k, head.return_embeddings)
            finally:
                if traced:
                    launch_dt = time.perf_counter() - launch_p0
                    obs_spans.set_current_trace(None)
                    for r in traced:
                        self.spans.record(
                            r.trace_id, "server.device", launch_w0,
                            launch_dt, window=window, rows=n_rows)
            if not isinstance(result, tuple):
                result = (result,)
            offsets, ofs = [], 0
            for r in live:
                offsets.append((ofs, ofs + r.rows))
                ofs += r.rows
            per_elem = [_split_rows(v, offsets) for v in result]
            for i, r in enumerate(live):
                r.result = tuple(elem[i] for elem in per_elem)
        except Exception as exc:
            # one application error fails exactly the callers whose rows
            # shared the launch — never the rest of the queue. Each caller
            # gets its OWN exception object: submit() re-raises from N
            # threads concurrently, and raising one shared instance races
            # on its __traceback__ (interleaved frames in error reports).
            for r in live:
                try:
                    err = type(exc)(*exc.args)
                except Exception:
                    err = RuntimeError(f"scheduled search failed: {exc!r}")
                err.__cause__ = exc
                r.error = err
        finally:
            for r in live:
                self._finish(r)

    # ------------------------------------------------------------- lifecycle

    def stop(self) -> None:
        """Stop the batcher and fail everything still queued (callers see
        ``SchedulerStopped``; in-flight launches complete normally)."""
        with self._cond:
            self._stopping = True
            stranded, self._queue = self._queue, []
            self._cond.notify_all()
        for r in stranded:
            r.error = SchedulerStopped("scheduler stopped with request queued")
            self._finish(r)
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():  # pragma: no cover - launch wedged in device
            logger.warning("scheduler batcher thread did not exit in 10s")

    # ---------------------------------------------------------- observability

    def perf_stats(self, raw: bool = False) -> dict:
        """{"counters": {...}, "queues": {metric: histogram summary}} —
        merged into the rank's get_perf_stats surface under "scheduler";
        ``raw`` adds the bucket histograms (the Prometheus exporter's
        view)."""
        with self._cond:
            # torn-free counter snapshot taken beside the queue-length
            # read (AtomicCounters._lock is a leaf: safe under _cond).
            # Increments happen lock-free on the fast paths, so the two
            # reads are adjacent, not a cross-field consistency guarantee.
            counters = self._counters.snapshot()
            counters["queued"] = len(self._queue)
        out = {"counters": counters, "queues": self.stats.summary(raw=raw)}
        if self.tag:
            out["replica"] = dict(self.tag)
        return out
