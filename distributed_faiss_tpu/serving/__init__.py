"""Serving-side request scheduling (cross-request micro-batching).

The reference serves one RPC per connection thread with no coalescing
(distributed_faiss/server.py:95-135); under concurrent load every
``search`` RPC pays its own device dispatch. ``SearchScheduler`` puts a
bounded, deadline-aware queue and a batcher thread between the serving
loops and the engine: concurrent searches for the same index coalesce
into one padded device batch, results split back per caller, and
admission control sheds work the rank cannot serve in time (BUSY /
expired-deadline structured rejections instead of unbounded queueing).
"""

from distributed_faiss_tpu.serving.scheduler import (
    DeadlineExpired,
    SchedulerBusy,
    SchedulerStopped,
    SearchScheduler,
)

__all__ = [
    "SearchScheduler",
    "SchedulerBusy",
    "SchedulerStopped",
    "DeadlineExpired",
]
