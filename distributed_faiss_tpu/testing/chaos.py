"""Chaos harness: a TCP fault-injection proxy + a rank kill/restart driver.

``ChaosProxy`` sits between ``rpc.Client`` and an ``IndexServer`` and
injects scriptable transport faults — added latency, connection reset
(RST), blackhole (accept-then-stall), frame garbling, and cut-mid-frame —
without ever parsing or unpickling the stream: it forwards raw bytes, so
it cannot mask a protocol bug by "fixing" frames in flight. Faults are
assigned per ACCEPTED connection from a ``plan`` list (connection 0 gets
``plan[0]``, ...); connections beyond the plan get the settable default
fault (``set_fault``), which starts as pass-through.

``ServerHarness`` drives real server rank subprocesses: launch a cluster,
SIGKILL one rank, restart it on the same port (without re-appending to
the discovery file — the client already holds the server list). Together
they are the oracle for the self-healing write path (client retry +
reroute), the degraded read path, and torn-snapshot recovery.
"""

import logging
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from distributed_faiss_tpu.parallel import launcher, rpc
from distributed_faiss_tpu.utils import lockdep

logger = logging.getLogger()

_CHUNK = 65536


class Fault:
    """One scripted transport fault.

    kinds:
      - ``latency``: sleep ``delay`` seconds before forwarding each chunk
        in ``direction``.
      - ``reset``: hard RST the client after ``after_bytes`` upstream bytes
        (0 = immediately on accept).
      - ``blackhole``: accept, then never read or forward a byte — the
        peer's recv hangs until its own deadline fires.
      - ``garble``: XOR the bytes in window [``after_bytes``,
        ``after_bytes + nbytes``) of ``direction`` with 0xFF (frame
        corruption that keeps the stream length intact).
      - ``cut``: forward exactly ``after_bytes`` bytes of ``direction``,
        then close both sides mid-frame.
      - ``drop_kind``: parse the ``direction`` stream at FRAME granularity
        (header fields only — lengths and the kind byte; payload bytes are
        never decoded or unpickled) and silently swallow every frame whose
        wire kind is in ``drop_kinds``, forwarding all other frames
        untouched. This is the surgical fault the anti-entropy failure
        detector is tested with: blackhole only the KIND_DIGEST exchange
        while query traffic on the same link flows normally.
    """

    LATENCY = "latency"
    RESET = "reset"
    BLACKHOLE = "blackhole"
    GARBLE = "garble"
    CUT = "cut"
    DROP_KIND = "drop_kind"
    KINDS = frozenset({LATENCY, RESET, BLACKHOLE, GARBLE, CUT, DROP_KIND})

    def __init__(self, kind: str, delay: float = 0.05, after_bytes: int = 0,
                 nbytes: int = 8, direction: str = "up", drop_kinds=None):
        if kind not in self.KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if direction not in ("up", "down"):
            raise ValueError("direction must be 'up' (client->server) or 'down'")
        if kind == self.DROP_KIND and not drop_kinds:
            raise ValueError("drop_kind fault needs a non-empty drop_kinds set")
        self.kind = kind
        self.delay = delay
        self.after_bytes = after_bytes
        self.nbytes = nbytes
        self.direction = direction
        self.drop_kinds = frozenset(int(k) for k in (drop_kinds or ()))

    def __repr__(self):
        return (f"Fault({self.kind!r}, delay={self.delay}, "
                f"after_bytes={self.after_bytes}, nbytes={self.nbytes}, "
                f"direction={self.direction!r}, drop_kinds={set(self.drop_kinds)})")


def _rst_close(sock: socket.socket) -> None:
    """Close with SO_LINGER(1, 0): the kernel sends RST, not FIN."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _quiet_close(sock: socket.socket) -> None:
    """shutdown + close. The shutdown is load-bearing: a bare close() while
    ANOTHER thread is blocked in recv() on the same fd leaves the kernel-side
    connection open (the blocked syscall pins the file description), so the
    peer never sees FIN and a "dead" connection hangs forever; shutdown()
    tears the connection down immediately and wakes the blocked recv."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    """TCP interposer with scriptable fault plans (one fault per accepted
    connection; None = pass-through)."""

    def __init__(self, target_host: str, target_port: int,
                 listen_port: int = 0, plan: Optional[List[Optional[Fault]]] = None):
        self.target = (target_host, target_port)
        self._listen_port = listen_port
        self._lock = lockdep.lock("ChaosProxy._lock")
        self._plan: List[Optional[Fault]] = list(plan) if plan else []
        self._default_fault: Optional[Fault] = None
        self._accepted = 0
        self._conns: List[socket.socket] = []
        # forwarding threads (accept loop, per-connection handler/pump):
        # tracked so stop() can join them after tearing their sockets
        # down — a drill must not bleed pump threads into the next test
        # (the DFT_THREADCHECK witness polices exactly that)
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._listener: Optional[socket.socket] = None
        self.port: Optional[int] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "ChaosProxy":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", self._listen_port))
        s.listen(16)
        # graftlint: atomic(_listener, port): published in start() strictly before the accept thread exists — Thread.start() is the happens-before edge, and neither is ever rebound while the proxy lives
        self._listener = s
        self.port = s.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"chaos-accept:{self.port}")
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self._listener is not None:
            _quiet_close(self._listener)
        with self._lock:
            conns, self._conns = self._conns, []
            threads, self._threads = self._threads, []
        for c in conns:
            _quiet_close(c)
        # closed sockets wake every pump/handler out of recv; the joins
        # are bounded so a wedged kernel socket can't hostage teardown
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----------------------------------------------------------------- plan

    def set_fault(self, fault: Optional[Fault]) -> None:
        """Default fault for connections beyond the scripted plan."""
        with self._lock:
            self._default_fault = fault

    def connections_seen(self) -> int:
        with self._lock:
            return self._accepted

    def _next_fault(self) -> Optional[Fault]:
        with self._lock:
            idx = self._accepted
            self._accepted += 1
            if idx < len(self._plan):
                return self._plan[idx]
            return self._default_fault

    # ------------------------------------------------------------ forwarding

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                client, addr = self._listener.accept()
            except OSError:
                break
            fault = self._next_fault()
            t = threading.Thread(target=self._handle, args=(client, fault),
                                 daemon=True,
                                 name=f"chaos-conn:{self.port}")
            with self._lock:
                self._threads.append(t)
            t.start()

    def _handle(self, client: socket.socket, fault: Optional[Fault]) -> None:
        if fault is not None and fault.kind == Fault.RESET and fault.after_bytes == 0:
            _rst_close(client)
            return
        if fault is not None and fault.kind == Fault.BLACKHOLE:
            # accept-then-stall: never read a byte; the connection looks
            # established but nothing ever flows until the proxy stops
            with self._lock:
                self._conns.append(client)
            self._stopping.wait()
            _quiet_close(client)
            return
        try:
            upstream = socket.create_connection(self.target, timeout=10)
        except OSError:
            _quiet_close(client)
            return
        with self._lock:
            self._conns.append(client)
            self._conns.append(upstream)
        up_fault = fault if fault is not None and fault.direction == "up" else None
        down_fault = fault if fault is not None and fault.direction == "down" else None
        t = threading.Thread(target=self._pump,
                             args=(client, upstream, up_fault),
                             daemon=True, name=f"chaos-pump:{self.port}")
        with self._lock:
            self._threads.append(t)
        t.start()
        self._pump(upstream, client, down_fault)

    def _pump(self, src: socket.socket, dst: socket.socket,
              fault: Optional[Fault]) -> None:
        if fault is not None and fault.kind == Fault.DROP_KIND:
            self._pump_frames(src, dst, fault)
            return
        sent = 0
        try:
            while True:
                data = src.recv(_CHUNK)
                if not data:
                    break
                if fault is not None:
                    if fault.kind == Fault.LATENCY:
                        time.sleep(fault.delay)
                    elif fault.kind == Fault.GARBLE:
                        data = self._garble(data, sent, fault)
                    elif fault.kind == Fault.RESET:
                        if sent + len(data) >= fault.after_bytes:
                            dst.sendall(data[: max(0, fault.after_bytes - sent)])
                            # linger-RST src (only THIS thread recvs it, so
                            # close really fires the RST); the peer socket
                            # has the other pump blocked in recv and needs
                            # the shutdown-first teardown
                            _rst_close(src)
                            _quiet_close(dst)
                            self._forget(src, dst)
                            return
                    elif fault.kind == Fault.CUT:
                        if sent + len(data) >= fault.after_bytes:
                            dst.sendall(data[: max(0, fault.after_bytes - sent)])
                            _quiet_close(dst)
                            _quiet_close(src)
                            self._forget(src, dst)
                            return
                dst.sendall(data)
                sent += len(data)
        except OSError:
            pass
        # one direction ended: tear down both so the peer sees EOF, not a
        # half-open connection
        _quiet_close(src)
        _quiet_close(dst)
        self._forget(src, dst)

    # frame header shared with parallel/rpc.py (magic, kind u8, skel_len
    # u32, narr u32) — aliased, not mirrored, so a wire-format change
    # cannot silently desync the proxy into corrupting streams instead of
    # dropping frames. The proxy reads LENGTH fields and the kind byte
    # only; payload bytes are forwarded (or dropped) opaque, never
    # unpickled. _read_exact stays local: the pump needs owned bytes
    # (indexing, .decode()), not a view into rpc.FrameReader's buffer.
    _FRAME_HDR = rpc._HDR
    _FRAME_MAGIC = rpc.MAGIC

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(min(_CHUNK, n - len(buf)))
            if not chunk:
                raise EOFError("stream ended mid-frame")
            buf += chunk
        return bytes(buf)

    def _pump_frames(self, src: socket.socket, dst: socket.socket,
                     fault: Fault) -> None:
        """Frame-granular pump for drop_kind faults: swallow whole frames
        of the targeted kinds, forward every other frame byte-exact. A
        stream that stops framing (bad magic — not this protocol, or
        already desynced) degrades to raw forwarding of what was read."""
        import numpy as _np

        try:
            while True:
                head = self._read_exact(src, self._FRAME_HDR.size)
                magic, kind, skel_len, narr = self._FRAME_HDR.unpack(head)
                if magic != self._FRAME_MAGIC:
                    # unknown dialect: stop parsing, forward verbatim
                    dst.sendall(head)
                    while True:
                        data = src.recv(_CHUNK)
                        if not data:
                            break
                        dst.sendall(data)
                    break
                parts = [head, self._read_exact(src, skel_len)]
                for _ in range(narr):
                    dl = self._read_exact(src, 1)
                    dt = self._read_exact(src, dl[0])
                    nd = self._read_exact(src, 1)
                    dims_raw = self._read_exact(src, 8 * nd[0])
                    dims = struct.unpack(f"<{nd[0]}Q", dims_raw)
                    itemsize = _np.dtype(dt.decode()).itemsize
                    nbytes = itemsize
                    for d in dims:
                        nbytes *= d
                    parts += [dl, dt, nd, dims_raw,
                              self._read_exact(src, int(nbytes))]
                if kind in fault.drop_kinds:
                    continue  # swallowed: the peer never sees this frame
                for p in parts:
                    dst.sendall(p)
        except (OSError, EOFError, ValueError, TypeError):
            # ValueError/TypeError: a desynced stream fed garbage into
            # np.dtype(dt.decode()) — same terminal condition as a torn
            # socket, and the cleanup below must still run (a dead pump
            # thread that skips it leaks both sockets and wedges the
            # peer mid-frame until its own timeout)
            pass
        _quiet_close(src)
        _quiet_close(dst)
        self._forget(src, dst)

    def _forget(self, *socks) -> None:
        """Drop finished sockets from the live list — a long-lived proxy
        (operator game-day drills) must not accumulate two dead socket
        objects per connection until stop()."""
        with self._lock:
            for s in socks:
                if s in self._conns:
                    self._conns.remove(s)

    @staticmethod
    def _garble(data: bytes, sent: int, fault: Fault) -> bytes:
        lo = max(fault.after_bytes, sent)
        hi = min(fault.after_bytes + fault.nbytes, sent + len(data))
        if lo >= hi:
            return data
        buf = bytearray(data)
        for i in range(lo - sent, hi - sent):
            buf[i] ^= 0xFF
        return bytes(buf)


class ServerHarness:
    """Process-level chaos: launch, SIGKILL, and restart real server ranks.

    Initial launch goes through ``launcher.launch_local`` (ranks register
    in the discovery file); ``restart`` re-spawns a single rank on its
    original port WITHOUT re-appending a discovery entry — live clients
    already hold the server list, and their stubs redial the same
    host:port automatically on the next call.
    """

    def __init__(self, num_servers: int, discovery_path: str, storage_dir: str,
                 base_port: int = 13700, env: Optional[dict] = None):
        self.num_servers = num_servers
        self.discovery_path = discovery_path
        self.storage_dir = storage_dir
        self.base_port = base_port
        self.env = dict(env) if env else {}
        self._lock = lockdep.lock("ServerHarness._lock")
        self.procs: Dict[int, subprocess.Popen] = {}

    def port(self, rank: int) -> int:
        return self.base_port + rank

    def start(self) -> "ServerHarness":
        procs = launcher.launch_local(
            self.num_servers, self.discovery_path, self.storage_dir,
            base_port=self.base_port, env=self.env,
        )
        with self._lock:
            self.procs = dict(enumerate(procs))
        return self

    def __enter__(self) -> "ServerHarness":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def kill(self, rank: int) -> None:
        """SIGKILL one rank (no shutdown hooks run — the crash case)."""
        with self._lock:
            proc = self.procs[rank]
        proc.kill()
        proc.wait()

    def restart(self, rank: int, load_index: bool = False,
                extra_env: Optional[dict] = None) -> None:
        """Re-spawn a killed rank on its original port. ``extra_env``
        overlays per-rank environment for THIS spawn only — e.g.
        ``DFT_SHARD_GROUP`` so a rejoining rank comes back pre-registered
        into its replica group (replication membership)."""
        cmd = [sys.executable, "-m", "distributed_faiss_tpu.parallel.server",
               "--rank", str(rank), "--port", str(self.port(rank)),
               "--storage-dir", self.storage_dir,
               "--discovery", self.discovery_path]
        if load_index:
            cmd.append("--load-index")
        proc = subprocess.Popen(
            cmd, env={**os.environ, **self.env, **(extra_env or {})})
        with self._lock:
            self.procs[rank] = proc

    def wait_port(self, rank: int, timeout: float = 30.0) -> None:
        """Block until the rank's accept loop answers (post-restart sync)."""
        deadline = time.time() + timeout
        while True:
            try:
                socket.create_connection(("localhost", self.port(rank)),
                                         timeout=1).close()
                return
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rank {rank} (port {self.port(rank)}) never came up")
                time.sleep(0.1)

    def stop(self) -> None:
        with self._lock:
            procs, self.procs = list(self.procs.values()), {}
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        for p in procs:  # reap: no zombie ranks left behind
            try:
                p.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass


class QueryStorm:
    """Live query load for fault windows: N client threads re-issue one
    search in a tight loop while the test injects faults (SIGKILL a rank,
    garble a link), then ``stop()`` hands back every (result, error)
    observed. The replication acceptance gate asserts byte-identity of
    every storm result against the healthy cluster's golden answer —
    proving a rank death under load costs neither rows nor correctness.

    ``allow_partial`` selects the degraded-read contract under test:
    False (the default) means every storm search must be served complete
    (replication failover), True tolerates the pre-replication partial
    contract. Errors are collected, never raised into the storm threads.
    """

    def __init__(self, client, index_id: str, query, topk: int,
                 threads: int = 4, allow_partial: bool = False,
                 interval: float = 0.0):
        self.client = client
        self.index_id = index_id
        self.query = query
        self.topk = topk
        self.allow_partial = allow_partial
        self.interval = interval
        self.num_threads = threads
        self._stop = threading.Event()
        self._lock = lockdep.lock("QueryStorm._lock")
        self.results: List[tuple] = []
        self.errors: List[BaseException] = []
        self._threads: List[threading.Thread] = []

    def start(self) -> "QueryStorm":
        for i in range(self.num_threads):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"query-storm-{i}")
            self._threads.append(t)
            t.start()
        return self

    def __enter__(self) -> "QueryStorm":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                out = self.client.search(
                    self.query, self.topk, self.index_id,
                    allow_partial=self.allow_partial)
            except Exception as e:
                with self._lock:
                    self.errors.append(e)
            else:
                with self._lock:
                    self.results.append(out)
            if self.interval:
                time.sleep(self.interval)

    def stop(self) -> Tuple[List[tuple], List[BaseException]]:
        """End the storm and return (results, errors) collected so far."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30.0)
        with self._lock:
            return list(self.results), list(self.errors)
