"""Fault-injection harnesses for exercising the failure paths in CI.

Per "The Tail at Scale" (Dean & Barroso, 2013), fault tolerance that is
not continuously exercised regresses: this package is the oracle the
robustness layer is tested against — a scriptable TCP chaos proxy
(``chaos.ChaosProxy``) and a process-level rank kill/restart harness
(``chaos.ServerHarness``). Test-support code, but shipped inside the
package so operators can drive game-day drills against staging clusters
with the same tooling CI uses.
"""

from distributed_faiss_tpu.testing.chaos import ChaosProxy, Fault, ServerHarness

__all__ = ["ChaosProxy", "Fault", "ServerHarness"]
