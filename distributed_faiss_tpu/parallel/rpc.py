"""Binary tensor RPC: the client<->server control/data plane over DCN.

Replaces the reference's pickle-over-TCP transport
(distributed_faiss/rpc.py: FileSock 64 MiB chunked pickle streams, dynamic
method dispatch via __getattr__, server exceptions re-raised client-side).

Design differences (conscious, SURVEY §2.4):
- Length-prefixed binary frames instead of a raw pickle stream: numpy/jax
  tensors travel as raw buffers (dtype/shape header + bytes, no pickle
  copy of the payload); only the object *skeleton* (method name, scalars,
  metadata lists) is pickled. Embedding batches therefore move at
  socket-memcpy speed and deserialize zero-copy into numpy.
- Same external contract: ``Client.<anything>(...)`` performs a remote
  call of that method name; server-side exceptions come back as
  ``ServerException`` with the remote traceback (reference rpc.py:126-131);
  clean shutdown via a CLOSE frame (reference ClientExit, rpc.py:96).

Frame layout (little-endian):
  magic b"DFT1" | kind u8 | skel_len u32 | narr u32 | skel bytes |
  narr x [ dtype_len u8 | dtype utf8 | ndim u8 | dims u64* | data bytes ]
"""

import io
import os
import pickle
import random
import socket
import struct
import threading
import time

import numpy as np

DEFAULT_PORT = 12032  # same default port as the reference (rpc.py:22)

# jitter draws come from a private generator: retry timing must never
# perturb the host process's global RNG stream (test reproducibility)
_jitter_rng = random.Random()

# ---------------------------------------------------------------- unpickling
#
# The frame skeleton is pickled bytes read off a TCP socket; a bare
# pickle.loads there is remote code execution by design (GLOBAL/REDUCE
# opcodes resolve and call any importable callable). The reference inherits
# exactly this exposure (distributed_faiss/rpc.py FileSock pickle streams).
# _RestrictedUnpickler resolves only what RPC payloads legitimately
# contain: numpy array/scalar reconstruction, a safe builtins subset
# (containers that pickle via REDUCE), and the three package types the RPC
# surface actually ships (IndexCfg, IndexState, _TensorRef) — as EXACT
# (module, name) pairs, never a namespace prefix. Two reasons exact pairs
# are load-bearing: protocol >= 4 find_class getattr-walks DOTTED names,
# so a prefix match would let a crafted frame resolve e.g.
# ("<package>.parallel.rpc", "os.system") through this module's own
# imports; and whole-namespace trust would let REDUCE call any package
# callable with attacker-chosen args (SSRF via Client(...), etc.).
# Operators shipping custom metadata classes can opt out with
# DFT_RPC_UNSAFE_PICKLE=1 (documented in docs/LINTING.md#pickle-safety).

_SAFE_BUILTINS = frozenset({
    "set", "frozenset", "complex", "bytearray", "slice", "range",
})
_SAFE_NUMPY = frozenset({
    "ndarray", "dtype", "_reconstruct", "scalar", "bool_",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "longlong", "ulonglong",
})
_PACKAGE = __name__.split(".")[0]
_SAFE_PACKAGE_GLOBALS = frozenset({
    (f"{_PACKAGE}.utils.config", "IndexCfg"),
    (f"{_PACKAGE}.utils.state", "IndexState"),
    (__name__, "_TensorRef"),
})


def _unsafe_pickle_ok() -> bool:
    return os.environ.get("DFT_RPC_UNSAFE_PICKLE", "0") == "1"


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        # "." in name would getattr-traverse past the allowlisted symbol
        # (proto >= 4 dotted-name resolution); every branch requires an
        # exact, dot-free name
        if "." not in name:
            if module == "builtins" and name in _SAFE_BUILTINS:
                return super().find_class(module, name)
            if (module == "numpy" or module.startswith(("numpy.core.",
                                                        "numpy._core."))) \
                    and name in _SAFE_NUMPY:
                return super().find_class(module, name)
            if (module, name) in _SAFE_PACKAGE_GLOBALS:
                return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"RPC payload references disallowed global {module}.{name} "
            "(set DFT_RPC_UNSAFE_PICKLE=1 to trust peers with arbitrary "
            "pickles)"
        )


def restricted_loads(data) -> object:
    """``pickle.loads`` for wire bytes, through the allowlisted Unpickler."""
    if _unsafe_pickle_ok():
        return pickle.loads(data)  # graftlint: ok(pickle-safety): explicit operator opt-out
    return _RestrictedUnpickler(io.BytesIO(bytes(data))).load()

MAGIC = b"DFT1"
KIND_CALL = 0
KIND_RESULT = 1
KIND_ERROR = 2
KIND_CLOSE = 3
# structured admission-control rejection (serving scheduler): the payload is
# a dict with at least {"reason": "queue_full" | "deadline"}. Distinct from
# KIND_ERROR because it is an expected, retryable load-shedding signal, not
# a server-side exception with a traceback.
KIND_BUSY = 4

_HDR = struct.Struct("<4sBII")


class ClientExit(Exception):
    """Raised server-side when a client sends a CLOSE frame."""


class ServerException(Exception):
    """A remote exception, carrying the server-side traceback text."""


class BusyError(Exception):
    """The server shed this request (scheduler queue full). The rank is
    alive and healthy — retry after backoff (RetryPolicy treats this as
    retryable), don't reroute or mark the rank dead."""

    def __init__(self, message: str, info: dict = None):
        super().__init__(message)
        self.info = dict(info or {})


class DeadlineExceeded(Exception):
    """The call's deadline passed — either client-side before send, or
    server-side before the request reached the device. NOT retryable: the
    budget is already spent; retrying can only miss it again."""


class FrameError(RuntimeError):
    """The byte stream violated the frame protocol (bad magic): corruption
    or desync. The connection that produced it must never be reused."""


# exception classes that mean "the bytes never made it intact / the peer is
# gone", i.e. the rank may be dead, restarting, or behind a corrupting
# link. FrameError and UnpicklingError are here because a garbled RESPONSE
# surfaces client-side as one of them — generic_fun has already dropped the
# connection, so a retry redials cleanly (no less safe than the lost-ack
# case the at-least-once design accepts). ServerException is deliberately
# NOT here: it means the rank is alive and rejected the request (retrying
# an application error just repeats it, and masking it would hide a
# misconfigured shard).
TRANSPORT_ERRORS = (OSError, EOFError, FrameError, pickle.UnpicklingError)

# retryable = transport failures PLUS structured load-shedding (BUSY). Kept
# separate from TRANSPORT_ERRORS because transport classification also
# drives rerouting and partial-search "rank missing" decisions, where a
# busy-but-alive rank must NOT count as dead.
RETRYABLE_ERRORS = TRANSPORT_ERRORS + (BusyError,)


class RetryPolicy:
    """Bounded exponential backoff with jitter for transient failures:
    TRANSPORT errors and structured BUSY load-shedding.

    The write path wraps per-rank RPCs in ``run``: a call that fails with a
    transport error (rank dead, connection reset, deadline expired) or a
    BUSY rejection (scheduler queue full — the rank is alive but shedding
    load) is re-attempted up to ``max_attempts`` times, sleeping
    ``base_delay * multiplier**attempt`` (capped at ``max_delay``) between
    attempts, with +/- ``jitter`` fractional randomization so a fleet of
    retrying clients doesn't stampede a restarting (or overloaded) rank in
    lockstep. Application errors (ServerException and anything else
    non-retryable) propagate immediately — they are deterministic and
    retrying them only hides the real failure. DeadlineExceeded is likewise
    never retried: the call's budget is already spent.
    """

    transport_errors = TRANSPORT_ERRORS
    retryable_errors = RETRYABLE_ERRORS

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.5):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.jitter = jitter

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable_errors)

    def delay(self, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (0-based: the delay between
        the first failure and the second attempt is ``delay(0)``)."""
        d = min(self.max_delay, self.base_delay * (self.multiplier ** attempt))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _jitter_rng.random() - 1.0)
        return max(0.0, d)

    def run(self, fn, *args, **kwargs):
        """Call ``fn(*args, **kwargs)``, retrying transient failures."""
        return self.run_filtered(self.retryable_errors, None, fn,
                                 *args, **kwargs)

    def run_filtered(self, retryable, abs_deadline, fn, *args, **kwargs):
        """``run`` with an explicit retryable-exception tuple and an
        optional absolute ``time.time()`` deadline: a retry whose backoff
        sleep would land past the deadline is abandoned (the exception
        propagates) instead of burning budget the caller no longer has."""
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except retryable:
                if attempt + 1 >= self.max_attempts:
                    raise
                d = self.delay(attempt)
                if abs_deadline is not None and time.time() + d >= abs_deadline:
                    raise
                time.sleep(d)


class _TensorRef:
    __slots__ = ("idx",)

    def __init__(self, idx: int):
        self.idx = idx

    def __reduce__(self):
        return (_TensorRef, (self.idx,))


def _extract(obj, arrays):
    """Replace ndarrays in (nested) containers with _TensorRef placeholders."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        if a.dtype.hasobject:
            return obj  # object arrays can't travel as raw buffers
        arrays.append(a)
        return _TensorRef(len(arrays) - 1)
    if type(obj) is list:
        return [_extract(v, arrays) for v in obj]
    if type(obj) is tuple:
        return tuple(_extract(v, arrays) for v in obj)
    if type(obj) is dict:
        return {k: _extract(v, arrays) for k, v in obj.items()}
    # jax arrays and anything array-like with __array__ but not ndarray
    if hasattr(obj, "__array__") and not isinstance(obj, (str, bytes)):
        try:
            return _extract(np.asarray(obj), arrays)
        except Exception:
            return obj
    return obj


def _restore(obj, arrays):
    if isinstance(obj, _TensorRef):
        return arrays[obj.idx]
    if type(obj) is list:
        return [_restore(v, arrays) for v in obj]
    if type(obj) is tuple:
        return tuple(_restore(v, arrays) for v in obj)
    if type(obj) is dict:
        return {k: _restore(v, arrays) for k, v in obj.items()}
    return obj


def _send_parts(sock: socket.socket, parts) -> None:
    for p in parts:
        sock.sendall(p)


def pack_frame(kind: int, obj=None):
    arrays = []
    skel = pickle.dumps(_extract(obj, arrays), protocol=4)
    parts = [_HDR.pack(MAGIC, kind, len(skel), len(arrays)), skel]
    for a in arrays:
        dt = a.dtype.str.encode()
        hdr = struct.pack("<B", len(dt)) + dt + struct.pack("<B", a.ndim) + struct.pack(
            f"<{a.ndim}Q", *a.shape
        )
        parts.append(hdr)
        if a.size:  # zero-size arrays can't be cast to a byte view
            parts.append(memoryview(a).cast("B"))
    return parts


def send_frame(sock: socket.socket, kind: int, obj=None) -> None:
    _send_parts(sock, pack_frame(kind, obj))


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise EOFError("connection closed mid-frame" if got else "connection closed")
        got += r
    return view


def recv_frame(sock: socket.socket):
    head = _recv_exact(sock, _HDR.size)
    magic, kind, skel_len, narr = _HDR.unpack(head)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {bytes(magic)!r}")
    skel = restricted_loads(_recv_exact(sock, skel_len))
    arrays = []
    for _ in range(narr):
        (dt_len,) = struct.unpack("<B", _recv_exact(sock, 1))
        dt = np.dtype(bytes(_recv_exact(sock, dt_len)).decode())
        (ndim,) = struct.unpack("<B", _recv_exact(sock, 1))
        dims = struct.unpack(f"<{ndim}Q", _recv_exact(sock, 8 * ndim))
        nbytes = int(np.prod(dims, dtype=np.int64)) * dt.itemsize if ndim else dt.itemsize
        a = np.empty(dims, dtype=dt)
        if nbytes:
            view = memoryview(a).cast("B")
            got = 0
            while got < nbytes:
                r = sock.recv_into(view[got:], nbytes - got)
                if r == 0:
                    raise EOFError("connection closed mid-tensor")
                got += r
        arrays.append(a)
    return kind, _restore(skel, arrays)


class Client:
    """Dynamic-dispatch RPC stub: any attribute is a remote method
    (reference rpc.py:137-138). One persistent connection, thread-safe."""

    # redial budget for a stub whose previous call hit a transport failure:
    # short, so a still-dead rank fails fast inside degraded-mode fan-outs,
    # but enough for a restarted rank's accept loop
    RECONNECT_TIMEOUT = 2.0
    # after a failed redial, calls fail instantly for this long instead of
    # each burning the full RECONNECT_TIMEOUT — a degraded-mode fan-out
    # during an outage pays the redial budget once per cooldown window,
    # not once per search
    REDIAL_COOLDOWN = 2.0
    # slack added to the socket wait when it is derived from a deadline:
    # the server rebases the stamped budget at frame DECODE time (strictly
    # later than our send), so a socket wait of exactly the budget would
    # always fire before the server's flush-time shed frame (BUSY
    # reason=deadline) could arrive — the structured DeadlineExceeded would
    # be unreachable and every expiry would cost a torn connection. A
    # result landing inside the grace was dispatched pre-deadline and is
    # still correct; a truly hung rank is bounded at budget + grace.
    DEADLINE_GRACE = 0.5

    def __init__(self, client_id: int, host: str, port: int, v6: bool = False,
                 connect_timeout: float = 60.0):
        self.id = client_id
        self.host = host
        self.port = port
        self._fam = socket.AF_INET6 if v6 else socket.AF_INET
        self._connect(connect_timeout)
        self._lock = threading.Lock()
        self._closed = False
        self._shutdown = False
        self._next_redial = 0.0

    # graftlint: ok(lock-discipline): called only from __init__ (pre-threading) and generic_fun (holding _lock)
    def _connect(self, connect_timeout: float) -> None:
        # a server may register in the discovery file moments before its
        # accept loop is up (the reference has the same gap,
        # server_launcher.py:64 vs server.py:95): retry with backoff.
        # Each attempt carries a socket deadline bounded by the remaining
        # budget — without it, a blackholed host blocks connect() for the
        # kernel SYN timeout (minutes), far past connect_timeout
        deadline = time.time() + connect_timeout
        delay = 0.05
        while True:
            self.sock = socket.socket(self._fam, socket.SOCK_STREAM)
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                self.sock.settimeout(
                    max(0.05, min(connect_timeout, deadline - time.time())))
                self.sock.connect((self.host, self.port))
                self.sock.settimeout(None)
                return
            except OSError:
                self.sock.close()
                if time.time() + delay > deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 1.6, 2.0)

    def generic_fun(self, fname: str, args=(), kwargs=None, timeout: float = None,
                    deadline: float = None):
        """Remote call. With ``timeout``, the socket gets a deadline for this
        call; on expiry the connection is closed (a partial frame would
        desync the stream) and socket.timeout propagates. Any transport
        failure likewise drops the connection, and the NEXT call redials
        (RECONNECT_TIMEOUT) — so a rank restarted on the same host:port
        rejoins the fan-out without rebuilding the IndexClient.

        ``deadline`` is an absolute ``time.time()`` instant: the REMAINING
        budget is stamped into the call frame (as a relative duration —
        clock-skew-safe) so the server's scheduler can shed the request
        unserved once it can no longer answer in time, and it also bounds
        the socket wait. An already-expired deadline raises
        ``DeadlineExceeded`` without touching the wire."""
        if deadline is not None and deadline - time.time() <= 0:
            # cheap fast-fail before contending for the stub lock
            raise DeadlineExceeded(
                f"deadline expired {time.time() - deadline:.3f}s before "
                f"calling {fname}")
        with self._lock:
            if self._shutdown:
                raise RuntimeError(f"client to {self.host}:{self.port} is closed")
            if self._closed:
                if time.time() < self._next_redial:
                    raise ConnectionRefusedError(
                        f"rank at {self.host}:{self.port} is down "
                        "(redial cooldown)")
                try:
                    self._connect(self.RECONNECT_TIMEOUT)
                except OSError:
                    self._next_redial = time.time() + self.REDIAL_COOLDOWN
                    raise
                self._closed = False
            # budget is computed HERE — after the lock wait and any redial —
            # so the stamped value reflects what genuinely remains; a budget
            # measured at entry could be stale by a whole in-flight call
            # from another thread plus RECONNECT_TIMEOUT
            budget = None
            if deadline is not None:
                budget = deadline - time.time()
                if budget <= 0:
                    raise DeadlineExceeded(
                        f"deadline expired {-budget:.3f}s before sending "
                        f"{fname}")
                # socket wait = budget + grace, so the server's structured
                # shed response can win the race against our own timeout
                wait = budget + self.DEADLINE_GRACE
                timeout = wait if timeout is None else min(timeout, wait)
            # pack BEFORE touching the socket: a client-side pickling failure
            # (unpicklable argument) must raise without tearing down a
            # healthy connection — zero bytes have hit the wire.
            # The 4th payload element (frame meta) is only added when a
            # deadline is set, so deadline-less frames stay byte-compatible
            # with pre-deadline peers.
            payload = (fname, tuple(args), kwargs or {})
            if budget is not None:
                payload = payload + ({"deadline_s": budget},)
            parts = pack_frame(KIND_CALL, payload)
            if timeout is not None:
                self.sock.settimeout(timeout)
            try:
                _send_parts(self.sock, parts)
                kind, payload = recv_frame(self.sock)
            except Exception:
                # OSError/EOFError (socket timeouts, mid-frame stream ends)
                # but also FrameError ("bad frame magic") and unpickling
                # failures (ADVICE r4): any mid-frame failure leaves the
                # stream position unknown, so the connection must never be
                # reused — drop it and let the NEXT call redial cleanly
                # instead of serving garbage from a desynced stream.
                self._closed = True
                self.sock.close()
                raise
            finally:
                if timeout is not None and not self._closed:
                    self.sock.settimeout(None)
        if kind == KIND_RESULT:
            return payload
        if kind == KIND_ERROR:
            raise ServerException(payload)
        if kind == KIND_BUSY:
            info = payload if isinstance(payload, dict) else {}
            if info.get("reason") == "deadline":
                raise DeadlineExceeded(
                    f"server shed {fname}: deadline expired before dispatch")
            raise BusyError(
                f"server shed {fname}: {info.get('reason', 'busy')} "
                f"(queue {info.get('queue_depth', '?')}/"
                f"{info.get('max_queue', '?')})", info)
        raise RuntimeError(f"unexpected frame kind {kind}")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self.generic_fun(name, args, kwargs)

        call.__name__ = name
        return call

    def close(self):
        # the whole teardown runs under the call lock: the unlocked flag
        # flips of the previous version could race a concurrent
        # generic_fun (double CLOSE frame / closing a socket mid-call)
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True  # user-initiated: no auto-reconnect after this
            if self._closed:
                return
            self._closed = True
            try:
                send_frame(self.sock, KIND_CLOSE, None)
            except OSError:
                pass
            finally:
                self.sock.close()
